"""Pipelined host->device staging: the bounded, double-buffered streaming
engine behind every shard upload.

BENCH_r05's ``rowshard`` tier put the wall in sharp relief: streaming a
1M-cell CSR host->HBM took 21.9 s (0.37 GB/s dense-equivalent) while the
entire 3-pass K=9 solve took 1.4 s. The old loops were fully serial —
per device, per slab: slice CSR on host, ``np.zeros`` a fresh pad buffer,
``device_put``, wait, densify, repeat — so host prep, the wire, and the
device scatter each idled two-thirds of the time. "Distributed
Out-of-Memory NMF" (PAPERS.md) attributes most of its speedup to exactly
this overlap; MPI-FAUN's design keeps communication off the critical path
for the same reason.

This module provides the general machinery:

  * :func:`run_pipeline` — a sliding-window producer/consumer: host slab
    preparation (CSR row slicing, nnz padding, ELL conversion) runs on a
    small thread pool, transfers are issued (and awaited) inside the
    workers so uploads to *different devices* proceed concurrently, and
    the caller thread commits on-device compute (densify / donated slab
    placement) in deterministic task order. In-flight depth is capped by
    ``CNMF_TPU_STREAM_DEPTH`` and a host-bytes budget, so host RAM stays
    bounded no matter how large the matrix. Depth 1 (or 0 threads) is the
    exact serial fallback.
  * :class:`SlabBufferPool` — reusable host slab buffers (no ``np.zeros``
    per slab): each buffer remembers its dirty prefix so reuse zeroes only
    what the previous slab wrote.
  * power-of-two nnz *bucketing* (:func:`nnz_bucket`) — a single skewed
    slab no longer inflates every slab's transfer to the global max pad;
    slabs ride the smallest bucket that fits, and the compile count stays
    logarithmic.
  * :class:`StreamStats` — per-phase wall ledger (host_prep / h2d /
    device / wall, bytes) with an overlap fraction, recordable into a
    :class:`~cnmf_torch_tpu.utils.profiling.StageTimer` so the bench can
    verify the overlap instead of vibing it.
  * :func:`stream_to_device` — single-device staging of a dense or CSR
    host matrix (CSR densifies slab-by-slab, on device or on host per
    :func:`_csr_transport`; the full dense matrix never exists on host),
    used by ``cNMF._stage_dense`` and the replicate-sweep staging sites.

Shard-granular fault containment (ISSUE 6): a failed slab prep/transfer
retries with bounded exponential backoff (``CNMF_TPU_SHARD_RETRIES``)
instead of failing the whole staging call on a transient error, raising
:class:`ShardUploadError` only when the budget is exhausted; a transfer
that stops making progress for ``CNMF_TPU_STREAM_STALL_S`` seconds is
converted into a diagnosable :class:`ShardStallError` by the commit-side
watchdog instead of hanging the factorize (and, downstream, the whole
mesh) forever. Both emit telemetry ``fault`` events when the caller
threads an event log through.

Env knobs
---------
``CNMF_TPU_STREAM_DEPTH``    max prepared-but-uncommitted slabs in flight
                             (default ``2 x threads``; ``1`` = serial)
``CNMF_TPU_STREAM_THREADS``  host-prep worker threads (default
                             ``min(4, cpu_count)``; ``0`` = serial)
``CNMF_TPU_STREAM_BYTES``    host bytes budget for in-flight slab buffers
                             (default 4 GiB) — depth is clamped so
                             ``depth * slab_bytes`` stays under it
``CNMF_TPU_SHARD_RETRIES``   per-slab upload retry budget (default 2;
                             0 disables retries)
``CNMF_TPU_SHARD_BACKOFF_S`` retry backoff base: attempt N waits
                             ``base * 2^(N-1)`` seconds (default 0.1)
``CNMF_TPU_STREAM_STALL_S``  per-slab wall-clock watchdog on the
                             pipelined path (default 0 = off): a slab
                             whose prep+transfer exceeds it raises
                             ``ShardStallError``

All knobs are validated at parse time — a negative/zero-where-invalid or
non-numeric value raises immediately with a one-line message naming the
knob, instead of falling through to a confusing downstream error.
"""

from __future__ import annotations

import collections
import functools
import os
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from ..obs import metrics as obs_metrics
from ..runtime.faults import maybe_fail as _maybe_fail_fault

__all__ = ["StreamStats", "SlabBufferPool", "run_pipeline", "nnz_bucket",
           "stream_threads", "stream_depth", "stream_to_device",
           "stream_put_leaves", "DENSIFY_SLAB_ROWS",
           "ShardStallError", "ShardUploadError",
           "shard_retries", "stream_stall_s", "stream_store_sharded"]

# rows per on-device scatter / dense slab. TPU scatter materializes
# sort/workspace temporaries proportional to its OUTPUT, so densifying a
# multi-GB shard in one scatter can double its footprint and OOM;
# slab-sized scatters keep the transient small while the donated update
# assembles the shard.
DENSIFY_SLAB_ROWS = 65_536

# bytes per host-densified slab on the dense transport. ~32 MB is the
# measured sweet spot: the slab stays L3-resident between the worker's
# toarray write and the device_put read (h2d ran at cache speed, 2-3x the
# DRAM rate 64-256 MB slabs got), while the depth window stays meaningful
# on small-RAM hosts.
_DENSE_SLAB_BYTES = 32 << 20

DEPTH_ENV = "CNMF_TPU_STREAM_DEPTH"
THREADS_ENV = "CNMF_TPU_STREAM_THREADS"
BYTES_ENV = "CNMF_TPU_STREAM_BYTES"
TRANSPORT_ENV = "CNMF_TPU_STREAM_TRANSPORT"
SHARD_RETRIES_ENV = "CNMF_TPU_SHARD_RETRIES"
SHARD_BACKOFF_ENV = "CNMF_TPU_SHARD_BACKOFF_S"
STALL_ENV = "CNMF_TPU_STREAM_STALL_S"

_DEFAULT_BYTES_BUDGET = 4 << 30


class ShardUploadError(RuntimeError):
    """A shard/slab upload kept failing after the CNMF_TPU_SHARD_RETRIES
    budget — the staged array cannot be completed."""


class ShardStallError(RuntimeError):
    """A shard/slab transfer made no progress for CNMF_TPU_STREAM_STALL_S
    seconds — converted from a silent distributed hang into a diagnosable
    failure (abort cleanly, then relaunch to resume from the newest
    checkpoint)."""


# strict parsers (utils/envknobs.py — the ONE definition): bad values
# reject at parse time with a one-line message naming the knob
from ..utils.envknobs import (env_float as _env_float, env_int as _env_int,
                              env_str as _env_str)


def shard_retries() -> int:
    """Per-slab upload retry budget (``CNMF_TPU_SHARD_RETRIES``, default
    2; 0 disables retries — the first failure raises)."""
    return _env_int(SHARD_RETRIES_ENV, 2, lo=0)


def stream_stall_s() -> float:
    """Per-slab progress watchdog in seconds (``CNMF_TPU_STREAM_STALL_S``,
    default 0 = disabled). Enforced on the pipelined path, where the
    commit thread awaits worker futures; the serial fallback has no
    independent thread to watch."""
    return _env_float(STALL_ENV, 0.0, lo=0.0)


def stream_threads() -> int:
    """Host-prep worker count. 0 disables the pipeline (serial staging).
    Precedence (ISSUE 17 planner contract): an explicit
    ``CNMF_TPU_STREAM_THREADS`` pin wins; else the measured staging-
    throughput point from the autotune cache (``stream_threads``,
    ``utils/autotune.py``) when one exists for this device; else the
    static default, which leaves one core for the caller thread's commit
    dispatch and the XLA runtime (measured faster than cpu_count workers
    on small hosts, where an extra worker just contends for memory
    bandwidth). Negative or non-numeric values reject at parse time."""
    static = max(1, min(4, (os.cpu_count() or 2) - 1))
    if _env_str(THREADS_ENV, "").strip() == "":
        try:
            from ..utils.autotune import cached_plan_point

            tuned = cached_plan_point("stream_threads")
            if tuned is not None:
                return max(0, int(tuned))
        except Exception:
            pass
    return _env_int(THREADS_ENV, static, lo=0)


def stream_depth(slab_bytes: int | None = None,
                 threads: int | None = None, windows: int = 1) -> int:
    """In-flight slab cap: explicit ``CNMF_TPU_STREAM_DEPTH`` wins, else
    double-buffered per worker plus a slot for the commit window; either
    way clamped so the in-flight host buffers stay under the
    ``CNMF_TPU_STREAM_BYTES`` budget. ``windows`` is how many depth-sized
    windows of slab buffers the caller keeps alive at once (the CSR path
    holds a prep window AND a commit-drain window, so its budget share is
    per-window)."""
    if threads is None:
        threads = stream_threads()
    depth = _env_int(DEPTH_ENV, max(2 * threads + 1, 3), lo=1)
    if slab_bytes and slab_bytes > 0:
        budget = _env_int(BYTES_ENV, _DEFAULT_BYTES_BUDGET, lo=1)
        depth = min(depth,
                    max(budget // (int(slab_bytes) * max(windows, 1)), 1))
    return max(depth, 1)


class StreamStats:
    """Thread-safe per-phase wall ledger for one staging run.

    ``host_prep_s`` / ``h2d_s`` accumulate across worker threads (their sum
    can exceed ``wall_s`` — that IS the overlap); ``device_s`` is commit
    dispatch plus the final device sync; ``wall_s`` is end-to-end.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.host_prep_s = 0.0
        self.h2d_s = 0.0
        self.device_s = 0.0
        self.wall_s = 0.0
        self.nbytes = 0
        self.slabs = 0
        # disk-producer stage (out-of-core shard-store ingestion,
        # utils/shardstore.py): read wall + bytes read from disk, and the
        # host slab-residency high-water mark of the staging call
        self.disk_s = 0.0
        self.disk_nbytes = 0
        self.host_peak_bytes = 0
        # remote store-backend transport counters (utils/storebackend.py),
        # folded in as a snapshot delta around the staging pass — zero
        # (and omitted from telemetry) on local-backend runs
        self.store_remote = False
        self.store_retries = 0
        self.store_hedges = 0
        self.store_hedges_won = 0
        self.store_cache_hits = 0
        self.store_cache_misses = 0
        self.store_degraded = 0

    def add(self, host_prep_s=0.0, h2d_s=0.0, device_s=0.0, nbytes=0,
            slabs=0, disk_s=0.0, disk_nbytes=0):
        with self._lock:
            self.host_prep_s += host_prep_s
            self.h2d_s += h2d_s
            self.device_s += device_s
            self.nbytes += nbytes
            self.slabs += slabs
            self.disk_s += disk_s
            self.disk_nbytes += disk_nbytes
        # live-scrape mirror (obs/metrics.py, CNMF_TPU_METRICS): the
        # same slab/byte totals the stream_summary table reports per
        # pass, visible mid-pass on /metrics instead of post-hoc
        if slabs:
            obs_metrics.counter_inc("cnmf_stream_slabs_total", slabs)
        if nbytes:
            obs_metrics.counter_inc("cnmf_stream_bytes_total", nbytes)

    def fold_store_counters(self, before, after):
        """Fold a remote backend's counter delta (snapshots from
        ``storebackend.backend_counter_snapshot``, taken before/after
        the pass) into this ledger; no-op when either side is None
        (local backend)."""
        if before is None or after is None:
            return
        with self._lock:
            self.store_remote = True
            for field, key in (("store_retries", "retries"),
                               ("store_hedges", "hedges"),
                               ("store_hedges_won", "hedges_won"),
                               ("store_cache_hits", "cache_hits"),
                               ("store_cache_misses", "cache_misses"),
                               ("store_degraded", "degraded_reads")):
                delta = int(after.get(key, 0)) - int(before.get(key, 0))
                setattr(self, field, getattr(self, field) + max(delta, 0))

    @property
    def overlap_fraction(self) -> float:
        """How much of the phase work ran concurrently: 0 on the serial
        path (phase walls sum to the end-to-end wall), approaching 1 when
        disk read, prep, transfer, and device work fully hide behind each
        other."""
        busy = self.host_prep_s + self.h2d_s + self.device_s + self.disk_s
        if busy <= 0.0 or self.wall_s <= 0.0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - self.wall_s / busy))

    def gb_per_s(self) -> float:
        return (self.nbytes / self.wall_s / 1e9) if self.wall_s > 0 else 0.0

    def read_gb_per_s(self) -> float:
        """Disk-read throughput of the producer stage (0 when the staging
        call had no disk source)."""
        return (self.disk_nbytes / self.disk_s / 1e9) if self.disk_s > 0 \
            else 0.0

    def record_to(self, timer, prefix: str):
        """Write one row per phase (plus the wall) into a StageTimer so
        overlap is inspectable post-hoc from the timings TSV."""
        if timer is None:
            return
        if self.disk_s > 0:
            timer.record(f"{prefix}/disk", self.disk_s,
                         nbytes=self.disk_nbytes)
        timer.record(f"{prefix}/host_prep", self.host_prep_s)
        timer.record(f"{prefix}/h2d", self.h2d_s, nbytes=self.nbytes)
        timer.record(f"{prefix}/device", self.device_s)
        timer.record(f"{prefix}/wall", self.wall_s, nbytes=self.nbytes,
                     slabs=self.slabs,
                     overlap=round(self.overlap_fraction, 3))

    def __repr__(self):
        return (f"StreamStats(wall={self.wall_s:.3f}s "
                f"prep={self.host_prep_s:.3f}s h2d={self.h2d_s:.3f}s "
                f"device={self.device_s:.3f}s disk={self.disk_s:.3f}s "
                f"bytes={self.nbytes} "
                f"slabs={self.slabs} overlap={self.overlap_fraction:.2f})")


class _Buf:
    __slots__ = ("arr", "used")

    def __init__(self, arr):
        self.arr = arr
        self.used = 0  # dirty prefix length from the previous fill


class SlabBufferPool:
    """Reusable host slab buffers keyed by (shape, dtype).

    ``fill`` writes the payload prefix and zeroes only the stale remainder
    of the previous occupant — a fresh ``np.zeros`` per slab is exactly
    the host-side churn the pipeline is trying to hide. Buffers must be
    returned (``give``) only after the device transfer that reads them has
    completed.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._free: dict = collections.defaultdict(list)
        self.allocated = 0

    def take(self, shape, dtype) -> _Buf:
        key = (tuple(np.atleast_1d(shape)), np.dtype(dtype).str)
        with self._lock:
            free = self._free[key]
            if free:
                return free.pop()
            self.allocated += 1
        return _Buf(np.zeros(shape, np.dtype(dtype)))

    def give(self, buf: _Buf):
        key = (buf.arr.shape, buf.arr.dtype.str)
        with self._lock:
            self._free[key].append(buf)

    @staticmethod
    def fill(buf: _Buf, data) -> np.ndarray:
        n = len(data)
        buf.arr[:n] = data
        if buf.used > n:
            buf.arr[n:buf.used] = 0
        buf.used = n
        return buf.arr


def nnz_bucket(nnz: int, cap: int, floor: int = 1024) -> int:
    """Pad width for a slab's nnz: the smallest power-of-two bucket that
    fits (never below ``floor``, never above the global max ``cap``) — so
    one skewed slab compiles its own program instead of inflating every
    slab's transfer to the global max, and the total number of compiled
    scatter shapes stays logarithmic."""
    cap = max(int(cap), 1)
    b = max(int(floor), 1)
    n = max(int(nnz), 1)
    while b < n:
        b <<= 1
    return min(b, cap)


def _emit_fault(events, kind: str, context: dict):
    """Best-effort telemetry ``fault`` event — ``events`` is an optional
    EventLog-shaped object (``emit`` never raises there, but stay safe
    against foreign sinks: telemetry must not take staging down)."""
    if events is None:
        return
    try:
        events.emit("fault", kind=kind, context=context)
    except Exception:
        pass


def _retrying(prep, context: str | None, events, heartbeat: dict | None = None,
              cancelled: threading.Event | None = None):
    """Wrap a slab prep with the shard-granular retry policy: transient
    prep/transfer failures retry with bounded exponential backoff
    (``CNMF_TPU_SHARD_RETRIES`` / ``CNMF_TPU_SHARD_BACKOFF_S``) before
    the exhausted slab fails the staging call as
    :class:`ShardUploadError`. Also hosts the ``stall`` fault-injection
    hook (runtime/faults.py), which sits where a real wire hang would.

    ``heartbeat`` (threaded path): the wrapper stamps
    ``heartbeat[id(task)]`` at the start of every attempt — including
    after each backoff sleep — so the stall watchdog measures PER-ATTEMPT
    progress and legitimate retry/backoff time never masquerades as a
    hang (the two knobs compose instead of conflicting).

    ``cancelled``: set by the pipeline when a stall conviction abandons
    the worker threads — a thread that wakes from a hang (or from the
    injected ``stall`` clause) afterwards must not start fresh prep work
    against the dead pipeline (nothing will commit it, and a re-stage
    may already be racing on the same source)."""
    retries = shard_retries()
    backoff = _env_float(SHARD_BACKOFF_ENV, 0.1, lo=0.0)

    from ..runtime.faults import maybe_stall as _maybe_stall

    def wrapped(task, *extra):
        attempt = 0
        while True:
            if heartbeat is not None:
                heartbeat[id(task)] = time.monotonic()
            if attempt == 0:
                _maybe_stall(context=context)
            if cancelled is not None and cancelled.is_set():
                raise ShardStallError(
                    "staging call already aborted by the stall watchdog "
                    "(context=%s, task=%s); abandoned worker skips fresh "
                    "prep work" % (context, task))
            try:
                return prep(task, *extra)
            except (ShardStallError, ShardUploadError, KeyboardInterrupt,
                    SystemExit):
                raise
            except Exception as exc:
                # a TornShardError already burned read_slab's OWN
                # disk-retry ladder, and a RemoteStoreError already
                # exhausted the network transport's retry/backoff budget
                # (utils/storebackend.py) — re-running either here would
                # square the retries and misreport the failure as a
                # transfer fault (ShardUploadError). Lazy type lookup
                # keeps this jax-heavy module importable without the
                # store layer loaded.
                from ..utils.shardstore import (RemoteStoreError,
                                               TornShardError)

                if isinstance(exc, (TornShardError, RemoteStoreError)):
                    raise
                attempt += 1
                ctx = {"context": str(context), "task": str(task),
                       "attempt": attempt,
                       "error": f"{type(exc).__name__}: {exc}"}
                if attempt > retries:
                    _emit_fault(events, "shard_upload_failed", ctx)
                    raise ShardUploadError(
                        "shard upload failed after %d attempt(s) "
                        "(context=%s, task=%s): %s: %s — raise %s to retry "
                        "transient transfer faults more"
                        % (attempt, context, task, type(exc).__name__, exc,
                           SHARD_RETRIES_ENV)) from exc
                _emit_fault(events, "shard_retry", ctx)
                delay = backoff * (2 ** (attempt - 1))
                warnings.warn(
                    "shard upload attempt %d/%d failed (%s: %s); retrying "
                    "in %.2gs" % (attempt, retries, type(exc).__name__, exc,
                                  delay),
                    RuntimeWarning, stacklevel=2)
                if heartbeat is not None:
                    # stamp the backoff window FORWARD: the sleep is the
                    # retry policy working, not a hang — the stall budget
                    # starts counting again when the next attempt begins
                    heartbeat[id(task)] = time.monotonic() + delay
                time.sleep(delay)

    return wrapped


def run_pipeline(tasks, prep, commit, *, depth: int | None = None,
                 threads: int | None = None, fault_context: str | None = None,
                 events=None, liveness=None, source=None):
    """Sliding-window pipeline: ``prep(task)`` on worker threads, with at
    most ``depth`` tasks prepared-but-uncommitted; ``commit(task,
    payload)`` on the caller thread in exact submission order (donated
    device buffers chain per device, so commit order is load-bearing).

    ``depth <= 1``, ``threads <= 0``, or a single task degrade to the
    serial loop — bit-identical behavior, no threads spawned.

    ``source`` (out-of-core ingestion, ISSUE 10): an optional
    DISK-PRODUCER stage — ``source(task)`` runs on its own single reader
    thread ahead of the prep workers (disk is one spindle/page cache;
    parallel reads just seek-thrash), read-ahead bounded by the same
    sliding window, and ``prep`` is then called as ``prep(task, raw)``.
    The three stages — disk read, host prep, h2d transfer — overlap
    across slabs; a transient prep/transfer retry reuses the already-read
    ``raw`` (no disk re-read), while the source carries its own retry
    wrapper for read-side faults.

    Fault containment (ISSUE 6): every prep rides the shard-granular
    retry wrapper (:func:`_retrying`); on the threaded path the commit
    side additionally enforces the ``CNMF_TPU_STREAM_STALL_S`` watchdog —
    a slab whose prep+transfer makes no progress for that long raises
    :class:`ShardStallError` instead of hanging the caller forever (the
    stalled worker thread is abandoned, not joined: a hung transfer
    cannot be interrupted, only diagnosed and relaunched around).
    ``fault_context`` names the staging site in fault events/errors;
    ``events`` is an optional telemetry EventLog; ``liveness`` is an
    optional ``runtime.elastic.Heartbeat`` stamped (throttled) after
    every committed slab, so a participant mid-staging stays diagnosably
    alive to the liveness layer — a multi-minute atlas stage must not
    read as a wedge at the next barrier/straggler check. (Distinct from
    the internal per-slab ``heartbeat`` stamps the stall watchdog keeps.)
    """
    tasks = list(tasks)
    if threads is None:
        threads = stream_threads()
    if depth is None:
        depth = stream_depth(threads=threads)
    stall_s = stream_stall_s()
    read_context = f"{fault_context or 'stream'}:read"

    def _committed(i: int):
        if liveness is not None:
            liveness.beat(phase=f"stage:{fault_context or 'stream'}",
                          cursor=i)

    if depth <= 1 or threads <= 0 or len(tasks) <= 1:
        if source is not None:
            src_serial = _retrying(source, read_context, events)
            prep_serial = _retrying(prep, fault_context, events)
            for i, t in enumerate(tasks):
                # read once per task; a transient prep/transfer retry
                # then reuses the SAME raw payload (mirrors the threaded
                # path's cached future — no disk re-read per prep retry)
                commit(t, prep_serial(t, src_serial(t)))
                _committed(i)
            return
        serial_prep = _retrying(prep, fault_context, events)
        for i, t in enumerate(tasks):
            commit(t, serial_prep(t))
            _committed(i)
        return
    import concurrent.futures

    # per-attempt progress stamps from the retry wrapper: the watchdog
    # measures time since the slab's LAST attempt started, so retry
    # backoff sleeps (a different knob doing its job) never read as a hang
    heartbeat: dict = {}
    cancelled = threading.Event()
    src_ex = None
    if source is not None:
        src_wrapped = _retrying(source, read_context, events,
                                heartbeat=heartbeat, cancelled=cancelled)
        src_ex = concurrent.futures.ThreadPoolExecutor(
            1, thread_name_prefix="cnmf-stream-disk")
        base_prep = prep

        def prep(task, raw_fut):  # noqa: F811 — staged twin of the bare prep
            # a failed read future already burned the source's own retry
            # ladder; .result() re-raising here is final, while a
            # transient prep/transfer failure retries against the SAME
            # raw payload (no disk re-read)
            return base_prep(task, raw_fut.result())

    prep = _retrying(prep, fault_context, events, heartbeat=heartbeat,
                     cancelled=cancelled)

    def await_result(task, fut):
        if stall_s <= 0:
            return fut.result()
        poll = min(max(stall_s / 10.0, 0.05), 1.0)
        while True:
            try:
                return fut.result(timeout=poll)
            except concurrent.futures.TimeoutError:
                last = heartbeat.get(id(task))
                if last is not None and time.monotonic() - last <= stall_s:
                    continue  # attempt still within its progress budget
                if last is None and not fut.running():
                    continue  # still queued behind other slabs — not hung
                ctx = {"context": str(fault_context), "task": str(task),
                       "stall_s": stall_s}
                _emit_fault(events, "shard_stall", ctx)
                raise ShardStallError(
                    "shard upload made no progress for %gs (%s; context=%s, "
                    "task=%s) — the transfer is hung, not slow. Aborting "
                    "this staging call cleanly; relaunch resumes from the "
                    "newest valid checkpoint." % (stall_s, STALL_ENV,
                                                  fault_context, task)) \
                    from None

    pending = collections.deque()
    n_done = 0
    ex = concurrent.futures.ThreadPoolExecutor(
        max_workers=min(threads, len(tasks)),
        thread_name_prefix="cnmf-stream")
    try:
        for t in tasks:
            if len(pending) >= depth:
                tt, fut = pending.popleft()
                commit(tt, await_result(tt, fut))
                _committed(n_done)
                n_done += 1
            if src_ex is not None:
                # the reader thread runs ahead within the same sliding
                # window: at most `depth` raw slabs are read-but-unprepped,
                # so disk read-ahead respects the host-bytes budget too
                pending.append((t, ex.submit(prep, t,
                                             src_ex.submit(src_wrapped, t))))
            else:
                pending.append((t, ex.submit(prep, t)))
        while pending:
            tt, fut = pending.popleft()
            commit(tt, await_result(tt, fut))
            _committed(n_done)
            n_done += 1
    except ShardStallError:
        # a genuinely stalled worker cannot be joined without re-inheriting
        # the hang it was just converted from: abandon it (it finishes or
        # dies with the relaunched process) and cancel the queue; the
        # cancelled flag stops an eventually-waking abandoned thread from
        # starting fresh prep work against this dead pipeline
        cancelled.set()
        if src_ex is not None:
            src_ex.shutdown(wait=False, cancel_futures=True)
        ex.shutdown(wait=False, cancel_futures=True)
        raise
    except BaseException:
        # every other failure drains cleanly: workers are alive, so waiting
        # is safe and preserves the old invariant that no worker outlives a
        # failed staging call (no zombie transfers racing a re-stage)
        if src_ex is not None:
            src_ex.shutdown(wait=True, cancel_futures=True)
        ex.shutdown(wait=True, cancel_futures=True)
        raise
    else:
        if src_ex is not None:
            src_ex.shutdown(wait=True)
        ex.shutdown(wait=True)


# ---------------------------------------------------------------------------
# on-device slab assembly (shared by the sharded and single-device paths)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("rows", "g"))
def _csr_densify(vals, cols, indptr, rows: int, g: int):
    """Densify one CSR row slab ON DEVICE: row ids recovered from indptr
    by searchsorted, then one scatter-add. Padded tail entries (vals 0,
    cols 0, positions past indptr[-1]) land as +0 adds — harmless."""
    rowids = jnp.clip(
        jnp.searchsorted(indptr, jnp.arange(vals.shape[0]), side="right") - 1,
        0, rows - 1)
    # cols may arrive int16 (halves wire bytes when g < 2**15); widen on
    # device for the scatter
    return jnp.zeros((rows, g), vals.dtype).at[
        rowids, cols.astype(jnp.int32)].add(vals)


@functools.partial(jax.jit, donate_argnums=(0,))
def _place_slab(big, sub, start):
    """In-place (donated) row-slab write — the shard buffer is never
    duplicated, so peak device memory stays one shard + one slab."""
    return jax.lax.dynamic_update_slice(big, sub, (start, 0))


@functools.lru_cache(maxsize=None)
def _zeros_builder(dev, rows: int, g: int, dtype):
    """Per-(device, shape) cached allocator for a shard's dense buffer —
    built once, not re-traced per shard in the staging loop."""
    return jax.jit(lambda: jnp.zeros((rows, g), dtype),
                   out_shardings=jax.sharding.SingleDeviceSharding(dev))


def _slab_bounds(start: int, stop: int, step: int | None = None):
    # read the module global at call time so tests can shrink the slab size
    step = DENSIFY_SLAB_ROWS if step is None else step
    for lo in range(start, stop, step):
        yield lo, min(lo + step, stop)


def _shard_slices(sharding, shape):
    """Ordered [(device, row_start, row_stop)] for this process's shards."""
    n = shape[0]
    out = []
    for dev, idx in sharding.addressable_devices_indices_map(shape).items():
        s = idx[0]
        out.append((dev, s.start or 0, s.stop if s.stop is not None else n))
    return out


def _interleave(per_dev_tasks):
    """Round-robin task order across devices: [d0s0, d1s0, ..., d0s1, ...]
    so transfers to different devices are in flight concurrently instead
    of draining one device's queue before the next starts."""
    out = []
    longest = max((len(t) for t in per_dev_tasks), default=0)
    for i in range(longest):
        for t in per_dev_tasks:
            if i < len(t):
                out.append(t[i])
    return out


class _ShardAssembler:
    """Per-device donated-buffer chain: collects committed slabs into one
    dense buffer per shard (single-slab shards skip the zeros+place)."""

    def __init__(self, dtype):
        self.dtype = np.dtype(dtype)
        self._big: dict = {}
        self._n_slabs: dict = {}

    def expect(self, dev, n_slabs: int):
        self._n_slabs[dev] = n_slabs

    def place(self, dev, sub, offset: int, rows: int, g: int):
        if self._n_slabs.get(dev, 2) == 1:
            self._big[dev] = sub
            return
        big = self._big.get(dev)
        if big is None:
            big = _zeros_builder(dev, rows, g, self.dtype)()
        self._big[dev] = _place_slab(big, sub, offset)

    def blocks(self, order):
        return [self._big[dev] for dev in order]


def _csr_transport(devices) -> str:
    """How a sparse matrix should cross to these devices.

    ``csr``: ship (values, col_indices, indptr) and scatter-densify on
    device — wire bytes scale with nnz (~10x less than dense at
    single-cell sparsity), the right trade whenever the wire is the wall
    (TPU/GPU, tunneled links). ``dense``: densify slab-by-slab ON HOST
    (scipy ``toarray``, still never the full matrix) and upload dense
    slabs — the right trade when the "wire" is a local memcpy (CPU
    backend), where XLA's element-wise scatter costs ~4x the memcpy it
    replaces (measured 8.8 s scatter vs 2.2 s host toarray at 300k x 2k,
    5% density). ``CNMF_TPU_STREAM_TRANSPORT`` forces either."""
    forced = _env_str(TRANSPORT_ENV, "").strip().lower()
    if forced in ("csr", "dense"):
        return forced
    return "dense" if all(d.platform == "cpu" for d in devices) else "csr"


def _stream_csr_sharded(X, sharding, dtype, stats: StreamStats | None = None,
                        events=None, liveness=None):
    """Stage a host CSR matrix as a dense sharded device array through the
    pipeline: slab prep (CSR slicing + pad buffers, or host slab densify —
    :func:`_csr_transport`) on the stream thread pool, transfers issued
    round-robin so every device's wire is busy concurrently, and the
    donated densify/place chain committed per shard in order. In-flight
    host memory is capped by the stream depth; slab nnz pads to
    power-of-two buckets (:func:`nnz_bucket`), so one skewed slab no
    longer inflates every slab's transfer to the global max."""
    t_wall = time.perf_counter()
    n, g = X.shape
    shards = _shard_slices(sharding, (n, g))
    col_dtype = np.int16 if g < 2 ** 15 else np.int32
    val_dtype = np.dtype(dtype)
    transport = _csr_transport([dev for dev, _, _ in shards])

    # host-densify slabs are (rows x g) dense — capped at _DENSE_SLAB_BYTES
    # (32 MB L3-resident sweet spot; see its definition) so the depth
    # window stays meaningful on small-RAM hosts
    step = None
    if transport == "dense":
        step = max(1, min(DENSIFY_SLAB_ROWS,
                          _DENSE_SLAB_BYTES // max(int(g) * val_dtype.itemsize,
                                            1)))

    per_dev = []
    max_slab_nnz = 1
    for dev, start, stop in shards:
        slabs = list(_slab_bounds(start, stop, step))
        per_dev.append([(dev, start, stop, lo, hi) for lo, hi in slabs])
        for lo, hi in slabs:
            max_slab_nnz = max(max_slab_nnz,
                               int(X.indptr[hi] - X.indptr[lo]))
    tasks = _interleave(per_dev)

    if transport == "dense":
        slab_bytes = (step or DENSIFY_SLAB_ROWS) * g * val_dtype.itemsize
    else:
        slab_bytes = max_slab_nnz * (val_dtype.itemsize
                                     + np.dtype(col_dtype).itemsize)
    threads = stream_threads()
    # two depth-sized buffer windows are alive at once here (prep pending
    # + commit drain), so each gets half the bytes budget
    depth = stream_depth(slab_bytes=slab_bytes, threads=threads, windows=2)
    pool = SlabBufferPool()
    asm = _ShardAssembler(val_dtype)
    for group in per_dev:
        if group:
            asm.expect(group[0][0], len(group))

    def prep_csr(task):
        dev, start, stop, lo, hi = task
        t0 = time.perf_counter()
        blk = X[lo:hi]
        pad = nnz_bucket(blk.nnz, max_slab_nnz)
        vb = pool.take((pad,), val_dtype)
        cb = pool.take((pad,), col_dtype)
        vals = SlabBufferPool.fill(vb, blk.data)
        cols = SlabBufferPool.fill(cb, blk.indices)
        indptr = blk.indptr.astype(np.int32)
        t1 = time.perf_counter()
        parts = (jax.device_put(vals, dev), jax.device_put(cols, dev),
                 jax.device_put(indptr, dev))
        # await the transfers IN THE WORKER — other workers/devices keep
        # streaming while this thread sits on the wire
        jax.block_until_ready(parts)
        t2 = time.perf_counter()
        if stats is not None:
            stats.add(host_prep_s=t1 - t0, h2d_s=t2 - t1, slabs=1,
                      nbytes=vals.nbytes + cols.nbytes + indptr.nbytes)
        return parts, (vb, cb)

    def prep_dense(task):
        dev, start, stop, lo, hi = task
        t0 = time.perf_counter()
        blk = X[lo:hi].toarray()
        if blk.dtype != val_dtype:
            blk = blk.astype(val_dtype)
        t1 = time.perf_counter()
        sub = jax.device_put(blk, dev)
        jax.block_until_ready(sub)
        t2 = time.perf_counter()
        if stats is not None:
            stats.add(host_prep_s=t1 - t0, h2d_s=t2 - t1, slabs=1,
                      nbytes=blk.nbytes)
        return sub, None

    # pooled buffers go back only once the on-device scatter has CONSUMED
    # the staged slab: a CPU backend may zero-copy device_put (the device
    # array aliases the host buffer), so reusing a buffer any earlier
    # corrupts in-flight slabs. Blocking per slab would serialize every
    # device's scatters, so releases ride a bounded window instead: up to
    # ``depth`` densifies stay in flight (scatters on different devices
    # overlap) and the oldest is awaited only when the window slides. The
    # same window bounds how many dispatched-but-unexecuted host slabs XLA
    # can keep alive on the dense transport.
    inflight: collections.deque = collections.deque()

    def _drain_one():
        sub, bufs = inflight.popleft()
        jax.block_until_ready(sub)
        if bufs is not None:
            for b in bufs:
                pool.give(b)

    def commit(task, payload):
        dev, start, stop, lo, hi = task
        staged, bufs = payload
        t0 = time.perf_counter()
        if bufs is None:
            sub = staged
        else:
            sub = _csr_densify(*staged, rows=int(hi - lo), g=int(g))
        inflight.append((sub, bufs))
        # ``>=`` so depth=1 is strictly serial (slab work awaited before
        # the next slab preps — the documented no-overlap fallback)
        if len(inflight) >= depth:
            _drain_one()
        asm.place(dev, sub, lo - start, stop - start, int(g))
        if stats is not None:
            stats.add(device_s=time.perf_counter() - t0)

    run_pipeline(tasks, prep_dense if transport == "dense" else prep_csr,
                 commit, depth=depth, threads=threads,
                 fault_context=f"stream_csr:{transport}", events=events,
                 liveness=liveness)

    t0 = time.perf_counter()
    while inflight:
        _drain_one()
    blocks = asm.blocks([dev for dev, _, _ in shards])
    jax.block_until_ready(blocks)
    out = jax.make_array_from_single_device_arrays((n, g), sharding, blocks)
    if stats is not None:
        stats.add(device_s=time.perf_counter() - t0)
        stats.wall_s += time.perf_counter() - t_wall
    return out


def _stream_dense_sharded(X, sharding, dtype,
                          stats: StreamStats | None = None, events=None,
                          liveness=None):
    """Dense host matrix -> sharded device array, slab-pipelined: workers
    make each slab contiguous at the target dtype (a no-op view when the
    input already is) and upload it; the caller chains donated slab
    placement per shard. Replaces the serial ``make_array_from_callback``
    walk, which uploaded one whole shard at a time on one thread."""
    t_wall = time.perf_counter()
    n, g = X.shape
    shards = _shard_slices(sharding, (n, g))
    np_dtype = np.dtype(dtype)

    per_dev = []
    for dev, start, stop in shards:
        per_dev.append([(dev, start, stop, lo, hi)
                        for lo, hi in _slab_bounds(start, stop)])
    tasks = _interleave(per_dev)

    slab_bytes = DENSIFY_SLAB_ROWS * g * np_dtype.itemsize
    threads = stream_threads()
    depth = stream_depth(slab_bytes=slab_bytes, threads=threads)
    asm = _ShardAssembler(np_dtype)
    for group in per_dev:
        if group:
            asm.expect(group[0][0], len(group))

    def prep(task):
        dev, start, stop, lo, hi = task
        t0 = time.perf_counter()
        blk = np.ascontiguousarray(np.asarray(X[lo:hi], dtype=np_dtype))
        t1 = time.perf_counter()
        sub = jax.device_put(blk, dev)
        jax.block_until_ready(sub)
        t2 = time.perf_counter()
        if stats is not None:
            stats.add(host_prep_s=t1 - t0, h2d_s=t2 - t1, nbytes=blk.nbytes,
                      slabs=1)
        return sub

    def commit(task, sub):
        dev, start, stop, lo, hi = task
        t0 = time.perf_counter()
        asm.place(dev, sub, lo - start, stop - start, int(g))
        if stats is not None:
            stats.add(device_s=time.perf_counter() - t0)

    run_pipeline(tasks, prep, commit, depth=depth, threads=threads,
                 fault_context="stream_dense", events=events,
                 liveness=liveness)

    t0 = time.perf_counter()
    blocks = asm.blocks([dev for dev, _, _ in shards])
    jax.block_until_ready(blocks)
    out = jax.make_array_from_single_device_arrays((n, g), sharding, blocks)
    if stats is not None:
        stats.add(device_s=time.perf_counter() - t0)
        stats.wall_s += time.perf_counter() - t_wall
    return out


def stream_store_sharded(cursor, sharding, dtype=jnp.float32, *,
                         stats: StreamStats | None = None, events=None,
                         liveness=None, pad_rows: int = 0):
    """Out-of-core ingestion (ISSUE 10): stage a shard-store row range
    straight from DISK into a dense sharded device array through the
    three-stage pipeline — slab reads on the single disk-producer thread,
    host prep (row slicing / densify) on the stream workers, transfers
    awaited in-worker, donated on-device assembly on the caller thread.
    The full matrix never exists in host RAM: in-flight host slab bytes
    are bounded by ``CNMF_TPU_OOC_BUDGET_BYTES`` (depth clamp; one slab
    is the irreducible floor), and the realized high-water mark lands in
    ``stats.host_peak_bytes`` so the bound is asserted, not assumed.

    ``cursor``: a :class:`~cnmf_torch_tpu.utils.shardstore.SlabCursor`
    (row-range view — each worker/host stages ONLY the slabs overlapping
    its own rows). ``pad_rows`` appends that many zero rows (mesh-multiple
    padding; they cost no disk reads — shard buffers start zeroed).
    Values are placed, never summed, so the assembled array is
    bit-identical to staging the in-memory matrix regardless of slab
    boundaries."""
    from ..utils.shardstore import ooc_budget_bytes
    from ..utils.storebackend import backend_counter_snapshot

    t_wall = time.perf_counter()
    store = cursor.store
    # remote-transport accounting: the pass's retries/hedges/cache hits
    # are the counter delta across the staging window
    bk_before = backend_counter_snapshot(store)
    base = cursor.rows[0]
    n_data = cursor.n_rows
    n_out = n_data + int(pad_rows)
    g = store.n_genes
    val_dtype = np.dtype(dtype)
    shards = _shard_slices(sharding, (n_out, g))
    transport = (_csr_transport([dev for dev, _, _ in shards])
                 if store.format == "csr" else "dense")

    segs = cursor.tasks()  # (slab_i, global_lo, global_hi)
    per_dev = []
    max_seg_rows = 1
    max_raw = 0
    empty_devs = []
    for dev, start, stop in shards:
        dev_tasks = []
        for (si, glo, ghi) in segs:
            olo = max(glo - base, start)
            ohi = min(ghi - base, stop)
            if ohi > olo:
                dev_tasks.append((dev, start, stop, olo, ohi, si))
                max_seg_rows = max(max_seg_rows, ohi - olo)
                max_raw = max(max_raw, int(store.slabs[si]["raw_bytes"]))
        if dev_tasks:
            per_dev.append(dev_tasks)
        else:
            empty_devs.append((dev, start, stop))
            per_dev.append([])
    tasks = _interleave(per_dev)

    prep_bytes = max_seg_rows * g * val_dtype.itemsize \
        if transport == "dense" else max_raw
    task_bytes = max(max_raw + prep_bytes, 1)
    threads = stream_threads()
    depth = stream_depth(slab_bytes=task_bytes, threads=threads, windows=2)
    # the OOC budget bounds the SUM of the three live windows (disk
    # read-ahead, prep/transfer, commit drain), so each gets a third
    depth = max(1, min(depth, ooc_budget_bytes() // (task_bytes * 3)))

    asm = _ShardAssembler(val_dtype)
    for group in per_dev:
        if not group:
            continue
        dev, start, stop = group[0][0], group[0][1], group[0][2]
        seg_rows = sum(t[4] - t[3] for t in group)
        # n_slabs=1 lets the assembler adopt a single sub as the whole
        # shard — only valid when that sub covers every row of the shard
        asm.expect(dev, len(group)
                   if seg_rows == stop - start and len(group) == 1 else
                   max(len(group), 2))
    residency = cursor.residency

    def source(task):
        dev, start, stop, olo, ohi, si = task
        t0 = time.perf_counter()
        raw = cursor.read(si)  # digest-verified; charges residency
        if stats is not None:
            stats.add(disk_s=time.perf_counter() - t0,
                      disk_nbytes=int(store.slabs[si]["raw_bytes"]))
        return raw

    def prep(task, raw):
        """Slice the slab to this shard's rows and upload. Returns
        ``(staged, densify_rows, release_cbs)`` — the release callbacks
        run only after the on-device consumer is done with the staged
        buffers (a CPU backend's device_put may zero-copy-alias host
        memory, so releasing earlier would lie to the accounting)."""
        dev, start, stop, olo, ohi, si = task
        rows = ohi - olo
        meta = store.slabs[si]
        a = (base + olo) - int(meta["row0"])
        b = a + rows
        t0 = time.perf_counter()
        if store.format == "csr":
            seg = raw[a:b]
            if transport == "dense":
                blk = seg.toarray()
                if blk.dtype != val_dtype:
                    blk = blk.astype(val_dtype)
                residency.charge(blk.nbytes)
                cursor.release(si)  # the dense copy replaces the raw slab
                t1 = time.perf_counter()
                sub = jax.device_put(blk, dev)
                jax.block_until_ready(sub)
                t2 = time.perf_counter()
                if stats is not None:
                    stats.add(host_prep_s=t1 - t0, h2d_s=t2 - t1, slabs=1,
                              nbytes=blk.nbytes)
                nb = blk.nbytes
                return (sub, None,
                        [lambda: residency.release(nb)])
            vals = np.ascontiguousarray(seg.data.astype(val_dtype,
                                                        copy=False))
            cols = seg.indices.astype(
                np.int16 if g < 2 ** 15 else np.int32, copy=False)
            indptr = seg.indptr.astype(np.int32, copy=False)
            t1 = time.perf_counter()
            parts = (jax.device_put(vals, dev), jax.device_put(cols, dev),
                     jax.device_put(indptr, dev))
            jax.block_until_ready(parts)
            t2 = time.perf_counter()
            if stats is not None:
                stats.add(host_prep_s=t1 - t0, h2d_s=t2 - t1, slabs=1,
                          nbytes=vals.nbytes + cols.nbytes + indptr.nbytes)
            return (parts, rows, [lambda: cursor.release(si)])
        blk = np.ascontiguousarray(np.asarray(raw[a:b], dtype=val_dtype))
        t1 = time.perf_counter()
        sub = jax.device_put(blk, dev)
        jax.block_until_ready(sub)
        t2 = time.perf_counter()
        if stats is not None:
            stats.add(host_prep_s=t1 - t0, h2d_s=t2 - t1, slabs=1,
                      nbytes=blk.nbytes)
        return (sub, None, [lambda: cursor.release(si)])

    inflight: collections.deque = collections.deque()

    def _drain_one():
        sub, cbs = inflight.popleft()
        jax.block_until_ready(sub)
        for cb in cbs:
            cb()

    def commit(task, payload):
        dev, start, stop, olo, ohi, si = task
        staged, densify_rows, cbs = payload
        t0 = time.perf_counter()
        if densify_rows is not None:
            sub = _csr_densify(*staged, rows=int(densify_rows), g=int(g))
        else:
            sub = staged
        inflight.append((sub, cbs))
        if len(inflight) >= depth:
            _drain_one()
        asm.place(dev, sub, olo - start, stop - start, int(g))
        if stats is not None:
            stats.add(device_s=time.perf_counter() - t0)

    run_pipeline(tasks, prep, commit, depth=depth, threads=threads,
                 fault_context="stream_store", events=events,
                 liveness=liveness, source=source)

    t0 = time.perf_counter()
    while inflight:
        _drain_one()
    for dev, start, stop in empty_devs:
        # shards made entirely of pad rows: all zeros, no disk reads
        asm._big[dev] = _zeros_builder(dev, stop - start, int(g),
                                       val_dtype)()
    blocks = asm.blocks([dev for dev, _, _ in shards])
    jax.block_until_ready(blocks)
    out = jax.make_array_from_single_device_arrays((n_out, g), sharding,
                                                   blocks)
    if stats is not None:
        stats.add(device_s=time.perf_counter() - t0)
        stats.wall_s += time.perf_counter() - t_wall
        stats.host_peak_bytes = max(stats.host_peak_bytes, residency.peak)
        stats.fold_store_counters(bk_before,
                                  backend_counter_snapshot(store))
    return out


def stream_to_device(X, device=None, dtype=jnp.float32,
                     stats: StreamStats | None = None, events=None):
    """Stage one host matrix (dense or scipy-sparse) to ONE device as a
    dense f32 array, through the pipeline: sparse inputs ship CSR slabs
    and densify on device (the full dense matrix never exists on host —
    the ``cNMF._stage_dense`` contract at atlas sparsity), dense inputs
    upload slab-wise with conversion off the caller thread."""
    # fault-injection hook (runtime/faults.py): an `upload` clause makes
    # this staging entry raise, exercising failed-transfer containment
    _maybe_fail_fault("upload", context="stream_to_device")
    if device is None:
        device = jax.local_devices()[0]
    sharding = jax.sharding.SingleDeviceSharding(device)
    if sp.issparse(X):
        return _stream_csr_sharded(X.tocsr(), sharding, dtype, stats=stats,
                                   events=events)
    X = np.asarray(X)
    return _stream_dense_sharded(X, sharding, dtype, stats=stats,
                                 events=events)


def stream_put_leaves(arrays, shardings, stats: StreamStats | None = None):
    """Issue one ``device_put`` per (host array, sharding) pair from the
    stream thread pool — transfers overlap instead of queueing behind one
    another (an EllMatrix is four leaves; the old path staged them one by
    one). Order-preserving; serial under depth<=1/threads=0."""
    arrays = list(arrays)
    if not isinstance(shardings, (list, tuple)):
        shardings = [shardings] * len(arrays)
    out = [None] * len(arrays)

    def prep(i):
        t0 = time.perf_counter()
        a = arrays[i]
        d = (jax.device_put(a) if shardings[i] is None
             else jax.device_put(a, shardings[i]))
        jax.block_until_ready(d)
        if stats is not None:
            nb = a.nbytes if hasattr(a, "nbytes") else 0
            stats.add(h2d_s=time.perf_counter() - t0, nbytes=nb, slabs=1)
        return d

    def commit(i, d):
        out[i] = d

    t_wall = time.perf_counter()
    run_pipeline(range(len(arrays)), prep, commit)
    if stats is not None:
        stats.wall_s += time.perf_counter() - t_wall
    return out
