"""Tier-1 observability smoke gate (scripts/verify_tier1.sh, ISSUE 18).

Drives the live observability plane end-to-end against REAL processes:

  * serve path — daemon via the CLI with metrics + tracing + SLO armed,
    concurrent tenants, a ``/metrics`` scrape mid-load that parses back
    (exposition round-trip), ``/stats`` reservoir-honesty fields, and at
    least one request traced CLIENT -> DAEMON across two processes
    (client.request / serve.http / serve.solve share a trace id with two
    distinct pid prefixes) rendering a ``cnmf-tpu trace`` waterfall;
  * SLO flip — a second daemon with a tight p99 target plus an injected
    ``straggler:context=serve`` fault reports ``degraded`` on
    ``/healthz`` (the generous-target phase must NOT);
  * batch path — ``run_pipeline`` with sampling on traces parent ->
    worker (``launcher.run`` -> ``factorize.worker`` across processes,
    linked by ``CNMF_TPU_TRACE_CTX``) and leaves schema-valid
    ``metrics_snapshot`` events;
  * hygiene — clean shutdowns, no orphaned sockets, no lingering
    cnmf-* threads in this process.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fail(msg: str) -> int:
    print("obs smoke: " + msg, file=sys.stderr)
    return 1


def _start_daemon(run_dir: str, sock: str, env: dict):
    from cnmf_torch_tpu.serving import ServeClient

    proc = subprocess.Popen(
        [sys.executable, "-m", "cnmf_torch_tpu", "serve", run_dir,
         "--socket", sock],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    cli = ServeClient(socket_path=sock, timeout=60.0)
    deadline = time.time() + 120
    while True:
        if proc.poll() is not None:
            raise RuntimeError("daemon exited early:\n"
                               + (proc.stdout.read() or ""))
        try:
            if cli.healthz().get("ok"):
                return proc, cli
        except Exception:
            pass
        if time.time() > deadline:
            proc.kill()
            raise RuntimeError("daemon never came up")
        time.sleep(0.25)


def _stop_daemon(proc, cli, sock: str):
    cli.shutdown()
    rc = proc.wait(timeout=60)
    out = proc.stdout.read() or ""
    if rc != 0:
        raise RuntimeError("daemon exit code %d:\n%s" % (rc, out))
    if os.path.exists(sock):
        raise RuntimeError("orphaned socket file after shutdown")


def main() -> int:
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.obs import metrics as obs_metrics
    from cnmf_torch_tpu.obs import tracing as obs_tracing
    from cnmf_torch_tpu.serving import ServeClient, ServeError
    from cnmf_torch_tpu.utils import save_df_to_npz
    from cnmf_torch_tpu.utils.telemetry import (EventLog, read_events,
                                                validate_events_file)

    workdir = tempfile.mkdtemp(prefix="obs_smoke_")
    proc = None
    try:
        # -- fixture run (obs knobs still off) -----------------------------
        rng = np.random.default_rng(8)
        usage = rng.dirichlet(np.ones(4) * 0.3, size=160)
        spectra = rng.gamma(0.3, 1.0, size=(4, 90)) * 40.0 / 90
        counts = rng.poisson(usage @ spectra * 260.0).astype(np.float64)
        counts[counts.sum(axis=1) == 0, 0] = 1.0
        df = pd.DataFrame(counts, index=[f"c{i}" for i in range(160)],
                          columns=[f"g{j}" for j in range(90)])
        counts_fn = os.path.join(workdir, "counts.df.npz")
        save_df_to_npz(df, counts_fn)

        obj = cNMF(output_dir=workdir, name="smoke")
        obj.prepare(counts_fn, components=[3], n_iter=6, seed=4,
                    num_highvar_genes=70)
        obj.factorize()
        obj.combine()
        obj.consensus(k=3, density_threshold=2.0, show_clustering=False)
        run_dir = os.path.join(workdir, "smoke")
        ev_path = os.path.join(run_dir, "cnmf_tmp", "smoke.events.jsonl")

        # the whole plane on, in THIS process (client spans) and every
        # child (daemon, launcher workers) via inherited env
        os.environ["CNMF_TPU_TELEMETRY"] = "1"
        os.environ["CNMF_TPU_METRICS"] = "1"
        os.environ["CNMF_TPU_TRACE_SAMPLE"] = "1"

        # -- phase A: serve path with generous SLO -------------------------
        sock = os.path.join(workdir, "serve.sock")
        env = dict(os.environ,
                   CNMF_TPU_SLO_P99_MS="30000",
                   CNMF_TPU_SERVE_LINGER_MS="100",
                   CNMF_TPU_SERVE_WARM_START="0")
        proc, cli = _start_daemon(run_dir, sock, env)
        client_events = EventLog(ev_path)  # client spans, same O_APPEND file

        queries = {f"tenant{i}": rng.gamma(
            1.0, 1.0, size=(12 + 9 * i, 70)).astype(np.float32)
            for i in range(4)}
        results: dict = {}

        def client(tenant, X):
            try:
                c = ServeClient(socket_path=sock, timeout=60.0,
                                events=client_events)
                results[tenant] = c.project(X, tenant=tenant)
            except ServeError as exc:
                results[tenant] = exc

        threads = [threading.Thread(target=client, args=(t, X))
                   for t, X in queries.items()]
        for t in threads:
            t.start()
        # mid-load /metrics scrape: must answer while requests are in
        # flight (the endpoint shares the daemon's accept loop)
        mid = ServeClient(socket_path=sock, timeout=60.0).metrics()
        for t in threads:
            t.join()
        bad = [t for t, r in results.items() if isinstance(r, Exception)]
        if bad:
            return _fail(f"clients failed: { {t: str(results[t]) for t in bad} }")
        if not mid.startswith("#") and "cnmf" not in mid:
            return _fail(f"mid-load scrape looks wrong: {mid[:200]!r}")

        scraped = obs_metrics.parse_exposition(cli.metrics())
        samples, types = scraped["samples"], scraped["types"]
        ok_reqs = sum(v for (name, labels), v in samples.items()
                      if name == "cnmf_serve_requests_total"
                      and ("status", "ok") in labels)
        if ok_reqs < len(queries):
            return _fail(f"scrape saw {ok_reqs} ok requests, expected "
                         f">= {len(queries)}")
        for needed, kind in (("cnmf_serve_request_ms", "histogram"),
                             ("cnmf_serve_solve_ms", "histogram"),
                             ("cnmf_serve_batches_total", "counter"),
                             ("cnmf_serve_queue_depth", "gauge"),
                             ("cnmf_serve_latency_samples_kept", "gauge"),
                             ("cnmf_slo_target_p99_ms", "gauge")):
            if types.get(needed) != kind:
                return _fail(f"scrape missing {kind} {needed}: "
                             f"{sorted(types)}")
        if samples[("cnmf_serve_request_ms_count", ())] < len(queries):
            return _fail("request histogram undercounts")

        stats = cli.stats()
        for key in ("latency_samples_kept", "latency_samples_dropped",
                    "latency_window_coverage"):
            if key not in stats:
                return _fail(f"/stats missing honesty field {key}")
        health = cli.healthz()
        if "slo" not in health or health.get("degraded"):
            return _fail(f"generous-SLO healthz wrong: {health}")
        if health["slo"]["burning"]:
            return _fail(f"generous SLO burning: {health['slo']}")
        _stop_daemon(proc, cli, sock)
        proc = None

        # -- phase A assertions: one request traced across two processes --
        validate_events_file(ev_path)
        evs = read_events(ev_path)
        spans = [e for e in evs if e["t"] == "span"]
        by_trace: dict = {}
        for e in spans:
            by_trace.setdefault(e["trace"], []).append(e)
        crossed = None
        for tid, tspans in by_trace.items():
            names = {e["name"] for e in tspans}
            pids = {e["span"].split(".")[0] for e in tspans}
            if ({"client.request", "serve.http", "serve.solve"} <= names
                    and len(pids) >= 2):
                crossed = tid
                break
        if crossed is None:
            return _fail("no trace covers client.request -> serve.http -> "
                         "serve.solve across two processes; traces: "
                         + json.dumps({t: sorted({e['name'] for e in s})
                                       for t, s in by_trace.items()}))
        snaps = [e for e in evs if e["t"] == "metrics_snapshot"]
        if not snaps:
            return _fail("daemon left no metrics_snapshot event")
        waterfall = subprocess.run(
            [sys.executable, "-m", "cnmf_torch_tpu", "trace", run_dir],
            env=dict(os.environ), capture_output=True, text=True,
            timeout=120)
        if waterfall.returncode != 0:
            return _fail("cnmf-tpu trace failed: " + waterfall.stderr)
        for needle in (crossed, "client.request", "serve.solve", "#"):
            if needle not in waterfall.stdout:
                return _fail(f"serve waterfall missing {needle!r}:\n"
                             + waterfall.stdout)

        # -- phase B: SLO verdict flips under an injected straggler --------
        sock_b = os.path.join(workdir, "serve_b.sock")
        env_b = dict(env, CNMF_TPU_SLO_P99_MS="10",
                     CNMF_TPU_FAULT_SPEC="straggler:context=serve,"
                                         "seconds=0.05")
        proc, cli = _start_daemon(run_dir, sock_b, env_b)
        for i in range(4):
            cli.project(queries["tenant0"], tenant="t")
        health_b = cli.healthz()
        if not (health_b.get("degraded")
                and health_b["slo"]["burning"]
                and health_b["slo"]["p99_ms"] > 10):
            return _fail(f"tight SLO + straggler not burning: {health_b}")
        _stop_daemon(proc, cli, sock_b)
        proc = None

        # -- phase C: launcher parent -> worker trace ----------------------
        from cnmf_torch_tpu.launcher import run_pipeline

        run_pipeline(counts_fn, workdir, "obsrun", components=[3],
                     n_iter=4, total_workers=2, seed=4, numgenes=70,
                     max_nmf_iter=150, k_selection=False)
        run_dir_c = os.path.join(workdir, "obsrun")
        ev_c = os.path.join(run_dir_c, "cnmf_tmp", "obsrun.events.jsonl")
        validate_events_file(ev_c)
        evs_c = read_events(ev_c)
        spans_c = [e for e in evs_c if e["t"] == "span"]
        roots = [e for e in spans_c if e["name"] == "launcher.run"]
        workers = [e for e in spans_c if e["name"] == "factorize.worker"]
        if not roots or not workers:
            return _fail("launcher trace incomplete: "
                         + str(sorted({e['name'] for e in spans_c})))
        root = roots[0]
        linked = [w for w in workers
                  if w["trace"] == root["trace"]
                  and w.get("parent") == root["span"]
                  and w["span"].split(".")[0]
                  != root["span"].split(".")[0]]
        if not linked:
            return _fail(f"no worker span parented on launcher.run across "
                         f"processes: root={root}, workers={workers}")
        if not [e for e in evs_c if e["t"] == "metrics_snapshot"]:
            return _fail("workers left no metrics_snapshot event")
        waterfall_c = subprocess.run(
            [sys.executable, "-m", "cnmf_torch_tpu", "trace", run_dir_c],
            env=dict(os.environ), capture_output=True, text=True,
            timeout=120)
        if waterfall_c.returncode != 0 or \
                "launcher.run" not in waterfall_c.stdout or \
                "factorize.worker" not in waterfall_c.stdout:
            return _fail("launcher waterfall wrong:\n" + waterfall_c.stdout
                         + waterfall_c.stderr)

        # -- hygiene: no lingering obs threads in this process -------------
        stragglers = [t.name for t in threading.enumerate()
                      if t.name.startswith("cnmf-")]
        if stragglers:
            return _fail(f"orphaned threads: {stragglers}")

        print("obs smoke: %d tenants served with mid-load /metrics scrape "
              "(%d series), trace %s spans client->daemon across 2 "
              "processes, SLO verdict flipped under injected straggler "
              "(p99 %.1f ms > 10 ms), launcher run traced parent->worker "
              "(%d worker span(s)), waterfalls rendered, clean shutdowns"
              % (len(queries), len(samples), crossed,
                 health_b["slo"]["p99_ms"], len(linked)))
        return 0
    except RuntimeError as exc:
        return _fail(str(exc))
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
