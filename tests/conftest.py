import os

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
# exercised without TPU hardware (SURVEY.md §4 "what the reference lacks").
# Two mechanisms, tried in order:
#   * XLA_FLAGS=--xla_force_host_platform_device_count=8 — set BEFORE jax
#     import (XLA reads it at CPU-backend init, so it also works when the
#     environment pre-imports jax at interpreter startup, as long as no
#     backend has been initialized yet);
#   * jax.config.update("jax_num_cpu_devices", 8) — the modern option,
#     unrecognized by older JAX releases (guarded: its absence is fine
#     because the XLA flag above already forces the device count).
# Override with CNMF_TEST_PLATFORM=tpu to run on hardware.
if os.environ.get("CNMF_TEST_PLATFORM", "cpu") == "cpu":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if os.environ.get("CNMF_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # older JAX: the XLA_FLAGS fallback above covers it

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import scipy.sparse as sp  # noqa: E402

from cnmf_torch_tpu.utils.envknobs import env_flag  # noqa: E402

# ---------------------------------------------------------------------------
# runtime sanitizers (ISSUE 7): CNMF_TPU_SANITIZE=1 wraps a designated
# tier-1 subset in jax.transfer_guard("disallow") + jax_debug_nans — an
# implicit host transfer or a NaN escaping a jitted solver then FAILS the
# test instead of silently costing a per-dispatch sync. Off by default:
# most tests legitimately pass numpy arrays across the dispatch boundary.
# tests/test_sanitize.py carries the always-on transfer-guard smoke for
# the solver hot paths regardless of this knob.
# ---------------------------------------------------------------------------

# the designated subset, two tiers by nodeid substring:
#   * sanitize      — full jax.transfer_guard("disallow") + debug-NaN.
#     These tests are written guard-clean: inputs staged via explicit
#     device_put, results fetched via device_get (tests/test_sanitize.py).
#   * sanitize_nans — debug-NaN only. The existing solver hot-path tests
#     legitimately hand numpy across the dispatch boundary (that IS the
#     boundary), so the transfer guard would flag their staging, not a
#     bug; a NaN escaping the jitted solve still fails hard.
SANITIZE_GUARD_SUBSET = (
    "test_sanitize.py",
    # the serving tier's batched projection dispatch (ISSUE 12): the
    # daemon's per-request device work is guard-clean end to end
    "test_serving.py::test_serve_program_no_implicit_transfers",
)
SANITIZE_NANS_SUBSET = (
    "test_nmf.py::test_vmapped_replicates_differ_and_converge",
    "test_nmf.py::test_bundled_batch_solver_matches_vmapped",
    "test_nmf.py::test_online_schedule_default_matches_tight_inner",
    "test_parallel.py::test_rowsharded_nmf_converges",
)


def pytest_collection_modifyitems(items):
    for item in items:
        if any(pat in item.nodeid for pat in SANITIZE_GUARD_SUBSET):
            item.add_marker(pytest.mark.sanitize)
        elif any(pat in item.nodeid for pat in SANITIZE_NANS_SUBSET):
            item.add_marker(pytest.mark.sanitize_nans)


@pytest.fixture(autouse=True)
def _sanitize_guard(request):
    """Opt-in sanitizer wrapper for the designated subset (see above)."""
    guarded = request.node.get_closest_marker("sanitize") is not None
    nans = guarded or \
        request.node.get_closest_marker("sanitize_nans") is not None
    if not nans or not env_flag("CNMF_TPU_SANITIZE", False):
        yield
        return
    # debug_nans via config.update (the context-manager spelling is not
    # stable across jax releases); the transfer guard has one
    prev_nans = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        if guarded:
            with jax.transfer_guard("disallow"):
                yield
        else:
            yield
    finally:
        jax.config.update("jax_debug_nans", prev_nans)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture()
def counts_100x500():
    """The reference's synthetic smoke fixture: binomial counts with seed 42
    (test_prepare.py:10-14)."""
    np.random.seed(42)
    return np.random.binomial(100, 0.01, size=(100, 500)).astype(np.float64)


@pytest.fixture()
def sparse_counts_100x500(counts_100x500):
    return sp.csr_matrix(counts_100x500)
