"""Principal component analysis on device.

Replaces the reference's ``sc.pp.pca`` call in the batch-correction path
(``/root/reference/src/cnmf/preprocess.py:250-338``). The factorization is
computed from the smaller gram matrix (g x g or n x n, whichever is
smaller) with one MXU matmul + ``eigh`` rather than ``jnp.linalg.svd`` of
the rectangular matrix: TPU's iterative SVD on an 8.5k x 2k input takes
minutes, the gram path milliseconds (squared condition number is harmless
for the leading components PCA keeps). Signs are fixed to scanpy/sklearn's
``svd_flip`` convention (largest-|loading| positive per component) so
downstream Harmony runs see the same basis orientation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

__all__ = ["pca"]

_HI = jax.lax.Precision.HIGHEST


@functools.partial(jax.jit, static_argnames=("n_comps", "zero_center"))
def _pca_jit(X, n_comps: int, zero_center: bool):
    n, g = X.shape
    if zero_center:
        X = X - jnp.mean(X, axis=0, keepdims=True)
    if g <= n:
        G = jnp.matmul(X.T, X, precision=_HI)              # (g, g)
        evals, evecs = jnp.linalg.eigh(G)                  # ascending
        S = jnp.sqrt(jnp.clip(evals[::-1][:n_comps], 0.0))
        V = evecs[:, ::-1][:, :n_comps]                    # (g, k)
        Vt = V.T
        X_pca = jnp.matmul(X, V, precision=_HI)            # = U * S
    else:
        G = jnp.matmul(X, X.T, precision=_HI)              # (n, n)
        evals, evecs = jnp.linalg.eigh(G)
        S = jnp.sqrt(jnp.clip(evals[::-1][:n_comps], 0.0))
        U = evecs[:, ::-1][:, :n_comps]                    # (n, k)
        # rank-overflow guard (cf. ops/nmf.py:gram_svd_base): S ~ 0 columns
        # would divide fp32 noise by EPS
        ok = S > 1e-6 * jnp.maximum(S[0], 1e-30)
        Vt = jnp.where(ok[:, None],
                       jnp.matmul(U.T, X, precision=_HI)
                       / jnp.maximum(S, 1e-30)[:, None], 0.0)
        X_pca = U * S[None, :]
    # svd_flip: orient each component so its largest-|value| loading is
    # positive (removes the sign ambiguity; matches sklearn/scanpy)
    max_idx = jnp.argmax(jnp.abs(Vt), axis=1)
    signs = jnp.sign(Vt[jnp.arange(n_comps), max_idx])
    Vt = Vt * signs[:, None]
    X_pca = X_pca * signs[None, :]
    explained_var = (S ** 2) / jnp.maximum(n - 1, 1)
    return X_pca, Vt, explained_var


def pca(X, n_comps: int = 50, zero_center: bool = True):
    """Returns ``(X_pca (n, n_comps), components (n_comps, g),
    explained_variance_ratio (n_comps,))`` as numpy arrays."""
    if sp.issparse(X):
        X = X.toarray()
    X = np.asarray(X, dtype=np.float32)
    n_comps = int(min(n_comps, min(X.shape) - 1 if zero_center else min(X.shape)))
    X_pca, Vt, ev = _pca_jit(jnp.asarray(X), n_comps, bool(zero_center))
    if zero_center:
        total_var = float(np.var(X, axis=0, ddof=1).sum())
    else:
        # uncentered SVD energy includes the mean component, so the ratio
        # denominator must be the uncentered second moment or ratios blow
        # past 1 for data with a large mean offset
        total_var = float((np.asarray(X, np.float64) ** 2).sum()
                          / max(X.shape[0] - 1, 1))
    ratio = np.asarray(ev, dtype=np.float64) / max(total_var, 1e-30)
    return np.asarray(X_pca), np.asarray(Vt), ratio
