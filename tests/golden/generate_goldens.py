"""Regenerate the golden artifacts for test_reproducibility.py.

Mirrors the reference's golden design (``/root/reference/tests/
test_reproducibility.py`` + ``Extras/prepare_unittest_*.ipynb``): the
stochastic factorize stage is NOT under golden test — a fixed
merged-spectra fixture is generated once from seeded replicate runs, and
the deterministic stages around it (prepare artifacts, consensus math) are
snapshotted for RMS < 1e-4 comparison. The reference fetches its goldens
from GCS (``download_pytest_data.py``); this environment has no egress, so
goldens are generated locally by this script and committed.

Run from the repo root:  python tests/golden/generate_goldens.py
Goldens land in tests/golden/data/ — regenerate ONLY when an intentional
numeric-contract change is made, and say so in the commit message.
"""

import os
import shutil
import sys
import tempfile

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax  # noqa: E402

# goldens are defined on the CPU backend — the same backend the test suite
# runs on (conftest.py); fp32 TPU drift is absorbed by the RMS tolerance
jax.config.update("jax_platforms", "cpu")

from cnmf_torch_tpu import cNMF  # noqa: E402
from cnmf_torch_tpu.utils import save_df_to_npz  # noqa: E402

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "data")
N, G, K_TRUE = 90, 180, 4
KS = [4, 5]
N_ITER = 6
SEED = 14
NUM_HVG = 120
CONSENSUS = [(4, 0.5), (4, 2.0)]


def make_counts() -> pd.DataFrame:
    rng = np.random.default_rng(123)
    usage = rng.dirichlet(np.ones(K_TRUE) * 0.3, size=N)
    spectra = rng.gamma(0.3, 1.0, size=(K_TRUE, G)) * 50.0 / G
    counts = rng.poisson(usage @ spectra * 250.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    return pd.DataFrame(counts, index=[f"cell{i}" for i in range(N)],
                        columns=[f"gene{j}" for j in range(G)])


def main():
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    workdir = tempfile.mkdtemp(prefix="golden_gen_")

    counts_fn = os.path.join(GOLDEN_DIR, "counts.df.npz")
    save_df_to_npz(make_counts(), counts_fn)

    obj = cNMF(output_dir=workdir, name="golden")
    obj.prepare(counts_fn, components=KS, n_iter=N_ITER, seed=SEED,
                num_highvar_genes=NUM_HVG, batch_size=64, max_NMF_iter=200)
    obj.factorize()
    obj.combine()
    for k, dt in CONSENSUS:
        obj.consensus(k, density_threshold=dt, show_clustering=False,
                      build_ref=True)
    obj.k_selection_plot(close_fig=True)

    keep = [
        ("nmf_replicate_parameters", ()),
        ("nmf_run_parameters", ()),
        ("nmf_genes_list", ()),
        ("tpm_stats", ()),
        ("k_selection_stats", ()),
    ]
    keep += [("merged_spectra", (k,)) for k in KS]
    for k, dt in CONSENSUS:
        dtr = str(dt).replace(".", "_")
        keep += [(key, (k, dtr)) for key in
                 ["consensus_spectra", "consensus_usages",
                  "gene_spectra_score", "gene_spectra_tpm",
                  "starcat_spectra"]]

    for key, fmt in keep:
        src = obj.paths[key] % fmt if fmt else obj.paths[key]
        dst = os.path.join(GOLDEN_DIR, os.path.basename(src))
        shutil.copyfile(src, dst)
        print("golden:", os.path.basename(src))
    shutil.rmtree(workdir)


if __name__ == "__main__":
    main()
