"""True 2-D (cells x genes) grid with compute-overlapped collectives
(ISSUE 13, ``parallel/grid2d.py``) — parity with the 1-D rowshard path
at 4 and 8 simulated devices (2x2, 2x4, 4x2 grids), overlap on/off
bit-identity, ragged gene shards, store-backed staging, degraded-mesh
re-planning on the grid, and the slab-looped consensus refit's
bit-identity contract (``ops.nmf.fit_h_slabbed``)."""

import os

import numpy as np
import pandas as pd
import pytest
import jax
from jax.sharding import Mesh

from cnmf_torch_tpu.models.cnmf import cNMF
from cnmf_torch_tpu.ops.nmf import fit_h, fit_h_slabbed
from cnmf_torch_tpu.ops.recipe import resolve_recipe
from cnmf_torch_tpu.parallel.grid2d import (
    _grid_rc,
    grid_blocks,
    measure_collectives,
    mesh_grid2d,
    nmf_fit_grid2d,
    stage_x_grid,
)
from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded
from cnmf_torch_tpu.runtime import elastic, faults
from cnmf_torch_tpu.utils import save_df_to_npz
from cnmf_torch_tpu.utils.io import load_df_from_npz

pytestmark = pytest.mark.skipif(
    len(jax.devices()) != 8,
    reason="grid tests assume the 8-device simulated mesh (conftest)")


def _fixture(n=96, g=48, seed=0):
    rng = np.random.default_rng(seed)
    return rng.gamma(2.0, 1.0, size=(n, g)).astype(np.float32)


# ---------------------------------------------------------------------------
# mesh planning
# ---------------------------------------------------------------------------

def test_grid_rc_single_host_cells_biased():
    # most-square with cells taking the larger factor
    assert _grid_rc(8, 1) == (4, 2)
    assert _grid_rc(4, 1) == (2, 2)
    assert _grid_rc(6, 1) == (3, 2)
    assert _grid_rc(1, 1) == (1, 1)
    # multi-host: cells across hosts, genes within
    assert _grid_rc(8, 2) == (2, 4)


def test_grid_shape_knob(monkeypatch):
    monkeypatch.setenv("CNMF_TPU_GRID_SHAPE", "2x4")
    mesh = mesh_grid2d()
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("cells", "genes")
    monkeypatch.setenv("CNMF_TPU_GRID_SHAPE", "3x2")
    with pytest.raises(ValueError, match="devices"):
        mesh_grid2d()
    monkeypatch.setenv("CNMF_TPU_GRID_SHAPE", "bogus")
    with pytest.raises(ValueError, match="CxG"):
        mesh_grid2d()


def test_mesh_grid2d_explicit_and_invalid():
    assert mesh_grid2d(cell_shards=4).devices.shape == (4, 2)
    assert mesh_grid2d(gene_shards=4).devices.shape == (2, 4)
    with pytest.raises(ValueError, match="tile"):
        mesh_grid2d(cell_shards=3)


def test_grid_blocks_clamps_to_divisor(monkeypatch):
    assert grid_blocks(128) == 4
    assert grid_blocks(30) == 1          # < 64: no blocking by default
    monkeypatch.setenv("CNMF_TPU_GRID_BLOCKS", "4")
    assert grid_blocks(30) == 3          # clamped to a divisor
    assert grid_blocks(128) == 4
    monkeypatch.setenv("CNMF_TPU_GRID_BLOCKS", "1")
    assert grid_blocks(128) == 1


# ---------------------------------------------------------------------------
# staging
# ---------------------------------------------------------------------------

def test_stage_x_grid_dense_csr_store_identical(tmp_path):
    import scipy.sparse as sp

    from cnmf_torch_tpu.utils import shardstore

    X = _fixture(40, 20)
    X[X < 1.0] = 0.0  # sparsify
    mesh = mesh_grid2d(cell_shards=4, gene_shards=2)
    Xd_dense, rp, cp = stage_x_grid(X, mesh)
    assert (rp, cp) == (0, 0)
    np.testing.assert_array_equal(np.asarray(Xd_dense), X)

    Xd_csr, _, _ = stage_x_grid(sp.csr_matrix(X), mesh)
    np.testing.assert_array_equal(np.asarray(Xd_csr), X)

    store_dir = str(tmp_path / "store")
    shardstore.write_shard_store(store_dir, sp.csr_matrix(X), slab_rows=16)
    store = shardstore.open_shard_store(store_dir)
    Xd_store, _, _ = stage_x_grid(store, mesh)
    np.testing.assert_array_equal(np.asarray(Xd_store), X)


def test_stage_x_grid_ragged_pads_zero():
    X = _fixture(42, 19)  # ragged on both axes for a 4x2 grid
    mesh = mesh_grid2d(cell_shards=4, gene_shards=2)
    Xd, rp, cp = stage_x_grid(X, mesh)
    assert (rp, cp) == (2, 1)
    full = np.asarray(Xd)
    np.testing.assert_array_equal(full[:42, :19], X)
    assert (full[42:] == 0).all() and (full[:, 19:] == 0).all()


# ---------------------------------------------------------------------------
# solver parity vs the 1-D rowshard path
# ---------------------------------------------------------------------------

def _parity(X, k, grid_mesh, beta_loss, n_passes=12, seed=5, **kw):
    mesh1 = Mesh(np.asarray(jax.devices()), ("cells",))
    H1, W1, e1 = nmf_fit_rowsharded(X, k, mesh1, beta_loss=beta_loss,
                                    seed=seed, n_passes=n_passes, **kw)
    H2, W2, e2 = nmf_fit_grid2d(X, k, grid_mesh, beta_loss=beta_loss,
                                seed=seed, n_passes=n_passes, **kw)
    return (H1, W1, e1), (H2, W2, e2)


@pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
@pytest.mark.parametrize("beta_loss", ["frobenius", "kullback-leibler"])
def test_grid_parity_8dev(shape, beta_loss):
    """(cells x genes) factorize matches the 1-D rowshard path at the
    same seed to collective-reduction tolerance (the gene axis splits
    contractions the 1-D path runs whole): matched objectives, same
    shapes, finite nonnegative spectra."""
    X = _fixture()
    mesh = mesh_grid2d(cell_shards=shape[0], gene_shards=shape[1])
    (H1, W1, e1), (H2, W2, e2) = _parity(X, 3, mesh, beta_loss)
    assert W2.shape == W1.shape and H2.shape == H1.shape
    assert np.isfinite(W2).all() and (W2 >= 0).all()
    assert abs(e1 - e2) / abs(e1) < 5e-3
    # spectra match component-for-component (same init, same pass
    # structure — only reduction grouping differs)
    for r in range(3):
        a, b = W1[r], W2[r]
        cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)))
        assert cos > 0.999


@pytest.mark.parametrize("beta_loss", ["frobenius", "itakura-saito"])
def test_grid_parity_4dev_2x2(beta_loss):
    X = _fixture()
    mesh = mesh_grid2d(cell_shards=2, gene_shards=2,
                       devices=jax.devices()[:4])
    mesh1 = Mesh(np.asarray(jax.devices()[:4]), ("cells",))
    H1, W1, e1 = nmf_fit_rowsharded(X, 3, mesh1, beta_loss=beta_loss,
                                    seed=5, n_passes=8)
    H2, W2, e2 = nmf_fit_grid2d(X, 3, mesh, beta_loss=beta_loss,
                                seed=5, n_passes=8)
    assert abs(e1 - e2) / abs(e1) < 5e-3


def test_grid_trivial_gene_axis_bit_identical_to_rowshard():
    """An 8x1 grid has a trivial gene axis and (at this width, unblocked
    statistics) reduces exactly like the 1-D mesh — pinning the shared
    convergence arithmetic bit-for-bit."""
    X = _fixture()
    mesh = mesh_grid2d(cell_shards=8, gene_shards=1)
    (H1, W1, e1), (H2, W2, e2) = _parity(X, 3, mesh, "frobenius")
    np.testing.assert_array_equal(W1, W2)
    np.testing.assert_array_equal(H1, H2)
    assert e1 == e2


def test_grid_ragged_gene_shards():
    """Gene count not divisible by the gene axis: padded columns are
    masked to exact zero in W and trimmed on return; the solve lands in
    the 1-D path's objective band. (The band is wider than the aligned
    cases: the init draw happens at the padded width, so the ragged
    grid runs a DIFFERENT random init than the 1-D path — statistically
    equivalent, not trajectory-matched.)"""
    X = _fixture(96, 49)
    mesh = mesh_grid2d(cell_shards=4, gene_shards=2)
    (H1, W1, e1), (H2, W2, e2) = _parity(X, 3, mesh, "frobenius")
    assert W2.shape == (3, 49)
    assert np.isfinite(W2).all() and (W2 >= 0).all()
    assert abs(e1 - e2) / abs(e1) < 2e-2


def test_grid_overlap_toggle_bit_identical(monkeypatch):
    """CNMF_TPU_GRID_OVERLAP=0 serializes each block's reduce before the
    next gemm — same partials, same order, so results are BIT-identical
    to the overlapped dispatch (blocking engaged: local tiles >= 64)."""
    X = _fixture(256, 256, seed=2)
    mesh = mesh_grid2d(cell_shards=2, gene_shards=4)
    assert grid_blocks(256 // 4) == 4  # blocking really engaged
    H_a, W_a, e_a = nmf_fit_grid2d(X, 4, mesh, seed=7, n_passes=6)
    monkeypatch.setenv("CNMF_TPU_GRID_OVERLAP", "0")
    H_b, W_b, e_b = nmf_fit_grid2d(X, 4, mesh, seed=7, n_passes=6)
    np.testing.assert_array_equal(W_a, W_b)
    np.testing.assert_array_equal(H_a, H_b)
    assert e_a == e_b


def test_grid_kl_newton_recipe():
    """The Diagonalized-Newton KL lane runs on the grid and lands near
    the 1-D dna solve; a dna recipe on a non-KL grid solve raises."""
    X = _fixture(128, 64, seed=3)
    rec = resolve_recipe(1.0, "rowshard", accel="1", kl_newton=True,
                         n=128, g=64, k=3)
    assert rec.kl_newton
    mesh = mesh_grid2d(cell_shards=4, gene_shards=2)
    mesh1 = Mesh(np.asarray(jax.devices()), ("cells",))
    H1, W1, e1 = nmf_fit_rowsharded(X, 3, mesh1, "kullback-leibler",
                                    seed=5, n_passes=6, recipe=rec)
    H2, W2, e2 = nmf_fit_grid2d(X, 3, mesh, "kullback-leibler",
                                seed=5, n_passes=6, recipe=rec)
    assert abs(e1 - e2) / abs(e1) < 5e-3
    with pytest.raises(ValueError, match="beta=1"):
        nmf_fit_grid2d(X, 3, mesh, "frobenius", recipe=rec)


def test_grid_rejects_sketch_and_nonrandom_init():
    X = _fixture(64, 32)
    mesh = mesh_grid2d(cell_shards=4, gene_shards=2)
    sk = resolve_recipe(1.0, "rowshard", sketch="1", n=64, g=32, k=3)
    with pytest.raises(ValueError, match="sketch"):
        nmf_fit_grid2d(X, 3, mesh, "kullback-leibler", recipe=sk)
    with pytest.raises(ValueError, match="init"):
        nmf_fit_grid2d(X, 3, mesh, init="nndsvd")


def test_measure_collectives_reports():
    X = _fixture(512, 256, seed=4)
    mesh = mesh_grid2d(cell_shards=4, gene_shards=2)
    Xd, _, _ = stage_x_grid(X, mesh)
    probe = measure_collectives(Xd, 4, mesh, beta=2.0, repeats=3)
    for key in ("coll_chained_s", "coll_free_s", "overlap_fraction",
                "pass_overlap_s", "pass_serial_s",
                "pass_hidden_fraction", "nbytes_per_pass"):
        assert key in probe
    assert probe["coll_chained_s"] > 0 and probe["nbytes_per_pass"] > 0
    assert 0.0 <= probe["overlap_fraction"] <= 1.0


# ---------------------------------------------------------------------------
# degraded-mesh re-planning on the grid
# ---------------------------------------------------------------------------

def test_plan_degraded_mesh_grid_axes():
    mesh = mesh_grid2d(cell_shards=4, gene_shards=2)
    lost = list(mesh.devices.flat)[-2:]
    new = elastic.plan_degraded_mesh(mesh, lost)
    assert new.axis_names == ("cells", "genes")
    assert int(np.prod(new.devices.shape)) == 6
    assert new.devices.shape == (3, 2)


def _prepare_mini(tmp_path, name, n_iter=2):
    counts = np.random.default_rng(5).binomial(
        40, 0.02, size=(60, 100)).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    counts_fn = str(tmp_path / f"{name}_counts.df.npz")
    save_df_to_npz(df, counts_fn)
    obj = cNMF(output_dir=str(tmp_path), name=name)
    obj.prepare(counts_fn, components=[3], n_iter=n_iter, seed=4,
                num_highvar_genes=50, batch_size=64, max_NMF_iter=50)
    return obj


def test_factorize_grid2d_pipeline(tmp_path):
    """factorize(mesh_shape='grid2d') produces the standard artifact
    contract, grid provenance, and consensus runs downstream."""
    obj = _prepare_mini(tmp_path, "g2d", n_iter=4)
    obj.factorize(mesh_shape="grid2d")
    for it in range(4):
        assert os.path.exists(obj.paths["iter_spectra"] % (3, it))
    obj.combine()
    obj.consensus(3, density_threshold=2.0, show_clustering=False,
                  build_ref=False)
    assert os.path.exists(obj.paths["consensus_spectra"] % (3, "2_0"))
    import yaml

    prov = yaml.safe_load(open(obj.paths["factorize_provenance"] % 0))
    assert prov["engaged_path"] == "grid2d"
    assert prov["effective_params"]["mesh_shape"] == [4, 2]
    assert "overlap" in prov["effective_params"]


def test_factorize_grid2d_hostloss_remesh(tmp_path, monkeypatch):
    """A device loss at a grid pass boundary re-plans the (cells x
    genes) grid over the survivors, re-stages, and completes from the
    pass checkpoint — remesh + host_loss on the telemetry record."""
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    obj = _prepare_mini(tmp_path, "g2dloss")
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "hostloss:context=pass,after=1,count=2")
    with pytest.warns(RuntimeWarning, match="continuing degraded"):
        obj.factorize(mesh_shape="grid2d")
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)
    for it in range(2):
        spec = load_df_from_npz(obj.paths["iter_spectra"] % (3, it)).values
        assert np.isfinite(spec).all() and (spec >= 0).all()
    ev_path = os.path.join(str(tmp_path), "g2dloss", "cnmf_tmp",
                           "g2dloss.events.jsonl")
    validate_events_file(ev_path)
    evs = list(read_events(ev_path))
    kinds = [e["kind"] for e in evs if e["t"] == "fault"]
    assert "host_loss" in kinds and "remesh" in kinds
    remesh = next(e for e in evs if e["t"] == "fault"
                  and e["kind"] == "remesh")
    assert remesh["context"]["from_devices"] == 8
    assert remesh["context"]["to_devices"] == 6
    # the grid provenance + collective events survive the re-mesh
    assert any(e["t"] == "collective" for e in evs)


# ---------------------------------------------------------------------------
# slab-looped consensus refit (ops.nmf.fit_h_slabbed)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("beta", [2.0, 1.0])
def test_fit_h_slabbed_bit_identical(beta):
    """Chunk-aligned slab blocks reproduce the resident fit_h refit
    BIT-for-bit (same init stream, same chunk partition)."""
    rng = np.random.default_rng(7)
    n, g, k, chunk = 210, 40, 3, 32
    X = rng.gamma(2.0, 1.0, size=(n, g)).astype(np.float32)
    W = rng.gamma(1.0, 1.0, size=(k, g)).astype(np.float32) + 0.1
    H_res = fit_h(X, W, chunk_size=chunk, beta=beta)

    def blocks(rows_per):
        for lo in range(0, n, rows_per):
            hi = min(lo + rows_per, n)
            yield lo, hi, X[lo:hi]

    # one chunk per block AND several chunks per block (ragged tail)
    for rows_per in (chunk, 3 * chunk):
        H_slab = fit_h_slabbed(blocks(rows_per), n, W, chunk_size=chunk,
                               beta=beta)
        np.testing.assert_array_equal(H_res, H_slab)


def test_fit_h_slabbed_rejects_misaligned_blocks():
    X = np.ones((64, 8), np.float32)
    W = np.ones((2, 8), np.float32)
    with pytest.raises(ValueError, match="chunk"):
        fit_h_slabbed([(0, 30, X[:30]), (30, 64, X[30:])], 64, W,
                      chunk_size=32)


def test_fit_h_slabbed_collect_hook():
    rng = np.random.default_rng(1)
    X = rng.gamma(2.0, 1.0, size=(64, 8)).astype(np.float32)
    W = rng.random((2, 8)).astype(np.float32) + 0.1
    seen = []
    H = fit_h_slabbed([(0, 32, X[:32]), (32, 64, X[32:])], 64, W,
                      chunk_size=32,
                      collect=lambda lo, hi, xb, hb: seen.append(
                          (lo, hi, xb.shape, hb.shape)))
    assert seen == [(0, 32, (32, 8), (32, 2)),
                    (32, 64, (32, 8), (32, 2))]
    assert H.shape == (64, 2)
