"""Perf-regression observatory (ISSUE 19).

One schema for machine-readable bench results, shared by ``bench.py
--json-out`` and the snapshot store (the ad-hoc per-tier dict shapes
stay available under each tier's ``raw`` key, but every number the
regression machinery compares goes through :func:`extract_metrics`
into typed ``{value, unit, direction}`` entries). Snapshots are
schema-versioned and keyed by the autotune device fingerprint
(:func:`~cnmf_torch_tpu.utils.autotune.device_fingerprint`) — a
baseline from different hardware is loudly incomparable, never
silently diffed as a regression.

Comparison (:func:`diff_snapshots`) is noise-aware for this
oversubscribed-container reality: wall-type metrics compare min-of-N
when samples are recorded (min is the low-noise estimator of the true
cost under scheduler interference), every metric carries a relative
band before it can go red, perf-exempt tiers (interpret mode, nominal
CPU peaks) render but never gate, and an improvement is reported —
not celebrated into the regression count.

Consumers: ``cnmf-tpu benchdiff <a> <b>`` and scripts/perf_gate.py
(the verify_tier1.sh lane).
"""

from __future__ import annotations

import json
import math
import os

__all__ = ["BENCH_SCHEMA", "BENCH_SCHEMA_VERSION", "build_snapshot",
           "validate_bench", "extract_metrics", "save_snapshot",
           "load_snapshot", "diff_snapshots", "render_diff",
           "GATE_BAND_ENV", "GATE_N_ENV", "DEFAULT_BAND", "DEFAULT_N",
           "gate_band", "gate_n"]

BENCH_SCHEMA = "cnmf-bench"
BENCH_SCHEMA_VERSION = 1

GATE_BAND_ENV = "CNMF_TPU_PERF_GATE_BAND"
GATE_N_ENV = "CNMF_TPU_PERF_GATE_N"

# relative band a comparable metric must move past before the diff
# calls it: generous by default because the tier-1 gate runs on a
# 2-core oversubscribed container where honest walls wobble ±30%;
# calm dedicated hardware can tighten it via CNMF_TPU_PERF_GATE_BAND
DEFAULT_BAND = 0.6
DEFAULT_N = 3


def gate_band() -> float:
    """Relative regression band (CNMF_TPU_PERF_GATE_BAND, default 0.6)."""
    from ..utils.envknobs import env_float

    return float(env_float(GATE_BAND_ENV, DEFAULT_BAND))


def gate_n() -> int:
    """Min-of-N sample count for gate walls (CNMF_TPU_PERF_GATE_N)."""
    from ..utils.envknobs import env_int

    return max(1, int(env_int(GATE_N_ENV, DEFAULT_N)))


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

_LOWER_HINTS = ("seconds", "wall", "_ms", "_s", "latency", "overhead",
                "p50", "p95", "p99", "compile")
_HIGHER_HINTS = ("mfu", "flops", "gb_per_s", "qps", "throughput",
                 "overlap_fraction", "speedup", "per_second")
_SKIP_HINTS = ("vs_baseline",
               # counts are occupancy, not cost: histogram buckets shift
               # with scheduler noise and `.count`/samples_* track request
               # volume — gating on them red-flags honest jitter
               "histogram", ".count", "_count", "samples_kept",
               "samples_dropped")


def _direction(name: str) -> str | None:
    low = name.lower()
    for h in _SKIP_HINTS:
        if h in low:
            return None
    for h in _HIGHER_HINTS:
        if h in low:
            return "higher"
    for h in _LOWER_HINTS:
        if h in low:
            return "lower"
    return None


def extract_metrics(raw, prefix: str = "") -> dict:
    """Walk one tier's ad-hoc result dict and lift every comparable
    numeric leaf into a typed metric: ``{value, unit, direction}`` with
    dotted-path names. Only leaves whose name declares a direction
    (wall/latency-like => lower is better, MFU/throughput-like =>
    higher) are lifted — shape/config integers never become gate
    metrics. Deterministic: same raw dict, same metric set."""
    out: dict = {}
    if not isinstance(raw, dict):
        return out
    for key in sorted(raw):
        val = raw[key]
        name = f"{prefix}{key}"
        if isinstance(val, bool):
            continue
        if isinstance(val, dict):
            out.update(extract_metrics(val, prefix=f"{name}."))
            continue
        if not isinstance(val, (int, float)) or not math.isfinite(val):
            continue
        direction = _direction(name)
        if direction is None:
            continue
        low = name.lower()
        unit = ("s" if ("seconds" in low or low.endswith("_s")
                        or "wall" in low) else
                "ms" if "_ms" in low or low.endswith("ms") else
                "frac" if "mfu" in low or "fraction" in low else "")
        out[name] = {"value": float(val), "unit": unit,
                     "direction": direction}
    return out


def build_snapshot(tiers: dict, *, fingerprint: str, created: float,
                   label: str | None = None) -> dict:
    """Wrap raw per-tier bench results into a schema-versioned
    snapshot. Each tier entry keeps the full ad-hoc payload under
    ``raw`` and gains the typed ``metrics`` the diff machinery
    compares; a tier whose raw result carries ``perf_exempt`` (or an
    ``error``) is marked so and never gates."""
    tdocs = {}
    for tier, raw in (tiers or {}).items():
        raw = raw if isinstance(raw, dict) else {"value": raw}
        tdocs[str(tier)] = {
            "metrics": extract_metrics(raw),
            "perf_exempt": bool(raw.get("perf_exempt")
                                or raw.get("error")),
            "raw": raw,
        }
    doc = {"schema": BENCH_SCHEMA, "schema_version": BENCH_SCHEMA_VERSION,
           "fingerprint": str(fingerprint), "created": float(created),
           "tiers": tdocs}
    if label:
        doc["label"] = str(label)
    validate_bench(doc)
    return doc


def validate_bench(doc) -> None:
    """Raise ``ValueError`` unless ``doc`` is a schema-valid bench
    snapshot — same contract validate_event gives telemetry lines."""
    if not isinstance(doc, dict):
        raise ValueError(f"bench doc is not an object: {type(doc).__name__}")
    if doc.get("schema") != BENCH_SCHEMA:
        raise ValueError(f"not a {BENCH_SCHEMA} document: "
                         f"schema={doc.get('schema')!r}")
    if doc.get("schema_version") != BENCH_SCHEMA_VERSION:
        raise ValueError(
            f"bench schema_version={doc.get('schema_version')!r} (this "
            f"build understands {BENCH_SCHEMA_VERSION})")
    for field, typ in (("fingerprint", str), ("created", (int, float)),
                       ("tiers", dict)):
        if not isinstance(doc.get(field), typ):
            raise ValueError(f"bench doc field {field!r} must be "
                             f"{typ}: {doc.get(field)!r}")
    for tier, tdoc in doc["tiers"].items():
        if not isinstance(tdoc, dict) or not isinstance(
                tdoc.get("metrics"), dict):
            raise ValueError(f"tier {tier!r} must carry a metrics dict")
        for name, m in tdoc["metrics"].items():
            if not isinstance(m, dict) or not isinstance(
                    m.get("value"), (int, float)):
                raise ValueError(
                    f"tier {tier!r} metric {name!r} must be an object "
                    f"with a numeric value: {m!r}")
            if m.get("direction") not in ("lower", "higher"):
                raise ValueError(
                    f"tier {tier!r} metric {name!r} direction must be "
                    f"lower|higher: {m.get('direction')!r}")
            samples = m.get("samples")
            if samples is not None and (
                    not isinstance(samples, list)
                    or not all(isinstance(s, (int, float))
                               for s in samples)):
                raise ValueError(
                    f"tier {tier!r} metric {name!r} samples must be a "
                    f"numeric list: {samples!r}")


def save_snapshot(doc: dict, path: str) -> str:
    """Validate + atomically write a snapshot (tmp + rename, the house
    artifact rule). Returns ``path``."""
    from ..utils.anndata_lite import atomic_artifact

    validate_bench(doc)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with atomic_artifact(path) as tmp:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
    return path


def load_snapshot(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    validate_bench(doc)
    return doc


# ---------------------------------------------------------------------------
# noise-aware diff
# ---------------------------------------------------------------------------

def _effective(m: dict) -> float:
    """The comparison value of one metric: min-of-N over samples for
    lower-is-better (the low-noise estimator under scheduler
    interference), max-of-N for higher-is-better, else the scalar."""
    samples = m.get("samples")
    if isinstance(samples, list) and samples:
        vals = [float(s) for s in samples]
        return min(vals) if m.get("direction") == "lower" else max(vals)
    return float(m["value"])


def diff_snapshots(base: dict, new: dict, band: float | None = None) -> dict:
    """Compare two validated snapshots. Returns ``{rows, regressions,
    improvements, ok, fingerprint_match}`` where each row is one
    (tier, metric) with the relative move and a verdict in
    {ok, regressed, improved, exempt, missing}. ``ok`` is False iff
    any comparable row regressed past the band."""
    validate_bench(base)
    validate_bench(new)
    band = gate_band() if band is None else float(band)
    fp_match = base.get("fingerprint") == new.get("fingerprint")
    rows = []
    regressions = improvements = 0
    for tier in sorted(set(base["tiers"]) | set(new["tiers"])):
        bt, nt = base["tiers"].get(tier), new["tiers"].get(tier)
        if bt is None or nt is None:
            rows.append({"tier": tier, "metric": "*",
                         "verdict": "missing",
                         "note": "tier absent from "
                                 + ("baseline" if bt is None else "new")})
            continue
        exempt = bool(bt.get("perf_exempt") or nt.get("perf_exempt")
                      or not fp_match)
        for name in sorted(set(bt["metrics"]) | set(nt["metrics"])):
            bm, nm = bt["metrics"].get(name), nt["metrics"].get(name)
            if bm is None or nm is None:
                rows.append({"tier": tier, "metric": name,
                             "verdict": "missing"})
                continue
            bv, nv = _effective(bm), _effective(nm)
            if bv == 0:
                rel = 0.0 if nv == 0 else math.inf
            else:
                rel = (nv - bv) / abs(bv)
            direction = bm.get("direction", "lower")
            # normalize so positive `moved` always means "got worse"
            moved = rel if direction == "lower" else -rel
            if exempt:
                verdict = "exempt"
            elif moved > band:
                verdict = "regressed"
                regressions += 1
            elif moved < -band:
                verdict = "improved"
                improvements += 1
            else:
                verdict = "ok"
            rows.append({"tier": tier, "metric": name, "base": bv,
                         "new": nv, "rel": (round(rel, 4)
                                            if math.isfinite(rel)
                                            else None),
                         "direction": direction,
                         "unit": bm.get("unit", ""), "verdict": verdict})
    return {"rows": rows, "regressions": regressions,
            "improvements": improvements, "band": band,
            "fingerprint_match": fp_match,
            "base_fingerprint": base.get("fingerprint"),
            "new_fingerprint": new.get("fingerprint"),
            "ok": regressions == 0}


def render_diff(diff: dict) -> str:
    """Human-readable benchdiff table."""
    lines = []
    if not diff.get("fingerprint_match"):
        lines.append(
            f"NOTE: device fingerprints differ "
            f"({diff.get('base_fingerprint')} vs "
            f"{diff.get('new_fingerprint')}) — all rows exempt, nothing "
            f"gates across hardware")
    lines.append(f"{'tier':<12s} {'metric':<44s} {'base':>12s} "
                 f"{'new':>12s} {'rel':>8s}  verdict")
    for r in diff.get("rows", []):
        if r.get("verdict") == "missing" and r.get("metric") == "*":
            lines.append(f"{r['tier']:<12s} {'*':<44s} "
                         f"{'':>12s} {'':>12s} {'':>8s}  "
                         f"missing ({r.get('note', '')})")
            continue
        base, new = r.get("base"), r.get("new")
        rel = r.get("rel")
        lines.append(
            f"{str(r.get('tier'))[:12]:<12s} "
            f"{str(r.get('metric'))[:44]:<44s} "
            + (f"{base:>12.4f}" if isinstance(base, (int, float))
               else f"{'n/a':>12s}") + " "
            + (f"{new:>12.4f}" if isinstance(new, (int, float))
               else f"{'n/a':>12s}") + " "
            + (f"{100 * rel:>+7.1f}%" if isinstance(rel, (int, float))
               else f"{'n/a':>8s}")
            + f"  {r.get('verdict')}")
    lines.append(
        f"-- {diff.get('regressions', 0)} regression(s), "
        f"{diff.get('improvements', 0)} improvement(s), band "
        f"±{100 * diff.get('band', 0.0):.0f}% => "
        + ("OK" if diff.get("ok") else "RED"))
    return "\n".join(lines)
