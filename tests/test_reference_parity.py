"""Cross-implementation parity: repo kernels vs reference-math oracles.

The golden tier pins the repo against its own snapshots; these tests pin it
against independent re-derivations of the *reference's* numerics
(tests/reference_oracles.py) on the golden fixture, at the reference's own
RMS < 1e-4 bar (/root/reference/tests/test_reproducibility.py:12). A failure
here means the repo's kernels drifted from the reference's math, not merely
from their own past output.
"""

import os

import warnings

import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu.ops import (
    fit_h,
    highvar_genes,
    local_density as repo_local_density,
    ols_all_cols,
    run_nmf,
)
from cnmf_torch_tpu.utils import load_df_from_npz

from reference_oracles import (
    consensus_medians_oracle,
    fit_h_online_oracle,
    highvar_genes_oracle,
    local_density_oracle,
    mean_var_oracle,
    ols_oracle,
    reorder_oracle,
)

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "data")
RMS_BAR = 1e-4


def rms(a, b):
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return float(np.sqrt(np.mean((a - b) ** 2)))


@pytest.fixture(scope="module")
def golden_counts():
    return load_df_from_npz(os.path.join(GOLDEN, "counts.df.npz"))


@pytest.fixture(scope="module")
def golden_merged():
    return load_df_from_npz(os.path.join(GOLDEN, "golden.spectra.k_4.merged.df.npz"))


@pytest.fixture(scope="module")
def nonneg_fixture(golden_counts):
    """Scaled golden counts + a W fitted on them — realistic NMF operands."""
    X = golden_counts.values.astype(np.float64)
    X = X / X.std(axis=0, ddof=1).clip(min=1e-12)
    H, W, _ = run_nmf(X.astype(np.float32), n_components=4, random_state=3,
                      mode="batch", batch_max_iter=100)
    return X, np.asarray(W, np.float64)


class TestOlsParity:
    def test_dense(self, rng):
        X = rng.random((257, 5))
        Y = rng.random((257, 83))
        got = ols_all_cols(X, Y, batch_size=64)
        want = ols_oracle(X, Y, batch_size=100)
        assert rms(got, want) < 1e-10

    @pytest.mark.parametrize("normalize_y", [False, True])
    def test_sparse_normalized(self, rng, normalize_y):
        X = rng.random((300, 6))
        Y = sp.random(300, 120, density=0.15, random_state=7, format="csr")
        got = ols_all_cols(X, Y, batch_size=77, normalize_y=normalize_y)
        want = ols_oracle(X, Y, batch_size=128, normalize_y=normalize_y)
        assert rms(got, want) < 1e-10

    def test_fp32_path_within_reference_bar(self, rng):
        X = rng.random((300, 6))
        Y = sp.random(300, 120, density=0.15, random_state=8, format="csr")
        got = ols_all_cols(X, Y, batch_size=90, normalize_y=True,
                           precision="float32")
        want = ols_oracle(X, Y, normalize_y=True)
        assert rms(got, want) < RMS_BAR


class TestHvgParity:
    @pytest.mark.parametrize("sparse", [False, True])
    @pytest.mark.parametrize("numgenes", [None, 120])
    def test_stats_and_selection(self, counts_100x500, sparse, numgenes):
        X = sp.csr_matrix(counts_100x500) if sparse else counts_100x500
        got_stats, got_p = highvar_genes(X, numgenes=numgenes)
        want_stats, want_p = highvar_genes_oracle(X, numgenes=numgenes)
        for col in ["mean", "var", "fano", "expected_fano", "fano_ratio"]:
            g = got_stats[col].values
            w = want_stats[col].values
            ok = np.isfinite(w)
            assert rms(g[ok], w[ok]) < RMS_BAR, col
        assert (got_stats["high_var"].values
                == want_stats["high_var"].values).all()
        assert abs(got_p["A"] - want_p["A"]) < 1e-5
        assert abs(got_p["B"] - want_p["B"]) < 1e-5
        if numgenes is None:
            assert abs(got_p["T"] - want_p["T"]) < 1e-5

    def test_mean_var_matches_sklearn(self, sparse_counts_100x500):
        from cnmf_torch_tpu.ops import column_mean_var

        mu, var = column_mean_var(sparse_counts_100x500, ddof=0)
        mu_o, var_o = mean_var_oracle(sparse_counts_100x500)
        # fp32 block accumulation: ~1e-7 noise, far under the 1e-4 bar
        assert rms(mu, mu_o) < 1e-6 and rms(var, var_o) < 1e-6


class TestFitHParity:
    @pytest.mark.parametrize("chunk_size", [97, 1000])
    def test_same_trajectory(self, nonneg_fixture, rng, chunk_size):
        X, W = nonneg_fixture
        H0 = rng.random((X.shape[0], W.shape[0]))
        got = fit_h(X, W, H_init=H0, chunk_size=chunk_size,
                    chunk_max_iter=200, h_tol=0.05)
        want = fit_h_online_oracle(X, W, H0, chunk_size=chunk_size,
                                   chunk_max_iter=200, h_tol=0.05)
        assert rms(got, want) < RMS_BAR

    def test_regularized(self, nonneg_fixture, rng):
        X, W = nonneg_fixture
        H0 = rng.random((X.shape[0], W.shape[0]))
        got = fit_h(X, W, H_init=H0, chunk_size=64, chunk_max_iter=150,
                    h_tol=0.01, l1_reg_H=0.1, l2_reg_H=0.05)
        want = fit_h_online_oracle(X, W, H0, chunk_size=64,
                                   chunk_max_iter=150, h_tol=0.01,
                                   l1_reg_H=0.1, l2_reg_H=0.05)
        assert rms(got, want) < RMS_BAR


class TestConsensusMathParity:
    def test_local_density(self, golden_merged):
        merged = golden_merged
        k = 4
        n_neighbors = int(0.30 * merged.shape[0] / k)
        l2 = (merged.T / np.sqrt((merged ** 2).sum(axis=1))).T
        got, _ = repo_local_density(l2.values, n_neighbors)
        want = local_density_oracle(l2.values.astype(np.float64), n_neighbors)
        assert rms(got, want) < RMS_BAR

    def test_medians_and_reorder_chain(self, golden_merged):
        """Fix the cluster labels (sklearn KMeans, the reference's dep) and
        push both implementations through medians -> usage refit -> reorder;
        the downstream artifacts must agree at the reference bar."""
        from sklearn.cluster import KMeans

        merged = golden_merged
        k = 4
        l2 = (merged.T / np.sqrt((merged ** 2).sum(axis=1))).T
        labels = pd.Series(
            KMeans(n_clusters=k, n_init=10, random_state=1)
            .fit(l2.values).labels_ + 1, index=l2.index)

        med = consensus_medians_oracle(l2, labels)

        # usage refit on the golden normalized counts analog: rebuild the
        # norm matrix the oracle way (HVG subset + unit variance columns)
        counts = load_df_from_npz(os.path.join(GOLDEN, "counts.df.npz"))
        genes = [ln.strip() for ln in open(
            os.path.join(GOLDEN, "golden.overdispersed_genes.txt"))]
        sub = counts[genes].values.astype(np.float64)
        norm = sub / sub.std(axis=0, ddof=1).clip(min=1e-12)

        H0 = np.random.default_rng(11).random((norm.shape[0], k))
        got_usage = fit_h(norm, med.values, H_init=H0, chunk_size=5000)
        want_usage = fit_h_online_oracle(norm, med.values, H0,
                                         chunk_size=5000)
        assert rms(got_usage, want_usage) < RMS_BAR

        usages = pd.DataFrame(want_usage, columns=med.index)
        _, norm_usages, med_re = reorder_oracle(usages, med)
        # z-score spectra: repo OLS vs oracle OLS on the raw counts as the
        # TPM stand-in (same math path as cnmf.py:1132)
        got_beta = ols_all_cols(usages.values, counts.values,
                                normalize_y=True)
        want_beta = ols_oracle(usages.values, counts.values,
                               normalize_y=True)
        assert rms(got_beta, want_beta) < 1e-10
        assert list(med_re.index) == list(range(1, k + 1))


def test_refit_usage_solves_the_runs_beta_objective(tmp_path):
    """Documented divergence (cnmf.py:944-976 vs 260-271): the reference
    maps beta for its refits but fit_H_online has no beta parameter, so its
    KL-run refits minimize Frobenius. Our refit must solve the run's ACTUAL
    objective: on a KL-prepared run, the refit usages score better under KL
    than the Frobenius-subproblem solution does."""
    import pandas as pd

    from cnmf_torch_tpu.models.cnmf import cNMF
    from cnmf_torch_tpu.ops.nmf import beta_divergence, fit_h
    from cnmf_torch_tpu.utils.io import save_df_to_npz

    rng = np.random.default_rng(3)
    H_true = rng.gamma(1.0, 1.0, size=(80, 3))
    W_true = rng.gamma(1.0, 1.0, size=(3, 50))
    counts = rng.poisson(H_true @ W_true) + 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(80)],
                      columns=[f"g{j}" for j in range(50)])
    fn = str(tmp_path / "c.df.npz")
    save_df_to_npz(df, fn)

    obj = cNMF(output_dir=str(tmp_path), name="kl")
    obj.prepare(fn, components=[3], n_iter=2, seed=1,
                beta_loss="kullback-leibler", num_highvar_genes=40)
    import yaml

    with open(obj.paths["nmf_run_parameters"]) as f:
        assert yaml.safe_load(f)["beta_loss"] == "kullback-leibler"

    X = counts[:, :40].astype(np.float32) + 0.1
    spectra = np.abs(rng.normal(size=(3, 40))).astype(np.float32) + 0.1
    H_ours = obj.refit_usage(X, spectra)
    H_frob = fit_h(X, spectra, beta=2.0, h_tol=1e-4, chunk_max_iter=500)
    kl_ours = float(beta_divergence(X, np.asarray(H_ours), spectra, beta=1.0))
    kl_frob = float(beta_divergence(X, np.asarray(H_frob), spectra, beta=1.0))
    assert kl_ours < kl_frob, (kl_ours, kl_frob)


@pytest.mark.parametrize("beta,beta_loss", [
    (2.0, "frobenius"), (1.0, "kullback-leibler"), (0.0, "itakura-saito")])
def test_batch_mu_trajectory_matches_sklearn_elementwise(beta, beta_loss):
    """ELEMENT-WISE trajectory parity of the batch MU solver against
    sklearn's multiplicative-update NMF from a shared custom init: after
    1, 5, and 20 iterations, H and W agree to fp32 precision for all three
    beta losses (sklearn runs float64). This pins the update equations,
    their application order (usages first — sklearn's W, the reference's
    swapped convention, cnmf.py:758), and the eps handling — a far tighter
    contract than the final-loss comparison (VERDICT r2 weak #8)."""
    import jax.numpy as jnp
    from sklearn.decomposition import NMF

    from cnmf_torch_tpu.ops.nmf import nmf_fit_batch

    rng = np.random.default_rng(0)
    n, g, k = 60, 40, 4
    X = (rng.gamma(1.0, 1.0, (n, k)) @ rng.gamma(1.0, 1.0, (k, g))
         + 0.05 * rng.random((n, g))).astype(np.float64)
    H0 = rng.random((n, k)) + 0.1   # usages  == sklearn's W
    W0 = rng.random((k, g)) + 0.1   # spectra == sklearn's H

    for iters in (1, 5, 20):
        sk = NMF(n_components=k, init="custom", solver="mu",
                 beta_loss=beta_loss, max_iter=iters, tol=0.0)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # sklearn max_iter warning
            W_sk = sk.fit_transform(X.copy(), W=H0.copy(), H=W0.copy())
        H_sk = sk.components_
        H, W, _err = nmf_fit_batch(
            jnp.asarray(X, jnp.float32), jnp.asarray(H0, jnp.float32),
            jnp.asarray(W0, jnp.float32), beta=beta, tol=0.0,
            max_iter=iters)
        assert (np.abs(np.asarray(H) - W_sk).max()
                / np.abs(W_sk).max()) < 5e-5
        assert (np.abs(np.asarray(W) - H_sk).max()
                / np.abs(H_sk).max()) < 5e-5
