"""Execution planner (ISSUE 17): plan build determinism, JSON
round-trip + replay pinning, override precedence (pin > autotuned >
heuristic), the identity-plan lowering byte-identity behind the auto
default flips, autotune-cache invalidation, and the checkpoint-identity
fragment (a plan change restarts, never splices)."""

import json
import os

import pytest

from cnmf_torch_tpu.runtime.planner import (
    DISPATCH_KNOBS,
    DeviceInventory,
    ExecutionPlan,
    InputStats,
    apply_plan,
    build_plan,
    load_plan,
    maybe_apply_plan_env,
    render_plan,
)

INV = DeviceInventory(backend="cpu", device_kind="cpu", n_devices=1,
                      n_processes=1, cpu_count=4)

# a sparse batch KL sweep: the stats shape where every contested
# decision (encoding / recipe / kernel) actually has two live outcomes
SPARSE_KL = InputStats(n=2000, g=800, beta=1.0, mode="batch",
                      init="random", algo="mu", sparse=True,
                      density=0.05, ell_width=40, k_max=8, n_ks=2,
                      max_replicates=3, total_workers=1)


@pytest.fixture(autouse=True)
def _isolated(monkeypatch, tmp_path):
    """Every test here runs with (a) no dispatch knobs in the
    environment — apply_plan writes os.environ via pin_knob, so the
    whole map is snapshotted/restored — and (b) a PRIVATE autotune
    cache dir, so the machine-level measured cache can't steer plans."""
    from cnmf_torch_tpu.utils import autotune

    env0 = dict(os.environ)
    for knob in DISPATCH_KNOBS:
        monkeypatch.delenv(knob, raising=False)
    real_cache_path = autotune.cache_path
    monkeypatch.setattr(
        autotune, "cache_path",
        lambda cache_dir=None: real_cache_path(
            cache_dir or str(tmp_path / "autotune")))
    yield
    os.environ.clear()
    os.environ.update(env0)


def _plant_points(points: dict) -> None:
    from cnmf_torch_tpu.utils import autotune

    autotune._merge_write(autotune.cache_path(), {"plan_points": points})


# ---------------------------------------------------------------------------
# determinism + serialization
# ---------------------------------------------------------------------------

def test_build_plan_deterministic():
    a = build_plan(SPARSE_KL, INV)
    b = build_plan(SPARSE_KL, INV)
    assert a.to_dict() == b.to_dict()
    assert a.signature() == b.signature()
    # the shipped auto defaults on this stats shape: ELL encoding
    # (density 0.05 <= 0.10), dna recipe (batch KL), no Pallas off-TPU
    assert a.use_ell and a.recipe_algo == "dna" and not a.use_pallas
    assert set(a.sources.values()) == {"heuristic"}


def test_json_round_trip(tmp_path):
    plan = build_plan(SPARSE_KL, INV)
    back = ExecutionPlan.from_json(plan.to_json())
    assert back.to_dict() == plan.to_dict()
    assert back.signature() == plan.signature()
    path = str(tmp_path / "plan.json")
    plan.save(path)
    assert load_plan(path).to_dict() == plan.to_dict()


def test_from_dict_rejects_unknown_fields_and_versions():
    plan = build_plan(SPARSE_KL, INV)
    d = plan.to_dict()
    with pytest.raises(ValueError, match="unknown plan fields"):
        ExecutionPlan.from_dict(dict(d, not_a_field=1))
    with pytest.raises(ValueError, match="plan_version"):
        ExecutionPlan.from_dict(dict(d, plan_version=99))


def test_signature_excludes_provenance_and_measured_density():
    plan = build_plan(SPARSE_KL, INV)
    sig = plan.signature()
    pinned = ExecutionPlan.from_dict(plan.to_dict())
    pinned.sources = {k: "pin" for k in plan.sources}
    pinned.density = 0.0123
    assert pinned.signature() == sig  # same DISPATCH, same signature
    flipped = ExecutionPlan.from_dict(plan.to_dict())
    flipped.use_ell = not flipped.use_ell
    assert flipped.signature() != sig


def test_render_plan_covers_every_decision_group():
    text = "\n".join(render_plan(build_plan(SPARSE_KL, INV).to_dict()))
    for token in ("encoding:", "recipe:", "kernel:", "program:",
                  "layout:", "stream:", "ingest:", "[heuristic]"):
        assert token in text, token


# ---------------------------------------------------------------------------
# replay: apply_plan pins / CNMF_TPU_PLAN / round-trip
# ---------------------------------------------------------------------------

def test_apply_plan_round_trips_to_the_same_signature():
    plan = build_plan(SPARSE_KL, INV)
    pins = apply_plan(plan)
    assert pins["CNMF_TPU_SPARSE_BETA"] == "1"
    assert pins["CNMF_TPU_ACCEL"] == "1"  # dna
    assert pins["CNMF_TPU_AUTOTUNE"] == "0"  # replay never re-measures
    replay = build_plan(SPARSE_KL, INV)
    assert replay.signature() == plan.signature()
    # provenance records the pins; the dispatch itself is unchanged
    assert replay.sources["encoding"] == "pin"
    assert replay.sources["recipe"] == "pin"


def test_maybe_apply_plan_env(tmp_path):
    assert maybe_apply_plan_env() is None  # knob unset: no-op
    plan = build_plan(SPARSE_KL, INV)
    path = str(tmp_path / "plan.json")
    plan.save(path)
    os.environ["CNMF_TPU_PLAN"] = path
    applied = maybe_apply_plan_env()
    assert applied.signature() == plan.signature()
    assert os.environ["CNMF_TPU_SPARSE_BETA"] == "1"
    # a missing plan file is an ERROR, not a silent different dispatch
    os.environ["CNMF_TPU_PLAN"] = str(tmp_path / "nope.json")
    with pytest.raises(OSError):
        maybe_apply_plan_env()


# ---------------------------------------------------------------------------
# precedence: pin > autotuned > heuristic
# ---------------------------------------------------------------------------

def test_autotuned_crossover_beats_static_heuristic():
    stats = InputStats(**dict(SPARSE_KL.__dict__, density=0.15))
    base = build_plan(stats, INV)
    assert not base.use_ell  # 0.15 > the static 0.10 crossover
    assert base.sources["encoding"] == "heuristic"
    _plant_points({"ell_density_crossover": 0.2})
    tuned = build_plan(stats, INV)
    assert tuned.use_ell  # 0.15 <= the measured 0.2 crossover
    assert tuned.sources["encoding"] == "autotuned"
    assert tuned.density_threshold == 0.2


def test_pin_beats_autotuned_point():
    stats = InputStats(**dict(SPARSE_KL.__dict__, density=0.15))
    _plant_points({"ell_density_crossover": 0.2, "stream_threads": 3})
    tuned = build_plan(stats, INV)
    assert tuned.use_ell and tuned.stream_threads == 3
    assert tuned.sources["streaming"] == "autotuned"
    os.environ["CNMF_TPU_SPARSE_BETA"] = "0"
    os.environ["CNMF_TPU_STREAM_THREADS"] = "2"
    pinned = build_plan(stats, INV)
    assert not pinned.use_ell and pinned.stream_threads == 2
    assert pinned.sources["encoding"] == "pin"
    assert pinned.sources["streaming"] == "pin"


def test_caller_override_is_a_pin():
    plan = build_plan(SPARSE_KL, INV, overrides={"packed": True})
    assert plan.sources["packed"] == "pin"
    auto = build_plan(SPARSE_KL, INV)
    assert auto.sources["packed"] == "heuristic"


# ---------------------------------------------------------------------------
# autotune cache invalidation
# ---------------------------------------------------------------------------

def test_cache_invalidated_by_fingerprint_change(monkeypatch):
    from cnmf_torch_tpu import version
    from cnmf_torch_tpu.utils.autotune import cached_plan_points

    _plant_points({"stream_threads": 3})
    assert cached_plan_points().get("stream_threads") == 3
    # a package-version bump changes the device fingerprint, which is
    # part of the cache FILENAME: stale measured points are orphaned
    monkeypatch.setattr(version, "__version__", "999.0.0")
    assert cached_plan_points() == {}


def test_autotune_off_disables_consumption():
    from cnmf_torch_tpu.utils.autotune import cached_plan_points

    _plant_points({"stream_threads": 3, "ell_density_crossover": 0.2})
    os.environ["CNMF_TPU_AUTOTUNE"] = "0"
    assert cached_plan_points() == {}
    stats = InputStats(**dict(SPARSE_KL.__dict__, density=0.15))
    plan = build_plan(stats, INV)
    assert not plan.use_ell  # static heuristics only
    assert plan.sources["encoding"] == "heuristic"


# ---------------------------------------------------------------------------
# the default flips: identity-plan lowering byte-identity
# ---------------------------------------------------------------------------

def test_online_auto_default_lowers_byte_identical_to_zero():
    """Where the auto lanes do NOT engage (online mode, CPU backend),
    the flipped defaults must compile the EXACT pre-flip program:
    unset == ACCEL=0/PALLAS=0, lowering equality."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cnmf_torch_tpu.ops.nmf import nmf_fit_batch, random_init
    from cnmf_torch_tpu.ops.pallas import resolve_pallas
    from cnmf_torch_tpu.ops.recipe import resolve_recipe

    rec_auto = resolve_recipe(1.0, "online")
    os.environ["CNMF_TPU_ACCEL"] = "0"
    rec_zero = resolve_recipe(1.0, "online")
    del os.environ["CNMF_TPU_ACCEL"]
    assert rec_auto.is_identity and rec_zero.is_identity
    assert not resolve_pallas()  # auto off-TPU == off
    os.environ["CNMF_TPU_PALLAS"] = "0"
    assert not resolve_pallas()
    del os.environ["CNMF_TPU_PALLAS"]

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.gamma(1.0, 1.0, (60, 30)).astype(np.float32))
    H0, W0 = random_init(jax.random.key(0), 60, 30, 3, jnp.mean(X))
    low_auto = nmf_fit_batch.lower(
        X, H0, W0, beta=1.0, max_iter=10,
        inner_repeats=rec_auto.inner_repeats,
        kl_newton=rec_auto.kl_newton).as_text()
    low_zero = nmf_fit_batch.lower(
        X, H0, W0, beta=1.0, max_iter=10,
        inner_repeats=rec_zero.inner_repeats,
        kl_newton=rec_zero.kl_newton).as_text()
    low_bare = nmf_fit_batch.lower(X, H0, W0, beta=1.0,
                                   max_iter=10).as_text()
    assert low_auto == low_zero == low_bare


# ---------------------------------------------------------------------------
# checkpoint identity: a plan change restarts, never splices
# ---------------------------------------------------------------------------

def test_identity_fragment_tracks_math_affecting_fields_only():
    plan = build_plan(SPARSE_KL, INV)
    frag = plan.identity_fragment()
    assert "enc=ell" in frag

    def variant(**kw):
        v = ExecutionPlan.from_dict(plan.to_dict())
        for k, val in kw.items():
            setattr(v, k, val)
        return v.identity_fragment()

    # recipe / kernel / encoding flips change the fragment => restart
    assert variant(recipe_algo="mu", kl_newton=False) != frag
    assert variant(use_pallas=True, kernel="ell-pallas") != frag
    assert variant(use_ell=False) != frag
    # layout / streaming replay the same trajectory => same fragment
    assert variant(stream_threads=7, stream_depth=9) == frag
    assert variant(layout="grid2d", mesh_devices=8) == frag


def test_plan_signature_rides_factorize_provenance_contract():
    # the solver_recipe the plan rebuilds is the object the sweeps key
    # on: algo/repeats/newton/sketch fields survive the round trip
    plan = build_plan(SPARSE_KL, INV)
    rec = plan.solver_recipe()
    assert rec.algo == plan.recipe_algo
    assert rec.inner_repeats == plan.inner_repeats
    assert rec.kl_newton == plan.kl_newton
    assert json.loads(plan.to_json())["recipe_label"] == rec.label
