"""Mid-run pass-statistics checkpoints for streaming/rowsharded solves.

PR 4's resilience layer recovers at replicate/artifact granularity: a
multi-hour rowsharded pass (ROADMAP item 1) that dies mid-replicate
loses every completed pass of that replicate. The online/rowsharded
solvers' per-pass ``(A, B)`` sufficient statistics and the replicated
``W`` are tiny next to X — exactly the state MPI-FAUN (arxiv 1609.09154)
and the distributed out-of-memory NMF design (arxiv 2202.09518) keep
globally consistent while the data shards stay local — so they make a
checkpoint whose size is independent of the cell count. This module is
that checkpoint:

  * after each solver pass (every ``CNMF_TPU_CKPT_EVERY_PASSES`` passes,
    default 1; ``0`` disables the subsystem entirely — factorize then
    compiles the exact pre-checkpoint programs), the replicated ``W``,
    the last pass's ``(A, B)`` statistics (β=2; zeros otherwise — the
    β≠2 W step needs only W), the pass cursor, the objective state, the
    telemetry trace, and the replicate's seed identity are persisted
    atomically (``atomic_artifact``) per ``(k, iter)`` replicate;
  * the usage matrix ``H`` additionally rides the checkpoint while it
    fits ``CNMF_TPU_CKPT_H_BYTES`` (default 256 MB) — below the budget a
    resumed run is bit-identical to the uninterrupted one; above it the
    resume re-derives usages from the restored W (one tightly solved
    block-coordinate pass), matching within solver tolerance: the
    sufficient-statistics trade the out-of-core designs make;
  * a content digest of the input (shape + nnz + checksum) is stored and
    verified on resume, so a checkpoint can never silently continue a
    DIFFERENT matrix's factorization;
  * every load validates structurally (readable zip, matching identity,
    matching shapes, finite state) and raises
    :class:`TornCheckpointError` otherwise — a checkpoint torn by a
    mid-write kill is discarded and the replicate restarts from scratch,
    never trusted.

:class:`PassCheckpointer` is the policy object ``cNMF.factorize`` hands
to ``parallel.rowshard.nmf_fit_rowsharded``; the solver stays
policy-free (it only calls ``load``/``save``). Telemetry ``checkpoint``
events (``action`` in write/resume/discard) make recovery auditable, and
the ``kill:stage=pass`` / ``torn:artifact=ckpt`` hooks fire at the same
points a real preemption would, keeping every path chaos-testable.
"""

from __future__ import annotations

import os
import warnings

import numpy as np

__all__ = [
    "CKPT_EVERY_ENV",
    "CKPT_H_BUDGET_ENV",
    "CKPT_SCHEMA",
    "ckpt_every_passes",
    "ckpt_h_budget_bytes",
    "TornCheckpointError",
    "input_digest",
    "save_pass_checkpoint",
    "load_pass_checkpoint",
    "probe_pass_checkpoint",
    "PassCheckpointer",
]

CKPT_EVERY_ENV = "CNMF_TPU_CKPT_EVERY_PASSES"
CKPT_H_BUDGET_ENV = "CNMF_TPU_CKPT_H_BYTES"
CKPT_MIN_INTERVAL_ENV = "CNMF_TPU_CKPT_MIN_INTERVAL_S"

CKPT_SCHEMA = 1

_DEFAULT_H_BUDGET = 256 << 20

# identity fields a checkpoint must match before resume trusts it: the
# replicate's ledger coordinates, the derived-seed state, the input
# digest, and the resolved solver-parameter signature — a mismatch on
# any of them means the file describes a different solve (different
# matrix OR different recipe) and is treated exactly like a torn
# artifact. The "params" signature includes the resolved SOLVER RECIPE
# (ops/recipe.py: SolverRecipe.signature(), folded in by
# models/cnmf.py): a CNMF_TPU_ACCEL/KL_NEWTON flip between runs changes
# the convergence math itself, and a resume across it would splice a
# plain-MU trajectory onto a Diagonalized-Newton one — the identity
# mismatch makes such a resume restart the replicate instead (pinned by
# tests/test_accel.py). "params" is optional in ``meta`` (defaults to
# "") for callers outside the pipeline.
_IDENTITY_KEYS = ("k", "iter", "seed", "attempt", "digest", "beta",
                  "params")

_ARRAY_KEYS = ("W", "A", "B", "trace")


def _env_nonneg_int(name: str, default: int) -> int:
    from ..utils.envknobs import env_int

    return env_int(name, default, lo=0)


def ckpt_every_passes() -> int:
    """Checkpoint cadence in solver passes (``CNMF_TPU_CKPT_EVERY_PASSES``,
    default 1 — after every pass). ``0`` disables mid-run checkpointing:
    factorize then runs the exact pre-checkpoint (single fused while_loop)
    programs, byte-identical to a build without this subsystem."""
    return _env_nonneg_int(CKPT_EVERY_ENV, 1)


def ckpt_h_budget_bytes() -> int:
    """Byte budget above which the usage matrix H is NOT persisted in the
    checkpoint (``CNMF_TPU_CKPT_H_BYTES``, default 256 MB). Below it
    resume is bit-identical; above it resume re-derives H from W within
    solver tolerance — see the module docstring."""
    return _env_nonneg_int(CKPT_H_BUDGET_ENV, _DEFAULT_H_BUDGET)


def ckpt_min_interval_s() -> float:
    """Wall-clock floor between checkpoint writes
    (``CNMF_TPU_CKPT_MIN_INTERVAL_S``, default 0 = persist every eligible
    pass). On runs whose passes take seconds rather than minutes, a
    nonzero floor (e.g. ``30``) caps the gather+write amplification of
    the default per-pass cadence while keeping the recovery property —
    resume just restarts from a slightly older pass."""
    from ..utils.envknobs import env_float

    return env_float(CKPT_MIN_INTERVAL_ENV, 0.0, lo=0.0)


class TornCheckpointError(RuntimeError):
    """A pass checkpoint exists but cannot be trusted (unreadable,
    truncated, wrong replicate identity, wrong shapes, or nonfinite)."""


def input_digest(X) -> str:
    """Cheap content digest of the factorization input: shape + nnz +
    f64 checksum + a strided 64-element sample, hashed. O(nnz) for the
    sum — microseconds next to a host→device transfer — yet any
    different matrix (other run, re-prepared HVG subset, edited shard)
    collides with negligible probability, so a resumed checkpoint can
    never continue the wrong input."""
    import hashlib

    import scipy.sparse as sp

    buf = X.data if sp.issparse(X) else np.asarray(X).ravel()
    step = max(1, buf.size // 64)
    h = hashlib.sha1()
    h.update(repr((tuple(int(s) for s in X.shape),
                   int(getattr(X, "nnz", buf.size)),
                   float(buf.sum(dtype=np.float64)))).encode())
    h.update(np.ascontiguousarray(buf[::step][:64],
                                  dtype=np.float64).tobytes())
    if sp.issparse(X):
        # the value buffer alone cannot tell two sparsity PATTERNS apart
        # (same values, shifted columns) — fold in the structure arrays
        # so a resumed checkpoint never continues a re-indexed matrix
        for arr in (X.indices, X.indptr):
            a = np.asarray(arr)
            s = max(1, a.size // 64)
            h.update(repr(int(a.sum(dtype=np.int64))).encode())
            h.update(np.ascontiguousarray(a[::s][:64],
                                          dtype=np.int64).tobytes())
    return h.hexdigest()


def save_pass_checkpoint(path, *, k, it, seed, attempt, digest, beta,
                         pass_idx, err_prev, err, trace, W, A, B, H=None,
                         params: str = ""):
    """Atomically persist one replicate's pass state. Objective scalars
    are stored as float32 (the dtype the solver loop carries), so a
    resumed host loop sees bit-identical convergence-test inputs."""
    from ..utils.anndata_lite import atomic_artifact

    from . import faults

    arrays = {
        "schema": np.int64(CKPT_SCHEMA),
        "k": np.int64(k),
        "iter": np.int64(it),
        "seed": np.int64(seed),
        "attempt": np.int64(attempt),
        "digest": np.asarray(str(digest)),
        "beta": np.float64(beta),
        "params": np.asarray(str(params)),
        "pass_idx": np.int64(pass_idx),
        "err_prev": np.float32(err_prev),
        "err": np.float32(err),
        "trace": np.asarray(trace, np.float32),
        "W": np.asarray(W, np.float32),
        "A": np.asarray(A, np.float32),
        "B": np.asarray(B, np.float32),
    }
    if H is not None:
        arrays["H"] = np.asarray(H, np.float32)
    with atomic_artifact(path) as tmp:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
    faults.maybe_tear(path)  # no-op unless CNMF_TPU_FAULT_SPEC


def load_pass_checkpoint(path, *, expect: dict | None = None,
                         n_genes: int | None = None,
                         n_rows: int | None = None,
                         n_rows_min: int | None = None) -> dict:
    """Load + validate a pass checkpoint; :class:`TornCheckpointError` on
    ANY defect. ``expect`` pins the replicate identity (the
    ``_IDENTITY_KEYS`` subset it carries); ``n_genes``/``n_rows`` pin the
    factor shapes of the solve about to resume.

    ``n_rows_min`` (elastic degraded re-mesh, ISSUE 8): accept an ``H``
    whose row count is at least this many rows instead of exactly
    ``n_rows`` — the checkpoint's H carries the WRITING mesh's
    zero-padding (rows past the true cell count are exactly zero: a
    zero X row collapses its usage row in one multiplicative step), and
    a continuation on a shrunk mesh pads to a different multiple. The
    resuming loop trims/re-pads the zero tail to its own padding; the
    true rows are pinned by ``n_rows_min`` (the original cell count) and
    the identity digest as before."""
    try:
        with np.load(path, allow_pickle=False) as f:
            data = {key: np.asarray(f[key]) for key in f.files}
    except Exception as exc:
        raise TornCheckpointError(
            f"{path}: unreadable checkpoint ({type(exc).__name__}: {exc})")
    required = set(_IDENTITY_KEYS) | set(_ARRAY_KEYS) | {
        "schema", "pass_idx", "err_prev", "err"}
    missing = required - set(data)
    if missing:
        raise TornCheckpointError(
            f"{path}: checkpoint missing members {sorted(missing)}")
    if int(data["schema"]) != CKPT_SCHEMA:
        raise TornCheckpointError(
            f"{path}: checkpoint schema {int(data['schema'])} (this build "
            f"understands {CKPT_SCHEMA})")
    state: dict = {
        "pass_idx": int(data["pass_idx"]),
        "err_prev": float(data["err_prev"]),
        "err": float(data["err"]),
        "trace": np.asarray(data["trace"], np.float32),
        "W": np.asarray(data["W"], np.float32),
        "A": np.asarray(data["A"], np.float32),
        "B": np.asarray(data["B"], np.float32),
        "H": (np.asarray(data["H"], np.float32) if "H" in data else None),
    }
    for key in _IDENTITY_KEYS:
        state[key] = (str(data[key]) if key in ("digest", "params")
                      else (float(data[key]) if key == "beta"
                            else int(data[key])))
    if expect:
        for key, want in expect.items():
            have = state.get(key)
            same = (str(want) == str(have) if key in ("digest", "params")
                    else float(want) == float(have))
            if not same:
                raise TornCheckpointError(
                    f"{path}: checkpoint {key}={have!r} does not match the "
                    f"replicate being resumed ({key}={want!r})")
    k = state["k"]
    if state["W"].ndim != 2 or state["W"].shape[0] != k:
        raise TornCheckpointError(
            f"{path}: W shape {state['W'].shape} does not match k={k}")
    if n_genes is not None and state["W"].shape[1] != int(n_genes):
        raise TornCheckpointError(
            f"{path}: W has {state['W'].shape[1]} gene columns, expected "
            f"{int(n_genes)}")
    if state["H"] is not None:
        rows = state["H"].shape[0] if state["H"].ndim == 2 else -1
        bad = state["H"].ndim != 2 or state["H"].shape[1] != k
        if n_rows_min is not None:
            bad = bad or rows < int(n_rows_min)
        elif n_rows is not None:
            bad = bad or rows != int(n_rows)
        if bad:
            raise TornCheckpointError(
                f"{path}: H shape {state['H'].shape} does not match the "
                f"resumed solve ({n_rows_min if n_rows_min is not None else n_rows} x {k})")
    if state["pass_idx"] < 1:
        raise TornCheckpointError(
            f"{path}: pass cursor {state['pass_idx']} < 1")
    finite = (np.isfinite(state["W"]).all()
              and np.isfinite(np.float32(state["err"]))
              and (state["H"] is None or np.isfinite(state["H"]).all()))
    if not finite:
        raise TornCheckpointError(f"{path}: nonfinite checkpoint state")
    return state


def probe_pass_checkpoint(path, **kwargs):
    """Resume-side probe: ``(state, None)`` when present AND valid,
    ``(None, "missing")`` when absent, else ``(None, reason)`` — the
    caller treats anything non-valid as "start this replicate from
    scratch", never trusting a damaged file."""
    if not os.path.exists(path):
        return None, "missing"
    try:
        return load_pass_checkpoint(path, **kwargs), None
    except TornCheckpointError as exc:
        return None, str(exc)


class PassCheckpointer:
    """Per-replicate checkpoint policy handed to the rowsharded solver.

    Holds the path, cadence (``every`` passes; <= 0 is inert), the
    replicate identity (``meta``: k/iter/seed/attempt/digest/beta), and
    the telemetry sink. A FRESH factorize (``resume=False``) discards any
    stale file at construction — a fresh run recomputes every replicate,
    so a prior run's cursor is void (same rule as
    ``resilience.sweep_stale_ledgers``); only ``--skip-completed-runs``
    resumes load.
    """

    def __init__(self, path, every: int, *, meta: dict, events=None,
                 worker=0, resume: bool = False,
                 h_budget_bytes: int | None = None,
                 min_interval_s: float | None = None):
        self.path = os.fspath(path)
        self.every = int(every)
        self.meta = {key: (meta[key] if key != "params"
                           else str(meta.get(key, "")))
                     for key in _IDENTITY_KEYS}
        self.events = events
        self.worker = worker
        self.resume = bool(resume)
        self.h_budget = (ckpt_h_budget_bytes() if h_budget_bytes is None
                         else int(h_budget_bytes))
        self.min_interval_s = (ckpt_min_interval_s()
                               if min_interval_s is None
                               else float(min_interval_s))
        self._last_save: float | None = None
        if not self.resume:
            self.discard()

    def _emit(self, action: str, **ctx):
        if self.events is not None:
            context = {key: val for key, val in self.meta.items()
                       if key != "digest"}
            context.update(path=self.path, **ctx)
            self.events.emit("checkpoint", action=action, context=context)

    def due(self) -> bool:
        """Whether a save at this point would actually persist — lets the
        solver skip the device→host gather entirely when the wall-clock
        floor (``min_interval_s``) says the write would be dropped."""
        if self.every <= 0:
            return False
        if self.min_interval_s > 0 and self._last_save is not None:
            import time

            return (time.monotonic() - self._last_save
                    >= self.min_interval_s)
        return True

    def load(self, n_rows: int | None = None, n_genes: int | None = None,
             n_rows_min: int | None = None):
        """Validated state for a resume, or ``None`` (absent / fresh run /
        torn — a torn checkpoint is discarded, surfaced as a telemetry
        ``fault``, and the replicate restarts from scratch).
        ``n_rows_min`` relaxes the exact H row check to a floor for
        degraded re-mesh continuations (see
        :func:`load_pass_checkpoint`)."""
        if not self.resume or self.every <= 0:
            return None
        state, reason = probe_pass_checkpoint(
            self.path, expect=self.meta, n_genes=n_genes, n_rows=n_rows,
            n_rows_min=n_rows_min)
        if state is None:
            if reason != "missing":
                warnings.warn(
                    "resume: pass checkpoint failed validation and is "
                    "discarded; the replicate restarts from scratch — %s"
                    % reason, RuntimeWarning, stacklevel=2)
                if self.events is not None:
                    self.events.emit("fault", kind="torn_artifact",
                                     context={"path": self.path,
                                              "reason": reason})
                self.discard(emit=False)
            return None
        self._emit("resume", pass_idx=state["pass_idx"],
                   with_h=state["H"] is not None)
        return state

    def save(self, *, pass_idx, err_prev, err, trace, W, A, B, H=None):
        """Persist the pass state (H only under the byte budget), then run
        the chaos hooks in real-preemption order: tear-after-write
        (``torn:artifact=ckpt``, inside ``save_pass_checkpoint``) before
        kill-at-stage (``kill:stage=pass``). Writes closer together than
        ``min_interval_s`` wall-clock are skipped (resume just restarts
        from the slightly older pass) — the amplification cap for runs
        whose passes take seconds."""
        if self.every <= 0:
            return
        import time

        if (self.min_interval_s > 0 and self._last_save is not None
                and time.monotonic() - self._last_save
                < self.min_interval_s):
            return
        if H is not None and getattr(H, "nbytes", 0) > self.h_budget:
            H = None
        save_pass_checkpoint(
            self.path, pass_idx=pass_idx, err_prev=err_prev, err=err,
            trace=trace, W=W, A=A, B=B, H=H,
            k=self.meta["k"], it=self.meta["iter"], seed=self.meta["seed"],
            attempt=self.meta["attempt"], digest=self.meta["digest"],
            beta=self.meta["beta"], params=self.meta["params"])
        self._last_save = time.monotonic()
        self._emit("write", pass_idx=int(pass_idx), with_h=H is not None)
        from . import faults

        faults.maybe_kill("pass", self.worker)

    def discard(self, emit: bool = True):
        """Remove the checkpoint (replicate completed, superseded, or
        invalid) — missing file is a no-op."""
        if not os.path.exists(self.path):
            return
        try:
            os.unlink(self.path)
        except OSError:
            return
        if emit:
            self._emit("discard")
