"""Command-line interface: the reference's five subcommands, plus
``run_parallel`` (the launcher), ``report`` (render a run's telemetry —
see ``utils/telemetry.py``), ``lint`` (static analysis), ``serve``
(the warm projection daemon over a run's consensus reference —
``cnmf_torch_tpu/serving/``), and ``fleet`` (the replicated serving
fleet: tenant routing, failover, and reference rollover over N serve
replicas — ``cnmf_torch_tpu/serving/fleet.py``).

Flag-compatible with the reference CLI (``/root/reference/src/cnmf/cnmf.py:
1387-1470``): ``prepare | factorize | combine | consensus |
k_selection_plot`` with the same ~20 options. Two deliberate repairs of
reference defects, both documented in the reference survey:

  * ``--worker-index`` works. The fork comments the flag out and its
    factorize dispatch passes no worker arguments (``cnmf.py:1430, 1449``),
    so CLI sharding is broken there even though its own docs and
    ``Extras/run_parallel.py:49`` still use it. Here the flag exists and is
    forwarded, alongside ``--total-workers``.
  * ``consensus`` loads the merged-spectra file inside ``cNMF.consensus``
    only (the reference's dispatch pre-loads it into a dead variable,
    ``cnmf.py:1461``).

Run as ``python -m cnmf_torch_tpu.cli ...`` or via the ``cnmf-tpu`` console
script.
"""

from __future__ import annotations

import argparse

from .utils.io import load_df_from_npz

__all__ = ["main", "build_parser"]


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cnmf-tpu",
        description="TPU-native consensus NMF (cNMF) pipeline")
    parser.add_argument(
        "command", type=str,
        choices=["prepare", "factorize", "combine", "consensus",
                 "k_selection_plot", "run_parallel", "report", "lint",
                 "serve", "fleet", "plan", "trace"])
    parser.add_argument(
        "run_dir", type=str, nargs="?", default=None,
        help="[report|serve|fleet|plan|trace] Run directory "
             "([output-dir]/[name]) whose telemetry to render / whose "
             "consensus reference to serve / whose resolved execution "
             "plan to show / whose sampled trace waterfalls to render; "
             "defaults to --output-dir/--name")
    parser.add_argument("--name", type=str, nargs="?", default="cNMF",
                        help="[all] Name for analysis. All output will be "
                             "placed in [output-dir]/[name]/...")
    parser.add_argument("--output-dir", type=str, nargs="?", default=".",
                        help="[all] Output directory. All output will be "
                             "placed in [output-dir]/[name]/...")
    parser.add_argument("-c", "--counts", type=str,
                        help="[prepare] Input (cell x gene) counts matrix as "
                             ".h5ad, .mtx, df.npz, or tab delimited text "
                             "file")
    parser.add_argument("-k", "--components", type=int, nargs="+",
                        help="[prepare] Number of components (k) for matrix "
                             "factorization. Several can be specified with "
                             '"-k 8 9 10"')
    parser.add_argument("-n", "--n-iter", type=int, default=100,
                        help="[prepare] Number of factorization replicates")
    parser.add_argument("--total-workers", type=int, default=-1,
                        help="[all] Total number of workers to distribute "
                             "jobs to")
    parser.add_argument("--worker-index", type=int, default=0,
                        help="[factorize] Index of current worker (the first "
                             "worker should have index 0)")
    parser.add_argument("--use_gpu", action="store_true", default=False,
                        help="[prepare] Accepted for reference-CLI "
                             "compatibility; accelerator placement is "
                             "automatic under JAX")
    parser.add_argument("--seed", type=int, default=None,
                        help="[prepare] Seed for pseudorandom number "
                             "generation")
    parser.add_argument("--genes-file", type=str, default=None,
                        help="[prepare] File containing a list of genes to "
                             "include, one gene per line. Must match column "
                             "labels of counts matrix.")
    parser.add_argument("--numgenes", type=int, default=2000,
                        help="[prepare] Number of high variance genes to use "
                             "for matrix factorization.")
    parser.add_argument("--tpm", type=str, default=None,
                        help="[prepare] Pre-computed (cell x gene) TPM "
                             "values as df.npz or tab separated txt file. If "
                             "not provided TPM will be calculated "
                             "automatically")
    parser.add_argument("--max-nmf-iter", type=int, default=1000,
                        help="[prepare] Max number of iterations per "
                             "individual NMF run (default 1000)")
    parser.add_argument("--beta-loss", type=str, default="frobenius",
                        choices=["frobenius", "kullback-leibler",
                                 "itakura-saito"],
                        help="[prepare] Loss function for NMF (default "
                             "frobenius)")
    parser.add_argument("--init", type=str, default="random",
                        choices=["random", "nndsvd"],
                        help="[prepare] Initialization algorithm for NMF "
                             "(default random)")
    parser.add_argument("--densify", dest="densify", action="store_true",
                        default=False,
                        help="[prepare] Treat the input data as non-sparse "
                             "(default False)")
    parser.add_argument("--batch_size", type=int, default=5000,
                        help="[prepare] Size of batch for online NMF "
                             "learning.")
    parser.add_argument("--skip-completed-runs", action="store_true",
                        default=False,
                        help="[factorize] Resume: skip replicates whose "
                             "artifacts probe AND validate on disk (torn "
                             "files are rerun, quarantined lanes stay "
                             "excluded). No prepare re-run needed.")
    parser.add_argument("--sequential", action="store_true", default=False,
                        help="[factorize] Run replicates one at a time "
                             "instead of as one batched device program")
    parser.add_argument("--rowshard", dest="rowshard",
                        action=argparse.BooleanOptionalAction, default=None,
                        help="[factorize] Shard the cells axis across the "
                             "device mesh (atlas-scale inputs), streaming "
                             "sparse row blocks host-to-HBM instead of "
                             "densifying. Default: auto above "
                             "--rowshard-threshold cells")
    parser.add_argument("--rowshard-threshold", type=int, default=200_000,
                        help="[factorize] Cell count at which factorize "
                             "auto-switches to the row-sharded path")
    parser.add_argument("--mesh-2d", dest="mesh_2d", action="store_true",
                        default=False,
                        help="[factorize] Run the sweep over the 2-D "
                             "(replicates x cells) device mesh — the "
                             "multi-host layout: replicate shards across "
                             "hosts, cells-axis collectives on ICI")
    parser.add_argument("--mesh-grid2d", dest="mesh_grid2d",
                        action="store_true", default=False,
                        help="[factorize] Run replicates over the true 2-D "
                             "(cells x genes) processor grid with "
                             "compute-overlapped statistics collectives "
                             "(MPI-FAUN): X sharded over both axes, W over "
                             "genes, H over cells; on pods the cells axis "
                             "spans hosts so only k-sized reductions cross "
                             "DCN")
    parser.add_argument("--distributed", action="store_true", default=False,
                        help="[factorize] Initialize jax.distributed from "
                             "CNMF_COORDINATOR_ADDRESS / CNMF_NUM_PROCESSES "
                             "/ CNMF_PROCESS_ID before running (multi-host "
                             "pods; also implied when those env vars are "
                             "set)")
    parser.add_argument("--per-k-programs", action="store_true",
                        default=False,
                        help="[factorize] Force one compiled program per K; "
                             "by default quick multi-K scans (>=4 Ks, <=32 "
                             "replicates per K) run as one packed K_max "
                             "program with bit-identical spectra")
    parser.add_argument("--plan", type=str, default=None,
                        help="[factorize] Replay a dumped execution plan "
                             "(JSON from a run's `plan` telemetry event or "
                             "`cnmf-tpu plan <run_dir> --out`): pins the "
                             "whole dispatch surface — encoding, solver "
                             "recipe, kernel, streaming, serve buckets — so "
                             "the run's dispatch reproduces bit-identically "
                             "(sets CNMF_TPU_PLAN for this run)")
    parser.add_argument("--out", type=str, default=None,
                        help="[plan] Also dump the plan JSON to this file "
                             "(replayable via factorize --plan)")
    parser.add_argument("--store-uri", type=str, default=None,
                        help="[all] Shard-store transport (sets "
                             "CNMF_TPU_STORE_URI for this run and every "
                             "spawned worker): unset "
                             "= local paths, file:///base relocates the "
                             "store, http(s)://host/prefix streams it from "
                             "an object store with retry/hedge/cache fault "
                             "containment")
    parser.add_argument("--engine", type=str, default="subprocess",
                        choices=["subprocess", "multihost"],
                        help="[run_parallel] How factorize workers run: "
                             "independent OS processes sharing files (the "
                             "reference's GNU-parallel model) or one "
                             "jax.distributed program over a 2-D mesh")
    parser.add_argument("--devices-per-host", type=int, default=None,
                        help="[run_parallel] Virtual CPU devices per "
                             "multihost process (pod simulation; omit on "
                             "real hardware)")
    parser.add_argument("--clean", action="store_true", default=False,
                        help="[run_parallel] Delete per-replicate spectra "
                             "files after combine")
    # default None is the "not given" sentinel: consensus resolves it to
    # the reference's 0.5, while serve uses an explicit value to pick
    # among several consensus artifacts (a hardcoded 0.5 would silently
    # filter out a run's only artifact at another threshold)
    parser.add_argument("--local-density-threshold", type=float,
                        default=None,
                        help="[consensus] Threshold for the local density "
                             "filtering, >0 and <=2 (default 0.5); "
                             "[serve] pick the consensus artifact at this "
                             "density threshold")
    parser.add_argument("--local-neighborhood-size", type=float, default=0.30,
                        help="[consensus] Fraction of the number of "
                             "replicates to use as nearest neighbors for "
                             "local density filtering")
    parser.add_argument("--show-clustering", dest="show_clustering",
                        action="store_true",
                        help="[consensus] Produce a clustergram figure "
                             "summarizing the spectra clustering")
    parser.add_argument("--socket", type=str, default=None,
                        help="[serve|fleet] Unix-socket path for the "
                             "projection daemon / fleet router (default: "
                             "<run_dir>/cnmf_tmp/<name>.serve.sock or "
                             "<name>.fleet.sock)")
    parser.add_argument("--port", type=int, default=None,
                        help="[serve|fleet] Serve HTTP on 127.0.0.1:PORT "
                             "instead of the unix socket")
    parser.add_argument("--spectra", type=str, default=None,
                        help="[serve|fleet] Explicit reference spectra: a "
                             "consensus .df.npz artifact or a ShardStore "
                             "directory (overrides -k/--local-density-"
                             "threshold selection)")
    parser.add_argument("--replicas", type=int, default=None,
                        help="[fleet] Number of serve replicas to spawn "
                             "and route over (default: "
                             "CNMF_TPU_FLEET_REPLICAS)")
    parser.add_argument("--replica-index", type=int, default=0,
                        help="[serve] Replica ordinal within a fleet "
                             "(fleet-internal: keys the daemon's "
                             "heartbeat stamp and events stream so N "
                             "replicas of one run dir never collide)")
    # BooleanOptionalAction repairs the reference's dead flag (store_true
    # with default=True can never be disabled, cnmf.py:1437): here
    # --no-build-reference actually turns starCAT output off
    parser.add_argument("--build-reference", dest="build_reference",
                        action=argparse.BooleanOptionalAction, default=True,
                        help="[consensus] Generate reference spectra for "
                             "use in starCAT")
    parser.add_argument("--json", action="store_true", default=False,
                        help="[report] Emit the full report summary as "
                             "machine-readable JSON (the structure "
                             "`summarize_events` builds — what the perf "
                             "gate and fleet dashboards consume) instead "
                             "of the rendered text")
    return parser


def main(argv=None):
    import os
    import sys

    if argv is None:
        argv = sys.argv[1:]

    if argv and argv[0] == "lint":
        # the static-analysis subcommand owns its argument surface
        # (paths, --format, --baseline, ... — see analysis/engine.py) and,
        # like `report`, never touches jax — dispatch before the
        # reference-compatible parser can mangle its positionals
        from .analysis.engine import main as lint_main

        raise SystemExit(lint_main(argv[1:]))

    if argv and argv[0] == "benchdiff":
        # noise-aware comparison of two bench snapshots (obs/regress.py):
        # two positionals don't fit the single optional run_dir the
        # reference-compatible parser exposes, so — like `lint` — it owns
        # its argument surface and dispatches early. Never touches jax.
        import argparse as _ap
        import json as _json

        from .obs.regress import diff_snapshots, load_snapshot, render_diff

        bp = _ap.ArgumentParser(
            prog="cnmf-tpu benchdiff",
            description="Compare two bench snapshots (bench.py --json-out "
                        "/ obs.regress schema) with noise-aware relative "
                        "bands; exit 1 when any lane regresses past the "
                        "band.")
        bp.add_argument("base", help="baseline snapshot JSON")
        bp.add_argument("new", help="candidate snapshot JSON")
        bp.add_argument("--band", type=float, default=None,
                        help="relative regression band (fraction; default "
                             "CNMF_TPU_PERF_GATE_BAND or 0.6)")
        bp.add_argument("--json", action="store_true", default=False,
                        help="emit the diff as machine-readable JSON")
        ba = bp.parse_args(argv[1:])
        try:
            diff = diff_snapshots(load_snapshot(ba.base),
                                  load_snapshot(ba.new), band=ba.band)
        except (OSError, ValueError) as exc:
            bp.error(str(exc))
        if ba.json:
            print(_json.dumps(diff, indent=1, sort_keys=True))
        else:
            print(render_diff(diff))
        raise SystemExit(0 if diff["ok"] else 1)

    # parse BEFORE any jax import: --help / usage errors must not pay the
    # backend-initialization cost or touch the cache directory.
    # parse_intermixed_args so flags may precede the optional run_dir
    # positional (`report --json <run_dir>` and `report <run_dir> --json`
    # both parse).
    parser = build_parser()
    args = parser.parse_intermixed_args(argv)

    if args.command == "lint":  # e.g. `cnmf-tpu --name x lint`
        parser.error("lint takes its own options; use: cnmf-tpu lint "
                     "[paths ...] [--format text|json] [--baseline FILE] "
                     "[--write-baseline] [--knob-table]")

    if args.command not in ("report", "serve", "fleet", "plan", "trace") \
            and args.run_dir is not None:
        # the optional positional exists for `report`/`serve`/`fleet`/
        # `plan`/`trace` only; for every other subcommand a stray
        # positional (e.g. `consensus 9` meaning `-k 9`) must fail fast,
        # not be silently swallowed
        parser.error(f"unrecognized argument: {args.run_dir!r} "
                     f"(a positional run directory applies to 'report', "
                     f"'serve', 'fleet', 'plan', and 'trace' only)")

    if args.command == "plan":
        # like `report`: pure host-side rendering of the run's recorded
        # `plan` telemetry event (runtime/planner.py is stdlib-only at
        # import), so it works on machines without the run's accelerator
        from .runtime.planner import (ExecutionPlan, plan_from_run_dir,
                                      render_plan)

        run_dir = args.run_dir or os.path.join(args.output_dir, args.name)
        if not os.path.isdir(run_dir):
            parser.error(f"plan: run directory not found: {run_dir}")
        plan_dict = plan_from_run_dir(run_dir)
        if plan_dict is None:
            parser.error(
                f"plan: no `plan` event recorded under {run_dir} — run "
                "factorize with CNMF_TPU_TELEMETRY=1 (only the batched "
                "resident path records a plan)")
        print(f"Execution plan — {run_dir}")
        for line in render_plan(plan_dict):
            print(line)
        if args.out:
            ExecutionPlan.from_dict(plan_dict).save(args.out)
            print(f"plan JSON written to {args.out} "
                  f"(replay with: cnmf-tpu factorize --plan {args.out})")
        return

    if args.command == "factorize" and args.plan:
        # sugar for the knob: factorize applies CNMF_TPU_PLAN before any
        # dispatch resolves; validate the file now for a fast usage error
        from .runtime.planner import PLAN_ENV

        if not os.path.isfile(args.plan):
            parser.error(f"factorize: plan file not found: {args.plan}")
        os.environ[PLAN_ENV] = args.plan

    if args.command == "trace":
        # like `report`: pure host-side rendering of the run's recorded
        # `span` events (obs/tracing.py) — per-request/per-run waterfalls
        # of queue wait vs batch linger vs device dispatch vs store I/O
        from .obs.tracing import render_run_traces

        run_dir = args.run_dir or os.path.join(args.output_dir, args.name)
        if not os.path.isdir(run_dir):
            parser.error(f"trace: run directory not found: {run_dir}")
        print(render_run_traces(run_dir))
        return

    if args.command == "report":
        # pure host-side rendering of a run's telemetry (events JSONL from
        # CNMF_TPU_TELEMETRY=1 runs; timings TSV fallback) — never touches
        # jax, so it works on machines without the run's accelerator
        from .utils.telemetry import render_report

        run_dir = args.run_dir or os.path.join(args.output_dir, args.name)
        if not os.path.isdir(run_dir):
            parser.error(f"report: run directory not found: {run_dir}")
        if args.json:
            # machine-readable twin of the rendered report: the merged
            # summarize_events structure (incl. the roofline block) that
            # benchdiff/perf-gate tooling consumes
            import json as _json

            from .utils.telemetry import (_find_event_files, read_events,
                                          summarize_events)

            events: list[dict] = []
            files = _find_event_files(run_dir)
            for path in files:
                events.extend(read_events(path))
            doc = summarize_events(events)
            doc["run_dir"] = run_dir
            doc["event_files"] = len(files)
            print(_json.dumps(doc, indent=1, sort_keys=True, default=str))
        else:
            print(render_report(run_dir))
        return

    if args.command in ("prepare", "run_parallel"):
        # fail as a usage error, not a traceback from deep inside prepare
        missing = [flag for flag, val in
                   (("--counts/-c", args.counts),
                    ("--components/-k", args.components)) if val is None]
        if missing:
            parser.error(f"{args.command} requires {' and '.join(missing)}")

    if getattr(args, "store_uri", None):
        # the flag is sugar for the knob: exported here so this process,
        # run_parallel's spawned workers, and the multihost engine's
        # subprocesses all resolve the same backend
        from .utils.storebackend import STORE_URI_ENV

        os.environ[STORE_URI_ENV] = args.store_uri

    # pod-simulation hook (set by the multihost launcher engine): force N
    # virtual CPU devices BEFORE the backend initializes. Env vars are too
    # late here — this environment pre-imports jax at interpreter startup —
    # so go through jax.config like tests/conftest.py does.
    from .utils.envknobs import env_int

    sim = env_int("CNMF_SIM_CPU_DEVICES", 0, lo=0)
    if sim:
        from .utils.jax_compat import force_cpu_devices

        force_cpu_devices(sim)

    # persistent XLA compile cache (no-op if the user configured their own):
    # repeat runs and the per-K k-selection loop skip recompilation
    from .utils.compile_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    if args.command == "serve":
        # the warm serving tier (ISSUE 12): load + stage the run's
        # consensus reference spectra, warm the bucketed program cache,
        # and serve projection requests until SIGINT/SIGTERM. Reference
        # selection reuses -k and --local-density-threshold (only when
        # explicitly given — the dt default must not filter out a run's
        # single consensus artifact at another threshold).
        from .serving import ReferenceError, serve_forever

        run_dir = args.run_dir or os.path.join(args.output_dir, args.name)
        if not os.path.isdir(run_dir):
            parser.error(f"serve: run directory not found: {run_dir}")
        if args.socket is not None and args.port is not None:
            parser.error("serve: pass --socket or --port, not both")
        dt = args.local_density_threshold
        k = args.components[0] if args.components else None
        try:
            raise SystemExit(serve_forever(
                run_dir, k=k, density_threshold=dt,
                spectra_path=args.spectra,
                socket_path=args.socket, port=args.port,
                replica=args.replica_index))
        except ReferenceError as exc:
            # a missing/ambiguous reference is a usage problem, not a
            # daemon crash — fail with the one-line diagnosis
            parser.error(f"serve: {exc}")

    if args.command == "fleet":
        # the replicated serving fleet (ISSUE 20): spawn N `serve`
        # replicas, front them with the consistent-hash tenant router,
        # and keep them alive (failover + respawn + rollover) until
        # SIGINT/SIGTERM. Reference selection matches `serve`.
        from .serving import ReferenceError, fleet_forever

        run_dir = args.run_dir or os.path.join(args.output_dir, args.name)
        if not os.path.isdir(run_dir):
            parser.error(f"fleet: run directory not found: {run_dir}")
        if args.socket is not None and args.port is not None:
            parser.error("fleet: pass --socket or --port, not both")
        if args.replicas is not None and args.replicas < 1:
            parser.error("fleet: --replicas must be >= 1")
        dt = args.local_density_threshold
        k = args.components[0] if args.components else None
        try:
            raise SystemExit(fleet_forever(
                run_dir, replicas=args.replicas, k=k,
                density_threshold=dt, spectra_path=args.spectra,
                socket_path=args.socket, port=args.port))
        except ReferenceError as exc:
            parser.error(f"fleet: {exc}")

    if args.command == "run_parallel":
        from .launcher import run_pipeline

        # forward the factorize-mode flags to every spawned worker (they
        # share this parser, so accepting-but-dropping them would silently
        # run a different execution path than the operator asked for)
        factorize_flags = []
        if args.mesh_2d:
            factorize_flags.append("--mesh-2d")
        if args.mesh_grid2d:
            factorize_flags.append("--mesh-grid2d")
        if args.sequential:
            factorize_flags.append("--sequential")
        if args.rowshard is not None:
            factorize_flags.append(
                "--rowshard" if args.rowshard else "--no-rowshard")
        factorize_flags += ["--rowshard-threshold",
                            str(args.rowshard_threshold)]
        if args.skip_completed_runs:
            factorize_flags.append("--skip-completed-runs")
        if args.per_k_programs:
            factorize_flags.append("--per-k-programs")

        run_pipeline(
            args.counts, args.output_dir, args.name,
            components=args.components, n_iter=args.n_iter,
            total_workers=max(args.total_workers, 1), seed=args.seed,
            numgenes=args.numgenes, genes_file=args.genes_file,
            tpm=args.tpm, beta_loss=args.beta_loss, init=args.init,
            max_nmf_iter=args.max_nmf_iter, batch_size=args.batch_size,
            engine=args.engine, devices_per_host=args.devices_per_host,
            clean=args.clean, factorize_flags=factorize_flags)
        return

    from .utils.envknobs import env_str

    if args.command == "factorize" and (
            args.distributed or env_str("CNMF_COORDINATOR_ADDRESS")):
        from .parallel import initialize_distributed

        pid, nproc = initialize_distributed(auto=args.distributed)
        print(f"jax.distributed: process {pid}/{nproc}")

    from .models.cnmf import cNMF

    cnmf_obj = cNMF(output_dir=args.output_dir, name=args.name)

    if args.command == "prepare":
        cnmf_obj.prepare(
            args.counts, components=args.components, n_iter=args.n_iter,
            densify=args.densify, tpm_fn=args.tpm, seed=args.seed,
            beta_loss=args.beta_loss, max_NMF_iter=args.max_nmf_iter,
            num_highvar_genes=args.numgenes, genes_file=args.genes_file,
            init=args.init, total_workers=args.total_workers,
            use_gpu=args.use_gpu, batch_size=args.batch_size)

    elif args.command == "factorize":
        from .runtime.resilience import (UNHEALTHY_EXIT_CODE,
                                         UnhealthySweepError)

        try:
            cnmf_obj.factorize(
                worker_i=args.worker_index,
                total_workers=max(args.total_workers, 1),
                skip_completed_runs=args.skip_completed_runs,
                batched=not args.sequential,
                mesh="2d" if args.mesh_2d else None,
                mesh_shape="grid2d" if args.mesh_grid2d else None,
                rowshard=args.rowshard,
                rowshard_threshold=args.rowshard_threshold,
                packed=False if args.per_k_programs else None)
        except UnhealthySweepError as exc:
            # a distinct exit code: the launcher must NOT respawn (the
            # derived retry seeds are deterministic — a rerun fails
            # identically) and must NOT fall back to skip-missing combine
            # (that would produce the degraded consensus the
            # CNMF_TPU_MIN_HEALTHY_FRAC floor exists to prevent)
            import sys

            print(f"factorize: {exc}", file=sys.stderr)
            sys.exit(UNHEALTHY_EXIT_CODE)

    elif args.command == "combine":
        cnmf_obj.combine(components=args.components)

    elif args.command == "consensus":
        if args.components is None:
            run_params = load_df_from_npz(
                cnmf_obj.paths["nmf_replicate_parameters"])
            ks = sorted(set(run_params.n_components))
        else:
            ks = args.components
        dt = (0.5 if args.local_density_threshold is None
              else args.local_density_threshold)
        for k in ks:
            cnmf_obj.consensus(
                int(k), dt,
                args.local_neighborhood_size, args.show_clustering,
                args.build_reference, close_clustergram_fig=True)

    elif args.command == "k_selection_plot":
        cnmf_obj.k_selection_plot(close_fig=True)


if __name__ == "__main__":
    main()
