"""Remote object-store ShardStore backend (ISSUE 15): local-vs-remote
staging bit-parity (dense / CSR / ELL, ragged final slab), the transport
retry/backoff/hedge ladder under injected network faults, the crash-safe
read-through cache (LRU eviction, digest revalidation, partial-write
recovery), URI dispatch, and the degradation contract (warm cache serves
a down remote loudly; a cold miss raises ``RemoteStoreError``).

The remote endpoint is the in-repo stdlib fixture
(``utils/netstore.ObjectStoreServer``); network faults are injected
client-side via ``CNMF_TPU_FAULT_SPEC`` (``runtime/faults.py``), so the
same server serves every scenario.
"""

import os
import threading
import time
import warnings

import numpy as np
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu.utils.netstore import ObjectStoreServer
from cnmf_torch_tpu.utils.shardstore import (
    RemoteStoreError,
    TornShardError,
    open_shard_store,
    probe_shard_store,
    write_shard_store,
)
from cnmf_torch_tpu.utils.storebackend import (
    STORE_URI_ENV,
    LocalBackend,
    RemoteBackend,
    _reset_degraded_warnings,
    backend_counter_snapshot,
    backoff_delay,
    resolve_backend,
    store_cache_dir,
    store_retries,
)


def _dense(n=219, g=37, seed=0):
    return np.abs(np.random.default_rng(seed).random((n, g))
                  ).astype(np.float32)


def _csr(n=219, g=37, seed=1, density=0.15):
    X = sp.random(n, g, density=density, format="lil", random_state=seed)
    X[40:60, :] = 0.0
    X[n - 1, :] = 0.0
    return sp.csr_matrix(X).astype(np.float32)


def _set_spec(monkeypatch, spec):
    """Install a fault spec with a parse-cache flush first: the cache is
    keyed on the raw env value, so re-using a spec string from an earlier
    test would otherwise inherit its exhausted fire counters."""
    from cnmf_torch_tpu.runtime import faults

    monkeypatch.setenv("CNMF_TPU_FAULT_SPEC", "")
    faults.maybe_netfault(op="flush", context="flush")
    monkeypatch.setenv("CNMF_TPU_FAULT_SPEC", spec)


@pytest.fixture()
def srv():
    with ObjectStoreServer() as s:
        yield s


@pytest.fixture()
def remote_env(srv, monkeypatch):
    monkeypatch.setenv(STORE_URI_ENV, srv.url + "/t")
    monkeypatch.setenv("CNMF_TPU_STORE_BACKOFF_S", "0.01")
    monkeypatch.delenv("CNMF_TPU_FAULT_SPEC", raising=False)
    _reset_degraded_warnings()
    yield srv
    _reset_degraded_warnings()


# ---------------------------------------------------------------------------
# local-vs-remote bit parity
# ---------------------------------------------------------------------------

def _write_both(tmp_path, monkeypatch, X, slab_rows=50):
    monkeypatch.delenv(STORE_URI_ENV, raising=False)
    write_shard_store(tmp_path / "local.store", X, slab_rows=slab_rows,
                      obs_names=[f"c{i}" for i in range(X.shape[0])],
                      var_names=[f"g{i}" for i in range(X.shape[1])])
    local = open_shard_store(tmp_path / "local.store")
    monkeypatch.setenv(STORE_URI_ENV, os.environ["_TEST_STORE_URL"])
    write_shard_store(tmp_path / "remote.store", X, slab_rows=slab_rows,
                      obs_names=[f"c{i}" for i in range(X.shape[0])],
                      var_names=[f"g{i}" for i in range(X.shape[1])])
    remote = open_shard_store(tmp_path / "remote.store")
    assert remote.backend.kind == "remote"
    return local, remote


@pytest.fixture()
def both_env(remote_env, monkeypatch):
    monkeypatch.setenv("_TEST_STORE_URL", remote_env.url + "/t")
    yield


def test_remote_bit_parity_dense_ragged(tmp_path, monkeypatch, both_env):
    X = _dense()  # 219 rows at 50/slab: ragged 19-row final slab
    local, remote = _write_both(tmp_path, monkeypatch, X)
    assert len(remote.slabs) == 5
    assert local.manifest["store_digest"] == remote.manifest["store_digest"]
    for i in range(len(local.slabs)):
        assert np.array_equal(np.asarray(local.read_slab(i)),
                              np.asarray(remote.read_slab(i)))
    assert local.obs_names() == remote.obs_names()
    assert local.var_names() == remote.var_names()


def test_remote_bit_parity_csr_zero_slab(tmp_path, monkeypatch, both_env):
    X = _csr()
    local, remote = _write_both(tmp_path, monkeypatch, X, slab_rows=20)
    assert remote.slabs[2]["nnz"] == 0  # the all-zero row band
    for i in range(len(local.slabs)):
        a, b = local.read_slab(i), remote.read_slab(i)
        assert np.array_equal(np.asarray(a.todense()),
                              np.asarray(b.todense()))


def test_remote_staging_bit_parity(tmp_path, monkeypatch, both_env):
    """The staged device arrays — dense rows and the ELL sparse layout —
    are bit-identical whether the slabs came over HTTP or from disk."""
    import jax
    from jax.sharding import Mesh

    from cnmf_torch_tpu.parallel.rowshard import (stream_ell_to_mesh,
                                                  stream_rows_to_mesh)

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("cells",))
    X = _csr()
    local, remote = _write_both(tmp_path, monkeypatch, X, slab_rows=60)
    A, pad_a = stream_rows_to_mesh(local, mesh, "cells")
    B, pad_b = stream_rows_to_mesh(remote, mesh, "cells")
    assert pad_a == pad_b
    assert np.array_equal(np.asarray(A), np.asarray(B))
    E1, pad1 = stream_ell_to_mesh(local, mesh, "cells")
    E2, pad2 = stream_ell_to_mesh(remote, mesh, "cells")
    assert pad1 == pad2 and E1.width == E2.width
    for leaf in ("vals", "cols", "rows_t", "perm_t"):
        assert np.array_equal(np.asarray(getattr(E1, leaf)),
                              np.asarray(getattr(E2, leaf)))


# ---------------------------------------------------------------------------
# retry / backoff / hedging
# ---------------------------------------------------------------------------

def test_backoff_delay_deterministic():
    a1 = backoff_delay("slab_00001.npz", 1, base=0.1)
    assert a1 == backoff_delay("slab_00001.npz", 1, base=0.1)
    # exponential in the attempt, decorrelated across objects
    assert backoff_delay("slab_00001.npz", 3, base=0.1) > a1
    assert a1 != backoff_delay("slab_00002.npz", 1, base=0.1)
    # jitter bounded: base * 2^(n-1) <= delay < 1.5x that
    assert 0.1 <= a1 < 0.15


def test_netflake_heals_with_retry(tmp_path, monkeypatch, remote_env):
    monkeypatch.setenv("CNMF_TPU_OOC_SLAB_ROWS", "32")
    X = _dense(100, 20)
    write_shard_store(tmp_path / "st", X)
    _set_spec(monkeypatch, "netflake:context=get:slab")
    store = open_shard_store(tmp_path / "st")
    (block,) = store._load_arrays(store.slabs[0]["file"], refresh=True)
    assert np.array_equal(block, X[:32])
    snap = backend_counter_snapshot(store)
    assert snap["retries"] >= 1 and snap["healed"] >= 1


def test_netdown_exhausts_budget_with_named_error(tmp_path, monkeypatch,
                                                  remote_env):
    monkeypatch.setenv("CNMF_TPU_STORE_RETRIES", "2")
    monkeypatch.setenv("CNMF_TPU_STORE_CACHE_BYTES", "0")
    X = _dense(64, 10)
    write_shard_store(tmp_path / "st", X)
    store = open_shard_store(tmp_path / "st")
    _set_spec(monkeypatch, "netdown:context=get:slab")
    with pytest.raises(RemoteStoreError) as ei:
        store.read_slab(0)
    msg = str(ei.value)
    # actionable: names the retry/timeout/URI knobs and the attempt count
    assert "CNMF_TPU_STORE_RETRIES" in msg
    assert "CNMF_TPU_STORE_URI" in msg
    assert "3 attempt(s)" in msg
    # NOT an OSError: must escape the shard reader's disk-reread ladder
    assert not isinstance(ei.value, OSError)


def test_hedge_wins_against_slow_primary(tmp_path, monkeypatch, remote_env):
    monkeypatch.setenv("CNMF_TPU_OOC_SLAB_ROWS", "32")
    monkeypatch.setenv("CNMF_TPU_STORE_HEDGE_S", "0.1")
    X = _dense(64, 10)
    write_shard_store(tmp_path / "st", X)
    store = open_shard_store(tmp_path / "st")
    # only the FIRST slab GET stalls (netslow default limit is one
    # firing); the hedge issued after 0.1 s answers at full speed
    _set_spec(monkeypatch, "netslow:context=get:slab,seconds=3")
    t0 = time.perf_counter()
    raw = store.backend.get(store.slabs[0]["file"], refresh=True)
    waited = time.perf_counter() - t0
    assert raw and waited < 2.0  # did not sit out the 3 s stall
    snap = backend_counter_snapshot(store)
    assert snap["hedges"] == 1 and snap["hedges_won"] == 1


def test_nettorn_response_healed_by_reread(tmp_path, monkeypatch,
                                           remote_env):
    monkeypatch.setenv("CNMF_TPU_OOC_SLAB_ROWS", "32")
    X = _dense(64, 10)
    write_shard_store(tmp_path / "st", X)
    store = open_shard_store(tmp_path / "st")
    _set_spec(monkeypatch, "nettorn:context=get:slab")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        got = np.asarray(store.read_slab(0))
    assert np.array_equal(got, X[:32])
    assert any("re-reading" in str(x.message) for x in w)


def test_404_is_file_not_found_without_retry(remote_env, tmp_path):
    bk = RemoteBackend(remote_env.url + "/empty")
    with pytest.raises(FileNotFoundError):
        bk.get("nope.npz")
    assert backend_counter_snapshot(bk)["retries"] == 0
    assert bk.exists("nope.npz") is False


# ---------------------------------------------------------------------------
# read-through cache
# ---------------------------------------------------------------------------

def _cached_backend(srv, tmp_path, prefix="c"):
    return RemoteBackend(srv.url + "/" + prefix,
                         cache_dir=str(tmp_path / "cache"))


def test_cache_hit_skips_network(tmp_path, remote_env):
    bk = _cached_backend(remote_env, tmp_path)
    bk.put("a", b"payload-a")
    assert bk.get("a") == b"payload-a"     # miss -> fetch -> cache
    with remote_env.lock:
        remote_env.objects.clear()         # remote forgets the object
    assert bk.get("a") == b"payload-a"     # served from cache
    snap = backend_counter_snapshot(bk)
    assert snap["cache_hits"] == 1 and snap["cache_misses"] == 1


def test_cache_lru_eviction(tmp_path, remote_env, monkeypatch):
    monkeypatch.setenv("CNMF_TPU_STORE_CACHE_BYTES", "256")
    bk = _cached_backend(remote_env, tmp_path)
    for i in range(4):
        bk.put("o%d" % i, bytes([i]) * 100)
        bk.get("o%d" % i)
        time.sleep(0.02)  # distinct mtimes order the LRU sweep
    entries = [fn for fn in os.listdir(tmp_path / "cache")
               if not fn.endswith(".sha1")]
    # 4 x 100 B against a 256 B budget: oldest evicted, newest survives
    assert len(entries) <= 2 and "o3" in entries
    assert "o0" not in entries


def test_cache_digest_revalidation_discards_corruption(tmp_path,
                                                       remote_env):
    bk = _cached_backend(remote_env, tmp_path)
    bk.put("a", b"good-bytes")
    bk.get("a")
    entry = os.path.join(tmp_path / "cache", "a")
    with open(entry, "wb") as f:
        f.write(b"rotten-bytes")          # flip the entry, keep the sidecar
    assert bk.get("a") == b"good-bytes"   # mismatch -> drop -> refetch
    snap = backend_counter_snapshot(bk)
    assert snap["cache_hits"] == 0 and snap["cache_misses"] == 2


def test_cache_partial_write_is_a_miss(tmp_path, remote_env):
    """A crash mid-landing leaves an entry without its sidecar (or the
    sidecar without its entry): both shapes read as a miss, never as
    unvalidated bytes."""
    bk = _cached_backend(remote_env, tmp_path)
    bk.put("a", b"remote-truth")
    os.makedirs(tmp_path / "cache", exist_ok=True)
    with open(os.path.join(tmp_path / "cache", "a"), "wb") as f:
        f.write(b"orphan-no-sidecar")
    assert bk.get("a") == b"remote-truth"
    assert backend_counter_snapshot(bk)["cache_misses"] == 1


def test_crash_temps_swept(tmp_path, monkeypatch, remote_env):
    from cnmf_torch_tpu.utils.shardstore import sweep_store_temps

    monkeypatch.setenv("CNMF_TPU_OOC_SLAB_ROWS", "32")
    store_dir = tmp_path / "st"
    write_shard_store(store_dir, _dense(64, 10))
    cache_dir = store_cache_dir(store_dir)
    os.makedirs(cache_dir, exist_ok=True)
    orphan = os.path.join(cache_dir, "slab_00000.npz.tmp-12345")
    with open(orphan, "wb") as f:
        f.write(b"partial")
    swept = sweep_store_temps(store_dir)
    assert not os.path.exists(orphan) and swept >= 1


# ---------------------------------------------------------------------------
# degradation contract
# ---------------------------------------------------------------------------

def test_down_remote_serves_warm_cache_loudly(tmp_path, monkeypatch,
                                              remote_env):
    monkeypatch.setenv("CNMF_TPU_OOC_SLAB_ROWS", "32")
    monkeypatch.setenv("CNMF_TPU_STORE_RETRIES", "1")
    X = _dense(100, 20)
    write_shard_store(tmp_path / "st", X)
    warm = open_shard_store(tmp_path / "st")
    ref = [np.asarray(warm.read_slab(i)) for i in range(len(warm.slabs))]
    warm.obs_names()
    _set_spec(monkeypatch, "netdown:context=get:")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        store = open_shard_store(tmp_path / "st")
        got = [np.asarray(store.read_slab(i))
               for i in range(len(store.slabs))]
    for a, b in zip(got, ref):
        assert np.array_equal(a, b)
    snap = backend_counter_snapshot(store)
    assert snap["degraded_reads"] >= 1
    loud = [x for x in w if issubclass(x.category, RuntimeWarning)
            and "DEGRADED" in str(x.message)]
    assert len(loud) == 1  # once per run, not once per slab


def test_probe_missing_vs_down(tmp_path, monkeypatch, remote_env):
    # absent store probes as a clean miss through the backend
    store, reason = probe_shard_store(tmp_path / "absent.store")
    assert store is None and reason == "missing"
    # a DOWN remote with a cold cache is not "missing": the probe's
    # exists() raises the named error instead of silently re-preparing
    monkeypatch.setenv("CNMF_TPU_STORE_RETRIES", "0")
    monkeypatch.setenv("CNMF_TPU_STORE_CACHE_BYTES", "0")
    _set_spec(monkeypatch, "netdown:context=head:")
    with pytest.raises(RemoteStoreError):
        probe_shard_store(tmp_path / "absent.store")


def test_no_lingering_threads_after_failure(tmp_path, monkeypatch,
                                            remote_env):
    monkeypatch.setenv("CNMF_TPU_STORE_RETRIES", "0")
    monkeypatch.setenv("CNMF_TPU_STORE_CACHE_BYTES", "0")
    monkeypatch.setenv("CNMF_TPU_STORE_HEDGE_S", "0.05")
    bk = RemoteBackend(remote_env.url + "/z",
                       cache_dir=str(tmp_path / "cache"))
    bk.put("a", b"x")
    _set_spec(monkeypatch, "netslow:context=get:a,seconds=1")
    assert bk.get("a", refresh=True) == b"x"  # hedge wins the stall
    time.sleep(1.2)  # let the abandoned primary drain
    lingering = [t for t in threading.enumerate()
                 if t.name.startswith("cnmf-store")]
    assert not lingering


# ---------------------------------------------------------------------------
# dispatch + knob validation
# ---------------------------------------------------------------------------

def test_uri_dispatch(tmp_path, monkeypatch):
    sd = str(tmp_path / "x.store")
    monkeypatch.delenv(STORE_URI_ENV, raising=False)
    bk = resolve_backend(sd)
    assert isinstance(bk, LocalBackend) and bk.root == sd
    # file:// relocates the store under <base>/<leaf>
    bk = resolve_backend(sd, uri="file://%s/alt" % tmp_path)
    assert isinstance(bk, LocalBackend)
    assert bk.root == os.path.join(str(tmp_path), "alt", "x.store")
    # http(s) namespaces by leaf and hangs the cache beside the store
    bk = resolve_backend(sd, uri="http://h:9/pfx")
    assert isinstance(bk, RemoteBackend)
    assert bk.base == "http://h:9/pfx/x.store"
    assert bk.cache_dir == sd + ".cache"
    # env fallback
    monkeypatch.setenv(STORE_URI_ENV, "https://h:9/p")
    assert resolve_backend(sd).kind == "remote"
    with pytest.raises(ValueError, match="CNMF_TPU_STORE_URI"):
        resolve_backend(sd, uri="s3://unsupported")


def test_knob_validation_one_line_errors(monkeypatch):
    monkeypatch.setenv("CNMF_TPU_STORE_RETRIES", "many")
    with pytest.raises(ValueError, match="CNMF_TPU_STORE_RETRIES"):
        store_retries()
    monkeypatch.setenv("CNMF_TPU_STORE_RETRIES", "-1")
    with pytest.raises(ValueError, match="CNMF_TPU_STORE_RETRIES"):
        store_retries()


def test_remote_knobs_registered():
    from cnmf_torch_tpu.utils.envknobs import REGISTRY

    for knob in ("CNMF_TPU_STORE_URI", "CNMF_TPU_STORE_RETRIES",
                 "CNMF_TPU_STORE_BACKOFF_S", "CNMF_TPU_STORE_TIMEOUT_S",
                 "CNMF_TPU_STORE_HEDGE_S", "CNMF_TPU_STORE_CACHE_BYTES"):
        assert knob in REGISTRY
