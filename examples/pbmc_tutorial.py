"""Tutorial: PBMC-style end-to-end cNMF workflow from an .h5ad file.

The runnable equivalent of the reference's PBMC3k walkthrough
(`Tutorials/analyze_pbmc_example_data.ipynb`, which downloads the 10x PBMC3k
dataset; the dataset is not redistributable here, so a PBMC3k-SHAPED dataset
— 2,700 cells, sparse counts, ~10 planted immune-like programs, matched
library-size distribution — is simulated in-process). The workflow is the
reference's exactly:

1. write the counts as ``.h5ad`` (the tutorial's input format);
2. ``prepare``: TPM + 2,000 HVGs + variance normalization + seed ledger
   for K = 5..10 x n_iter replicates;
3. ``factorize`` all replicates (one batched TPU program per K here,
   vs. the notebook's GNU-parallel worker pool);
4. ``combine`` + ``k_selection_plot`` -> pick K at the stability elbow;
5. two-pass ``consensus`` (unfiltered 2.0 pass to read the distance
   histogram, then the 0.1-filtered pass — `Stepwise_Guide.md:98`);
6. ``load_results``: usages, z-score spectra, TPM spectra, top genes.

Run:  python examples/pbmc_tutorial.py [output_dir]
Takes ~2-4 minutes on one TPU chip or a few CPU cores.
"""

import os
import sys
import tempfile

import numpy as np
import pandas as pd

try:
    import cnmf_torch_tpu  # noqa: F401
except ImportError:  # uninstalled source checkout: use the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def simulate_pbmc_like(n_cells=2700, n_genes=3000, k_true=10, seed=3):
    """PBMC3k-shaped counts: a few dominant cell-identity programs plus
    minor activity programs, steep depth distribution, sparse."""
    rng = np.random.default_rng(seed)
    programs = rng.gamma(0.25, 1.0, size=(k_true, n_genes))
    block = n_genes // k_true
    for k in range(k_true):
        programs[k, k * block:(k + 1) * block] *= 10.0
    programs /= programs.sum(axis=1, keepdims=True)
    # identity-like usage: most cells dominated by one program
    usage = rng.dirichlet(np.full(k_true, 0.08), size=n_cells)
    depth = np.exp(rng.normal(7.6, 0.35, size=(n_cells, 1)))  # ~2k median
    counts = rng.poisson(usage @ programs * depth).astype(np.float32)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    return counts, usage, programs


def main(output_dir=None, n_cells=2700, n_genes=3000, n_iter=20,
         ks=None, k_final=None):
    import scipy.sparse as sp

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils.anndata_lite import AnnDataLite, write_h5ad

    output_dir = output_dir or tempfile.mkdtemp(prefix="cnmf_pbmc_")
    os.makedirs(output_dir, exist_ok=True)
    counts, usage_true, programs_true = simulate_pbmc_like(
        n_cells=n_cells, n_genes=n_genes)

    # the notebook starts from an .h5ad of raw counts — same here
    adata = AnnDataLite(
        X=sp.csr_matrix(counts),
        obs=pd.DataFrame(index=[f"cell_{i}" for i in range(n_cells)]),
        var=pd.DataFrame(index=[f"gene_{j}" for j in range(n_genes)]))
    counts_fn = os.path.join(output_dir, "pbmc_like_counts.h5ad")
    write_h5ad(counts_fn, adata)
    print(f"wrote {n_cells} x {n_genes} sparse counts -> {counts_fn}")

    ks = ks or list(range(5, 12))
    obj = cNMF(output_dir=output_dir, name="pbmc")
    obj.prepare(counts_fn, components=ks, n_iter=n_iter, seed=14,
                num_highvar_genes=2000)
    obj.factorize()            # the notebook's `cnmf factorize` worker pool
    obj.combine()
    obj.k_selection_plot(close_fig=True)
    print(f"K selection plot -> {obj.paths['k_selection_plot']}")

    # pick K the way the notebook does — at the stability (silhouette)
    # peak of the selection curve — unless the caller pinned one
    from cnmf_torch_tpu.utils import load_df_from_npz

    kstats = load_df_from_npz(obj.paths["k_selection_stats"])
    if k_final is None:
        k_final = int(kstats.loc[kstats["silhouette"].idxmax(), "k"])
    print(f"chosen K = {k_final} (stability peak)")

    # two-pass consensus at the chosen K (Stepwise_Guide.md:98): first pass
    # unfiltered to see the replicate-distance histogram, then filtered
    obj.consensus(k_final, density_threshold=2.0, show_clustering=True,
                  close_clustergram_fig=True)
    obj.consensus(k_final, density_threshold=0.1, show_clustering=True,
                  close_clustergram_fig=True)
    usage, scores, tpm_spectra, top_genes = obj.load_results(
        K=k_final, density_threshold=0.1)
    print(f"consensus usages {usage.shape}; z-score spectra {scores.shape}")
    print("top genes per program:\n", top_genes.iloc[:5, :].to_string())

    # sanity: recovered TPM spectra line up with planted programs (when
    # the chosen K is below the planted count, merged programs dilute the
    # tail correlations — require recovery for the top min(K, k_true))
    gene_idx = [int(g.split("_")[1]) for g in tpm_spectra.index]
    truth = programs_true[:, gene_idx]
    corr = np.corrcoef(np.vstack([truth, tpm_spectra.values.T]))[
        :truth.shape[0], truth.shape[0]:]
    best = np.sort(corr.max(axis=1))[::-1]
    print("planted-program best correlations:", np.round(best, 3))
    n_req = min(k_final, truth.shape[0]) - 1
    assert (best[:n_req] > 0.8).all(), "programs were not recovered"
    print(f"OK. Artifacts in {output_dir}/pbmc/")
    return best


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
