"""Execution-resilience runtime: fault injection + quarantine/retry.

Two modules, imported explicitly by their consumers (this package pulls
in no heavy dependencies at import time):

  * :mod:`.faults` — the deterministic fault-injection harness behind
    ``CNMF_TPU_FAULT_SPEC`` (NaN replicate lanes, worker SIGKILL, torn
    artifact files, failed device uploads). Stdlib-only; every hook is a
    no-op when the spec is unset.
  * :mod:`.resilience` — the recovery layer: per-replicate health
    evaluation, quarantine + reseeded retry bookkeeping
    (``ReplicateGuard``), torn-artifact validation for resume/combine,
    and the ``CNMF_TPU_MAX_RETRIES`` / ``CNMF_TPU_MIN_HEALTHY_FRAC``
    policy knobs.
"""
