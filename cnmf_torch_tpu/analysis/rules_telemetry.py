"""Telemetry-schema rule: emit sites are validated at lint time.

``utils/telemetry.py`` holds the ONE schema (``EVENT_TYPES`` — required
fields per event type) and ``validate_event`` enforces it in the tier-1
smoke; but a typo'd event type or a dropped required field only surfaces
when someone actually runs with ``CNMF_TPU_TELEMETRY=1`` and validates
the stream. This rule closes the gap statically for the common shape —
``events.emit("<literal type>", field=..., ...)``:

  * an event type not in ``EVENT_TYPES`` is rejected (``validate_event``
    would reject the line at runtime; the report renderer would drop it);
  * when every field is a plain keyword (no ``**splat``), a missing
    required field is rejected — with the caveat that ``None``-valued
    fields are omitted at emit time, which the static check cannot see
    (the runtime smoke still catches that case).
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding

COMMON_FIELDS = {"v", "t", "ts"}


def check(ctx: FileContext):
    from ..utils.telemetry import EVENT_TYPES

    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit" and node.args):
            continue
        etype = node.args[0]
        if not (isinstance(etype, ast.Constant)
                and isinstance(etype.value, str)):
            continue  # forwarding wrappers (telemetry.EventLog internals)
        if etype.value not in EVENT_TYPES:
            findings.append(ctx.finding(
                node, "telemetry-schema",
                f"unknown telemetry event type {etype.value!r} "
                f"(schema knows: {', '.join(sorted(EVENT_TYPES))})",
                "add the type to utils/telemetry.py EVENT_TYPES or fix "
                "the call"))
            continue
        if any(kw.arg is None for kw in node.keywords):
            continue  # **fields splat: field set is dynamic
        provided = {kw.arg for kw in node.keywords} | COMMON_FIELDS
        missing = sorted(set(EVENT_TYPES[etype.value]) - provided)
        if missing:
            findings.append(ctx.finding(
                node, "telemetry-schema",
                f"emit({etype.value!r}, ...) omits required field(s) "
                f"{', '.join(missing)} — validate_event rejects the line "
                "at runtime",
                "pass every field EVENT_TYPES requires for this type"))
    return findings
