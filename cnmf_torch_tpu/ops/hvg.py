"""Over-dispersed (high-variance) gene selection by Fano factor.

JAX reimplementation of ``get_highvar_genes_sparse`` / ``get_highvar_genes``
(``/root/reference/src/cnmf/cnmf.py:133-238``): genes are scored by the ratio
of their Fano factor (var/mean) to an expected-Fano line ``A^2 * mean + B^2``,
where ``A`` comes from the top-20-mean genes' coefficient of variation and
``B`` from the winsorized (10-90th percentile box) median Fano. Selection is
either top-``numgenes`` by ``fano_ratio`` or thresholded at
``T = 1 + std(fano in box)`` with a ``minimal_mean`` floor.

The moment pass is the only O(cells x genes) work and runs on device via
:func:`cnmf_torch_tpu.ops.stats.column_mean_var`; the scoring itself is
O(genes) and computed in one fused jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from .stats import column_mean_var

__all__ = ["highvar_genes"]


@functools.partial(jax.jit, static_argnames=("numgenes", "has_threshold"))
def _fano_scores(mean, var, numgenes, has_threshold, expected_fano_threshold,
                 minimal_mean):
    fano = var / mean

    # A: min CV among the 20 highest-mean genes (cnmf.py:144-145)
    top20 = jax.lax.top_k(mean, min(20, mean.shape[0]))[1]
    A = jnp.min(jnp.sqrt(var[top20]) / mean[top20])

    # winsor box: 10th-90th pctile in both mean and fano (cnmf.py:147-152).
    # NaN fano (zero-mean genes) never enters the box: comparisons are False.
    w_mean_low, w_mean_high = jnp.nanquantile(mean, jnp.array([0.10, 0.90]))
    w_fano_low, w_fano_high = jnp.nanquantile(fano, jnp.array([0.10, 0.90]))
    box = ((fano > w_fano_low) & (fano < w_fano_high)
           & (mean > w_mean_low) & (mean < w_mean_high))
    boxed_fano = jnp.where(box, fano, jnp.nan)
    fano_median = jnp.nanmedian(boxed_fano)
    B = jnp.sqrt(fano_median)

    expected_fano = (A ** 2) * mean + (B ** 2)
    fano_ratio = fano / expected_fano

    if numgenes is not None:
        # top-N selection; NaN ratios (zero-mean genes) sort last
        score = jnp.where(jnp.isnan(fano_ratio), -jnp.inf, fano_ratio)
        idx = jax.lax.top_k(score, numgenes)[1]
        high_var = jnp.zeros(mean.shape, dtype=bool).at[idx].set(True)
        T = jnp.nan
    else:
        if has_threshold:
            T = expected_fano_threshold
        else:
            # pandas .std() on the boxed fano = sample std, ddof=1 (cnmf.py:167)
            nbox = jnp.sum(box)
            mu = jnp.nanmean(boxed_fano)
            ssq = jnp.nansum((boxed_fano - mu) ** 2)
            T = 1.0 + jnp.sqrt(ssq / jnp.maximum(nbox - 1, 1))
        high_var = (fano_ratio > T) & (mean > minimal_mean)

    return fano, expected_fano, fano_ratio, high_var, A, B, T


def highvar_genes(X, expected_fano_threshold=None, minimal_mean: float = 0.5,
                  numgenes: int | None = None):
    """Score genes for over-dispersion; X is cells x genes (sparse or dense).

    Returns ``(gene_stats, params)`` with the same schema as the reference:
    ``gene_stats`` has columns [mean, var, fano, expected_fano, high_var,
    fano_ratio]; ``params`` is ``{'A','B','T','minimal_mean'}``.

    The reference's sparse path uses population variance (ddof=0 via
    StandardScaler, cnmf.py:138) and its dense path likewise (ddof=0,
    cnmf.py:192); both map to one kernel here.
    """
    mean, var = column_mean_var(X, ddof=0)
    mean = jnp.asarray(mean, dtype=jnp.float32)
    var = jnp.asarray(var, dtype=jnp.float32)
    # mirrors the reference's truthiness test `if not expected_fano_threshold`
    # (cnmf.py:166): None or 0.0 both fall back to the computed T
    has_threshold = bool(expected_fano_threshold)
    fano, expected_fano, fano_ratio, high_var, A, B, T = _fano_scores(
        mean, var,
        None if numgenes is None else min(int(numgenes), X.shape[1]),
        has_threshold,
        jnp.float32(expected_fano_threshold if has_threshold else 0.0),
        jnp.float32(minimal_mean),
    )
    gene_stats = pd.DataFrame({
        "mean": np.asarray(mean, dtype=np.float64),
        "var": np.asarray(var, dtype=np.float64),
        "fano": np.asarray(fano, dtype=np.float64),
        "expected_fano": np.asarray(expected_fano, dtype=np.float64),
        "high_var": np.asarray(high_var),
        "fano_ratio": np.asarray(fano_ratio, dtype=np.float64),
    })
    params = {
        "A": float(A), "B": float(B),
        "T": None if numgenes is not None else float(T),
        "minimal_mean": minimal_mean,
    }
    return gene_stats, params
