"""Multi-host story: 2-D (replicates x cells) mesh, jax.distributed across
processes, and the run_parallel launcher (the reference's
``Extras/run_parallel.py:1-70`` orchestration contract).

The in-process tests run on the conftest 8-device virtual CPU mesh; the
process-level tests spawn real OS processes that form a 2-process x
4-device distributed program (a simulated 2-host pod), which is how the
multi-host path is CI-tested without TPU-pod hardware (SURVEY.md §4).
"""

import os
import subprocess
import sys

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from cnmf_torch_tpu.parallel import mesh_2d, replicate_sweep_2d
from cnmf_torch_tpu.parallel.multihost import _balanced_rc
from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the mesh-geometry assertions ([2, 4] shapes, _balanced_rc(8, ...)) and
# the spawned 2-process x 4-device pods are written against the canonical
# 8-device conftest mesh; under scripts/verify_tier1.sh <N != 8> (used to
# exercise the staging parity tests in a second geometry) they would fail
# on geometry, not behavior — skip instead
pytestmark = pytest.mark.skipif(
    len(jax.devices()) != 8,
    reason="multihost geometry tests assume the canonical 8-device mesh")


def _fixture_X(n=64, g=24, seed=123):
    rng = np.random.default_rng(seed)
    return (rng.gamma(0.8, 1.0, size=(n, g)) *
            rng.binomial(1, 0.4, size=(n, g))).astype(np.float32)


def test_balanced_rc():
    assert _balanced_rc(8, 1) == (2, 4)      # square-ish, cells larger
    assert _balanced_rc(8, 2) == (2, 4)      # one replicate shard per host
    assert _balanced_rc(16, 4) == (4, 4)
    assert _balanced_rc(7, 1) == (1, 7)      # prime: all cells
    assert _balanced_rc(8, 3) == (2, 4)      # non-dividing host count


def test_initialize_distributed_guards(monkeypatch):
    """No-op single-process path must not latch (a later call with real
    coordinates still initializes), and partial coordinates — e.g. a stale
    CNMF_COORDINATOR_ADDRESS in the env — fail loud instead of hanging in
    jax.distributed.initialize."""
    from cnmf_torch_tpu.parallel import initialize_distributed
    from cnmf_torch_tpu.parallel import multihost

    for var in ("CNMF_COORDINATOR_ADDRESS", "CNMF_NUM_PROCESSES",
                "CNMF_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(multihost, "_initialized", False)
    pid, nproc = initialize_distributed()
    assert (pid, nproc) == (0, 1)
    assert multihost._initialized is False  # no latch on the no-op path

    monkeypatch.setenv("CNMF_COORDINATOR_ADDRESS", "127.0.0.1:1")
    with pytest.raises(ValueError, match="all three"):
        initialize_distributed()


def test_mesh_2d_axes():
    mesh = mesh_2d()
    assert mesh.axis_names == ("replicates", "cells")
    assert int(np.prod(mesh.devices.shape)) == len(jax.devices())
    with pytest.raises(ValueError):
        mesh_2d(replicate_shards=3)  # does not divide 8


@pytest.mark.parametrize("beta_loss", ["frobenius", "kullback-leibler"])
def test_sweep2d_matches_rowsharded_per_seed(beta_loss):
    """Each 2-D replicate must solve the same program as the 1-D row-sharded
    solver: same seeded init, same pass loop, same cells-shard boundaries
    (4 shards both ways) -> near-identical spectra."""
    X = _fixture_X()
    mesh2 = mesh_2d(replicate_shards=2)          # (2, 4)
    seeds = [11, 22, 33]
    spectra, errs = replicate_sweep_2d(
        X, seeds, k=3, mesh=mesh2, beta_loss=beta_loss, tol=1e-5,
        n_passes=30)
    assert spectra.shape == (3, 3, 24) and errs.shape == (3,)

    flat4 = Mesh(np.asarray(jax.devices()[:4]), ("cells",))
    for r, s in enumerate(seeds):
        _H, W_ref, err_ref = nmf_fit_rowsharded(
            X, 3, flat4, beta_loss=beta_loss, seed=s, tol=1e-5, n_passes=30)
        np.testing.assert_allclose(spectra[r], W_ref, rtol=2e-3, atol=2e-4)
        assert abs(errs[r] - err_ref) / max(err_ref, 1e-9) < 1e-3


def test_sweep2d_replicate_padding():
    """R not divisible by the replicate axis: pad replicates recompute
    existing seeds and are dropped from the result."""
    X = _fixture_X()
    mesh2 = mesh_2d(replicate_shards=2)
    spectra, errs = replicate_sweep_2d(X, [7, 8, 9], k=2, mesh=mesh2,
                                       n_passes=10)
    assert spectra.shape == (3, 2, 24)
    spectra2, _ = replicate_sweep_2d(X, [7], k=2, mesh=mesh2, n_passes=10)
    np.testing.assert_allclose(spectra[0], spectra2[0], rtol=1e-5)


def test_sweep2d_memory_bounded_slicing():
    """replicates_per_batch slices a wide sweep into replicate-shard-multiple
    batches (the 1-D path's OOM guard, now shared): sliced and unsliced
    sweeps must agree replicate-for-replicate."""
    X = _fixture_X()
    mesh2 = mesh_2d(replicate_shards=2)
    seeds = [3, 1, 4, 1, 5, 9]
    full, errs_full = replicate_sweep_2d(X, seeds, k=2, mesh=mesh2,
                                         n_passes=10)
    sliced, errs_sl = replicate_sweep_2d(X, seeds, k=2, mesh=mesh2,
                                         n_passes=10, replicates_per_batch=2)
    np.testing.assert_allclose(sliced, full, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(errs_sl, errs_full, rtol=1e-5)


def test_sweep2d_nndsvd_init():
    X = _fixture_X()
    mesh2 = mesh_2d(replicate_shards=2)
    spectra, errs = replicate_sweep_2d(X, [5, 6], k=3, mesh=mesh2,
                                       init="nndsvd", n_passes=10)
    assert np.isfinite(errs).all() and (spectra >= 0).all()
    # seeded nndsvdar fill: replicates must differ (consensus non-vacuous)
    assert np.abs(spectra[0] - spectra[1]).max() > 1e-6


def test_factorize_mesh2d_pipeline(tmp_path):
    """factorize(mesh='2d') produces the standard artifact contract and
    consensus runs downstream — the dryrun layout is now reachable from the
    pipeline (VERDICT r2 gap #1)."""
    import pandas as pd
    import scipy.sparse as sp

    from cnmf_torch_tpu.models.cnmf import cNMF
    from cnmf_torch_tpu.utils.io import load_df_from_npz

    rng = np.random.default_rng(0)
    counts = sp.csr_matrix(rng.binomial(40, 0.02, size=(80, 120)).astype(
        np.float64))
    counts_fn = str(tmp_path / "counts.df.npz")
    df = pd.DataFrame(counts.toarray(),
                      index=[f"c{i}" for i in range(80)],
                      columns=[f"g{j}" for j in range(120)])
    from cnmf_torch_tpu.utils.io import save_df_to_npz

    save_df_to_npz(df, counts_fn)

    obj = cNMF(output_dir=str(tmp_path), name="m2d")
    obj.prepare(counts_fn, components=[3], n_iter=4, seed=9,
                num_highvar_genes=60, total_workers=1)
    obj.factorize(mesh="2d")
    for it in range(4):
        assert os.path.exists(obj.paths["iter_spectra"] % (3, it))
    obj.combine()
    merged = load_df_from_npz(obj.paths["merged_spectra"] % 3)
    assert merged.shape[0] == 12  # 4 iters x k=3
    obj.consensus(3, density_threshold=2.0, show_clustering=False,
                  build_ref=False)
    assert os.path.exists(obj.paths["consensus_spectra"] % (3, "2_0"))
    # provenance records the engaged 2-D path
    import yaml

    prov = yaml.safe_load(open(obj.paths["factorize_provenance"] % 0))
    assert prov["engaged_path"] == "mesh2d"
    assert prov["effective_params"]["mesh_shape"] == [2, 4]


# ---------------------------------------------------------------------------
# failure paths (ISSUE 6): missing-host barrier timeout, relaunch-from-
# checkpoint — runnable under simulated devices in tier-1
# ---------------------------------------------------------------------------


def test_barrier_timeout_watchdog():
    """A barrier a dead host can never join must become a clean
    HostBarrierTimeout within the deadline, not a distributed hang; a
    completing barrier passes through, and a failing one propagates its
    own error."""
    import threading
    import time

    from cnmf_torch_tpu.parallel.multihost import (HostBarrierTimeout,
                                                   _wait_with_timeout)

    t0 = time.monotonic()
    with pytest.raises(HostBarrierTimeout, match="resume"):
        _wait_with_timeout(lambda: threading.Event().wait(5.0), 0.2, "dead")
    assert time.monotonic() - t0 < 2.0

    done = []
    _wait_with_timeout(lambda: done.append(1), 5.0, "ok")
    assert done == [1]
    _wait_with_timeout(lambda: done.append(2), 0.0, "inline")  # 0 = no watchdog
    assert done == [1, 2]

    def boom():
        raise RuntimeError("collective failed")

    with pytest.raises(RuntimeError, match="collective failed"):
        _wait_with_timeout(boom, 5.0, "err")


def test_barrier_timeout_knob_validation(monkeypatch):
    from cnmf_torch_tpu.parallel.multihost import (BARRIER_TIMEOUT_ENV,
                                                   barrier_timeout_s)

    monkeypatch.delenv(BARRIER_TIMEOUT_ENV, raising=False)
    assert barrier_timeout_s() == 0.0
    monkeypatch.setenv(BARRIER_TIMEOUT_ENV, "12.5")
    assert barrier_timeout_s() == 12.5
    for bad in ("-1", "forever"):
        monkeypatch.setenv(BARRIER_TIMEOUT_ENV, bad)
        with pytest.raises(ValueError, match=BARRIER_TIMEOUT_ENV):
            barrier_timeout_s()


def test_rowshard_relaunch_resumes_from_checkpoint(tmp_path):
    """The multihost recovery protocol end-to-end at worker granularity: a
    factorize worker SIGKILLed mid-pass (kill:stage=pass fires AFTER a
    checkpoint write lands) leaves a valid pass checkpoint; relaunching
    with --skip-completed-runs resumes MID-RUN (checkpoint `resume`
    telemetry event, not from scratch) and reproduces the uninterrupted
    run's spectra bit-for-bit (H rides the checkpoint at this scale)."""
    import glob
    import warnings

    import pandas as pd
    import scipy.sparse as sp

    from cnmf_torch_tpu.models.cnmf import cNMF
    from cnmf_torch_tpu.utils.io import load_df_from_npz, save_df_to_npz
    from cnmf_torch_tpu.utils.telemetry import read_events

    rng = np.random.default_rng(8)
    counts = sp.csr_matrix(
        rng.binomial(40, 0.02, size=(60, 100)).astype(np.float64))
    df = pd.DataFrame(counts.toarray(),
                      index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, counts_fn)

    prep = dict(components=[3], n_iter=2, seed=4, num_highvar_genes=50,
                total_workers=1)
    clean = cNMF(output_dir=str(tmp_path), name="ckclean")
    clean.prepare(counts_fn, **prep)
    clean.factorize(rowshard=True)

    killed = cNMF(output_dir=str(tmp_path), name="ckkill")
    killed.prepare(counts_fn, **prep)
    sentinel = str(tmp_path / "pass_kill.done")
    env = dict(os.environ, JAX_PLATFORMS="cpu", CNMF_TPU_TELEMETRY="1",
               CNMF_TPU_FAULT_SPEC="kill:stage=pass,after=3,once=" + sentinel,
               PYTHONPATH=os.pathsep.join(
                   [REPO, os.environ.get("PYTHONPATH", "")]))
    p = _spawn([sys.executable, "-m", "cnmf_torch_tpu", "factorize",
                "--output-dir", str(tmp_path), "--name", "ckkill",
                "--rowshard"], env)
    (out,) = _wait_all([p])
    assert p.returncode not in (0,), out     # SIGKILLed mid-pass
    assert os.path.exists(sentinel), out
    ckpts = glob.glob(str(tmp_path / "ckkill" / "cnmf_tmp" / "*.ckpt.*"))
    assert len(ckpts) == 1, (ckpts, out)     # the interrupted replicate's

    os.environ["CNMF_TPU_TELEMETRY"] = "1"
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            killed.factorize(rowshard=True, skip_completed_runs=True)
    finally:
        del os.environ["CNMF_TPU_TELEMETRY"]

    ev = read_events(str(tmp_path / "ckkill" / "cnmf_tmp"
                         / "ckkill.events.jsonl"))
    resumes = [e for e in ev
               if e["t"] == "checkpoint" and e["action"] == "resume"]
    assert resumes and resumes[0]["context"]["pass_idx"] >= 1, \
        "relaunch did not resume from the checkpoint"
    # checkpoints discarded once replicates completed
    assert not glob.glob(str(tmp_path / "ckkill" / "cnmf_tmp" / "*.ckpt.*"))

    for it in range(2):
        a = load_df_from_npz(clean.paths["iter_spectra"] % (3, it)).values
        b = load_df_from_npz(killed.paths["iter_spectra"] % (3, it)).values
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# process-level: a real jax.distributed program across 2 OS processes
# ---------------------------------------------------------------------------


def _spawn(cmd, env):
    return subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)


def _wait_all(procs, timeout=600):
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outs.append(out.decode(errors="replace"))
    return outs


from cnmf_torch_tpu.launcher import _free_port  # noqa: E402  (shared helper)


def test_two_process_distributed_sweep(tmp_path):
    """2 processes x 4 virtual devices stitch into one 8-device program via
    jax.distributed; the 2-D sweep's results match a single-process run of
    the same mesh shape bit-for-tolerance. Proves: cross-process init,
    global mesh construction, cells-psum collectives, process_allgather
    fetch, coordinator-only IO."""
    out = str(tmp_path / "dist_result.npz")
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ,
                   CNMF_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   CNMF_NUM_PROCESSES="2", CNMF_PROCESS_ID=str(pid),
                   CNMF_SIM_CPU_DEVICES="4")
        procs.append(_spawn(
            [sys.executable, os.path.join("tests", "multihost_worker.py"),
             out], env))
    outs = _wait_all(procs)
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o
    assert os.path.exists(out), outs[0]

    got = np.load(out)
    assert tuple(got["mesh_shape"]) == (2, 4)

    # single-process reference on the same (2, 4) mesh shape
    X = _fixture_X()
    mesh2 = mesh_2d(replicate_shards=2)
    spectra, errs = replicate_sweep_2d(
        X, seeds=[11, 22, 33, 44], k=3, mesh=mesh2, beta_loss="frobenius",
        tol=1e-5, n_passes=30)
    np.testing.assert_allclose(got["spectra"], spectra, rtol=2e-3,
                               atol=2e-4)
    np.testing.assert_allclose(got["errs"], errs, rtol=1e-3)


@pytest.mark.parametrize("engine,workers,extra", [
    ("subprocess", 2, []),
    ("subprocess", 1, ["--mesh-2d"]),   # factorize-mode flag forwarding
    ("multihost", 2, ["--devices-per-host", "2"]),
])
def test_run_parallel_launcher(tmp_path, engine, workers, extra):
    """The reference orchestration contract (run_parallel.py:1-70): one
    command does prepare -> parallel factorize -> combine ->
    k_selection_plot, with per-replicate files cleaned after merge."""
    import pandas as pd

    rng = np.random.default_rng(1)
    df = pd.DataFrame(rng.binomial(40, 0.02, size=(60, 100)).astype(float),
                      index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    from cnmf_torch_tpu.utils.io import save_df_to_npz

    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, counts_fn)

    env = dict(os.environ, CNMF_SIM_CPU_DEVICES="2")
    cmd = [sys.executable, "-m", "cnmf_torch_tpu", "run_parallel",
           "--output-dir", str(tmp_path), "--name", "launch",
           "--counts", counts_fn, "-k", "3", "4", "--n-iter", "3",
           "--total-workers", str(workers), "--seed", "4",
           "--numgenes", "50", "--engine", engine, "--clean"] + extra
    p = _spawn(cmd, env)
    (out,) = _wait_all([p])
    assert p.returncode == 0, out

    base = tmp_path / "launch"
    assert (base / "launch.k_selection.png").exists(), out
    for k in (3, 4):
        assert (base / "cnmf_tmp" / f"launch.spectra.k_{k}.merged.df.npz"
                ).exists(), out
    # --clean removed the per-replicate files after merge
    import glob

    assert not glob.glob(str(base / "cnmf_tmp" / "*.iter_*.df.npz"))

    # the workers' provenance must reflect the forwarded execution mode
    import yaml

    prov = yaml.safe_load(
        open(base / "cnmf_tmp" / "launch.factorize_provenance.w0.yaml"))
    if "--mesh-2d" in extra or engine == "multihost":
        assert prov["engaged_path"] == "mesh2d", out
    else:
        assert prov["engaged_path"] == "batched", out


def test_run_parallel_dead_worker_tolerance(tmp_path):
    """Kill one of two subprocess factorize workers mid-run and assert the
    launcher completes end-to-end on the survivor's replicates — the
    reference's dead-worker contract (combine(skip_missing_files=True),
    cnmf.py:904-909 / README.md:117) at the CLI level."""
    import pandas as pd

    from cnmf_torch_tpu.utils.io import load_df_from_npz, save_df_to_npz

    rng = np.random.default_rng(2)
    df = pd.DataFrame(rng.binomial(40, 0.02, size=(60, 100)).astype(float),
                      index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, counts_fn)

    # poison sitecustomize: any worker whose argv carries the targeted
    # --worker-index dies instantly (simulating a preempted/crashed node);
    # every other process (parent included) continues on the CPU backend
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "sitecustomize.py").write_text(
        "import os, sys\n"
        "kill = os.environ.get('CNMF_TEST_KILL_WORKER')\n"
        "argv = sys.argv\n"
        "if kill is not None and '--worker-index' in argv:\n"
        "    if argv[argv.index('--worker-index') + 1] == kill:\n"
        "        os._exit(17)\n")

    env = dict(os.environ, CNMF_TEST_KILL_WORKER="1",
               PYTHONPATH=os.pathsep.join(
                   [str(poison), os.environ.get("PYTHONPATH", "")]),
               JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "cnmf_torch_tpu", "run_parallel",
           "--output-dir", str(tmp_path), "--name", "deadw",
           "--counts", counts_fn, "-k", "3", "--n-iter", "4",
           "--total-workers", "2", "--seed", "4", "--numgenes", "50",
           "--engine", "subprocess"]
    p = _spawn(cmd, env)
    (out,) = _wait_all([p])
    assert p.returncode == 0, out

    base = tmp_path / "deadw"
    # worker 1 owned the odd ledger rows; only worker 0's replicates merged
    merged = load_df_from_npz(
        str(base / "cnmf_tmp" / "deadw.spectra.k_3.merged.df.npz"))
    assert merged.shape[0] == 2 * 3  # 2 surviving replicates x k rows
    assert (base / "deadw.k_selection.png").exists(), out
