#!/usr/bin/env bash
# Canonical tier-1 gate (ROADMAP.md "Tier-1 verify") — builders and CI call
# THIS, not a hand-copied pytest line, so the marker filter, plugin
# disables, and the DOTS_PASSED count stay in one place.
#
# Usage: scripts/verify_tier1.sh [device_count]
#   device_count  optional simulated CPU device count (sets
#                 --xla_force_host_platform_device_count BEFORE conftest
#                 runs; conftest defaults to 8 when unset). Run once with 4
#                 to exercise the multi-device staging parity tests in a
#                 second mesh geometry.
set -o pipefail
cd "$(dirname "$0")/.."

if [ -n "${1:-}" ]; then
  case "$1" in
    ''|*[!0-9]*) echo "device_count must be an integer, got: $1" >&2; exit 2 ;;
  esac
  export XLA_FLAGS="${XLA_FLAGS:+$XLA_FLAGS }--xla_force_host_platform_device_count=$1"
fi

# static-analysis gate first (scripts/lint_gate.py): sub-second, no jax —
# zero unbaselined `cnmf-tpu lint` findings across the package (trace
# safety, knob hygiene + README knob-table drift, artifact atomicity,
# telemetry schema, lock discipline)
echo "[tier1] lint gate (cnmf-tpu lint cnmf_torch_tpu/) ..."
if python scripts/lint_gate.py; then
  echo LINT_GATE=ok
else
  echo LINT_GATE=fail
  exit 1
fi

LOG="${TIER1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
  -p no:randomly 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)

# telemetry smoke: run the mini pipeline with telemetry enabled and
# validate every emitted event line against the schema
# (scripts/telemetry_smoke.py) — malformed events fail the gate
if [ "$rc" -eq 0 ]; then
  echo "[tier1] telemetry smoke (schema-validated events.jsonl) ..."
  if timeout -k 10 300 env JAX_PLATFORMS=cpu CNMF_TPU_TELEMETRY=1 \
      python scripts/telemetry_smoke.py; then
    echo TELEMETRY_SMOKE=ok
  else
    echo TELEMETRY_SMOKE=fail
    exit 1
  fi
fi

# chaos smoke: run the mini pipeline once per injected fault class
# (nonfinite lane, killed worker, torn artifact, stalled shard upload,
# mid-pass kill + checkpoint resume, torn checkpoint, simulated host
# loss mid-sweep, straggler worker — scripts/chaos_smoke.py) and assert
# degraded-mode accounting: quarantine + derived-seed retry, respawn +
# bit-identical resumed consensus, torn-artifact detection, the stream
# stall watchdog, mid-run checkpoint resume (relaunch continues from the
# pass cursor, not from scratch), elastic degraded re-mesh with
# bit-identical consensus parity, and straggler-deadline containment +
# work-stealing adoption
if [ "$rc" -eq 0 ]; then
  echo "[tier1] chaos smoke (fault injection: nonfinite/kill/torn/stall/ckpt-kill/torn-ckpt/hostloss/straggler) ..."
  if timeout -k 10 900 env JAX_PLATFORMS=cpu \
      python scripts/chaos_smoke.py; then
    echo CHAOS_SMOKE=ok
  else
    echo CHAOS_SMOKE=fail
    exit 1
  fi
fi

# ooc smoke: mini pipeline with the slab budget forced below the fixture
# size — prepare writes the shard store, factorize streams every slab
# from disk, consensus + k_selection run their budget-bounded slab loops
# (host-residency peak asserted under the budget, no full-matrix
# assembly), and the merged spectra + consensus must be BIT-identical to
# the resident run; a shard_read-injected torn slab must be detected by
# the digest check and healed by a disk re-read (scripts/ooc_smoke.py)
if [ "$rc" -eq 0 ]; then
  echo "[tier1] ooc smoke (shard-store ingestion: bit parity + streamed consensus/k-selection + torn-slab re-read) ..."
  if timeout -k 10 600 env JAX_PLATFORMS=cpu \
      python scripts/ooc_smoke.py; then
    echo OOC_SMOKE=ok
  else
    echo OOC_SMOKE=fail
    exit 1
  fi
fi

# netstore smoke: mini pipeline against the in-repo HTTP object store
# under each injected network fault class (scripts/netstore_smoke.py) —
# netflake heals via transport retries (bit-identical), netslow's
# stalled read is won by the hedged request, netdown with a warm
# read-through cache completes degraded (one loud warning, bit-identical),
# and netdown with a cold cache fails fast with the named
# RemoteStoreError, ledger kind remote_store, no lingering threads
if [ "$rc" -eq 0 ]; then
  echo "[tier1] netstore smoke (remote store: netflake/netslow/netdown warm+cold) ..."
  if timeout -k 10 600 env JAX_PLATFORMS=cpu \
      python scripts/netstore_smoke.py; then
    echo NETSTORE_SMOKE=ok
  else
    echo NETSTORE_SMOKE=fail
    exit 1
  fi
fi

# accel parity smoke: a mini sweep under each solver recipe (plain MU /
# accelerated-MU / Diagonalized-Newton KL / HALS) asserting matched
# final objectives within tolerance and schema-valid dispatch +
# replicates events carrying the engaged recipe (scripts/accel_smoke.py)
if [ "$rc" -eq 0 ]; then
  echo "[tier1] accel parity smoke (solver recipes: mu/amu/dna/hals) ..."
  if timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python scripts/accel_smoke.py; then
    echo ACCEL_SMOKE=ok
  else
    echo ACCEL_SMOKE=fail
    exit 1
  fi
fi

# sketch parity smoke: the sketched-KL solver lane (dense + ELL) must
# match plain MU within its declared band with sketch-off programs
# lowering byte-identical to the defaults, and the sketched consensus
# stage (random-projected density filter + k-means) must reproduce the
# exact outlier set and cluster medians; emitted events carrying the
# sketch context must validate against the schema (scripts/sketch_smoke.py)
if [ "$rc" -eq 0 ]; then
  echo "[tier1] sketch parity smoke (sketched KL W updates + sketched consensus) ..."
  if timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python scripts/sketch_smoke.py; then
    echo SKETCH_SMOKE=ok
  else
    echo SKETCH_SMOKE=fail
    exit 1
  fi
fi

# pallas parity smoke: mini ELL beta=1 sweeps with the fused kernels
# off/on (interpret mode on this CPU gate) — knob-unset and knob=0 must
# share one cached program (byte-identical lowering, default == explicit
# off), forced-on must change the lowering and land within the accel
# objective band of the jnp ELL oracle, the engaged kernel label must
# ride schema-valid dispatch + replicates events, and bad knob words
# must fail loudly (scripts/pallas_smoke.py)
if [ "$rc" -eq 0 ]; then
  echo "[tier1] pallas parity smoke (fused ELL KL kernels: off-identity + interpret parity) ..."
  if timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python scripts/pallas_smoke.py; then
    echo PALLAS_SMOKE=ok
  else
    echo PALLAS_SMOKE=fail
    exit 1
  fi
fi

# plan smoke: three mini runs against one prepared counts fixture —
# the shipped auto defaults must record exactly ONE schema-valid `plan`
# telemetry event per factorize, `cnmf-tpu plan <run_dir>` must render
# and dump it, a CNMF_TPU_PLAN replay of the dumped JSON must reproduce
# the run bit-identically (same plan signature, byte-equal spectra),
# and the =0 escape hatches (ACCEL/PALLAS) must stay byte-identical to
# the auto defaults (scripts/plan_smoke.py)
if [ "$rc" -eq 0 ]; then
  echo "[tier1] plan smoke (execution planner: one plan event + --plan replay bit-parity + =0 escape hatch) ..."
  if timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python scripts/plan_smoke.py; then
    echo PLAN_SMOKE=ok
  else
    echo PLAN_SMOKE=fail
    exit 1
  fi
fi

# serve smoke: consensus-complete mini run served by the REAL daemon
# (CLI subprocess on a unix socket) under concurrent clients + one
# poison tenant — asserts cross-request batching engaged (telemetry
# batch sizes > 1), every projection bit-identical to solo refit_usage,
# poison isolated + quarantine-accounted, schema-valid serve events,
# clean shutdown with no orphaned sockets/temp files
# (scripts/serve_smoke.py)
if [ "$rc" -eq 0 ]; then
  echo "[tier1] serve smoke (projection daemon: batching + bit-parity + poison isolation) ..."
  if timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python scripts/serve_smoke.py; then
    echo SERVE_SMOKE=ok
  else
    echo SERVE_SMOKE=fail
    exit 1
  fi
fi

# fleet smoke: the replicated serving fleet under chaos
# (scripts/fleet_smoke.py, ISSUE 20) — the REAL fleet (CLI subprocess
# fronting two serve daemon subprocesses) under sustained multi-tenant
# load while a replica is SIGKILLed mid-load (failover + respawn, zero
# lost accepted requests), the reference rolls over to a v2 published
# through the remote ShardStore with one injected store outage (zero
# downtime: every reply bit-identical to solo refit_usage against v1 or
# v2, never mixed), and one tenant turns poisonous (quarantined AT THE
# ROUTER after 3 strikes, isolated from its neighbors) — then SLO not
# burning, schema-valid fleet events, clean shutdown with no orphans
if [ "$rc" -eq 0 ]; then
  echo "[tier1] fleet smoke (replica kill + rollover + poison quarantine under load) ..."
  if timeout -k 10 900 env JAX_PLATFORMS=cpu \
      python scripts/fleet_smoke.py; then
    echo FLEET_SMOKE=ok
  else
    echo FLEET_SMOKE=fail
    exit 1
  fi
fi

# obs smoke: the live observability plane end-to-end against real
# processes (scripts/obs_smoke.py) — concurrent tenants with a mid-load
# /metrics scrape that parses back, /stats reservoir-honesty fields, one
# request traced client->daemon across two processes and one launcher
# run traced parent->worker (both rendering `cnmf-tpu trace`
# waterfalls), SLO verdict flipping to degraded under an injected
# serve-dispatch straggler, schema-valid span/metrics_snapshot events,
# clean shutdowns with no orphaned sockets or threads
if [ "$rc" -eq 0 ]; then
  echo "[tier1] obs smoke (metrics scrape + cross-process tracing + SLO flip) ..."
  if timeout -k 10 600 env JAX_PLATFORMS=cpu \
      python scripts/obs_smoke.py; then
    echo OBS_SMOKE=ok
  else
    echo OBS_SMOKE=fail
    exit 1
  fi
fi

# perf gate: the continuous perf-regression lane (scripts/perf_gate.py,
# ISSUE 19) — measures a pinned dense MU lane min-of-N twice, asserts
# the noise-aware benchdiff machinery is green on the honest
# re-measurement AND red on an injected 2x lane slowdown (both
# end-to-end through `cnmf-tpu benchdiff`, exit 0/1), then gates
# against scripts/perf_baselines/<fingerprint>.json when one exists for
# this hardware (band CNMF_TPU_PERF_GATE_BAND, default +-60% to honor
# the oversubscribed-container noise floor)
if [ "$rc" -eq 0 ]; then
  echo "[tier1] perf gate (benchdiff self-test + fingerprint baseline) ..."
  if timeout -k 10 300 env JAX_PLATFORMS=cpu \
      python scripts/perf_gate.py; then
    echo PERF_GATE=ok
  else
    echo PERF_GATE=fail
    exit 1
  fi
fi
exit $rc
