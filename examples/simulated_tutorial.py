"""Tutorial: full cNMF analysis on simulated data with known ground truth.

The runnable equivalent of the reference's simulated-data walkthrough
(`Tutorials/analyze_simulated_example_data.ipynb`, whose scsim-based data is
downloaded; here the data is generated in-process so the tutorial is
self-contained). Simulates cells as mixtures of K_TRUE gene expression
programs, runs prepare -> factorize -> combine -> k_selection -> consensus,
and reports how well the consensus spectra recover the planted programs.

Run:  python examples/simulated_tutorial.py [output_dir]
Takes ~1-2 minutes on one TPU chip or a few CPU cores.
"""

import os
import sys
import tempfile

import numpy as np
import pandas as pd

try:
    import cnmf_torch_tpu  # noqa: F401
except ImportError:  # uninstalled source checkout: use the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def simulate_counts(n_cells=1000, n_genes=1500, k_true=6, seed=0):
    """Cells = Dirichlet mixtures of k_true gamma-shaped programs, counts
    Poisson-sampled — the same generative idea as the scsim simulator the
    reference tutorial uses, minus its doublet machinery."""
    rng = np.random.default_rng(seed)
    programs = rng.gamma(0.3, 1.0, size=(k_true, n_genes))
    # each program strongly marks its own gene block
    block = n_genes // k_true
    for k in range(k_true):
        programs[k, k * block:(k + 1) * block] *= 8.0
    programs /= programs.sum(axis=1, keepdims=True)
    usage = rng.dirichlet(np.full(k_true, 0.15), size=n_cells)
    rate = usage @ programs
    depth = rng.integers(2000, 6000, size=(n_cells, 1)).astype(float)
    counts = rng.poisson(rate * depth).astype(float)
    return counts, usage, programs


def main(output_dir=None):
    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    output_dir = output_dir or tempfile.mkdtemp(prefix="cnmf_tutorial_")
    os.makedirs(output_dir, exist_ok=True)
    k_true = 6
    counts, usage_true, programs_true = simulate_counts(k_true=k_true)
    counts_df = pd.DataFrame(
        counts,
        index=[f"cell_{i}" for i in range(counts.shape[0])],
        columns=[f"gene_{j}" for j in range(counts.shape[1])])
    counts_fn = f"{output_dir}/sim_counts.df.npz"
    save_df_to_npz(counts_df, counts_fn)
    print(f"simulated {counts.shape[0]} cells x {counts.shape[1]} genes "
          f"with {k_true} planted programs -> {counts_fn}")

    # ------------------------------------------------------------------
    # the five pipeline stages (identical to the CLI workflow)
    # ------------------------------------------------------------------
    obj = cNMF(output_dir=output_dir, name="sim_run")
    ks = list(range(4, 9))
    obj.prepare(counts_fn, components=ks, n_iter=20, seed=14,
                num_highvar_genes=800)
    obj.factorize()                       # all 5 Ks x 20 replicates
    obj.combine()
    obj.k_selection_plot(close_fig=True)
    print(f"K-selection plot: {obj.paths['k_selection_plot']}")

    # the documented two-pass consensus workflow: first pass unfiltered
    # (threshold 2.0) to see the replicate-distance histogram in the
    # clustergram figure, then re-run with the threshold set at the
    # outlier notch (cheap: the distance matrix is cached per K)
    obj.consensus(k_true, density_threshold=2.0, show_clustering=True,
                  close_clustergram_fig=True)
    print(f"inspect {obj.paths['clustering_plot'] % (k_true, '2_0')} "
          "for the density histogram, then filter:")
    obj.consensus(k_true, density_threshold=0.2, show_clustering=True,
                  close_clustergram_fig=True)
    usage, scores, tpm, top_genes = obj.load_results(
        K=k_true, density_threshold=0.2)
    print(f"consensus usages: {usage.shape}, spectra scores: {scores.shape}")
    print("top genes per program:\n", top_genes.iloc[:5, :].to_string())

    # ------------------------------------------------------------------
    # ground-truth check: each planted program should correlate strongly
    # with exactly one recovered TPM-unit spectrum
    # ------------------------------------------------------------------
    # load_results returns spectra as genes x K (reference orientation)
    gene_idx = [counts_df.columns.get_loc(g) for g in tpm.index]
    truth = programs_true[:, gene_idx]
    corr = np.corrcoef(np.vstack([truth, tpm.values.T]))[
        :k_true, k_true:]                      # (true x recovered)
    best = corr.max(axis=1)
    print("per-planted-program best correlation:", np.round(best, 3))
    assert (best > 0.95).all(), "a planted program was not recovered"
    print("OK: all planted programs recovered (r > 0.95). "
          f"Artifacts in {output_dir}/sim_run/")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
