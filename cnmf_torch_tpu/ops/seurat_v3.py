"""seurat_v3 highly-variable-gene selection (variance-stabilizing transform).

Replaces ``sc.pp.highly_variable_genes(flavor='seurat_v3')`` used by the
batch-correction sidecar (``/root/reference/src/cnmf/preprocess.py:295``).
The method (Stuart et al. 2019): fit a mean-variance trend in log10 space,
standardize each gene's counts by the trend-predicted std with values
clipped at sqrt(N), and rank genes by the variance of the clipped
standardized values.

Divergence note: scanpy fits the trend with skmisc's loess (unavailable
here). We fit the same tricube-weighted local quadratic regression on a
256-point quantile grid of the sorted log-means and interpolate — a
standard loess approximation whose fitted trend differs negligibly on
single-cell data (validated against scanpy's published ranks in tests by
rank overlap, not bit equality).

The O(cells x genes) standardized-variance pass runs on device in one jit;
the trend fit is O(genes) host work.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import scipy.sparse as sp

from .stats import column_mean_var

__all__ = ["seurat_v3_hvg"]

_GRID = 256
_SPAN = 0.3


def _loess_trend(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Tricube-weighted local quadratic fit of y on x, evaluated at x, via a
    quantile grid + interpolation."""
    order = np.argsort(x)
    xs, ys = x[order], y[order]
    n = len(xs)
    window = max(int(np.ceil(_SPAN * n)), 8)
    grid_idx = np.unique(
        np.linspace(0, n - 1, min(_GRID, n)).astype(int))
    fitted_grid = np.empty(len(grid_idx))
    for j, gi in enumerate(grid_idx):
        lo = max(0, min(gi - window // 2, n - window))
        sel = slice(lo, lo + window)
        xw, yw = xs[sel], ys[sel]
        d = np.abs(xw - xs[gi])
        dmax = d.max() if d.max() > 0 else 1.0
        w = (1.0 - (d / dmax) ** 3) ** 3
        # weighted quadratic: 3x3 normal equations
        A = np.stack([np.ones_like(xw), xw, xw * xw], axis=1)
        Aw = A * w[:, None]
        beta, *_ = np.linalg.lstsq(Aw.T @ A, Aw.T @ yw, rcond=None)
        fitted_grid[j] = beta[0] + beta[1] * xs[gi] + beta[2] * xs[gi] ** 2
    fitted = np.interp(x, xs[grid_idx], fitted_grid)
    return fitted


@functools.partial(jax.jit, static_argnames=())
def _clipped_std_var_dense(X, mean, reg_std, clip):
    # seurat v3 statistic: second moment of the upper-clipped standardized
    # values about the RAW mean — sum(min(z, sqrt(N))^2) / (N-1) with
    # z = (count - mean)/reg_std. No re-centering on the clipped mean:
    # clipping fires exactly on the extreme-dispersion HVG candidates, and
    # subtracting their shifted mean would understate them (scanpy's
    # formula is (N*mean^2 + sum(c^2) - 2*mean*sum(c)) / ((N-1)*reg_std^2)
    # over clipped counts c, which is algebraically this)
    Z = jnp.minimum((X - mean[None, :]) / reg_std[None, :], clip)
    return jnp.sum(Z * Z, axis=0) / (X.shape[0] - 1)


def seurat_v3_hvg(X, n_top_genes: int = 2000) -> pd.DataFrame:
    """Score genes; returns a DataFrame with columns
    [means, variances, variances_norm, highly_variable_rank, highly_variable]
    aligned to the input column order."""
    n, g = X.shape
    # sparse moments route through the host-f64 fused engine inside
    # column_mean_var (measured ~6 s of this scorer's 9.8 s on the islets
    # preprocess went to per-block device round trips before that routing)
    mean, var = column_mean_var(X, ddof=1)

    not_const = var > 0
    est_var = np.zeros(g)
    x_log = np.log10(np.maximum(mean[not_const], 1e-30))
    y_log = np.log10(var[not_const])
    est_var[not_const] = _loess_trend(x_log, y_log)
    reg_std = np.sqrt(10.0 ** est_var)
    reg_std[~not_const] = 1.0

    clip = np.sqrt(n)
    if sp.issparse(X):
        # sparse: clipped standardized moments from data + implicit zeros.
        # zeros standardize to -mean/reg_std (never clipped upward since
        # means are positive); O(nnz) device pass per block
        Xcsr = X.tocsr()
        z0 = -mean / reg_std
        s2 = np.zeros(g)
        nnz = np.zeros(g)
        block = 262_144
        for start in range(0, n, block):
            b = Xcsr[start:min(start + block, n)]
            if b.nnz == 0:
                continue
            zb = np.minimum(
                (b.data - mean[b.indices]) / reg_std[b.indices], clip)
            s2 += np.bincount(b.indices, weights=zb * zb, minlength=g)
            nnz += np.bincount(b.indices, minlength=g)
        s2 += (n - nnz) * z0 * z0
        var_std = s2 / (n - 1)
    else:
        var_std = np.asarray(_clipped_std_var_dense(
            jnp.asarray(np.asarray(X), jnp.float32),
            jnp.asarray(mean, jnp.float32),
            jnp.asarray(reg_std, jnp.float32),
            jnp.float32(clip)), dtype=np.float64)
    var_std[~not_const] = 0.0

    n_top = min(int(n_top_genes), g)
    # scanpy breaks ties by original order; argsort of -var_std is stable
    rank_order = np.argsort(-var_std, kind="stable")
    ranks = np.full(g, np.nan)
    ranks[rank_order[:n_top]] = np.arange(n_top)
    high_var = ~np.isnan(ranks)

    return pd.DataFrame({
        "means": mean,
        "variances": var,
        "variances_norm": var_std,
        "highly_variable_rank": ranks,
        "highly_variable": high_var,
    })
