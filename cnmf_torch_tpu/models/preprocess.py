"""Preprocessing sidecar: QC filtering, normalization, batch correction.

API-compatible reimplementation of the reference ``Preprocess`` class
(``/root/reference/src/cnmf/preprocess.py:41-439``) without the
scanpy/harmonypy dependency stack: QC filters and library-size scaling are
numpy/JAX ops, seurat_v3 HVG selection and PCA are the device kernels in
``cnmf_torch_tpu.ops``, and Harmony (with the gene-space MOE ridge
correction that distinguishes this pipeline from stock Harmony) is the JAX
port in :mod:`cnmf_torch_tpu.ops.harmony`. CITE-seq data is handled the
same way: ADT features are split off before RNA normalization and hstacked
back into the TPM output so ADT contributions to GEPs can be read out
(``preprocess.py:202-238``).
"""

from __future__ import annotations

from collections.abc import Collection

import numpy as np
import pandas as pd
import scipy.sparse as sp

from ..ops.harmony import moe_correct_ridge, run_harmony
from ..ops.pca import pca
from ..ops.seurat_v3 import seurat_v3_hvg
from ..ops.stats import normalize_total, row_sums, scale_columns
from ..utils.anndata_lite import AnnDataLite, write_h5ad

__all__ = ["Preprocess", "stdscale_quantile_celing"]


def stdscale_quantile_celing(_adata, max_value=None, quantile_thresh=None):
    """Unit-variance scale (no centering) then clip values above a quantile
    of the full matrix (``preprocess.py:21-29``; the reference keeps the
    typo'd name, kept here for API parity)."""
    X, _ = scale_columns(_adata.X, ddof=1, zero_std_to_one=True)
    if max_value is not None:
        if sp.issparse(X):
            X.data[X.data > max_value] = max_value
        else:
            X[X > max_value] = max_value
    if quantile_thresh is not None:
        if sp.issparse(X):
            # quantile over the dense value distribution (incl. zeros), as
            # the reference computes it via todense (preprocess.py:25); done
            # here without densifying: zeros shift the quantile position.
            # The implicit-zero merge below assumes all stored values are
            # nonnegative (true for scaled counts, which is the only path
            # the pipeline feeds here) — negatives would sort below the
            # zeros and the interpolation would be wrong.
            nnz_vals = np.sort(X.tocsr().data)
            if nnz_vals.size and nnz_vals[0] < 0:
                raise ValueError(
                    "stdscale_quantile_celing: sparse input contains "
                    "negative values; the sparse quantile path assumes "
                    "nonnegative data (densify first for signed data)")
            n_total = X.shape[0] * X.shape[1]
            pos = quantile_thresh * (n_total - 1)
            n_zeros = n_total - len(nnz_vals)
            # linear interpolation within the sorted implicit dense vector
            def dense_val(i):
                return 0.0 if i < n_zeros else nnz_vals[int(i - n_zeros)]
            lo, hi = int(np.floor(pos)), int(np.ceil(pos))
            frac = pos - lo
            threshval = dense_val(lo) * (1 - frac) + dense_val(hi) * frac
            X.data[X.data > threshval] = threshval
        else:
            threshval = np.quantile(np.asarray(X).reshape(-1),
                                    quantile_thresh)
            X[X > threshval] = threshval
    _adata.X = X
    return _adata


class Preprocess:
    """Optional upstream pipeline producing the three files ``prepare()``
    consumes (counts_fn / tpm_fn / genes_file, README.md:88-92)."""

    def __init__(self, random_seed=None, plot_dir=None):
        """``plot_dir``: where ``makeplots=True`` figures are saved as PNGs.
        When None, figures are left open on the pyplot stack (the
        reference's notebook-display behavior) for the caller to show or
        save."""
        self.random_seed = 0 if random_seed is None else int(random_seed)
        self.plot_dir = plot_dir
        self._warmed: set = set()
        self._warm_executor = None
        np.random.seed(random_seed)

    # ------------------------------------------------------------------

    def filter_adata(self, _adata, filter_mito_thresh=None,
                     min_cells_per_gene=10, min_counts_per_cell=500,
                     filter_mito_genes=False, filter_dot_genes=True,
                     makeplots=False):
        """QC filter (``preprocess.py:60-132``): genes by min cells, cells
        by min counts, optional mitochondrial-fraction cell filter (genes
        prefixed ``MT-``), optional removal of mito and dot-containing
        genes."""
        X = _adata.X
        if min_cells_per_gene is not None:
            if sp.issparse(X):
                cells_per_gene = np.asarray((X > 0).sum(axis=0)).ravel()
            else:
                cells_per_gene = (np.asarray(X) > 0).sum(axis=0)
            _adata = _adata[:, cells_per_gene >= min_cells_per_gene]

        _adata.obs = _adata.obs.copy()
        _adata.obs["n_counts"] = row_sums(_adata.X)

        if makeplots:
            self._hist(np.log10(np.maximum(_adata.obs["n_counts"], 1)),
                       "log10 n_counts")

        if min_counts_per_cell is not None:
            _adata = _adata[
                (_adata.obs["n_counts"] >= min_counts_per_cell).values, :]

        mt_genes = [x for x in _adata.var.index if "MT-" in x]
        if filter_mito_thresh is not None:
            num_mito = row_sums(_adata[:, mt_genes].X) if mt_genes else (
                np.zeros(_adata.n_obs))
            pct_mito = num_mito / _adata.obs["n_counts"].values
            _adata.obs = _adata.obs.copy()
            _adata.obs["pct_mito"] = pct_mito
            if makeplots:
                self._hist(pct_mito, "pct_mito")
            _adata = _adata[pct_mito < filter_mito_thresh, :]

        tofilter = []
        if filter_dot_genes:
            tofilter = [x for x in _adata.var.index if "." in x]
        if filter_mito_genes:
            tofilter += mt_genes
        _adata = _adata[:, ~_adata.var.index.isin(tofilter)]
        return _adata

    # ------------------------------------------------------------------

    def preprocess_for_cnmf(self, _adata, feature_type_col=None,
                            adt_feature_name="Antibody Capture",
                            harmony_vars=None, n_top_rna_genes=2000,
                            librarysize_targetsum=1e4,
                            max_scaled_thresh=None, quantile_thresh=0.9999,
                            makeplots=False, theta=1,
                            save_output_base=None, max_iter_harmony=20):
        """HVG-filtered, variance-normalized, optionally Harmony-corrected
        RNA plus a library-size-normalized (RNA [+ADT]) TPM companion
        (``preprocess.py:135-247``). Returns ``(adata_RNA, tp10k, hvgs)``."""
        if (not isinstance(_adata, Collection)) and feature_type_col is not None:
            adata_ADT = _adata[:, (_adata.var[feature_type_col]
                                   == adt_feature_name).values]
            adata_RNA = _adata[:, (_adata.var[feature_type_col]
                                   != adt_feature_name).values]
        elif not isinstance(_adata, Collection):
            adata_RNA = _adata
            adata_RNA.var_names_make_unique()
            adata_RNA.var = adata_RNA.var.copy()
            adata_RNA.var["features_renamed"] = adata_RNA.var.index
            adata_ADT = None
        elif len(_adata) == 2:
            adata_RNA, adata_ADT = _adata[0], _adata[1]
            if adata_ADT.shape[0] != adata_RNA.shape[0]:
                raise Exception(
                    "ADT and RNA AnnDatas don't have the same number of cells")
            if np.sum(adata_ADT.obs.index != adata_RNA.obs.index) > 0:
                raise Exception(
                    "Inconsistency of the index for the ADT and RNA AnnDatas")
        else:
            raise Exception("data should either be an AnnData object or a "
                            "list of 2 AnnData objects")

        tp10k = normalize_total(adata_RNA, target_sum=librarysize_targetsum)
        adata_RNA, hvgs = self.normalize_batchcorrect(
            adata_RNA, harmony_vars=harmony_vars,
            n_top_genes=n_top_rna_genes,
            librarysize_targetsum=librarysize_targetsum,
            max_scaled_thresh=max_scaled_thresh,
            quantile_thresh=quantile_thresh, theta=theta,
            makeplots=makeplots, max_iter_harmony=max_iter_harmony)

        if adata_ADT is not None:
            adata_ADT = adata_ADT[adata_RNA.obs.index, :]
            adata_ADT = normalize_total(adata_ADT,
                                        target_sum=librarysize_targetsum)
            merge_var = pd.concat([tp10k.var, adata_ADT.var], axis=0)
            if sp.issparse(tp10k.X) or sp.issparse(adata_ADT.X):
                Xm = sp.hstack([sp.csr_matrix(tp10k.X),
                                sp.csr_matrix(adata_ADT.X)]).tocsr()
            else:
                Xm = np.hstack([tp10k.X, adata_ADT.X])
            tp10k = AnnDataLite(Xm, obs=tp10k.obs, var=merge_var)

        if save_output_base is not None:
            write_h5ad(save_output_base + ".Corrected.HVG.Varnorm.h5ad",
                       adata_RNA)
            write_h5ad(save_output_base + ".TP10K.h5ad", tp10k)
            from ..utils.anndata_lite import atomic_artifact

            with atomic_artifact(
                    save_output_base + ".Corrected.HVGs.txt") as tmp:
                with open(tmp, "w") as f:
                    f.write("\n".join(hvgs))

        return adata_RNA, tp10k, hvgs

    # ------------------------------------------------------------------

    def _warm_harmony_programs(self, n, n_hvg, B, max_iter_kmeans=20,
                               block_size=0.05, sigma=0.1, lamb=1.0,
                               theta=1.0, d=50):
        """Warm every device program the Harmony path will hit —
        concurrently, on dummy data at the production shapes — mirroring
        ``cNMF._warm_consensus_programs``: on a tunneled TPU each
        executable's first dispatch pays a ~2 s program-upload round trip,
        and the three big compiles (kmeans init, the fused cluster phase,
        the gene-space MOE ridge) otherwise serialize inside the pipeline.
        Shape derivations (K, block split) replicate
        :func:`~cnmf_torch_tpu.ops.harmony.run_harmony` exactly; the
        dummy cluster phase runs with ``eps=inf`` so it exits after the
        mandatory 2 rounds. Jobs are submitted without joining — the
        pipeline's host-side stages (normalize/scale/quantile) overlap
        the warms, and production calls block on their own program's
        compile only."""
        sig = (int(n), int(n_hvg), int(B), int(max_iter_kmeans),
               float(block_size), int(d))
        if sig in self._warmed:
            return
        self._warmed.add(sig)

        import concurrent.futures

        import jax.numpy as jnp

        from ..ops.harmony import (_assign_R, _cluster_phase,
                                   _moe_ridge_scan, _normalize_cols,
                                   harmony_program_shapes)
        from ..ops.kmeans import kmeans

        K, n_blocks, n_pad = harmony_program_shapes(n,
                                                    block_size=block_size)
        f32 = jnp.float32

        def warm_kmeans():
            # all-ones rows: kmeans++ degenerates and Lloyd exits in one
            # step, so this pays (compile + upload), not a real clustering
            kmeans(np.ones((n, d), np.float32), K, n_init=10, max_iter=25,
                   seed=self.random_seed)

        def warm_cluster():
            Z = jnp.ones((d, n), f32)
            R = jnp.full((K, n), 1.0 / K, f32)
            phi = jnp.ones((B, n), f32) / B
            Pr_b = jnp.full((B,), 1.0 / B, f32)
            E = jnp.outer(R.sum(axis=1), Pr_b)
            O = R @ phi.T
            perms = np.full((max_iter_kmeans, n_pad), n, np.int32)
            perms[:, :n] = np.arange(n)[None, :]
            sigma_vec = jnp.full((K,), float(sigma), f32)
            theta_vec = jnp.full((B,), float(theta), f32)
            # production shape is (d, K) centroids against (d, n) cells
            _assign_R(_normalize_cols(jnp.ones((d, K), f32)),
                      _normalize_cols(Z), sigma_vec)
            _cluster_phase(_normalize_cols(Z), R, phi, E, O,
                           jnp.asarray(perms), Pr_b, sigma_vec, theta_vec,
                           jnp.float32(jnp.inf), n_blocks,
                           int(max_iter_kmeans))

        def warm_moe(rows):
            lamb_mat = jnp.diag(jnp.concatenate(
                [jnp.zeros((1,), f32), jnp.full((B,), float(lamb), f32)]))
            _moe_ridge_scan(jnp.ones((rows, n), f32),
                            jnp.full((K, n), 1.0 / K, f32),
                            jnp.ones((B + 1, n), f32), lamb_mat)

        def warm_pca():
            from ..ops.pca import pca

            pca(np.ones((n, n_hvg), np.float32), n_comps=d,
                zero_center=True)

        jobs = [warm_kmeans, warm_cluster, lambda: warm_moe(d)]
        # the pca and gene-space-moe dummies are the only (n x n_hvg)-sized
        # warm allocations; they run UNJOINED alongside production's
        # host-side stages, so cap them to keep warm+production peak HBM
        # bounded at atlas scale (the small warms above are K/d-sized)
        from ..utils.envknobs import env_int

        if 3 * n * n_hvg * 4 <= env_int(
                "CNMF_TPU_WARM_DUMMY_BUDGET_BYTES", 2 << 30, lo=0):
            jobs += [warm_pca, lambda: warm_moe(n_hvg)]

        def run_one(job):
            try:
                job()
            except Exception:
                pass

        # submitted WITHOUT joining: the compiles/uploads overlap the
        # host-side HVG scoring/scaling AND production's early device
        # stages — joining before pca was measured to serialize the big
        # _cluster_phase compile into the critical path (islets preprocess
        # 35 s -> 51 s). Peak-HBM safety comes from the dummy-size cap
        # above, not from a barrier; _join_warm() runs at the end of
        # normalize_batchcorrect (free by then) so no threads outlive it
        ex = concurrent.futures.ThreadPoolExecutor(len(jobs))
        for job in jobs:
            ex.submit(run_one, job)
        ex.shutdown(wait=False)
        self._warm_executor = ex

    def _join_warm(self):
        """Block until all outstanding warm jobs finish (and their dummy
        device buffers are released)."""
        ex = self._warm_executor
        if ex is not None:
            self._warm_executor = None
            ex.shutdown(wait=True)

    def normalize_batchcorrect(self, _adata, normalize_librarysize=False,
                               harmony_vars=None, n_top_genes=None,
                               librarysize_targetsum=1e4,
                               max_scaled_thresh=None,
                               quantile_thresh=0.9999, theta=1,
                               makeplots=False, max_iter_harmony=20):
        """HVG selection (seurat_v3 on raw counts), variance scaling with a
        quantile ceiling, and — when ``harmony_vars`` is given — PCA on the
        scaled TP10K view handed to Harmony, whose MOE ridge then corrects
        the gene matrix itself with negatives clipped to zero
        (``preprocess.py:250-338``)."""
        from ..utils.envknobs import env_flag

        if env_flag("CNMF_TPU_COMPILE_CACHE", True):
            # the pipeline entry points (CLI, bench, and this method — the
            # Preprocess compute entry) enable the persistent compile
            # cache; constructing the object stays side-effect-free, and
            # a user's explicit JAX cache config is never overridden
            from ..utils.compile_cache import (
                enable_persistent_compilation_cache,
            )

            enable_persistent_compilation_cache()

        if harmony_vars is not None and env_flag("CNMF_WARM_PREPROCESS",
                                                 True):
            # launch the device-program warms NOW so their compiles and
            # uploads overlap the host-side HVG scoring and scaling below
            if n_top_genes is not None:
                n_hvg_exp = int(min(int(n_top_genes), _adata.shape[1]))
            elif "highly_variable" in _adata.var.columns:
                n_hvg_exp = int(np.asarray(
                    _adata.var["highly_variable"]).astype(bool).sum())
            else:
                n_hvg_exp = 0
            if n_hvg_exp:
                from ..ops.harmony import design_width

                B = design_width(_adata.obs, harmony_vars)
                self._warm_harmony_programs(_adata.shape[0], n_hvg_exp, B,
                                            theta=theta)
        try:
            if n_top_genes is not None:
                hvg_stats = seurat_v3_hvg(_adata.X, n_top_genes=n_top_genes)
                _adata.var = _adata.var.copy()
                for col in hvg_stats.columns:
                    _adata.var[col] = hvg_stats[col].values
            elif "highly_variable" not in _adata.var.columns:
                raise Exception(
                    "If a numeric value for n_top_genes is not provided, you "
                    "must include a highly_variable column in _adata")

            hv_mask = _adata.var["highly_variable"].values.astype(bool)

            if harmony_vars is not None:
                anorm = normalize_total(_adata,
                                        target_sum=librarysize_targetsum)
                anorm = anorm[:, hv_mask]
                stdscale_quantile_celing(anorm, max_value=max_scaled_thresh,
                                         quantile_thresh=quantile_thresh)

                _adata = _adata[:, hv_mask]
                stdscale_quantile_celing(_adata, max_value=max_scaled_thresh,
                                         quantile_thresh=quantile_thresh)
                if makeplots:
                    self._count_hist(anorm)

                X_pca, _, _ = pca(anorm.X, n_comps=50, zero_center=True)
                _adata.obsm["X_pca"] = X_pca

                src = anorm if normalize_librarysize else _adata
                X_dense = (src.X.toarray() if sp.issparse(src.X)
                           else np.asarray(src.X))
                X_corr, pca_harmony = self.harmony_correct_X(
                    X_dense, src.obs, _adata.obsm["X_pca"], harmony_vars,
                    max_iter_harmony=max_iter_harmony, theta=theta)
                _adata.X = X_corr
                _adata.obsm["X_pca_harmony"] = pca_harmony
            else:
                if normalize_librarysize:
                    _adata = normalize_total(_adata,
                                             target_sum=librarysize_targetsum)
                _adata = _adata[:, hv_mask]
                stdscale_quantile_celing(_adata, max_value=max_scaled_thresh,
                                         quantile_thresh=quantile_thresh)
                if makeplots:
                    self._count_hist(_adata)
        finally:
            # join on EVERY exit: an exception mid-pipeline must not
            # leak the non-daemon warm threads (atexit would block)
            # or their device dummy buffers
            self._join_warm()
        return _adata, list(_adata.var.index)

    # ------------------------------------------------------------------

    def harmony_correct_X(self, X, obs, pca_embedding, harmony_vars,
                          theta=1, max_iter_harmony=20):
        """Learn Harmony's correction on the PCs, then apply the MOE ridge
        to the expression matrix, clipping negatives to zero
        (``preprocess.py:342-388``). Returns ``(X_corr, X_pca_harmony)``."""
        res = run_harmony(pca_embedding, obs, harmony_vars,
                          theta=theta, max_iter_harmony=max_iter_harmony,
                          random_state=self.random_seed)
        X_pca_harmony = res.Z_corr.T
        X_corr = moe_correct_ridge(np.asarray(X).T, res.R, res.Phi_moe,
                                   res.lamb).T
        # np.maximum also copies out of the read-only device buffer
        X_corr = np.maximum(X_corr, 0.0)
        return X_corr, X_pca_harmony

    # ------------------------------------------------------------------

    def select_features_MI(self, _adata, cluster, max_scaled_thresh=None,
                           quantile_thresh=0.9999, n_top_features=70,
                           makeplots=False):
        """Rank features by mutual information against a cluster label and
        mark the top ``n_top_features`` as highly variable
        (``preprocess.py:391-439``). The MI estimator is sklearn's (same
        dependency the reference uses); a host-side utility, not a TPU
        kernel."""
        from sklearn.feature_selection import mutual_info_classif

        _adata = normalize_total(_adata)
        stdscale_quantile_celing(_adata, max_value=max_scaled_thresh,
                                 quantile_thresh=quantile_thresh)
        X = _adata.X.toarray() if sp.issparse(_adata.X) else _adata.X
        res = mutual_info_classif(X, cluster, discrete_features="auto",
                                  n_neighbors=3, copy=True,
                                  random_state=self.random_seed)
        res = pd.Series(res, index=_adata.var.index).sort_values(
            ascending=False)
        resdf = pd.DataFrame(
            [res.values, np.arange(res.shape[0])],
            columns=res.index, index=["MI", "MI_Rank"]).T
        resdf["MI_diff"] = resdf["MI"].diff()

        if makeplots:
            self._mi_plot(resdf, n_top_features)

        _adata.var = _adata.var.copy()
        for v in resdf.columns:
            _adata.var[v] = resdf[v]
        _adata.var["highly_variable"] = _adata.var["MI_Rank"] < n_top_features
        return _adata

    # -- plotting helpers (host-side) ----------------------------------

    def _finish_fig(self, fig, slug: str):
        """Save to plot_dir when configured, else leave the figure open on
        the pyplot stack for interactive display."""
        if self.plot_dir is not None:
            import os

            from .plots import _save_fig_atomic

            os.makedirs(self.plot_dir, exist_ok=True)
            _save_fig_atomic(fig, os.path.join(self.plot_dir, slug + ".png"),
                             dpi=150)
            import matplotlib.pyplot as plt

            plt.close(fig)

    def _hist(self, values, title):
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots()
        ax.hist(np.asarray(values), bins=100)
        ax.set_title(title)
        self._finish_fig(fig, title.replace(" ", "_"))

    def _count_hist(self, adata, num_cells=1000):
        X = adata.X[:num_cells, :]
        y = (np.asarray(X.todense()) if sp.issparse(X)
             else np.asarray(X)).reshape(-1)
        self._hist(y[y > 0],
                   "Quantile thresholded normalized count distribution")

    def _mi_plot(self, resdf, n_top_features):
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(1, 1, figsize=(10, 3), dpi=100)
        ax.scatter(resdf["MI_Rank"], resdf["MI"])
        ax.set_ylabel("MI", fontsize=11)
        ax.set_xlabel("MI Rank", fontsize=11)
        ylim = ax.get_ylim()
        ax.vlines(x=n_top_features, ymin=ylim[0], ymax=ylim[1],
                  linestyle="--", color="k")
        ax.set_ylim(ylim)
        self._finish_fig(fig, "MI_rank")
