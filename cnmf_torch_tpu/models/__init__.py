from .cnmf import cNMF, compute_tpm
from .preprocess import Preprocess

__all__ = ["cNMF", "compute_tpm", "Preprocess"]
