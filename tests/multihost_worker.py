"""Subprocess body for the multi-host tests: one simulated pod host.

Launched N times by tests/test_multihost.py with CNMF_* coordinates in the
environment. Each process contributes 4 virtual CPU devices, joins the
distributed program, runs a 2-D replicate sweep on a deterministic fixture,
and the coordinator writes the gathered results for the parent to compare
against a single-process run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# force_cpu_devices rewrites XLA_FLAGS before any backend initializes, so
# the parent's inherited device count (the test suite's 8) never wins here
from cnmf_torch_tpu.utils.jax_compat import force_cpu_devices  # noqa: E402

force_cpu_devices(int(os.environ.get("CNMF_SIM_CPU_DEVICES", "4")))

import numpy as np  # noqa: E402


def main(out_path: str) -> None:
    from cnmf_torch_tpu.parallel import (
        initialize_distributed,
        is_coordinator,
        mesh_2d,
        replicate_sweep_2d,
        sync_hosts,
    )

    pid, nproc = initialize_distributed()
    assert nproc == int(os.environ["CNMF_NUM_PROCESSES"]), nproc

    mesh = mesh_2d()
    assert mesh.axis_names == ("replicates", "cells")
    # one replicate shard per host: the cells psum never crosses processes
    assert mesh.devices.shape[0] == nproc

    rng = np.random.default_rng(123)
    X = (rng.gamma(0.8, 1.0, size=(64, 24)) *
         rng.binomial(1, 0.4, size=(64, 24))).astype(np.float32)
    spectra, errs = replicate_sweep_2d(
        X, seeds=[11, 22, 33, 44], k=3, mesh=mesh, beta_loss="frobenius",
        tol=1e-5, n_passes=30)

    if is_coordinator():
        np.savez(out_path, spectra=spectra, errs=errs,
                 mesh_shape=np.asarray(mesh.devices.shape))
    sync_hosts("test_done")


if __name__ == "__main__":
    main(sys.argv[1])
