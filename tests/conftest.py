import os

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
# exercised without TPU hardware (SURVEY.md §4 "what the reference lacks").
# Two mechanisms, tried in order:
#   * XLA_FLAGS=--xla_force_host_platform_device_count=8 — set BEFORE jax
#     import (XLA reads it at CPU-backend init, so it also works when the
#     environment pre-imports jax at interpreter startup, as long as no
#     backend has been initialized yet);
#   * jax.config.update("jax_num_cpu_devices", 8) — the modern option,
#     unrecognized by older JAX releases (guarded: its absence is fine
#     because the XLA flag above already forces the device count).
# Override with CNMF_TEST_PLATFORM=tpu to run on hardware.
if os.environ.get("CNMF_TEST_PLATFORM", "cpu") == "cpu":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if os.environ.get("CNMF_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        pass  # older JAX: the XLA_FLAGS fallback above covers it

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import scipy.sparse as sp  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture()
def counts_100x500():
    """The reference's synthetic smoke fixture: binomial counts with seed 42
    (test_prepare.py:10-14)."""
    np.random.seed(42)
    return np.random.binomial(100, 0.01, size=(100, 500)).astype(np.float64)


@pytest.fixture()
def sparse_counts_100x500(counts_100x500):
    return sp.csr_matrix(counts_100x500)
