"""Tier-1 fleet chaos smoke gate (scripts/verify_tier1.sh, ISSUE 20).

Builds a consensus-complete mini run, starts the REAL replicated fleet
through the CLI surface (``cnmf-tpu fleet <run_dir> --socket ...
--replicas 2`` in a subprocess — which itself spawns two real ``serve``
daemon subprocesses), then drives the three chaos events the fleet
exists to survive, all under sustained multi-tenant load:

  * **replica SIGKILL mid-load** (``replicadeath`` fault clause): the
    router must fail the dead replica's tenants over to the survivor
    and respawn it — zero accepted requests lost;
  * **reference rollover with a store outage** (``netdown`` clause,
    ``once=`` sentinel): a v2 reference published through the remote
    ShardStore (``CNMF_TPU_STORE_URI``) replaces v1 with zero downtime
    — no request errors, and every reply is bit-identical to solo
    ``refit_usage`` against EITHER v1 or v2, never a mix — while the
    warming replicas heal one injected store failure via the transport
    retry ladder;
  * **a poison tenant**: three NaN strikes convict at the ROUTER
    (fleet-scoped quarantine), isolated from every other tenant.

Afterwards: SLO not burning (``CNMF_TPU_SLO_P99_MS``), schema-valid
fleet events (``replica_death`` / ``failover`` / ``rollover`` +
per-request routing), clean shutdown with no orphans.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.serving import (PoisonError, QuarantinedError,
                                        load_reference)
    from cnmf_torch_tpu.serving.fleet import FleetClient
    from cnmf_torch_tpu.utils import save_df_to_npz
    from cnmf_torch_tpu.utils.netstore import ObjectStoreServer
    from cnmf_torch_tpu.utils.shardstore import write_shard_store
    from cnmf_torch_tpu.utils.storebackend import resolve_backend
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    workdir = tempfile.mkdtemp(prefix="fleet_smoke_")
    proc = None
    store_srv = None
    try:
        # -- fixture run ---------------------------------------------------
        rng = np.random.default_rng(8)
        usage = rng.dirichlet(np.ones(4) * 0.3, size=160)
        spectra = rng.gamma(0.3, 1.0, size=(4, 90)) * 40.0 / 90
        counts = rng.poisson(usage @ spectra * 260.0).astype(np.float64)
        counts[counts.sum(axis=1) == 0, 0] = 1.0
        df = pd.DataFrame(counts, index=[f"c{i}" for i in range(160)],
                          columns=[f"g{j}" for j in range(90)])
        counts_fn = os.path.join(workdir, "counts.df.npz")
        save_df_to_npz(df, counts_fn)

        obj = cNMF(output_dir=workdir, name="smoke")
        obj.prepare(counts_fn, components=[3], n_iter=6, seed=4,
                    num_highvar_genes=70)
        obj.factorize()
        obj.combine()
        obj.consensus(k=3, density_threshold=2.0, show_clustering=False)
        run_dir = os.path.join(workdir, "smoke")

        # -- v2 reference published through the REMOTE shard store ---------
        ref = load_reference(run_dir)
        store_srv = ObjectStoreServer()
        store_srv.start()
        store_uri = store_srv.url + "/fleet"
        v2_dir = os.path.join(workdir, "ref_v2.store")
        os.makedirs(v2_dir, exist_ok=True)  # isdir gate; objects remote
        W2 = (np.asarray(ref.W, np.float32) * 1.25).astype(np.float32)
        write_shard_store(v2_dir, W2, var_names=list(ref.genes),
                          backend=resolve_backend(v2_dir, uri=store_uri))

        # expected usages, per tenant, for BOTH references: a reply that
        # matches neither is a lost/corrupt/mixed-reference answer
        tenants = [f"tenant{i}" for i in range(4)]
        queries = {t: rng.gamma(
            1.0, 1.0, size=(12 + 9 * i, ref.n_genes)).astype(np.float32)
            for i, t in enumerate(tenants)}
        df1 = pd.DataFrame(np.asarray(ref.W, np.float32),
                           columns=ref.genes)
        df2 = pd.DataFrame(W2, columns=ref.genes)
        exp1 = {t: np.asarray(obj.refit_usage(X, df1))
                for t, X in queries.items()}
        exp2 = {t: np.asarray(obj.refit_usage(X, df2))
                for t, X in queries.items()}

        # -- the fleet through the CLI surface -----------------------------
        sock = os.path.join(workdir, "fleet.sock")
        sentinel = os.path.join(workdir, "netdown.once")
        env = dict(
            os.environ,
            CNMF_TPU_TELEMETRY="1",
            CNMF_TPU_SERVE_LINGER_MS="40",
            CNMF_TPU_SERVE_WARM_START="0",
            CNMF_TPU_STORE_URI=store_uri,
            CNMF_TPU_SLO_P99_MS="8000",
            CNMF_TPU_FLEET_HEALTH_S="0.25",
            CNMF_TPU_WORKER_BACKOFF_S="0.2",
            # slot 1 is SIGKILLed on its 5th supervision poll (~1.5 s in,
            # squarely mid-load); one slab GET during the rollover warm
            # raises ConnectionError (healed by the store retry ladder)
            CNMF_TPU_FAULT_SPEC=(
                "replicadeath:context=fleet,worker=1,after=4;"
                f"netdown:context=get:slab,once={sentinel}"))
        proc = subprocess.Popen(
            [sys.executable, "-m", "cnmf_torch_tpu", "fleet", run_dir,
             "--socket", sock, "--replicas", "2"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        cli = FleetClient(socket_path=sock, timeout=300.0)
        deadline = time.time() + 240
        while True:
            if proc.poll() is not None:
                print("fleet smoke: fleet exited early:\n"
                      + (proc.stdout.read() or ""), file=sys.stderr)
                return 1
            try:
                if cli.healthz().get("ok"):
                    break
            except Exception:
                pass
            if time.time() > deadline:
                print("fleet smoke: fleet never came up", file=sys.stderr)
                return 1
            time.sleep(0.25)

        # -- sustained multi-tenant load across all chaos events -----------
        stop = threading.Event()
        lock = threading.Lock()
        replies: dict = {t: [] for t in tenants}  # (issued_at, H | exc)

        def load(tenant, X):
            i = 0
            c = FleetClient(socket_path=sock, timeout=120.0)
            while not stop.is_set():
                i += 1
                issued = time.monotonic()
                try:
                    H, _meta = c.project(X, tenant=tenant,
                                         request_id=f"{tenant}-{i}")
                    out = H
                except Exception as exc:
                    out = exc
                with lock:
                    replies[tenant].append((issued, out))
                time.sleep(0.1)

        threads = [threading.Thread(target=load, args=(t, queries[t]))
                   for t in tenants]
        for t in threads:
            t.start()

        def wait_stats(pred, what, timeout):
            end = time.time() + timeout
            while time.time() < end:
                st = cli.stats()
                if pred(st):
                    return st
                time.sleep(0.25)
            print(f"fleet smoke: timed out waiting for {what}: "
                  f"{cli.stats()}", file=sys.stderr)
            return None

        # chaos 1: the injected SIGKILL lands, tenants fail over, and the
        # replica respawns back into the ring
        if wait_stats(lambda s: s["replica_deaths"] >= 1,
                      "injected replica death", 60) is None:
            return 1
        if wait_stats(lambda s: s["replicas_up"] == 2,
                      "replica respawn", 120) is None:
            return 1

        # chaos 2: zero-downtime rollover to v2 (remote store, one
        # injected GET failure during the warm)
        out = cli.rollover(v2_dir)
        t_roll_done = time.monotonic()
        if out.get("generation") != 1:
            print(f"fleet smoke: bad rollover reply {out}",
                  file=sys.stderr)
            return 1
        if not os.path.exists(sentinel):
            print("fleet smoke: the netdown clause never fired — the "
                  "rollover did not exercise the store outage path",
                  file=sys.stderr)
            return 1

        time.sleep(2.0)  # a few more requests against generation 1
        stop.set()
        for t in threads:
            t.join(timeout=180)

        # chaos 3: poison tenant — three strikes convict at the router
        poison = queries["tenant0"].copy()
        poison[1, 1] = np.nan
        for strike in range(3):
            try:
                cli.project(poison, tenant="toxic")
                print("fleet smoke: poison request did not fail",
                      file=sys.stderr)
                return 1
            except PoisonError:
                pass
        try:
            cli.project(poison, tenant="toxic")
            print("fleet smoke: 4th poison request was not quarantined",
                  file=sys.stderr)
            return 1
        except QuarantinedError:
            pass
        # ...and the quarantine is tenant-scoped, not fleet-wide
        H, _ = cli.project(queries["tenant1"], tenant="tenant1")
        if not (np.array_equal(H, exp2["tenant1"])):
            print("fleet smoke: post-quarantine request not bit-"
                  "identical to v2 solo refit_usage", file=sys.stderr)
            return 1

        # -- zero lost accepted requests; never a mixed reference ----------
        total = 0
        for tenant in tenants:
            for issued, out in replies[tenant]:
                total += 1
                if isinstance(out, Exception):
                    print(f"fleet smoke: {tenant} request FAILED under "
                          f"chaos: {out!r}", file=sys.stderr)
                    return 1
                is_v1 = np.array_equal(out, exp1[tenant])
                is_v2 = np.array_equal(out, exp2[tenant])
                if not (is_v1 or is_v2):
                    print(f"fleet smoke: {tenant} reply matches NEITHER "
                          f"reference exactly (lost/mixed)",
                          file=sys.stderr)
                    return 1
                if issued > t_roll_done and not is_v2:
                    print(f"fleet smoke: {tenant} request issued after "
                          f"rollover still answered with v1",
                          file=sys.stderr)
                    return 1
        if total < 20:
            print(f"fleet smoke: only {total} requests completed — not "
                  f"a sustained load", file=sys.stderr)
            return 1

        # -- SLO + final accounting ----------------------------------------
        stats = cli.stats()
        slo = stats.get("slo") or {}
        if slo.get("burning"):
            print(f"fleet smoke: SLO burning through chaos: {slo}",
                  file=sys.stderr)
            return 1
        if stats["ok"] < total or stats["poison"] != 3 \
                or stats["quarantined"] != 1 or stats["error"] != 0:
            print(f"fleet smoke: bad outcome counts: {stats}",
                  file=sys.stderr)
            return 1

        # -- clean shutdown ------------------------------------------------
        cli.shutdown()
        rc = proc.wait(timeout=120)
        out_text = proc.stdout.read() or ""
        proc = None
        if rc != 0:
            print(f"fleet smoke: fleet exit code {rc}:\n{out_text}",
                  file=sys.stderr)
            return 1
        tmp = os.path.join(run_dir, "cnmf_tmp")
        orphans = [fn for fn in os.listdir(tmp)
                   if fn.endswith((".sock", ".tmp"))
                   or fn.startswith(".tmp")]
        if orphans or os.path.exists(sock):
            print(f"fleet smoke: orphans after shutdown: {orphans}",
                  file=sys.stderr)
            return 1

        # -- fleet telemetry: schema-valid, the full audit trail -----------
        ev_path = os.path.join(tmp, "smoke.fleet.events.jsonl")
        n = validate_events_file(ev_path)
        evs = read_events(ev_path)
        deaths = [e for e in evs if e["t"] == "replica_death"]
        fos = [e for e in evs if e["t"] == "failover"]
        rolls = [e for e in evs if e["t"] == "rollover"]
        reqs = [e for e in evs if e["t"] == "serve_request"]
        if not deaths or deaths[0]["reason"] != "exit":
            print(f"fleet smoke: missing/wrong replica_death events: "
                  f"{deaths}", file=sys.stderr)
            return 1
        if not fos or not rolls or rolls[0]["generation"] != 1:
            print(f"fleet smoke: missing failover/rollover events "
                  f"({len(fos)}/{len(rolls)})", file=sys.stderr)
            return 1
        routed = {e.get("replica") for e in reqs
                  if e["status"] == "ok"}
        if len(routed) < 2:
            print(f"fleet smoke: requests never spread over >1 replica "
                  f"({routed})", file=sys.stderr)
            return 1

        print(f"fleet smoke: {total} requests across {len(tenants)} "
              f"tenants all bit-identical to solo refit_usage (v1 or v2, "
              f"never mixed) through a SIGKILLed replica + respawn, a "
              f"zero-downtime rollover with an injected store outage "
              f"(healed), and a router-quarantined poison tenant; SLO "
              f"intact, {n} schema-valid fleet events, clean shutdown")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        if store_srv is not None:
            store_srv.stop()
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
