"""Native (C++) runtime components with lazy in-tree compilation.

The compute path is JAX/XLA; the IO runtime around it is native where the
hot spots are host-bound. Currently: the MatrixMarket coordinate parser
(:func:`read_mtx`), which replaces scipy.io.mmread's pure-Python line
parsing with a single C++ pass over the raw buffer (~20-40x on 10x-scale
files).

The shared library is compiled on first use with the system toolchain and
cached next to the source (``_mtx_reader_<abi>.so``); every entry point
falls back to the scipy implementation if the toolchain or the cached
binary is unavailable, so the package never hard-depends on a compiler.
"""

from __future__ import annotations

import ctypes
import gzip
import os
import subprocess
import sys
import threading

import numpy as np
import scipy.sparse as sp

__all__ = ["read_mtx", "native_available"]

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "mtx_reader.cpp")
_LIB_PATH = os.path.join(
    _HERE, f"_mtx_reader_cp{sys.version_info.major}{sys.version_info.minor}.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _build() -> bool:
    # compile to a process-unique temp path, then atomically rename into
    # place: concurrent worker processes (e.g. a GNU-parallel factorize
    # fleet) may race this build, and a half-written .so at _LIB_PATH would
    # poison every loser of the race. rename() on the same filesystem is
    # atomic, so each racer installs a complete binary and the last one wins.
    tmp_path = f"{_LIB_PATH}.build-{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           _SRC, "-o", tmp_path]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        if res.returncode != 0 or not os.path.exists(tmp_path):
            return False
        os.replace(tmp_path, _LIB_PATH)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp_path):
            try:
                os.unlink(tmp_path)
            except OSError:
                pass


def _load():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.mtx_parse_body.restype = ctypes.c_longlong
        lib.mtx_parse_body.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double), ctypes.c_longlong,
            ctypes.c_int, ctypes.c_int,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _read_raw(path: str) -> bytes:
    if path.endswith(".gz"):
        with gzip.open(path, "rb") as f:
            return f.read()
    with open(path, "rb") as f:
        return f.read()


def read_mtx(path: str) -> sp.coo_matrix:
    """Read a MatrixMarket coordinate file (optionally .gz) to COO.

    Header (banner + size line) parses in Python; the body parses in C++.
    Falls back to ``scipy.io.mmread`` when the native library is
    unavailable or the format is outside the fast path (array format,
    complex fields).
    """
    lib = _load()
    raw = _read_raw(path)

    # banner
    nl = raw.find(b"\n")
    banner = raw[:nl].decode("latin1").lower().split()
    fast = (lib is not None and len(banner) >= 4
            and banner[0] == "%%matrixmarket" and banner[1] == "matrix"
            and banner[2] == "coordinate"
            and banner[3] in ("real", "integer", "pattern")
            and (len(banner) < 5 or banner[4] in ("general",)))
    if not fast:
        import io

        import scipy.io

        return sp.coo_matrix(scipy.io.mmread(io.BytesIO(raw)))

    pattern = banner[3] == "pattern"
    # skip comments to the size line; a truncated file ending mid-comment
    # must raise, not loop (find() returning -1 would reset pos to 0)
    pos = nl + 1
    while pos < len(raw) and raw[pos : pos + 1] == b"%":
        next_nl = raw.find(b"\n", pos)
        if next_nl < 0:
            raise ValueError(f"{path}: truncated header (unterminated comment)")
        pos = next_nl + 1
    size_end = raw.find(b"\n", pos)
    if size_end < 0:
        size_end = len(raw)
    try:
        n_rows, n_cols, nnz = (int(t) for t in raw[pos:size_end].split())
    except ValueError:
        raise ValueError(f"{path}: malformed MatrixMarket size line") from None

    rows = np.empty(nnz, dtype=np.int32)
    cols = np.empty(nnz, dtype=np.int32)
    vals = np.empty(nnz, dtype=np.float64)
    body = raw[size_end + 1:]
    got = lib.mtx_parse_body(
        body, len(body),
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        nnz, int(pattern), 0)
    if got == -(len(body) + 2):
        raise ValueError(
            f"{path}: body contains more entries than the header declares")
    if got < 0:
        raise ValueError(
            f"malformed MatrixMarket entry near byte {-got - 1} of {path}")
    if got != nnz:
        raise ValueError(
            f"{path}: header declares {nnz} entries, parsed {got}")
    if nnz and (rows.max() >= n_rows or cols.max() >= n_cols
                or rows.min() < 0 or cols.min() < 0):
        raise ValueError(f"{path}: entry indices out of declared bounds")
    return sp.coo_matrix((vals, (rows, cols)), shape=(n_rows, n_cols))
