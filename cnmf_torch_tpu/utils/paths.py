"""Artifact path registry.

Reproduces the reference's 25-key templated path dict
(``/root/reference/src/cnmf/cnmf.py:416-455``) byte-for-byte in the
filenames so outputs are drop-in interchangeable: intermediates live in
``output_dir/name/cnmf_tmp/`` and final artifacts in ``output_dir/name/``.
The filesystem remains the durable checkpoint/output layer of the pipeline
(every stage's outputs are its checkpoint); on-device collectives replace it
only as the *communication* layer between replicates.
"""

from __future__ import annotations

import os

from .io import check_dir_exists

__all__ = ["build_paths"]


def build_paths(output_dir: str, name: str, create: bool = True) -> dict:
    if create:
        check_dir_exists(output_dir)
        check_dir_exists(os.path.join(output_dir, name))
        check_dir_exists(os.path.join(output_dir, name, "cnmf_tmp"))

    tmp = os.path.join(output_dir, name, "cnmf_tmp")
    top = os.path.join(output_dir, name)
    return {
        "normalized_counts": os.path.join(tmp, name + ".norm_counts.h5ad"),
        "nmf_replicate_parameters": os.path.join(tmp, name + ".nmf_params.df.npz"),
        "nmf_run_parameters": os.path.join(tmp, name + ".nmf_idvrun_params.yaml"),
        "nmf_genes_list": os.path.join(top, name + ".overdispersed_genes.txt"),

        "tpm": os.path.join(tmp, name + ".tpm.h5ad"),
        "tpm_stats": os.path.join(tmp, name + ".tpm_stats.df.npz"),

        "iter_spectra": os.path.join(tmp, name + ".spectra.k_%d.iter_%d.df.npz"),
        "iter_usages": os.path.join(tmp, name + ".usages.k_%d.iter_%d.df.npz"),
        "merged_spectra": os.path.join(tmp, name + ".spectra.k_%d.merged.df.npz"),

        "local_density_cache": os.path.join(tmp, name + ".local_density_cache.k_%d.merged.df.npz"),
        "consensus_spectra": os.path.join(tmp, name + ".spectra.k_%d.dt_%s.consensus.df.npz"),
        "consensus_spectra__txt": os.path.join(top, name + ".spectra.k_%d.dt_%s.consensus.txt"),
        "consensus_usages": os.path.join(tmp, name + ".usages.k_%d.dt_%s.consensus.df.npz"),
        "consensus_usages__txt": os.path.join(top, name + ".usages.k_%d.dt_%s.consensus.txt"),

        "consensus_stats": os.path.join(tmp, name + ".stats.k_%d.dt_%s.df.npz"),

        "clustering_plot": os.path.join(top, name + ".clustering.k_%d.dt_%s.png"),
        "gene_spectra_score": os.path.join(tmp, name + ".gene_spectra_score.k_%d.dt_%s.df.npz"),
        "gene_spectra_score__txt": os.path.join(top, name + ".gene_spectra_score.k_%d.dt_%s.txt"),
        "gene_spectra_tpm": os.path.join(tmp, name + ".gene_spectra_tpm.k_%d.dt_%s.df.npz"),
        "gene_spectra_tpm__txt": os.path.join(top, name + ".gene_spectra_tpm.k_%d.dt_%s.txt"),

        "starcat_spectra": os.path.join(tmp, name + ".starcat_spectra.k_%d.dt_%s.df.npz"),
        "starcat_spectra__txt": os.path.join(top, name + ".starcat_spectra.k_%d.dt_%s.txt"),

        "k_selection_plot": os.path.join(top, name + ".k_selection.png"),
        "k_selection_stats": os.path.join(top, name + ".k_selection_stats.df.npz"),

        # TPU-build addition (no reference counterpart): what factorize
        # ACTUALLY ran — engaged execution path + effective solver params —
        # so provenance matches execution even when auto-rowshard swaps the
        # solver family away from the prepared ledger's settings. Templated
        # on worker index: fleet workers must not clobber each other's
        # records (same write-disjointness rule as iter_spectra).
        "factorize_provenance": os.path.join(tmp, name + ".factorize_provenance.w%d.yaml"),

        # TPU-build addition (ISSUE 5): per-worker resilience ledger —
        # reseeded-retry records (original seed, attempt, derived seed,
        # outcome) and quarantined (k, iter) pairs that combine must
        # treat as deliberately absent. Worker-templated like provenance.
        "resilience_ledger": os.path.join(tmp, name + ".resilience.w%d.json"),

        # TPU-build addition (ISSUE 10): out-of-core row-slab shard store
        # (utils/shardstore.py) written at prepare next to the normalized
        # h5ad — per-slab npz shards + a digest-validated manifest, so
        # factorize workers stream only their own row-range slabs from
        # disk instead of each materializing the full matrix in host RAM.
        "shard_store": os.path.join(tmp, name + ".norm_counts.store"),

        # TPU-build addition (ISSUE 6): per-replicate mid-run pass
        # checkpoint (runtime/checkpoint.py) — (A, B)/W/cursor state the
        # rowsharded factorize persists every CNMF_TPU_CKPT_EVERY_PASSES
        # passes and discards once the replicate's spectra artifact
        # lands. The basename contains "ckpt" so the torn:artifact=ckpt
        # chaos clause can target it.
        "pass_checkpoint": os.path.join(tmp, name + ".ckpt.k_%d.iter_%d.npz"),
    }
