"""Elastic degraded-mesh execution (ISSUE 8): heartbeat liveness,
host/device-loss detection + degraded re-mesh continuation, launcher
work-stealing, and straggler containment.

The integration tests inject topology faults through
``CNMF_TPU_FAULT_SPEC`` (``hostloss`` / ``straggler`` clauses,
runtime/faults.py) — the same deterministic harness the chaos smoke gate
uses — so every recovery path exercises the production code."""

import json
import os
import sys
import threading
import time
import uuid
import warnings as _warnings

import numpy as np
import pandas as pd
import pytest

import jax
from jax.sharding import Mesh

from cnmf_torch_tpu import cNMF, load_df_from_npz, save_df_to_npz
from cnmf_torch_tpu.runtime import elastic, faults, resilience


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_elastic_knob_defaults_and_validation(monkeypatch):
    for var in (elastic.ELASTIC_ENV, elastic.HEARTBEAT_ENV,
                elastic.STRAGGLER_ENV, elastic.MIN_DEVICES_ENV):
        monkeypatch.delenv(var, raising=False)
    assert elastic.elastic_enabled() is True
    assert elastic.heartbeat_s() == 0.0
    assert elastic.straggler_deadline_s() == 0.0
    assert elastic.min_surviving_devices() == 1

    monkeypatch.setenv(elastic.ELASTIC_ENV, "0")
    assert elastic.elastic_enabled() is False
    monkeypatch.setenv(elastic.HEARTBEAT_ENV, "2.5")
    assert elastic.heartbeat_s() == 2.5
    for var, bad in ((elastic.HEARTBEAT_ENV, "-1"),
                     (elastic.STRAGGLER_ENV, "soon"),
                     (elastic.MIN_DEVICES_ENV, "0")):
        monkeypatch.setenv(var, bad)
        with pytest.raises(ValueError, match=var):
            {elastic.HEARTBEAT_ENV: elastic.heartbeat_s,
             elastic.STRAGGLER_ENV: elastic.straggler_deadline_s,
             elastic.MIN_DEVICES_ENV: elastic.min_surviving_devices}[var]()
        monkeypatch.delenv(var)


# ---------------------------------------------------------------------------
# heartbeat liveness
# ---------------------------------------------------------------------------

def test_heartbeat_write_probe_and_culprits(tmp_path):
    hb0 = elastic.Heartbeat(tmp_path, "run", 0, interval_s=0.01)
    hb1 = elastic.Heartbeat(tmp_path, "run", 1, interval_s=0.01)
    assert hb0.beat(phase="pass", cursor=7)
    assert hb1.beat(phase="stage_x")

    ages = hb0.probe_peers(3)
    assert ages[0] is not None and ages[0] < 60
    assert ages[1] is not None
    assert ages[2] is None  # never stamped

    # age out participant 1 by rewriting its stamp into the past
    rec = elastic.Heartbeat.read(hb0.path_for(1))
    rec["ts"] -= 1000.0
    with open(hb0.path_for(1), "w") as f:  # test fixture, not an artifact
        json.dump(rec, f)

    culprits = hb0.culprits(3, stale_after_s=100.0)
    assert [c["index"] for c in culprits] == [1, 2]
    assert culprits[0]["age_s"] > 100 and culprits[0]["phase"] == "stage_x"
    assert culprits[1]["age_s"] is None
    msg = elastic.Heartbeat.describe(culprits)
    assert "participant 1" in msg and "never stamped" in msg
    # a live peer is never a culprit; self is excluded by default
    assert all(c["index"] != 0 for c in hb1.culprits(3, stale_after_s=1e6))
    assert elastic.Heartbeat.describe([]).startswith("no stale heartbeats")


def test_heartbeat_throttle_and_disable(tmp_path):
    hb = elastic.Heartbeat(tmp_path, "thr", 0, interval_s=30.0)
    assert hb.beat(phase="a")
    assert not hb.beat(phase="b")           # throttled
    assert hb.beat(phase="c", force=True)   # phase transition bypasses
    assert elastic.Heartbeat.read(hb.path)["phase"] == "c"

    off = elastic.Heartbeat(tmp_path, "off", 0, interval_s=0.0)
    assert not off.enabled
    assert not off.beat(force=True)
    assert not os.path.exists(off.path)


# ---------------------------------------------------------------------------
# fault clauses: hostloss, straggler
# ---------------------------------------------------------------------------

def test_hostloss_clause_raises_with_lost_devices(monkeypatch):
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "hostloss:context=pass,devices=2+3,after=1")
    faults.maybe_hostloss(context="replicate")       # context mismatch
    faults.maybe_hostloss(context="pass")            # after=1 skips hit 1
    with pytest.raises(faults.HostLossError) as exc_info:
        faults.maybe_hostloss(context="pass")
    assert exc_info.value.lost == (2, 3)
    # default limit=1: the degraded continuation runs clean
    faults.maybe_hostloss(context="pass")


def test_hostloss_clause_count_and_worker_selector(monkeypatch):
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "hostloss:worker=1,count=2")
    faults.maybe_hostloss(context="pass", worker=0)  # wrong worker
    faults.maybe_hostloss(context="pass", worker=None)
    with pytest.raises(faults.HostLossError) as exc_info:
        faults.maybe_hostloss(context="pass", worker=1)
    assert exc_info.value.lost == () and exc_info.value.count == 2


def test_straggler_clause_sleeps_and_honors_once(tmp_path, monkeypatch):
    sentinel = str(tmp_path / "straggle.once")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       f"straggler:worker=1,seconds=0.2,once={sentinel}")
    assert faults.maybe_straggle(context="factorize", worker=0) == 0.0
    t0 = time.monotonic()
    assert faults.maybe_straggle(context="factorize", worker=1) == 0.2
    assert time.monotonic() - t0 >= 0.2
    # `once` claimed: an adopter process (or later hits) runs fast
    assert faults.maybe_straggle(context="factorize", worker=1) == 0.0


def test_straggler_clause_unbounded_without_limit(monkeypatch):
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "straggler:seconds=0.01")
    slept = [faults.maybe_straggle(context="factorize", worker=0)
             for _ in range(3)]
    assert slept == [0.01, 0.01, 0.01]  # consistently slow, not one-shot


# ---------------------------------------------------------------------------
# loss detection + degraded-mesh planning
# ---------------------------------------------------------------------------

def test_is_device_loss_classification():
    assert elastic.is_device_loss(faults.HostLossError("x", lost=(1,)))
    assert elastic.is_device_loss(RuntimeError("DATA_LOSS: socket closed"))
    assert elastic.is_device_loss(RuntimeError("Device halted: core dumped"))
    assert not elastic.is_device_loss(RuntimeError("nan in objective"))
    assert not elastic.is_device_loss(ValueError("socket closed"))
    # ordinary IO errors must NEVER shrink the mesh: an EBUSY from a
    # checkpoint write or a stray socket reset is a retry/abort, not a
    # topology loss
    assert not elastic.is_device_loss(
        OSError(16, "Device or resource busy"))
    assert not elastic.is_device_loss(
        OSError(104, "Connection reset by peer"))


def test_resolve_lost_devices_ids_and_count():
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs), ("cells",))
    exc = faults.HostLossError("x", lost=(devs[1].id, devs[2].id))
    lost = elastic.resolve_lost_devices(exc, mesh)
    assert [d.id for d in lost] == [devs[1].id, devs[2].id]
    # count fallback: the trailing devices
    lost = elastic.resolve_lost_devices(faults.HostLossError("x", count=2),
                                        mesh)
    assert [d.id for d in lost] == [devs[2].id, devs[3].id]
    # a real (non-injected) loss defaults to one trailing device
    lost = elastic.resolve_lost_devices(RuntimeError("socket closed"), mesh)
    assert [d.id for d in lost] == [devs[3].id]


def test_plan_degraded_mesh_1d_and_2d(monkeypatch):
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs), ("cells",))
    small = elastic.plan_degraded_mesh(mesh, devs[2:])
    assert small.axis_names == ("cells",)
    assert [d.id for d in small.devices.flat] == [devs[0].id, devs[1].id]

    from cnmf_torch_tpu.parallel import mesh_2d

    mesh2 = mesh_2d(replicate_shards=2, devices=devs)   # (2, 2)
    shrunk = elastic.plan_degraded_mesh(mesh2, [devs[3]])
    assert shrunk.axis_names == ("replicates", "cells")
    assert int(np.prod(shrunk.devices.shape)) == 3

    monkeypatch.setenv(elastic.MIN_DEVICES_ENV, "4")
    with pytest.raises(elastic.DegradedMeshError, match="below the"):
        elastic.plan_degraded_mesh(mesh, [devs[3]])


# ---------------------------------------------------------------------------
# barrier watchdog: no zombie threads, abandonment logged once
# ---------------------------------------------------------------------------

def _barrier_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("cnmf-barrier-")]


def test_wait_with_timeout_joins_every_nonwedge_path():
    """Satellite (ISSUE 8): success and error paths must JOIN the barrier
    thread — only a genuine wedge abandons it."""
    from cnmf_torch_tpu.parallel.multihost import _wait_with_timeout

    done = []
    _wait_with_timeout(lambda: done.append(1), 5.0, uuid.uuid4().hex)
    assert done == [1] and not _barrier_threads()

    def boom():
        raise RuntimeError("collective failed")

    with pytest.raises(RuntimeError, match="collective failed"):
        _wait_with_timeout(boom, 5.0, uuid.uuid4().hex)
    assert not _barrier_threads()


def test_wait_with_timeout_abandonment_logged_once_with_name():
    from cnmf_torch_tpu.parallel.multihost import (HostBarrierTimeout,
                                                   _wait_with_timeout)

    name = "wedge-" + uuid.uuid4().hex[:8]
    release = threading.Event()
    with pytest.warns(RuntimeWarning, match=name):
        with pytest.raises(HostBarrierTimeout):
            _wait_with_timeout(release.wait, 0.1, name)
    # second wedge on the SAME barrier name: no second log line
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        with pytest.raises(HostBarrierTimeout):
            _wait_with_timeout(release.wait, 0.1, name)
    assert not any("abandoning" in str(w.message) for w in caught)
    release.set()  # let the abandoned threads exit promptly


def test_sync_hosts_single_process_noop_with_heartbeat(tmp_path):
    from cnmf_torch_tpu.parallel import sync_hosts

    hb = elastic.Heartbeat(tmp_path, "sync", 0, interval_s=0.01)
    sync_hosts("unit", heartbeat=hb)  # single-process: no barrier, no beat
    assert not os.path.exists(hb.path)


def test_sync_hosts_timeout_names_culprit(tmp_path, monkeypatch):
    """A barrier timeout under heartbeat liveness is DIAGNOSED: the
    re-raised HostBarrierTimeout names the peer whose heartbeat went
    silent (with its last phase/cursor) and emits a host_loss fault."""
    from cnmf_torch_tpu.parallel import multihost

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost, "_wait_with_timeout",
        lambda fn, timeout_s, name: (_ for _ in ()).throw(
            multihost.HostBarrierTimeout(f"barrier {name!r} timed out.")))

    sink = []

    class _Events:
        def emit(self, t, **fields):
            sink.append((t, fields))

    hb = elastic.Heartbeat(tmp_path, "pod", 0, interval_s=0.01,
                           events=_Events())
    hb.beat(phase="pass", cursor=3, force=True)  # self is alive
    # peer 1 never stamped at all
    with pytest.raises(multihost.HostBarrierTimeout) as exc_info:
        multihost.sync_hosts("factorize_2d", timeout_s=1.0, heartbeat=hb)
    assert exc_info.value.culprits == [
        {"index": 1, "age_s": None, "phase": None, "cursor": None}]
    assert "participant 1" in str(exc_info.value)
    assert [(t, f["kind"]) for t, f in sink] == [("fault", "host_loss")]
    assert sink[0][1]["context"]["barrier"] == "factorize_2d"


# ---------------------------------------------------------------------------
# integration: degraded re-mesh continuation through factorize
# ---------------------------------------------------------------------------

def _prepare_mini(tmp_path, name, components=(3,), n_iter=2, seed=4):
    counts = np.random.default_rng(5).binomial(
        40, 0.02, size=(60, 100)).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    counts_fn = str(tmp_path / f"{name}_counts.df.npz")
    save_df_to_npz(df, counts_fn)
    obj = cNMF(output_dir=str(tmp_path), name=name)
    obj.prepare(counts_fn, components=list(components), n_iter=n_iter,
                seed=seed, num_highvar_genes=50, batch_size=64,
                max_NMF_iter=50)
    return obj


def _fault_kinds(tmp_path, name):
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    ev_path = os.path.join(str(tmp_path), name, "cnmf_tmp",
                           f"{name}.events.jsonl")
    validate_events_file(ev_path)
    return [e["kind"] for e in read_events(ev_path) if e["t"] == "fault"]


def test_rowshard_boundary_loss_bit_identical(tmp_path, monkeypatch):
    """A host dies at a replicate's post-checkpoint boundary (after its
    final pass checkpointed, before the artifact write): the degraded
    continuation completes the replicate FROM the checkpoint with zero
    passes on the shrunk mesh — merged artifacts bit-identical to an
    uninterrupted run (H under the byte budget)."""
    clean = _prepare_mini(tmp_path, "rsclean")
    clean.factorize(rowshard=True)

    lossy = _prepare_mini(tmp_path, "rsloss")
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "hostloss:context=replicate,after=1,count=4")
    with pytest.warns(RuntimeWarning, match="continuing degraded"):
        lossy.factorize(rowshard=True)
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)

    for it in range(2):
        a = load_df_from_npz(clean.paths["iter_spectra"] % (3, it)).values
        b = load_df_from_npz(lossy.paths["iter_spectra"] % (3, it)).values
        np.testing.assert_array_equal(a, b)
    kinds = _fault_kinds(tmp_path, "rsloss")
    assert "host_loss" in kinds and "remesh" in kinds
    # the host-loss record also lands in the resilience ledger audit trail
    with open(lossy.paths["resilience_ledger"] % 0) as f:
        ledger = json.load(f)
    assert any(rec["kind"] == "host_loss"
               for rec in ledger.get("shard_faults", []))
    # no zombie staging/barrier threads, no leftover checkpoints
    assert not [t for t in threading.enumerate()
                if t.name.startswith("cnmf-")]
    import glob

    assert not glob.glob(os.path.join(str(tmp_path), "rsloss", "cnmf_tmp",
                                      "*.ckpt.*"))


def test_rowshard_midpass_loss_completes_within_tolerance(tmp_path,
                                                          monkeypatch):
    """A mid-pass loss resumes from the checkpoint cursor and finishes the
    remaining passes on the shrunk mesh: completion + validity are
    guaranteed, parity is at solver tolerance (the shrunk mesh's psum
    reduction order differs at float rounding)."""
    clean = _prepare_mini(tmp_path, "mpclean", n_iter=1)
    clean.factorize(rowshard=True)

    lossy = _prepare_mini(tmp_path, "mploss", n_iter=1)
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "hostloss:context=pass,after=3,count=4")
    with pytest.warns(RuntimeWarning, match="continuing degraded"):
        lossy.factorize(rowshard=True)
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)

    a = load_df_from_npz(clean.paths["iter_spectra"] % (3, 0)).values
    b = load_df_from_npz(lossy.paths["iter_spectra"] % (3, 0)).values
    assert np.isfinite(b).all() and (b >= 0).all()
    # same optimum to solver tolerance, not necessarily bit-identical
    assert np.abs(a - b).max() / max(np.abs(a).max(), 1e-9) < 0.2
    kinds = _fault_kinds(tmp_path, "mploss")
    assert "host_loss" in kinds and "remesh" in kinds
    from cnmf_torch_tpu.utils.telemetry import read_events

    ev = read_events(os.path.join(str(tmp_path), "mploss", "cnmf_tmp",
                                  "mploss.events.jsonl"))
    resumes = [e for e in ev
               if e["t"] == "checkpoint" and e["action"] == "resume"]
    assert resumes and int(resumes[0]["context"]["pass_idx"]) >= 1


def test_rowshard_midpass_loss_over_h_budget(tmp_path, monkeypatch):
    """Over the H byte budget the checkpoint carries only (A, B)/W: a
    mid-pass loss re-derives usages from the restored spectra on the
    shrunk mesh and still completes within solver tolerance — the
    sufficient-statistics trade, degraded."""
    obj = _prepare_mini(tmp_path, "nohb", n_iter=1)
    monkeypatch.setenv("CNMF_TPU_CKPT_H_BYTES", "0")
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    # NOTE: spec string must differ from the test above — parsed clauses
    # (and their per-process injection counters) are cached per raw value
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "hostloss:context=pass,after=4,count=4")
    with pytest.warns(RuntimeWarning, match="continuing degraded"):
        obj.factorize(rowshard=True)
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)
    vals = load_df_from_npz(obj.paths["iter_spectra"] % (3, 0)).values
    assert np.isfinite(vals).all() and (vals >= 0).all()
    from cnmf_torch_tpu.utils.telemetry import read_events

    ev = read_events(os.path.join(str(tmp_path), "nohb", "cnmf_tmp",
                                  "nohb.events.jsonl"))
    resumes = [e for e in ev
               if e["t"] == "checkpoint" and e["action"] == "resume"]
    assert resumes and resumes[0]["context"]["with_h"] is False


def test_rowshard_loss_respects_elastic_off_and_min_devices(tmp_path,
                                                            monkeypatch):
    obj = _prepare_mini(tmp_path, "rsoff", n_iter=1)
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "hostloss:context=pass,after=1,count=2")
    monkeypatch.setenv(elastic.ELASTIC_ENV, "0")
    with pytest.raises(faults.HostLossError):
        obj.factorize(rowshard=True)
    monkeypatch.delenv(elastic.ELASTIC_ENV)

    # min-devices floor: losing 7 of 8 under a floor of 4 aborts cleanly
    obj2 = _prepare_mini(tmp_path, "rsfloor", n_iter=1)
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "hostloss:context=pass,after=1,count=7")
    monkeypatch.setenv(elastic.MIN_DEVICES_ENV, "4")
    with pytest.raises(elastic.DegradedMeshError, match="below the"):
        obj2.factorize(rowshard=True)


def test_factorize_2d_loss_remeshes_and_completes(tmp_path, monkeypatch):
    """Single-controller 2-D path: a lost device re-plans the
    (replicates x cells) mesh via _balanced_rc over the survivors, X
    re-stages, and the interrupted K's sweep reruns whole."""
    obj = _prepare_mini(tmp_path, "m2dloss")
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "hostloss:context=sweep2d,count=2")
    with pytest.warns(RuntimeWarning, match="re-planned"):
        obj.factorize(mesh="2d")
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)
    for it in range(2):
        vals = load_df_from_npz(obj.paths["iter_spectra"] % (3, it)).values
        assert np.isfinite(vals).all()
    kinds = _fault_kinds(tmp_path, "m2dloss")
    assert "host_loss" in kinds and "remesh" in kinds
    merged = obj.combine_nmf(3)
    assert merged.shape[0] == 2 * 3


def test_rowshard_heartbeat_stamps_pass_cursor(tmp_path, monkeypatch):
    obj = _prepare_mini(tmp_path, "hb", n_iter=1)
    monkeypatch.setenv(elastic.HEARTBEAT_ENV, "0.001")
    obj.factorize(rowshard=True)
    hb_path = os.path.join(str(tmp_path), "hb", "cnmf_tmp",
                           "hb.heartbeat.0.json")
    rec = elastic.Heartbeat.read(hb_path)
    assert rec is not None and rec["index"] == 0
    assert rec["phase"] == "pass" and rec["cursor"] >= 1


# ---------------------------------------------------------------------------
# launcher: work-stealing + straggler containment (monkeypatched workers)
# ---------------------------------------------------------------------------

class _EventSink:
    def __init__(self):
        self.events = []

    def emit(self, event_type, **fields):
        self.events.append((event_type, fields))

    def kinds(self):
        return [f.get("kind") for t, f in self.events if t == "fault"]


def _indexed_cmd(spawned, behaviors):
    """fake _worker_cmd: each worker index runs its own inline script;
    the script may branch on whether this spawn is a resume/adoption."""
    def fake_cmd(od, nm, extra):
        spawned.append(list(extra))
        i = int(extra[extra.index("--worker-index") + 1])
        resume = "--skip-completed-runs" in extra
        return [sys.executable, "-c", behaviors[i](resume)]
    return fake_cmd


def test_launcher_steals_dead_shard_immediately(tmp_path, monkeypatch):
    """Once a worker has finished cleanly, a dead worker's shard is
    adopted NOW (work-stealing) instead of waiting out the fixed-shard
    backoff — and the adoption resumes via --skip-completed-runs."""
    from cnmf_torch_tpu import launcher

    spawned: list = []
    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        # dies well after worker 0's interpreter can start and exit, so
        # the fleet is provably idle when the death is observed
        1: lambda resume: ("import sys; sys.exit(0)" if resume else
                           "import sys, time; time.sleep(1.5); sys.exit(5)"),
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "1")
    monkeypatch.setenv("CNMF_TPU_WORKER_BACKOFF_S", "30")  # steal skips it
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    sink = _EventSink()
    t0 = time.monotonic()
    with pytest.warns(RuntimeWarning, match="work-stealing"):
        failed, unhealthy = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert time.monotonic() - t0 < 20  # did NOT serve the 30s backoff
    assert failed == set() and unhealthy == set()
    adoption = spawned[-1]
    assert "--skip-completed-runs" in adoption
    assert adoption[adoption.index("--worker-index") + 1] == "1"
    assert sink.kinds() == ["worker_steal"]


def test_launcher_bonus_adoption_after_respawn_budget(tmp_path, monkeypatch):
    """A shard whose respawn budget is exhausted gets ONE adoption wave by
    the proven-healthy fleet before combine degrades around it; with
    CNMF_TPU_ELASTIC=0 the old budget-then-skip behavior returns."""
    from cnmf_torch_tpu import launcher

    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        1: lambda resume: ("import sys; sys.exit(0)" if resume else
                           "import sys, time; time.sleep(1.5); sys.exit(5)"),
    }
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "0")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)

    spawned: list = []
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    sink = _EventSink()
    with pytest.warns(RuntimeWarning, match="adoption wave"):
        failed, _ = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert failed == set()
    assert [f.get("context", {}).get("reason") for t, f in sink.events
            if f.get("kind") == "worker_steal"] \
        == ["respawn_budget_exhausted"]

    spawned2: list = []
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned2, behaviors))
    monkeypatch.setenv(elastic.ELASTIC_ENV, "0")
    with pytest.warns(RuntimeWarning, match="skipped at combine"):
        failed, _ = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ))
    assert failed == {1}
    assert len(spawned2) == 2  # no adoption spawn with elastic off


def test_launcher_straggler_contained_and_adopted(tmp_path, monkeypatch):
    """Once the first worker finishes, a worker still running
    CNMF_TPU_STRAGGLER_S later is killed (straggler telemetry) and its
    shard adopted — the sweep completes without serving the slow shard's
    full runtime."""
    from cnmf_torch_tpu import launcher

    spawned: list = []
    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        1: lambda resume: ("import sys; sys.exit(0)" if resume else
                           "import time; time.sleep(60)"),
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "1")
    monkeypatch.setenv("CNMF_TPU_WORKER_BACKOFF_S", "0.05")
    monkeypatch.setenv(elastic.STRAGGLER_ENV, "0.5")
    # conviction needs liveness armed; the fake straggler never beats,
    # so its missing heartbeat is the "no progress" evidence
    monkeypatch.setenv(elastic.HEARTBEAT_ENV, "0.1")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    sink = _EventSink()
    t0 = time.monotonic()
    with pytest.warns(RuntimeWarning, match="straggler"):
        failed, unhealthy = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert time.monotonic() - t0 < 30  # nowhere near the 60s sleep
    assert failed == set() and unhealthy == set()
    assert "straggler" in sink.kinds() and "worker_steal" in sink.kinds()


def test_launcher_straggler_convicts_at_most_once_per_shard(tmp_path,
                                                            monkeypatch):
    """One conviction per shard: when the containment respawn ALSO runs
    past the deadline without beating (a long jitted dispatch cannot
    stamp liveness mid-flight), it is left to finish instead of being
    killed again — the straggler path alone can never permanently fail
    a shard."""
    from cnmf_torch_tpu import launcher

    spawned: list = []
    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        # fresh run wedges forever; the adoption is slow (well past the
        # deadline, never beating) but must run to completion
        1: lambda resume: ("import sys, time; time.sleep(2.5); sys.exit(0)"
                           if resume else "import time; time.sleep(60)"),
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "3")
    monkeypatch.setenv("CNMF_TPU_WORKER_BACKOFF_S", "0.05")
    monkeypatch.setenv(elastic.STRAGGLER_ENV, "0.5")
    monkeypatch.setenv(elastic.HEARTBEAT_ENV, "0.1")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    sink = _EventSink()
    with pytest.warns(RuntimeWarning, match="straggler"):
        failed, _ = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert failed == set()
    assert sink.kinds().count("straggler") == 1
    assert len(spawned) == 3  # initial pair + exactly one containment


def test_launcher_deferred_adoption_after_early_budget_exhaustion(
        tmp_path, monkeypatch):
    """A shard whose respawn budget dies before ANY worker finishes is
    parked, and its adoption wave fires once the first clean finisher
    proves the environment — early crashes do not forfeit the wave."""
    from cnmf_torch_tpu import launcher

    spawned: list = []
    behaviors = {
        # slow healthy worker: finishes well after shard 1's budget dies
        0: lambda resume: "import sys, time; time.sleep(1.5); sys.exit(0)",
        1: lambda resume: ("import sys; sys.exit(0)" if resume else
                           "import sys; sys.exit(5)"),
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "0")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    sink = _EventSink()
    with pytest.warns(RuntimeWarning, match="deferred"):
        failed, _ = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert failed == set()
    steals = [f["context"] for t, f in sink.events
              if f.get("kind") == "worker_steal"]
    assert [s["reason"] for s in steals] == ["deferred_until_fleet_proved"]
    # with nothing ever finishing, the deferred shard fails like before
    behaviors2 = {
        0: lambda resume: "import sys; sys.exit(7)",
        1: lambda resume: "import sys; sys.exit(5)",
    }
    spawned2: list = []
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned2, behaviors2))
    with pytest.warns(RuntimeWarning, match="never ran"):
        failed, _ = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ))
    assert failed == {0, 1}


def test_launcher_straggler_never_convicts_without_recovery_lever(
        tmp_path, monkeypatch):
    """With the respawn budget and the adoption wave both spent, a
    conviction would permanently fail the shard — strictly worse than
    letting the still-working process finish, so it must not fire."""
    from cnmf_torch_tpu import launcher

    spawned: list = []
    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        # fresh spawn crashes quickly (before any deadline), burning the
        # 0-respawn budget; the (last-lever) adoption is then slow and
        # silent past the deadline but must be left to complete
        1: lambda resume: ("import sys, time; time.sleep(2.5); sys.exit(0)"
                           if resume else
                           "import sys, time; time.sleep(0.3); sys.exit(5)"),
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "0")
    monkeypatch.setenv(elastic.STRAGGLER_ENV, "0.5")
    monkeypatch.setenv(elastic.HEARTBEAT_ENV, "0.1")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    sink = _EventSink()
    with pytest.warns(RuntimeWarning, match="adoption"):
        failed, _ = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert failed == set()
    assert "straggler" not in sink.kinds()


def test_rowshard_elastic_gated_on_single_process(tmp_path, monkeypatch):
    """Multi-host pods cannot shrink in-process (survivors' collectives
    still span the dead host): the rowshard path must propagate the loss
    as the pre-elastic clean abort, exactly like the 2-D path."""
    import jax

    obj = _prepare_mini(tmp_path, "mh", n_iter=1)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "hostloss:context=pass,after=1,count=1")
    with pytest.raises(faults.HostLossError):
        obj.factorize(rowshard=True)


def test_launcher_straggler_requires_liveness(tmp_path, monkeypatch):
    """Without CNMF_TPU_HEARTBEAT_S there is no progress evidence, so the
    deadline is disabled (with a warning) rather than convicting on wall
    clock alone — a resumed run's near-instant complete shard must never
    get a slow-but-healthy peer killed."""
    from cnmf_torch_tpu import launcher

    spawned: list = []
    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        1: lambda resume: "import sys, time; time.sleep(2.0); sys.exit(0)",
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv(elastic.STRAGGLER_ENV, "0.3")
    monkeypatch.delenv(elastic.HEARTBEAT_ENV, raising=False)
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    sink = _EventSink()
    with pytest.warns(RuntimeWarning, match="needs liveness"):
        failed, unhealthy = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert failed == set() and unhealthy == set()
    assert sink.kinds() == [] and len(spawned) == 2  # ran to completion


def test_launcher_straggler_deadline_measured_from_respawn(tmp_path,
                                                           monkeypatch):
    """The deadline is each process's OWN elapsed vs the first finisher's
    wall + grace: an adoption spawned long after the first finisher gets
    the full allowance from its own start — never an instant kill while
    it legitimately redoes a whole shard."""
    from cnmf_torch_tpu import launcher

    spawned: list = []
    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        # the adoption outlives the wall-clock deadline the OLD absolute
        # rule would have imposed — it must still run to completion
        1: lambda resume: ("import sys, time; time.sleep(1.6); sys.exit(0)"
                           if resume else "import time; time.sleep(60)"),
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "3")
    monkeypatch.setenv("CNMF_TPU_WORKER_BACKOFF_S", "0.05")
    monkeypatch.setenv(elastic.STRAGGLER_ENV, "2.0")
    monkeypatch.setenv(elastic.HEARTBEAT_ENV, "0.1")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    with pytest.warns(RuntimeWarning, match="straggler"):
        failed, _ = launcher._run_subprocess_workers(
            str(tmp_path), "x", 2, [], dict(os.environ))
    assert failed == set()


def test_launcher_straggler_spared_by_fresh_heartbeat(tmp_path, monkeypatch):
    """A worker past the wall deadline but with a FRESH heartbeat is
    demonstrably progressing and must not be convicted — the protection
    for resumed runs' wildly unequal shards."""
    from cnmf_torch_tpu import launcher

    (tmp_path / "x" / "cnmf_tmp").mkdir(parents=True)
    hb_path = tmp_path / "x" / "cnmf_tmp" / "x.heartbeat.1.json"
    beat_script = (
        "import json, time\n"
        f"p = {str(hb_path)!r}\n"
        "for c in range(6):\n"
        "    with open(p + '.tmp', 'w') as f:\n"
        "        json.dump({'index': 1, 'pid': 0, 'ts': time.time(),"
        " 'phase': 'pass', 'cursor': c}, f)\n"
        "    import os; os.replace(p + '.tmp', p)\n"
        "    time.sleep(0.4)\n")
    spawned: list = []
    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        1: lambda resume: beat_script,  # slow (2.4s) but always beating
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "1")
    monkeypatch.setenv(elastic.STRAGGLER_ENV, "1.0")
    monkeypatch.setenv(elastic.HEARTBEAT_ENV, "0.1")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    sink = _EventSink()
    failed, unhealthy = launcher._run_subprocess_workers(
        str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert failed == set() and unhealthy == set()
    assert "straggler" not in sink.kinds()  # progress vetoed the kill
    assert len(spawned) == 2                # no containment respawns


def test_launcher_straggler_inert_with_elastic_off(tmp_path, monkeypatch):
    """CNMF_TPU_ELASTIC=0 restores pre-elastic behavior: the straggler
    deadline never fires, the slow-but-healthy worker runs to
    completion."""
    from cnmf_torch_tpu import launcher

    spawned: list = []
    behaviors = {
        0: lambda resume: "import sys; sys.exit(0)",
        1: lambda resume: "import sys, time; time.sleep(2.0); sys.exit(0)",
    }
    monkeypatch.setattr(launcher, "_worker_cmd",
                        _indexed_cmd(spawned, behaviors))
    monkeypatch.setenv(elastic.ELASTIC_ENV, "0")
    monkeypatch.setenv(elastic.STRAGGLER_ENV, "0.3")
    monkeypatch.setenv(elastic.HEARTBEAT_ENV, "0.1")  # armed, but elastic off
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    sink = _EventSink()
    failed, unhealthy = launcher._run_subprocess_workers(
        str(tmp_path), "x", 2, [], dict(os.environ), events=sink)
    assert failed == set() and unhealthy == set()
    assert sink.kinds() == [] and len(spawned) == 2


# ---------------------------------------------------------------------------
# telemetry: mesh-elasticity summary + report table
# ---------------------------------------------------------------------------

def test_summarize_and_report_render_mesh_elasticity(tmp_path):
    from cnmf_torch_tpu.utils.telemetry import (EventLog, render_report,
                                                summarize_events,
                                                validate_events_file)

    run_dir = tmp_path / "run"
    (run_dir / "cnmf_tmp").mkdir(parents=True)
    path = str(run_dir / "cnmf_tmp" / "run.events.jsonl")
    os.environ["CNMF_TPU_TELEMETRY"] = "1"
    try:
        log = EventLog(path)
        log.emit("fault", kind="host_loss",
                 context={"context": "rowshard", "lost_devices": [2, 3]})
        log.emit("fault", kind="remesh",
                 context={"from_devices": 4, "to_devices": 2})
        log.emit("fault", kind="worker_steal",
                 context={"shard": 1, "reason": "dead_worker"})
        log.emit("fault", kind="straggler",
                 context={"worker": 1, "deadline_s": 2.0})
        log.emit("checkpoint", action="resume",
                 context={"k": 3, "pass_idx": 17, "path": "x"})
    finally:
        del os.environ["CNMF_TPU_TELEMETRY"]
    validate_events_file(path)

    from cnmf_torch_tpu.utils.telemetry import read_events

    summary = summarize_events(read_events(path))
    assert summary["elasticity"] == {
        "host_losses": 1, "remeshes": 1, "stolen_shards": 1,
        "stragglers": 1, "remesh_devices": ["4->2"], "max_resume_pass": 17}

    report = render_report(str(run_dir))
    assert "Mesh elasticity" in report
    assert "degraded re-meshes" in report and "4->2" in report
    assert "stolen worker shards" in report
    assert "deepest resumed pass" in report and "17" in report


# ---------------------------------------------------------------------------
# satellite: adopted-shard ledger accounting
# ---------------------------------------------------------------------------

def test_adoption_carries_quarantine_ledger_once(tmp_path, monkeypatch):
    """Work-stealing accounting: when the fleet adopts a dead worker's
    shard (factorize --worker-index N --skip-completed-runs), the orphan
    shard's quarantine records carry into the ADOPTER's rewrite of the
    same w<N> ledger — exactly once, still excluded at combine, and the
    min-healthy-frac floor sees the shard's true per-K state."""
    obj = _prepare_mini(tmp_path, "adopt", n_iter=4)
    # worker 1 owns iters 1 and 3 of the round-robin ledger shard
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=3,iter=1")
    monkeypatch.setenv(resilience.MAX_RETRIES_ENV, "0")
    monkeypatch.setenv(resilience.MIN_HEALTHY_FRAC_ENV, "0.4")
    with pytest.warns(RuntimeWarning, match="quarantined"):
        obj.factorize(worker_i=1, total_workers=2)
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)
    ledger_path = obj.paths["resilience_ledger"] % 1
    with open(ledger_path) as f:
        before = json.load(f)
    assert [(q["k"], q["iter"]) for q in before["quarantined"]] == [(3, 1)]

    # the adoption: a fresh process resumes shard 1 (clean spec). The
    # carried quarantine must neither vanish nor double-count.
    obj.factorize(worker_i=1, total_workers=2, skip_completed_runs=True)
    with open(ledger_path) as f:
        after = json.load(f)
    assert [(q["k"], q["iter"]) for q in after["quarantined"]] == [(3, 1)]
    assert sum(1 for q in after["quarantined"]) == 1
    # worker 0's shard untouched by the adoption
    assert not os.path.exists(obj.paths["resilience_ledger"] % 0)
    # combine still excludes the quarantined lane without a skip flag
    obj.factorize(worker_i=0, total_workers=2)
    merged = obj.combine_nmf(3)
    assert merged.shape[0] == 3 * 3  # 4 iters minus the quarantined one
