"""Fused Pallas kernels for the ELL sparse-KL inner loop (ISSUE 16).

BENCH_r03-r05 put the dense Frobenius lanes at 36-42% MFU and the KL
lane at 2-3.4%: as XLA emits it, the ELL chain is a gather (slab table)
-> elementwise ratio -> reduce pipeline that re-reads HBM between every
stage. Each kernel here is ONE traversal of the stored nonzeros with the
full (k, g) W table resident in VMEM: the slab gathers, the WH
reconstruction, the ratio, and the f32 statistic reductions all happen
on the same (BLOCK_N x width) tile without round-tripping HBM — and
under PR 8's inner-repeat hoist the repeats re-enter the kernel with W
still on-chip. Math mirrors the jnp oracles in ``ops/sparse.py``
(``ell_kl_h_stats`` / ``ell_kl_h_newton_stats`` / ``ell_kl_w_stats`` /
``ell_beta_err``) to f32 tolerance — accumulation ORDER differs (block
tiles vs one flat reduce), bit parity is not claimed. bf16 value
storage with f32 accumulators follows the same ``resolve_bf16_ratio``
rules as the jnp chain.

Kernel inventory (all β=1/KL; the IS hybrid and the sketch scatter stay
jnp — see ``ops/pallas/__init__``):

  * :func:`pallas_wh_at_nz`        — SDDMM: WH at the stored coords;
  * :func:`pallas_kl_h_stats`      — MU H numerator (+ broadcast denom);
  * :func:`pallas_kl_h_newton_stats` — MU numerator + Diagonalized-
    Newton diagonal Hessian (arXiv 1301.3389) in the SAME pass;
  * :func:`pallas_kl_w_numer` / :func:`pallas_kl_w_stats` — W-side
    statistics as two passes: a fused ratio kernel over row tiles, then
    a transpose-side reduce over gene tiles through the precomputed
    ``rows_t``/``perm_t`` index set (a single fused kernel would need a
    cross-tile barrier: every row's ratio must exist before any gene
    reduces it);
  * :func:`pallas_kl_beta_err`     — the nonzero-supported KL objective
    contribution (per-tile partials; the k-sized ``Σ WH`` term is jnp).

Grid strategy: row-side kernels tile the rows ((BLOCK_N, width) blocks,
W resident via a constant index map); the transpose kernel tiles the
genes with the flat ratio buffer and H fully resident. Inputs are
zero-PADDED up to the tile multiple in the host wrappers rather than
masked in-kernel: interpret mode implements block indexing with clamped
dynamic slices, so boundary tiles OVERLAP rows and in-kernel row-index
masks are unsound — while by the ELL conventions (value 0 / column 0 /
zero H rows / ``perm_t`` sentinel -> appended zero) padded rows and
genes contribute exact +0.0 to every statistic and to the objective.

Off-TPU the wrappers run ``interpret=True`` (plain-jax reference
semantics, vmap/jit/shard_map composable) — that is how the CPU tier-1
suite tests this whole surface; on TPU they lower natively. Import this
module only behind ``ops.pallas.resolve_pallas`` so builds without
``jax.experimental.pallas`` never touch it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas import pallas_interpret
from .sparse import EPS, EllMatrix

__all__ = ["pallas_wh_at_nz", "pallas_kl_h_stats",
           "pallas_kl_h_newton_stats", "pallas_kl_w_numer",
           "pallas_kl_w_stats", "pallas_kl_beta_err",
           "BLOCK_N", "BLOCK_G"]

# row/gene tile sizes: multiples of the f32 sublane tile (8) with room
# for the (tile x width) slab working set in VMEM at single-cell widths
BLOCK_N = 128
BLOCK_G = 128


def _interp(interpret) -> bool:
    return pallas_interpret() if interpret is None else bool(interpret)


def _ceil_to(n: int, m: int) -> int:
    return -(-int(n) // m) * m


def _pad_rows(a, n_pad: int):
    n = a.shape[0]
    if n == n_pad:
        return a
    return jnp.pad(a, ((0, n_pad - n),) + ((0, 0),) * (a.ndim - 1))


def _row_specs(w: int, k: int, g: int):
    """BlockSpecs for the row-side kernels: (vals, cols, H) row tiles +
    the full W resident in every grid step."""
    return [pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0)),
            pl.BlockSpec((k, g), lambda i: (0, 0))]


def _gather_slabs(wt, cols, k: int):
    """The in-kernel slab table: one VMEM gather of W's row c at the
    tile's stored columns, per component — gathered ONCE per tile and
    reused by WH, the ratio statistics, and the squared-slab Hessian
    (the fusion the jnp chain cannot express across its HBM stages)."""
    return [jnp.take(wt[c], cols, mode="clip") for c in range(k)]


def _wh_from_slabs(h, slabs, k: int):
    acc = h[:, 0:1] * slabs[0]
    for c in range(1, k):
        acc = acc + h[:, c:c + 1] * slabs[c]
    return acc


# ---------------------------------------------------------------------------
# kernel bodies (traced scopes: interpret mode runs them as plain jax)
# ---------------------------------------------------------------------------

def _wh_body(vals_ref, cols_ref, h_ref, w_ref, o_ref, *, k):
    del vals_ref  # SDDMM needs the coordinates only; shared specs
    cols = cols_ref[...]
    slabs = _gather_slabs(w_ref[...], cols, k)
    o_ref[...] = _wh_from_slabs(h_ref[...], slabs, k)


def _h_stats_body(vals_ref, cols_ref, h_ref, w_ref, numer_ref, *,
                  k, bf16):
    vals, h, wt = vals_ref[...], h_ref[...], w_ref[...]
    if bf16:
        vals = vals.astype(jnp.bfloat16)
        h = h.astype(jnp.bfloat16)
        wt = wt.astype(jnp.bfloat16)
    slabs = _gather_slabs(wt, cols_ref[...], k)
    wh = _wh_from_slabs(h, slabs, k)
    ratio = vals / jnp.maximum(wh, jnp.asarray(EPS, wh.dtype))
    numer_ref[...] = jnp.stack(
        [jnp.sum((ratio * slabs[c]).astype(jnp.float32), axis=-1)
         for c in range(k)], axis=-1)


def _h_newton_body(vals_ref, cols_ref, h_ref, w_ref, numer_ref,
                   hess_ref, *, k):
    vals = vals_ref[...]
    slabs = _gather_slabs(w_ref[...], cols_ref[...], k)
    wh = _wh_from_slabs(h_ref[...], slabs, k)
    whm = jnp.maximum(wh, jnp.asarray(EPS, wh.dtype))
    ratio = vals / whm
    r2 = ratio / whm
    numer_ref[...] = jnp.stack(
        [jnp.sum((ratio * slabs[c]).astype(jnp.float32), axis=-1)
         for c in range(k)], axis=-1)
    hess_ref[...] = jnp.stack(
        [jnp.sum((r2 * slabs[c] * slabs[c]).astype(jnp.float32), axis=-1)
         for c in range(k)], axis=-1)


def _ratio_body(vals_ref, cols_ref, h_ref, w_ref, o_ref, *, k, bf16):
    vals, h, wt = vals_ref[...], h_ref[...], w_ref[...]
    if bf16:
        vals = vals.astype(jnp.bfloat16)
        h = h.astype(jnp.bfloat16)
        wt = wt.astype(jnp.bfloat16)
    slabs = _gather_slabs(wt, cols_ref[...], k)
    wh = _wh_from_slabs(h, slabs, k)
    o_ref[...] = vals / jnp.maximum(wh, jnp.asarray(EPS, wh.dtype))


def _obj_body(vals_ref, cols_ref, h_ref, w_ref, o_ref, *, k):
    vals = vals_ref[...]
    slabs = _gather_slabs(w_ref[...], cols_ref[...], k)
    wh = _wh_from_slabs(h_ref[...], slabs, k)
    # kl_nz_term (ops/sparse.py) inlined on the tile: both regimes of
    # the cancellation-safe form, minus the nonzero WH term
    xp = jnp.maximum(vals, jnp.float32(EPS))
    whs = jnp.maximum(wh, jnp.float32(EPS))
    ratio = whs / xp
    u = ratio - 1.0
    stable = u - jnp.log1p(jnp.maximum(u, -1.0 + EPS))
    tiny = u + jnp.log(xp) - jnp.log(whs)
    term = xp * jnp.where(ratio < 1e-6, tiny, stable)
    nz = jnp.where(vals > 0, term - wh, 0.0)
    o_ref[...] = jnp.sum(nz).reshape((1,))


def _w_numer_body(rows_t_ref, perm_t_ref, rflat_ref, h_ref, o_ref, *, k):
    rows_t = rows_t_ref[...]                      # (BLOCK_G, wt)
    r_t = jnp.take(rflat_ref[...], perm_t_ref[...], mode="clip")
    h = h_ref[...]                                # (n, k) resident
    o_ref[...] = jnp.stack(
        [jnp.sum((r_t * jnp.take(h[:, c], rows_t, mode="clip")).astype(
            jnp.float32), axis=-1) for c in range(k)], axis=0)


# ---------------------------------------------------------------------------
# host wrappers (jit-traceable; vmap adds a leading grid dim per the
# pallas_call batching rule, which is how the replicate sweeps hit them)
# ---------------------------------------------------------------------------

def _row_call(body, x: EllMatrix, H, W, out_shapes, out_specs,
              interpret, **static):
    n, w = x.cols.shape
    k, g = W.shape
    n_pad = _ceil_to(n, BLOCK_N)
    return pl.pallas_call(
        functools.partial(body, k=k, **static),
        out_shape=out_shapes,
        grid=(n_pad // BLOCK_N,),
        in_specs=_row_specs(w, k, g),
        out_specs=out_specs,
        interpret=_interp(interpret),
    )(_pad_rows(x.vals, n_pad), _pad_rows(x.cols, n_pad),
      _pad_rows(H, n_pad), W), n_pad


def pallas_wh_at_nz(x: EllMatrix, H, W, interpret=None):
    """Fused SDDMM: ``wh[i, j] = H[i, :] @ W[:, cols[i, j]]`` in one
    traversal. Parity oracle: ``ops.sparse.ell_wh_at_nz``."""
    n = x.cols.shape[0]
    dt = jnp.result_type(H.dtype, W.dtype)
    n_pad = _ceil_to(n, BLOCK_N)
    out, _ = _row_call(
        _wh_body, x, H.astype(dt), W.astype(dt),
        jax.ShapeDtypeStruct((n_pad, x.width), dt),
        pl.BlockSpec((BLOCK_N, x.width), lambda i: (i, 0)), interpret)
    return out[:n]


def pallas_kl_h_stats(x: EllMatrix, H, W, bf16_ratio: bool = False,
                      interpret=None):
    """KL H-update statistics in one fused pass (parity oracle:
    ``ops.sparse.ell_kl_h_stats``). The data-independent broadcast
    ``W.sum(axis=1)`` denominator never touches X and stays jnp —
    bitwise the oracle's."""
    n, k = H.shape
    n_pad = _ceil_to(n, BLOCK_N)
    numer, _ = _row_call(
        _h_stats_body, x, H, W,
        jax.ShapeDtypeStruct((n_pad, k), jnp.float32),
        pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0)), interpret,
        bf16=bool(bf16_ratio))
    denom = jnp.broadcast_to(W.sum(axis=1)[None, :], H.shape)
    return numer[:n], denom


def pallas_kl_h_newton_stats(x: EllMatrix, H, W, interpret=None):
    """MU numerator + Diagonalized-Newton diagonal Hessian in the SAME
    nonzero traversal (the jnp chain walks the gathers twice; arXiv
    1301.3389's statistics share every operand with the ratio). Strict
    f32, like the oracle ``ops.sparse.ell_kl_h_newton_stats``."""
    n, k = H.shape
    n_pad = _ceil_to(n, BLOCK_N)
    (numer, hess), _ = _row_call(
        _h_newton_body, x, H, W,
        (jax.ShapeDtypeStruct((n_pad, k), jnp.float32),
         jax.ShapeDtypeStruct((n_pad, k), jnp.float32)),
        (pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0)),
         pl.BlockSpec((BLOCK_N, k), lambda i: (i, 0))), interpret)
    denom = jnp.broadcast_to(W.sum(axis=1)[None, :], H.shape)
    return numer[:n], denom, hess[:n]


def pallas_kl_w_numer(x: EllMatrix, H, W, bf16_ratio: bool = False,
                      interpret=None):
    """KL W-update numerator ``H^T @ (X/WH)`` as two fused passes: the
    row-tile ratio kernel, then the gene-tile transpose reduce through
    ``rows_t``/``perm_t`` (parity oracle: ``ops.sparse.ell_kl_w_numer``).
    Padding genes carry the ``perm_t`` sentinel ``n*w`` -> the appended
    zero ratio slot, an exact +0.0."""
    if x.rows_t is None:
        raise ValueError(
            "this EllMatrix has no transpose index set (rows_t/perm_t); "
            "encode with csr_to_ell(transpose=True) / ell_chunk_rows "
            "for W-side updates")
    n, w = x.cols.shape
    k = H.shape[-1]
    rdt = jnp.bfloat16 if bf16_ratio else jnp.result_type(
        x.vals.dtype, H.dtype, W.dtype)
    n_pad = _ceil_to(n, BLOCK_N)
    ratio, _ = _row_call(
        _ratio_body, x, H, W,
        jax.ShapeDtypeStruct((n_pad, w), rdt),
        pl.BlockSpec((BLOCK_N, w), lambda i: (i, 0)), interpret,
        bf16=bool(bf16_ratio))
    r_flat = jnp.concatenate(
        [ratio[:n].reshape(-1), jnp.zeros((1,), ratio.dtype)])
    g, wt = x.rows_t.shape
    g_pad = _ceil_to(g, BLOCK_G)
    rows_t = _pad_rows(x.rows_t, g_pad)
    perm_t = x.perm_t if g == g_pad else jnp.pad(
        x.perm_t, ((0, g_pad - g), (0, 0)), constant_values=n * w)
    Hc = H.astype(jnp.bfloat16) if bf16_ratio else H
    numer = pl.pallas_call(
        functools.partial(_w_numer_body, k=k),
        out_shape=jax.ShapeDtypeStruct((k, g_pad), jnp.float32),
        grid=(g_pad // BLOCK_G,),
        in_specs=[pl.BlockSpec((BLOCK_G, wt), lambda i: (i, 0)),
                  pl.BlockSpec((BLOCK_G, wt), lambda i: (i, 0)),
                  pl.BlockSpec((n * w + 1,), lambda i: (0,)),
                  pl.BlockSpec((n, k), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((k, BLOCK_G), lambda i: (0, i)),
        interpret=_interp(interpret),
    )(rows_t, perm_t, r_flat, Hc)
    return numer[:, :g]


def pallas_kl_w_stats(x: EllMatrix, H, W, bf16_ratio: bool = False,
                      interpret=None):
    """Full KL W-update statistics (parity oracle:
    ``ops.sparse.ell_kl_w_stats``); the column-sum denominator is
    data-independent and stays jnp."""
    numer = pallas_kl_w_numer(x, H, W, bf16_ratio, interpret)
    denom = jnp.broadcast_to(H.sum(axis=0)[:, None], W.shape)
    return numer, denom


def pallas_kl_beta_err(x: EllMatrix, H, W, interpret=None):
    """``D_KL(X || HW)`` from the ELL encoding: the nonzero-supported
    terms reduce per tile inside the kernel (one (num_tiles,) partial
    buffer comes back), the k-sized ``Σ WH = H.sum(0)·W.sum(1)`` term is
    jnp. Parity oracle: ``ops.sparse.ell_beta_err`` at β=1."""
    n = x.cols.shape[0]
    n_pad = _ceil_to(n, BLOCK_N)
    xs = EllMatrix(x.vals.astype(jnp.float32), x.cols, x.g,
                   x.rows_t, x.perm_t)
    partials, _ = _row_call(
        _obj_body, xs, H.astype(jnp.float32), W.astype(jnp.float32),
        jax.ShapeDtypeStruct((n_pad // BLOCK_N,), jnp.float32),
        pl.BlockSpec((1,), lambda i: (i,)), interpret)
    total_wh = jnp.sum(H.sum(axis=0) * W.sum(axis=1))
    return jnp.sum(partials) + total_wh
