"""Run telemetry: structured event log, convergence records, memory
watermarks, and the ``cnmf report`` renderer.

The timings TSV (:mod:`.profiling`) answers "how long did each stage
take"; it cannot answer "did the 900 replicates behind this consensus
actually converge", "which dispatch path ran", or "how close to the HBM
ceiling did staging push the device" — the questions MPI-FAUN-style
per-phase instrumentation and the out-of-memory-NMF line of work
(PAPERS.md) show are what make scaling decisions defensible. This module
adds the missing ledger:

  * :class:`EventLog` — append-only JSON-lines event stream at
    ``<run_dir>/cnmf_tmp/<name>.events.jsonl`` with a versioned schema.
    A run manifest (package/jax versions, devices, ``CNMF_*`` env knobs,
    seed summary) is emitted once, automatically, before the first event.
    Emission is a no-op unless ``CNMF_TPU_TELEMETRY=1`` — the pipeline
    never changes behavior for users who didn't ask.
  * Event types: ``manifest``, ``dispatch`` (dense-vs-ELL, packed vs
    per-K, stream transport/depth, beta path), ``stage`` (the StageTimer
    walls/bytes, mirrored), ``replicates`` (per-replicate solver
    convergence records from the jitted sweeps), ``stream``
    (:class:`~cnmf_torch_tpu.parallel.streaming.StreamStats` folded in),
    and ``memory`` (device watermarks at stage boundaries).
  * :func:`validate_event` / :func:`validate_events_file` — the ONE
    schema definition, shared by tests and the tier-1 telemetry smoke
    gate (``scripts/verify_tier1.sh``).
  * :func:`render_report` — the ``cnmf-tpu report <run_dir>`` renderer:
    stage waterfall, staging GB/s, per-K replicate convergence summary
    (fraction capped, objective spread, nonfinite count), memory peaks.

The solver-side half lives in ``ops/nmf.py`` (fixed-length objective
traces threaded through the ``lax.while_loop`` carries, zero ops added
when telemetry is off) and is aggregated per sweep by
``parallel/replicates.py`` / ``parallel/rowshard.py``.
"""

from __future__ import annotations

import json
import os
import threading
import time

__all__ = [
    "TELEMETRY_ENV",
    "SCHEMA_VERSION",
    "EVENT_TYPES",
    "telemetry_enabled",
    "EventLog",
    "device_memory_snapshot",
    "device_memory_peak_bytes",
    "validate_event",
    "validate_events_file",
    "read_events",
    "summarize_events",
    "render_report",
]

TELEMETRY_ENV = "CNMF_TPU_TELEMETRY"

SCHEMA_VERSION = 1

# required fields per event type, beyond the common {"v", "t", "ts"}.
# This dict IS the schema: tests and the verify_tier1.sh smoke step
# validate every emitted line against it.
EVENT_TYPES = {
    "manifest": {"package_version", "jax_version", "backend", "devices",
                 "env"},
    "dispatch": {"decision", "context"},
    "stage": {"stage", "wall_s"},
    "replicates": {"k", "beta", "records"},
    "stream": {"context", "wall_s", "nbytes", "overlap_fraction"},
    "memory": {"stage", "devices"},
    # resilience + elasticity events (runtime/{resilience,elastic}.py,
    # parallel/streaming.py, launcher.py): nonfinite_replicate / retry /
    # quarantine / torn_artifact / shard_retry / shard_upload_failed /
    # shard_stall detections, plus the ISSUE-8 topology kinds —
    # host_loss (a mesh participant died; culprits/lost devices in
    # context), remesh (degraded continuation re-planned the mesh, with
    # from/to device counts), worker_steal (the fleet adopted a dead
    # worker's shard), straggler (deadline containment) — with the
    # (k, iter, seed, attempt) / (path, reason) / (context, task) /
    # topology context needed to audit a degraded run. ISSUE 15 adds
    # store_net — remote object-store transport faults, whose context
    # carries the op/object plus healed/degraded outcome flags
    "fault": {"kind", "context"},
    # mid-run checkpoint lifecycle (runtime/checkpoint.py): action in
    # {write, resume, discard} with the replicate identity + pass cursor
    # — the audit trail the chaos gate uses to prove a relaunch resumed
    # mid-run instead of from scratch
    "checkpoint": {"action", "context"},
    # warm serving tier (serving/, ISSUE 12): one event per projection
    # request (status in {ok, shed, poison, error, quarantined} plus
    # wait/solve/total walls and the batch it rode) and one per batched
    # dispatch (lanes/requests/padded shape/cache hit) — the per-tenant
    # audit trail behind the report's Serving section and the
    # `bench.py --tier serve` batching-engagement assertions
    "serve_request": {"tenant", "n_cells", "status"},
    "serve_batch": {"lanes", "requests", "bucket"},
    # replicated serving fleet (serving/fleet.py, ISSUE 20): the router's
    # audit trail behind the report's Fleet section. `replica_death` —
    # one per dead/wedged/exhausted replica (reason in {exit, wedge,
    # spawn_failed, respawns_exhausted} plus pid/uptime/requests-served
    # evidence); `failover` — the tenants remapped off a removed replica
    # onto the survivors (count in `tenants`, capped sample in context);
    # `rollover` — one per completed zero-downtime reference rollover
    # (new generation + end-to-end wall incl. warm + drain + swap).
    # Router-side `serve_request` events additionally carry `replica`,
    # which is where the per-replica request share comes from
    "replica_death": {"replica", "reason"},
    "failover": {"replica", "tenants"},
    "rollover": {"generation", "wall_s"},
    # 2-D grid statistics collectives (parallel/grid2d.py, ISSUE 13):
    # one event per grid solve (context: mesh shape, overlap blocks,
    # pass count; wall_s = solve wall, nbytes = logical per-pass psum
    # payload) plus one measured-probe event per factorize carrying the
    # overlap_fraction (the fraction of the collective wall hidden
    # behind compute — optional: present only when the probe ran)
    "collective": {"context", "wall_s", "nbytes"},
    # the resolved execution plan (runtime/planner.py, ISSUE 17): the
    # WHOLE dispatch surface as one auditable record — encoding, solver
    # recipe, kernel, mesh layout, streaming, OOC tier, store backend,
    # serve buckets — plus per-group provenance (pin / autotuned /
    # heuristic) and the identity signature carried into checkpoints.
    # Exactly ONE per factorize; `cnmf-tpu plan <run_dir>` re-renders it
    # and `--plan <file>` replays it bit-identically
    "plan": {"plan", "signature"},
    # live observability plane (obs/, ISSUE 18): one `span` per sampled
    # trace hop (client request, daemon admission, batcher queue/linger,
    # AOT dispatch, store GET, launcher parent/worker) — `trace` stitches
    # hops across processes, `parent` nests them, start_ts/wall_ms place
    # them on the `cnmf-tpu trace` waterfall; one `metrics_snapshot` per
    # Snapshotter tick (and per batch-stage boundary) carrying the full
    # metrics-registry state, so the post-hoc JSONL holds what a live
    # `GET /metrics` scrape would have shown (optionally plus the SLO
    # verdict that `/healthz` was serving at that moment)
    "span": {"trace", "span", "name", "start_ts", "wall_ms"},
    "metrics_snapshot": {"metrics"},
    # roofline cost model (obs/costmodel.py, ISSUE 19): one event per
    # (stage, kernel lane) joining the ExecutionPlan-derived analytic
    # predictions (flops / bytes / collective bytes per pass) with the
    # measured wall for that lane. `predicted` carries the per-pass
    # counts and pass multiplicity, `measured` the wall + work actually
    # done, `roofline` the verdict (achieved MFU, achieved bandwidth
    # fraction, arithmetic intensity vs the machine balance point,
    # compute- vs memory-bound call, peak provenance, perf_exempt flag
    # for interpret-mode/CPU runs). Rendered as the report's
    # "Roofline" section and consumed by scripts/perf_gate.py
    "perf_model": {"stage", "lane", "predicted", "measured", "roofline"},
}

# per-record required fields inside a "replicates" event's records list
REPLICATE_RECORD_FIELDS = {"seed", "err", "iters", "capped", "nonfinite"}


def telemetry_enabled() -> bool:
    """True when ``CNMF_TPU_TELEMETRY`` is set to anything but 0/off.
    Checked at every emission site, so tests (and long-lived processes)
    can toggle it without rebuilding pipeline objects."""
    from .envknobs import env_flag

    return env_flag(TELEMETRY_ENV, False)


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

def _jsonable(v):
    """Coerce numpy scalars/arrays (the natural products of a fetched
    sweep) into plain JSON types; anything else falls back to str."""
    import numpy as np

    if isinstance(v, (str, bool, int, float)) or v is None:
        return v
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        f = float(v)
        return f if np.isfinite(f) else repr(f)
    if isinstance(v, np.ndarray):
        return [_jsonable(x) for x in v.tolist()]
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


class _NanSafeEncoder(json.JSONEncoder):
    """JSON-lines must stay machine-parseable: a diverged replicate's
    ``inf``/``nan`` objective serializes as a string, not bare ``NaN``
    (which ``json.dumps`` emits by default and strict parsers reject)."""

    def iterencode(self, o, _one_shot=False):
        import math

        def scrub(v):
            if isinstance(v, float) and not math.isfinite(v):
                return repr(v)
            if isinstance(v, dict):
                return {k: scrub(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [scrub(x) for x in v]
            return v

        return super().iterencode(scrub(o), _one_shot)


class EventLog:
    """Thread-safe append-only JSONL event stream for one run.

    Construction is free; nothing touches the filesystem until the first
    :meth:`emit` with telemetry enabled. The manifest is emitted once per
    EventLog instance, before any other event, so a factorize-only worker
    still produces a self-describing file.
    """

    def __init__(self, path: str | None, manifest_extra: dict | None = None):
        self.path = path
        self._lock = threading.Lock()
        self._manifest_done = False
        self._manifest_extra = dict(manifest_extra or {})
        self._write_failed = False

    def set_manifest_extra(self, **fields):
        """Merge run-level manifest fields (seed summary, ledger Ks) known
        only after construction; effective until the manifest is written."""
        with self._lock:
            self._manifest_extra.update(fields)

    @property
    def enabled(self) -> bool:
        return self.path is not None and telemetry_enabled()

    def emit(self, event_type: str, **fields):
        """Append one event (no-op unless enabled). Never raises: telemetry
        must not take the pipeline down."""
        if not self.enabled:
            return
        try:
            with self._lock:
                if not self._manifest_done and event_type != "manifest":
                    self._manifest_done = True
                    self._write_line(self._build_manifest())
                elif event_type == "manifest":
                    self._manifest_done = True
                self._write_line(self._event(event_type, fields))
        except Exception:
            if not self._write_failed:
                self._write_failed = True
                import warnings

                warnings.warn(
                    "telemetry: failed to append to %r; further events "
                    "from this log are dropped silently" % (self.path,),
                    RuntimeWarning, stacklevel=2)

    def emit_memory(self, stage: str):
        """Device-memory watermark event at a stage boundary."""
        if not self.enabled:
            return
        self.emit("memory", stage=stage, devices=device_memory_snapshot())

    def emit_stream(self, context: str, stats):
        """Fold one ``StreamStats`` into the event stream. Staging calls
        with a disk-producer stage (out-of-core shard-store ingestion,
        ISSUE 10) additionally carry the disk wall/bytes/read-GB/s and
        the host slab-residency high-water mark — the report's
        "Ingestion" table and the bench ``ingest`` tier read them back."""
        if not self.enabled or stats is None:
            return
        disk_s = float(getattr(stats, "disk_s", 0.0))
        # remote-store transport counters (ISSUE 15) ride the same stream
        # event, present only when the slabs travelled over the network
        # backend — absence means the run never left the local filesystem
        remote = bool(getattr(stats, "store_remote", False))
        self.emit(
            "stream", context=context, wall_s=round(stats.wall_s, 4),
            host_prep_s=round(stats.host_prep_s, 4),
            h2d_s=round(stats.h2d_s, 4),
            device_s=round(stats.device_s, 4),
            nbytes=int(stats.nbytes), slabs=int(stats.slabs),
            gb_per_s=round(stats.gb_per_s(), 3),
            overlap_fraction=round(stats.overlap_fraction, 3),
            disk_s=round(disk_s, 4) if disk_s > 0 else None,
            disk_nbytes=(int(stats.disk_nbytes) if disk_s > 0 else None),
            disk_gb_per_s=(round(stats.read_gb_per_s(), 3)
                           if disk_s > 0 else None),
            host_peak_bytes=(int(stats.host_peak_bytes)
                             if getattr(stats, "host_peak_bytes", 0) > 0
                             else None),
            store_remote=(True if remote else None),
            store_retries=(int(stats.store_retries) if remote else None),
            store_hedges=(int(stats.store_hedges) if remote else None),
            store_hedges_won=(int(stats.store_hedges_won)
                              if remote else None),
            store_cache_hits=(int(stats.store_cache_hits)
                              if remote else None),
            store_cache_misses=(int(stats.store_cache_misses)
                                if remote else None),
            store_degraded=(int(stats.store_degraded) if remote else None))

    # -- internals -----------------------------------------------------

    def _event(self, event_type: str, fields: dict) -> dict:
        ev = {"v": SCHEMA_VERSION, "t": event_type, "ts": round(time.time(), 3)}
        # None-valued fields are omitted (absent == not measured): keeps
        # the stream compact and the schema's required-field check honest
        ev.update({k: _jsonable(v) for k, v in fields.items()
                   if v is not None})
        return ev

    def _build_manifest(self) -> dict:
        return self._event("manifest", dict(_manifest_fields(),
                                            **self._manifest_extra))

    def _write_line(self, ev: dict):
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        line = json.dumps(ev, cls=_NanSafeEncoder,
                          separators=(",", ":")) + "\n"
        # one os.write per line on an O_APPEND fd: run_parallel workers in
        # separate processes append to the SAME file, and buffered text
        # mode flushes a large (multi-KB `replicates`) line as several
        # write() syscalls — concurrent writers would tear lines mid-JSON.
        # A single write() to an O_APPEND regular file does not interleave.
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        try:
            os.write(fd, line.encode("utf-8"))
        finally:
            os.close(fd)


def _manifest_fields() -> dict:
    """Versions, device inventory, and the env knobs that steer dispatch —
    everything needed to interpret (or reproduce) the rest of the stream."""
    try:
        from ..version import __version__ as pkg_version
    except Exception:
        pkg_version = "unknown"
    fields = {"package_version": pkg_version}
    try:
        import jax

        fields["jax_version"] = jax.__version__
        devs = jax.local_devices()
        fields["backend"] = devs[0].platform if devs else "none"
        fields["devices"] = [
            {"id": int(d.id), "platform": d.platform,
             "kind": getattr(d, "device_kind", "")} for d in devs]
        fields["process_count"] = int(jax.process_count())
    except Exception:
        fields.setdefault("jax_version", "unavailable")
        fields.setdefault("backend", "unavailable")
        fields.setdefault("devices", [])
    fields["env"] = {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith(("CNMF_", "JAX_")) or k == "XLA_FLAGS"}
    return fields


# ---------------------------------------------------------------------------
# device-memory watermarks
# ---------------------------------------------------------------------------

def device_memory_snapshot() -> list[dict]:
    """Per-device memory watermarks where the runtime exposes them
    (``device.memory_stats()`` — empty on CPU and some tunneled backends),
    plus this process's live-buffer bytes from ``jax.live_arrays()`` as the
    backend-independent fallback signal."""
    out = []
    try:
        import jax

        live_by_dev: dict = {}
        try:
            for arr in jax.live_arrays():
                for s in arr.addressable_shards:
                    live_by_dev[s.device.id] = (
                        live_by_dev.get(s.device.id, 0)
                        + int(s.data.nbytes))
        except Exception:
            pass
        for d in jax.local_devices():
            ent = {"id": int(d.id), "platform": d.platform,
                   "live_buffer_bytes": int(live_by_dev.get(d.id, 0))}
            try:
                stats = d.memory_stats() or {}
            except Exception:
                stats = {}
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                        "largest_alloc_size"):
                if key in stats:
                    ent[key] = int(stats[key])
            out.append(ent)
    except Exception:
        pass
    return out


def device_memory_peak_bytes() -> int:
    """Max peak (or current) device bytes across local devices; falls back
    to the live-buffer sum when the backend reports no memory stats."""
    peak = 0
    for ent in device_memory_snapshot():
        peak = max(peak, ent.get("peak_bytes_in_use",
                                 ent.get("bytes_in_use",
                                         ent.get("live_buffer_bytes", 0))))
    return int(peak)


def replicate_records(payload) -> list[dict]:
    """The ONE payload->records conversion: turn a sweep telemetry payload
    (``parallel.replicates._sweep_telemetry_payload`` — array values may be
    device arrays) into the schema's per-replicate record list
    (:data:`REPLICATE_RECORD_FIELDS`). Shared by the pipeline's event
    emission (``models/cnmf.py``) and bench's convergence summaries, so the
    capped/nonfinite semantics cannot drift between producers."""
    import numpy as np

    trace = np.asarray(payload["trace"])
    iters = np.asarray(payload["iters"])
    nonfin = np.asarray(payload["nonfinite"])
    errs = np.asarray(payload["errs"])
    cap = int(payload["cap"])
    inner = (np.asarray(payload["inner_iters"])
             if payload.get("inner_iters") is not None else None)
    dna_fb = (np.asarray(payload["dna_fallback"])
              if payload.get("dna_fallback") is not None else None)
    records = []
    for i, seed in enumerate(payload["seeds"]):
        tr = trace[i]
        rec = {
            "seed": int(seed),
            "err": float(errs[i]),
            "iters": int(iters[i]),
            "capped": bool(iters[i] >= cap),
            "nonfinite": bool(nonfin[i]),
            # NaN marks never-evaluated slots; what remains is the
            # objective trajectory at the solver's evaluation cadence
            "trace": [float(v) for v in tr[~np.isnan(tr)]],
        }
        # solver-recipe accounting (ISSUE 9; batch solvers only): total
        # inner update applications, and the dna recipe's MU
        # fallback-lane fraction — additive fields, absent elsewhere
        if inner is not None:
            rec["inner_iters"] = int(inner[i])
        if dna_fb is not None:
            rec["dna_fallback"] = round(float(dna_fb[i]), 4)
        records.append(rec)
    return records


# ---------------------------------------------------------------------------
# schema validation (shared by tests and the tier-1 smoke gate)
# ---------------------------------------------------------------------------

def validate_event(ev: dict) -> None:
    """Raise ``ValueError`` unless ``ev`` is a schema-valid event."""
    if not isinstance(ev, dict):
        raise ValueError(f"event is not an object: {type(ev).__name__}")
    for field in ("v", "t", "ts"):
        if field not in ev:
            raise ValueError(f"event missing required field {field!r}: {ev}")
    if ev["v"] != SCHEMA_VERSION:
        raise ValueError(
            f"unknown schema version {ev['v']!r} (this build understands "
            f"{SCHEMA_VERSION})")
    t = ev["t"]
    if t not in EVENT_TYPES:
        raise ValueError(f"unknown event type {t!r}")
    if not isinstance(ev["ts"], (int, float)):
        raise ValueError(f"ts must be numeric, got {ev['ts']!r}")
    missing = EVENT_TYPES[t] - set(ev)
    if missing:
        raise ValueError(
            f"{t} event missing required fields {sorted(missing)}: {ev}")
    if t == "replicates":
        if not isinstance(ev["records"], list):
            raise ValueError("replicates.records must be a list")
        for rec in ev["records"]:
            rmissing = REPLICATE_RECORD_FIELDS - set(rec)
            if rmissing:
                raise ValueError(
                    f"replicate record missing {sorted(rmissing)}: {rec}")
    if t == "memory" and not isinstance(ev["devices"], list):
        raise ValueError("memory.devices must be a list")
    if t == "span":
        for field in ("start_ts", "wall_ms"):
            if not isinstance(ev[field], (int, float)):
                raise ValueError(f"span.{field} must be numeric: {ev}")
    if t == "metrics_snapshot" and not isinstance(ev["metrics"], dict):
        raise ValueError("metrics_snapshot.metrics must be an object")
    if t == "perf_model":
        for field in ("predicted", "measured", "roofline"):
            if not isinstance(ev[field], dict):
                raise ValueError(f"perf_model.{field} must be an object: {ev}")
        for field in ("flops", "bytes"):
            if not isinstance(ev["predicted"].get(field), (int, float)):
                raise ValueError(
                    f"perf_model.predicted.{field} must be numeric: {ev}")
        if not isinstance(ev["measured"].get("wall_s"), (int, float)):
            raise ValueError(
                f"perf_model.measured.wall_s must be numeric: {ev}")
        if not isinstance(ev["roofline"].get("bound"), str):
            raise ValueError(f"perf_model.roofline.bound must be a str: {ev}")


def validate_events_file(path: str) -> int:
    """Validate every line of an events.jsonl; returns the event count.
    The FIRST event must be a manifest (self-describing stream)."""
    count = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}:{lineno}: not valid JSON: {exc}")
            try:
                validate_event(ev)
            except ValueError as exc:
                raise ValueError(f"{path}:{lineno}: {exc}")
            if count == 0 and ev["t"] != "manifest":
                raise ValueError(
                    f"{path}:1: first event must be the manifest, "
                    f"got {ev['t']!r}")
            count += 1
    return count


def read_events(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def _find_event_files(run_dir: str) -> list[str]:
    tmp = os.path.join(run_dir, "cnmf_tmp")
    if not os.path.isdir(tmp):
        return []
    return sorted(os.path.join(tmp, fn) for fn in os.listdir(tmp)
                  if fn.endswith(".events.jsonl"))


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024.0 or unit == "TB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TB"


def summarize_events(events: list[dict]) -> dict:
    """Aggregate an event stream into the report's (and bench's) summary:
    stage walls, staging throughput, per-K convergence, memory peaks."""
    import math

    summary: dict = {"n_events": len(events)}
    manifest = next((e for e in events if e["t"] == "manifest"), None)
    if manifest:
        summary["manifest"] = {
            "package_version": manifest.get("package_version"),
            "jax_version": manifest.get("jax_version"),
            "backend": manifest.get("backend"),
            "n_devices": len(manifest.get("devices") or []),
        }
    summary["dispatch"] = [
        {k: e[k] for k in ("decision", "context") if k in e}
        for e in events if e["t"] == "dispatch"]

    # the resolved execution plan (ISSUE 17): one per factorize — keep
    # the LAST (a multi-worker run dir concatenates worker streams; they
    # resolved the same plan or the signatures differ loudly here)
    plan_ev = next((e for e in reversed(events) if e["t"] == "plan"), None)
    if plan_ev is not None:
        summary["plan"] = {"plan": plan_ev.get("plan"),
                           "signature": plan_ev.get("signature")}

    # consensus/k-selection dispatch lane (ISSUE 11): which geometry the
    # clustering stages ran on — sketched (random-projected) vs exact —
    # with the replicate counts and distance-matrix shapes that justify
    # it, so the sketched lane is auditable like factorize's
    cons_rows = []
    for e in events:
        if e["t"] != "dispatch" or e.get("decision") not in (
                "consensus_path", "k_selection"):
            continue
        ctx = e.get("context") or {}
        if not isinstance(ctx, dict):
            continue
        cons_rows.append(dict(ctx, decision=e.get("decision")))
    if cons_rows:
        summary["consensus"] = cons_rows

    stages: dict = {}
    for e in events:
        if e["t"] != "stage":
            continue
        ent = stages.setdefault(e["stage"], {"wall_s": 0.0, "nbytes": 0,
                                             "count": 0})
        ent["wall_s"] += float(e.get("wall_s", 0.0))
        ent["nbytes"] += int(e.get("nbytes") or 0)
        ent["count"] += 1
    summary["stages"] = {
        name: {"wall_s": round(v["wall_s"], 4), "nbytes": v["nbytes"],
               "count": v["count"]}
        for name, v in stages.items()}

    streams = [e for e in events if e["t"] == "stream"]
    if streams:
        summary["streaming"] = [
            {"context": e["context"], "wall_s": e["wall_s"],
             "nbytes": e["nbytes"], "gb_per_s": e.get("gb_per_s"),
             "overlap_fraction": e.get("overlap_fraction")}
            for e in streams]

    # 2-D grid statistics collectives (ISSUE 13, parallel/grid2d.py):
    # per-solve reduce wall + logical psum payload, and the measured
    # probe's hidden-collective (overlap) fraction when it ran
    colls = [e for e in events if e["t"] == "collective"]
    if colls:
        summary["collectives"] = [
            {"context": e.get("context"), "wall_s": e.get("wall_s"),
             "nbytes": e.get("nbytes"),
             "overlap_fraction": e.get("overlap_fraction")}
            for e in colls]

    # out-of-core ingestion (ISSUE 10): the shard store written at
    # prepare (dispatch decision=shard_store_write), factorize's store
    # engagement (decision=ooc_ingest), and the disk-producer staging
    # walls carried by store-backed stream events
    disk_streams = [e for e in streams if e.get("disk_nbytes")]
    store_ev = next((e for e in events if e["t"] == "dispatch"
                     and e.get("decision") == "shard_store_write"), None)
    ooc_ev = next((e for e in events if e["t"] == "dispatch"
                   and e.get("decision") == "ooc_ingest"), None)
    remote_streams = [e for e in streams if e.get("store_remote")]
    if disk_streams or store_ev or ooc_ev or remote_streams:
        ing: dict = {}
        ctx = (ooc_ev or store_ev or {}).get("context") or {}
        for key in ("slabs", "store_bytes", "format", "rows", "backend"):
            if ctx.get(key) is not None:
                ing[key] = ctx[key]
        if disk_streams:
            disk_s = sum(float(e.get("disk_s") or 0.0)
                         for e in disk_streams)
            disk_b = sum(int(e.get("disk_nbytes") or 0)
                         for e in disk_streams)
            ing["disk_read_nbytes"] = disk_b
            ing["disk_read_gb_per_s"] = (round(disk_b / disk_s / 1e9, 3)
                                         if disk_s > 0 else 0.0)
            fracs = [float(e["overlap_fraction"]) for e in disk_streams
                     if e.get("overlap_fraction") is not None]
            if fracs:
                ing["overlap_fraction"] = round(sum(fracs) / len(fracs), 3)
            peaks = [int(e.get("host_peak_bytes") or 0)
                     for e in disk_streams]
            if any(peaks):
                ing["host_peak_bytes"] = max(peaks)
        # remote-store transport health (ISSUE 15): transport retries,
        # hedge engagement, read-through cache hit rate and degraded
        # (cache-served-while-remote-down) reads, summed across every
        # stream that rode the network backend
        if remote_streams:
            rem = {out: sum(int(e.get(field) or 0) for e in remote_streams)
                   for out, field in (
                       ("retries", "store_retries"),
                       ("hedges", "store_hedges"),
                       ("hedges_won", "store_hedges_won"),
                       ("cache_hits", "store_cache_hits"),
                       ("cache_misses", "store_cache_misses"),
                       ("degraded_reads", "store_degraded"))}
            looked = rem["cache_hits"] + rem["cache_misses"]
            rem["cache_hit_rate"] = (round(rem["cache_hits"] / looked, 3)
                                     if looked else 0.0)
            ing["remote"] = rem
        if ing:
            summary["ingestion"] = ing

    conv: dict = {}
    for e in events:
        if e["t"] != "replicates":
            continue
        k = int(e["k"])
        ent = conv.setdefault(k, {"n": 0, "capped": 0, "nonfinite": 0,
                                  "errs": [], "iters": [], "recipes": set(),
                                  "dna_fb": []})
        if e.get("recipe"):
            ent["recipes"].add(str(e["recipe"]))
        for rec in e["records"]:
            ent["n"] += 1
            ent["capped"] += bool(rec.get("capped"))
            ent["nonfinite"] += bool(rec.get("nonfinite"))
            err = rec.get("err")
            if isinstance(err, (int, float)) and math.isfinite(err):
                ent["errs"].append(float(err))
            ent["iters"].append(int(rec.get("iters", 0)))
            fb = rec.get("dna_fallback")
            if isinstance(fb, (int, float)) and math.isfinite(fb):
                ent["dna_fb"].append(float(fb))
    convergence = {}
    for k, ent in sorted(conv.items()):
        errs = ent["errs"]
        row = {"replicates": ent["n"],
               "fraction_capped": round(ent["capped"] / max(ent["n"], 1), 4),
               "nonfinite": ent["nonfinite"],
               "mean_iters": round(sum(ent["iters"])
                                   / max(len(ent["iters"]), 1), 1)}
        if ent["recipes"]:
            # the engaged solver recipe(s) for this K (normally one)
            row["recipe"] = "+".join(sorted(ent["recipes"]))
        if ent["dna_fb"]:
            row["dna_fallback_mean"] = round(
                sum(ent["dna_fb"]) / len(ent["dna_fb"]), 4)
        if errs:
            lo, hi = min(errs), max(errs)
            med = sorted(errs)[len(errs) // 2]
            row.update(err_min=round(lo, 6), err_median=round(med, 6),
                       err_max=round(hi, 6),
                       err_rel_spread=round((hi - lo) / abs(med), 6)
                       if med else None)
        convergence[str(k)] = row
    if convergence:
        summary["convergence"] = convergence

    # faults & recoveries: per-class counts from the fault stream, plus
    # the recovery outcomes derivable from it (a `retry` event's context
    # carries the attempt's health) and the checkpoint lifecycle
    fault_by_kind: dict = {}
    retried = recovered = quarantined_n = 0
    net_recovered = net_degraded = 0
    for e in events:
        if e["t"] != "fault":
            continue
        kind = str(e.get("kind"))
        fault_by_kind[kind] = fault_by_kind.get(kind, 0) + 1
        if kind == "retry":
            retried += 1
            ctx = e.get("context")
            if isinstance(ctx, dict) and ctx.get("healthy"):
                recovered += 1
        elif kind == "quarantine":
            quarantined_n += 1
        elif kind == "store_net":
            # remote-store transport outcomes (ISSUE 15): a retry ladder
            # that eventually succeeded marks the event healed; a read
            # served from the local cache with the remote down marks it
            # degraded — plain store_net events are in-flight attempts
            ctx = e.get("context")
            if isinstance(ctx, dict):
                if ctx.get("healed"):
                    net_recovered += 1
                if ctx.get("degraded"):
                    net_degraded += 1
    if fault_by_kind:
        summary["faults"] = {"by_kind": dict(sorted(fault_by_kind.items())),
                             "retried": retried, "recovered": recovered,
                             "quarantined": quarantined_n}
        if fault_by_kind.get("store_net"):
            summary["faults"]["store_net_recovered"] = net_recovered
            summary["faults"]["store_net_degraded"] = net_degraded
    ckpt_actions: dict = {}
    max_resume_pass = None
    for e in events:
        if e["t"] != "checkpoint":
            continue
        action = str(e.get("action"))
        ckpt_actions[action] = ckpt_actions.get(action, 0) + 1
        if action == "resume":
            ctx = e.get("context")
            p = ctx.get("pass_idx") if isinstance(ctx, dict) else None
            if isinstance(p, (int, float)):
                max_resume_pass = max(int(p), max_resume_pass or 0)
    if ckpt_actions:
        ckpt_sum = {"actions": dict(sorted(ckpt_actions.items()))}
        if max_resume_pass is not None:
            ckpt_sum["max_resume_pass"] = max_resume_pass
        summary["checkpoints"] = ckpt_sum

    # mesh elasticity (ISSUE 8): topology losses, degraded re-meshes
    # (with the before/after device counts), launcher shard adoptions,
    # and straggler containments — the audit trail that distinguishes
    # "the run survived a dying pod" from "the run was never stressed"
    losses = remeshes = stolen = stragglers = 0
    remesh_paths: list[str] = []
    for e in events:
        if e["t"] != "fault":
            continue
        kind = str(e.get("kind"))
        ctx = e.get("context") if isinstance(e.get("context"), dict) else {}
        if kind == "host_loss":
            losses += 1
        elif kind == "remesh":
            remeshes += 1
            fd, td = ctx.get("from_devices"), ctx.get("to_devices")
            if isinstance(fd, int) and isinstance(td, int):
                remesh_paths.append(f"{fd}->{td}")
        elif kind == "worker_steal":
            stolen += 1
        elif kind == "straggler":
            stragglers += 1
    if losses or remeshes or stolen or stragglers:
        elasticity = {"host_losses": losses, "remeshes": remeshes,
                      "stolen_shards": stolen, "stragglers": stragglers}
        if remesh_paths:
            elasticity["remesh_devices"] = remesh_paths
        if max_resume_pass is not None:
            elasticity["max_resume_pass"] = max_resume_pass
        summary["elasticity"] = elasticity

    # warm serving tier (ISSUE 12): request outcomes, per-tenant traffic,
    # batch-size engagement, and the latency distribution — p50/p95/p99
    # via the shared percentile helper (utils/profiling.py), the same
    # implementation the bench serve tier reports
    reqs = [e for e in events if e["t"] == "serve_request"]
    batches = [e for e in events if e["t"] == "serve_batch"]
    if reqs or batches:
        from .profiling import latency_summary

        by_status: dict = {}
        by_tenant: dict = {}
        lat_ms = []
        for e in reqs:
            st = str(e.get("status"))
            by_status[st] = by_status.get(st, 0) + 1
            ten = str(e.get("tenant"))
            by_tenant[ten] = by_tenant.get(ten, 0) + 1
            if st == "ok" and isinstance(e.get("total_ms"), (int, float)):
                lat_ms.append(float(e["total_ms"]))
        serving: dict = {"requests": len(reqs),
                         "by_status": dict(sorted(by_status.items())),
                         "tenants": len(by_tenant)}
        if lat_ms:
            serving["latency_ms"] = latency_summary(lat_ms)
            span = max(e["ts"] for e in reqs) - min(e["ts"] for e in reqs)
            if span > 0:
                serving["qps"] = round(len(lat_ms) / span, 1)
        if batches:
            lanes = [int(e.get("lanes", 0)) for e in batches]
            nreq = [int(e.get("requests", 0)) for e in batches]
            serving["batches"] = len(batches)
            serving["mean_lanes"] = round(sum(lanes) / len(lanes), 2)
            serving["max_lanes"] = max(lanes)
            serving["multi_request_batches"] = sum(
                1 for r in nreq if r > 1)
            hits = [e.get("cache_hit") for e in batches
                    if e.get("cache_hit") is not None]
            if hits:
                serving["cache_hit_fraction"] = round(
                    sum(bool(h) for h in hits) / len(hits), 3)
        summary["serving"] = serving

    # replicated serving fleet (ISSUE 20): replica lifecycle + routing
    # outcomes from the router's event stream — deaths (with lifetimes),
    # tenant failovers, reference rollovers, and the per-replica request
    # share computed from router-side serve_request events (which carry
    # the replica slot each request was served by)
    deaths = [e for e in events if e["t"] == "replica_death"]
    failovers = [e for e in events if e["t"] == "failover"]
    rollovers = [e for e in events if e["t"] == "rollover"]
    share: dict = {}
    for e in reqs:
        if e.get("replica") is not None:
            rep = str(e["replica"])
            share[rep] = share.get(rep, 0) + 1
    if deaths or failovers or rollovers or share:
        fleet: dict = {"replica_deaths": len(deaths),
                       "failovers": len(failovers),
                       "rollovers": len(rollovers)}
        reasons: dict = {}
        lifetimes = []
        for e in deaths:
            reasons[str(e.get("reason"))] = \
                reasons.get(str(e.get("reason")), 0) + 1
            up = e.get("uptime_s")
            if isinstance(up, (int, float)) and math.isfinite(up):
                lifetimes.append(round(float(up), 3))
        if reasons:
            fleet["deaths_by_reason"] = dict(sorted(reasons.items()))
        if lifetimes:
            fleet["replica_lifetimes_s"] = sorted(lifetimes)
        t_failed = sum(int(e.get("tenants", 0)) for e in failovers)
        if failovers:
            fleet["tenants_failed_over"] = t_failed
        if rollovers:
            fleet["rollover_wall_s"] = [
                round(float(e.get("wall_s", 0.0)), 3) for e in rollovers]
            gens = [int(e["generation"]) for e in rollovers
                    if isinstance(e.get("generation"), int)]
            if gens:
                fleet["generation"] = max(gens)
        if share:
            total_share = sum(share.values())
            fleet["requests_by_replica"] = dict(sorted(share.items()))
            fleet["request_share"] = {
                rep: round(n / total_share, 3)
                for rep, n in sorted(share.items())}
        summary["fleet"] = fleet

    # live observability plane (ISSUE 18): sampled trace spans rolled up
    # by name (the waterfall itself is `cnmf-tpu trace`), and the LAST
    # SLO verdict carried by a metrics_snapshot — what /healthz was
    # reporting when the stream ended
    span_evs = [e for e in events if e["t"] == "span"]
    if span_evs:
        by_name: dict = {}
        for e in span_evs:
            ent = by_name.setdefault(str(e.get("name")),
                                     {"count": 0, "wall_ms": 0.0})
            ent["count"] += 1
            w = e.get("wall_ms")
            if isinstance(w, (int, float)) and math.isfinite(w):
                ent["wall_ms"] += float(w)
        summary["spans"] = {
            "count": len(span_evs),
            "traces": len({e.get("trace") for e in span_evs}),
            "by_name": {name: {"count": v["count"],
                               "wall_ms_total": round(v["wall_ms"], 3)}
                        for name, v in sorted(by_name.items())}}
    slo_ev = next((e for e in reversed(events)
                   if e["t"] == "metrics_snapshot"
                   and isinstance(e.get("slo"), dict)), None)
    if slo_ev is not None:
        summary["slo"] = slo_ev["slo"]

    # roofline cost model (ISSUE 19): one row per (stage, kernel lane)
    # joining predicted work with the measured wall — achieved MFU,
    # achieved bandwidth fraction, and the compute-/memory-bound call.
    # Interpret-mode / nominal-peak rows carry perf_exempt so consumers
    # (the perf gate, benchdiff) skip them instead of comparing
    perf_rows = []
    for e in events:
        if e["t"] != "perf_model":
            continue
        pred = e.get("predicted") or {}
        meas = e.get("measured") or {}
        roof = e.get("roofline") or {}
        row = {"stage": e.get("stage"), "lane": e.get("lane"),
               "wall_s": meas.get("wall_s"),
               "passes": meas.get("passes"),
               "flops": pred.get("flops"), "bytes": pred.get("bytes"),
               "mfu": roof.get("mfu"), "bw_frac": roof.get("bw_frac"),
               "intensity": roof.get("intensity"),
               "bound": roof.get("bound"),
               "peak_source": roof.get("peak_source"),
               "perf_exempt": bool(roof.get("perf_exempt"))}
        if pred.get("collective_bytes"):
            row["collective_bytes"] = pred["collective_bytes"]
        perf_rows.append(row)
    if perf_rows:
        summary["roofline"] = perf_rows

    mem_peak = 0
    mem_stage = None
    for e in events:
        if e["t"] != "memory":
            continue
        for dev in e.get("devices", []):
            b = dev.get("peak_bytes_in_use",
                        dev.get("bytes_in_use",
                                dev.get("live_buffer_bytes", 0)))
            if b and b > mem_peak:
                mem_peak, mem_stage = int(b), e.get("stage")
    if mem_peak:
        summary["memory_peak_bytes"] = mem_peak
        summary["memory_peak_stage"] = mem_stage
    return summary


def render_report(run_dir: str) -> str:
    """Human-readable run report from a run directory's telemetry (events
    JSONL preferred; the timings TSV alone still yields a stage table)."""
    lines: list[str] = []
    run_dir = run_dir.rstrip(os.sep)
    lines.append(f"cNMF run report — {run_dir}")
    lines.append("=" * min(78, len(lines[0])))

    event_files = _find_event_files(run_dir)
    events: list[dict] = []
    for path in event_files:
        events.extend(read_events(path))
    if not events:
        tsvs = []
        tmp = os.path.join(run_dir, "cnmf_tmp")
        if os.path.isdir(tmp):
            tsvs = [os.path.join(tmp, fn) for fn in sorted(os.listdir(tmp))
                    if fn.endswith(".timings.tsv")]
        if not tsvs:
            lines.append("no telemetry found (run with CNMF_TPU_TELEMETRY=1 "
                         "to produce an events.jsonl; no timings TSV either)")
            return "\n".join(lines)
        lines.append("no events.jsonl (telemetry was off) — stage walls "
                     "from the timings TSV:")
        stages: dict = {}
        for path in tsvs:
            with open(path) as f:
                next(f, None)
                for line in f:
                    parts = line.rstrip("\n").split("\t")
                    if len(parts) >= 2:
                        try:
                            stages[parts[0]] = (stages.get(parts[0], 0.0)
                                                + float(parts[1]))
                        except ValueError:
                            pass
        lines.extend(_stage_waterfall(
            {k: {"wall_s": v, "nbytes": 0, "count": 1}
             for k, v in stages.items()}))
        return "\n".join(lines)

    summary = summarize_events(events)

    man = summary.get("manifest")
    if man:
        lines.append("")
        lines.append("Manifest")
        lines.append("-" * 8)
        lines.append(
            f"  package {man.get('package_version')}   "
            f"jax {man.get('jax_version')}   backend {man.get('backend')} "
            f"({man.get('n_devices')} device(s))")

    plan_sum = summary.get("plan")
    if plan_sum and isinstance(plan_sum.get("plan"), dict):
        lines.append("")
        lines.append("Plan")
        lines.append("-" * 4)
        try:
            from ..runtime.planner import render_plan

            lines.extend("  " + ln
                         for ln in render_plan(plan_sum["plan"]))
        except Exception:
            lines.append("  (unrenderable plan payload)")
        if plan_sum.get("signature"):
            lines.append(f"  signature {plan_sum['signature']}")

    if summary.get("dispatch"):
        lines.append("")
        lines.append("Dispatch decisions")
        lines.append("-" * 18)
        for d in summary["dispatch"]:
            if d.get("decision") in ("consensus_path", "k_selection"):
                continue  # rendered in their own section below
            ctx = d.get("context", {})
            ctx_str = "  ".join(f"{k}={v}" for k, v in ctx.items()) \
                if isinstance(ctx, dict) else str(ctx)
            lines.append(f"  {d.get('decision')}: {ctx_str}")

    if summary.get("consensus"):
        lines.append("")
        lines.append("Consensus / k-selection dispatch")
        lines.append("-" * 32)
        for c in summary["consensus"]:
            if c.get("decision") == "k_selection":
                lines.append(
                    f"  k_selection: Ks={c.get('ks')}  "
                    f"R_max={c.get('R_max')}  packed={c.get('packed')}  "
                    f"sketch={'on dim=%s' % c.get('sketch_dim') if c.get('sketch') else 'off'}"
                    f" ({c.get('sketch_source')})")
            else:
                shape = c.get("distance_shape") or ["?", "?"]
                lines.append(
                    f"  {c.get('stage', 'consensus'):<18s} K={c.get('k')}"
                    f"  replicates={c.get('replicates')}"
                    f"  dist={shape[0]}x{shape[-1]}"
                    f" @ width {c.get('distance_width')}"
                    f"  sketch={'on dim=%s' % c.get('sketch_dim') if c.get('sketch') else 'off'}"
                    f" ({c.get('sketch_source')})"
                    f"{'  packed' if c.get('packed') else ''}")

    lines.append("")
    lines.append("Stage waterfall")
    lines.append("-" * 15)
    lines.extend(_stage_waterfall(summary.get("stages", {})))

    if summary.get("streaming"):
        lines.append("")
        lines.append("Host->device staging")
        lines.append("-" * 20)
        for s in summary["streaming"]:
            gbps = s.get("gb_per_s")
            lines.append(
                f"  {s['context']:<32s} {s['wall_s']:>8.3f} s  "
                f"{_fmt_bytes(s['nbytes']):>10s}  "
                f"{(f'{gbps:.2f} GB/s' if gbps is not None else ''):>11s}  "
                f"overlap {s.get('overlap_fraction', 0):.2f}")

    ing = summary.get("ingestion")
    if ing:
        lines.append("")
        lines.append("Ingestion (out-of-core shard store)")
        lines.append("-" * 35)
        if ing.get("store_bytes") is not None:
            lines.append(
                f"  {'store size':<28s} {_fmt_bytes(ing['store_bytes']):>10s}"
                f"  ({ing.get('slabs', '?')} slab(s), "
                f"{ing.get('format', '?')}, {ing.get('rows', '?')} rows)")
        if ing.get("backend") is not None:
            lines.append(f"  {'store backend':<28s}"
                         f" {str(ing['backend']):>10s}")
        elif ing.get("slabs") is not None:
            lines.append(f"  {'slabs':<28s} {ing['slabs']:>10d}")
        if ing.get("disk_read_nbytes") is not None:
            lines.append(
                f"  {'disk read':<28s}"
                f" {_fmt_bytes(ing['disk_read_nbytes']):>10s}"
                f"  ({ing.get('disk_read_gb_per_s', 0.0):.2f} GB/s)")
        if ing.get("overlap_fraction") is not None:
            lines.append(f"  {'disk/h2d overlap fraction':<28s}"
                         f" {ing['overlap_fraction']:>10.2f}")
        if ing.get("host_peak_bytes") is not None:
            lines.append(
                f"  {'host slab residency peak':<28s}"
                f" {_fmt_bytes(ing['host_peak_bytes']):>10s}")
        rem = ing.get("remote")
        if rem:
            lines.append(f"  {'remote cache hit rate':<28s}"
                         f" {rem.get('cache_hit_rate', 0.0):>10.1%}")
            lines.append(f"  {'remote transport retries':<28s}"
                         f" {rem.get('retries', 0):>10d}")
            lines.append(
                f"  {'remote hedges won':<28s}"
                f" {rem.get('hedges_won', 0):>10d}"
                f"  (of {rem.get('hedges', 0)} hedged)")
            lines.append(f"  {'remote degraded reads':<28s}"
                         f" {rem.get('degraded_reads', 0):>10d}")

    if summary.get("collectives"):
        lines.append("")
        lines.append("Collectives (2-D grid statistics reductions)")
        lines.append("-" * 44)
        for c in summary["collectives"]:
            ctx = c.get("context") or {}
            if not isinstance(ctx, dict):
                ctx = {}
            mesh_s = "x".join(str(x) for x in (ctx.get("mesh_shape")
                                               or [])) or "?"
            blocks = "/".join(str(x) for x in (ctx.get("blocks")
                                               or [])) or "?"
            frac = c.get("overlap_fraction")
            lines.append(
                f"  {str(ctx.get('stage', 'grid2d')):<20s} "
                f"k={str(ctx.get('k', '?')):<4s} mesh {mesh_s:<6s} "
                f"blocks {blocks:<6s} {float(c.get('wall_s', 0)):>8.3f} s"
                f"  {_fmt_bytes(c.get('nbytes', 0)):>10s}"
                + (f"  overlap {frac:.2f}" if frac is not None else ""))

    if summary.get("convergence"):
        lines.append("")
        lines.append("Replicate convergence (per K)")
        lines.append("-" * 29)
        # recipe + dna-fallback columns (ISSUE 9): which convergence math
        # ran, and — under the dna recipe — what fraction of lanes took
        # the monotone MU fallback instead of the Newton step
        any_fb = any(row.get("dna_fallback_mean") is not None
                     for row in summary["convergence"].values())
        lines.append(f"  {'K':>4s} {'reps':>6s} {'capped':>8s} "
                     f"{'nonfin':>7s} {'mean it':>8s} {'err median':>12s} "
                     f"{'rel spread':>11s} {'recipe':>12s}"
                     + (f" {'dna fb':>7s}" if any_fb else ""))
        for k, row in summary["convergence"].items():
            med = row.get("err_median")
            spread = row.get("err_rel_spread")
            fb = row.get("dna_fallback_mean")
            line = (
                f"  {k:>4s} {row['replicates']:>6d} "
                f"{row['fraction_capped']:>7.1%} "
                f"{row['nonfinite']:>7d} {row['mean_iters']:>8.1f} "
                f"{(f'{med:.5g}' if med is not None else '-'):>12s} "
                f"{(f'{spread:.2e}' if spread is not None else '-'):>11s} "
                f"{row.get('recipe') or '-':>12s}")
            if any_fb:
                line += f" {(f'{fb:.1%}' if fb is not None else '-'):>7s}"
            lines.append(line)

    if summary.get("faults") or summary.get("checkpoints"):
        lines.append("")
        lines.append("Faults & recoveries")
        lines.append("-" * 19)
        faults = summary.get("faults") or {}
        by_kind = faults.get("by_kind") or {}
        if by_kind:
            lines.append(f"  {'class':<28s} {'events':>7s}")
            for kind, n in by_kind.items():
                lines.append(f"  {kind:<28s} {n:>7d}")
            lines.append(
                "  retried %d (recovered %d), quarantined %d"
                % (faults.get("retried", 0), faults.get("recovered", 0),
                   faults.get("quarantined", 0)))
            if by_kind.get("store_net"):
                lines.append(
                    "  store_net: recovered %d, degraded reads %d"
                    % (faults.get("store_net_recovered", 0),
                       faults.get("store_net_degraded", 0)))
        ckpts = summary.get("checkpoints")
        if ckpts:
            actions = ckpts.get("actions", {})
            parts = [f"{n} {a}" for a, n in actions.items()]
            line = "  checkpoints: " + ", ".join(parts)
            if ckpts.get("max_resume_pass") is not None:
                line += (" (deepest resume: pass %d)"
                         % ckpts["max_resume_pass"])
            lines.append(line)

    el = summary.get("elasticity")
    if el:
        lines.append("")
        lines.append("Mesh elasticity")
        lines.append("-" * 15)
        lines.append(f"  {'host/device losses':<28s} {el['host_losses']:>7d}")
        remesh_detail = ("  (" + ", ".join(el["remesh_devices"]) + " devices)"
                         if el.get("remesh_devices") else "")
        lines.append(f"  {'degraded re-meshes':<28s} {el['remeshes']:>7d}"
                     + remesh_detail)
        lines.append(f"  {'stolen worker shards':<28s}"
                     f" {el['stolen_shards']:>7d}")
        lines.append(f"  {'stragglers contained':<28s}"
                     f" {el['stragglers']:>7d}")
        if el.get("max_resume_pass") is not None:
            lines.append(f"  {'deepest resumed pass':<28s}"
                         f" {el['max_resume_pass']:>7d}")

    srv = summary.get("serving")
    if srv:
        lines.append("")
        lines.append("Serving (projection daemon)")
        lines.append("-" * 27)
        status = "  ".join(f"{s}={n}" for s, n in
                           srv.get("by_status", {}).items())
        lines.append(f"  requests {srv['requests']} "
                     f"({srv.get('tenants', 0)} tenant(s))  {status}")
        if srv.get("batches"):
            lines.append(
                f"  batches {srv['batches']}  mean lanes "
                f"{srv.get('mean_lanes')}  max {srv.get('max_lanes')}  "
                f"cross-request batches "
                f"{srv.get('multi_request_batches', 0)}"
                + (f"  cache-hit {srv['cache_hit_fraction']:.0%}"
                   if srv.get("cache_hit_fraction") is not None else ""))
        lat = srv.get("latency_ms")
        if lat and lat.get("count"):
            lines.append(
                f"  latency p50 {lat.get('p50', 0):.2f} ms  "
                f"p95 {lat.get('p95', 0):.2f} ms  "
                f"p99 {lat.get('p99', 0):.2f} ms  "
                f"max {lat.get('max', 0):.2f} ms"
                + (f"  ({srv['qps']} req/s sustained)"
                   if srv.get("qps") is not None else ""))
            hist = lat.get("histogram") or {}
            if hist:
                total = sum(hist.values())
                for label, cnt in hist.items():
                    bar = "#" * max(1, int(round(cnt / total * 32)))
                    lines.append(f"    {label:>8s} ms {cnt:>7d}  {bar}")

    fleet = summary.get("fleet")
    if fleet:
        lines.append("")
        lines.append("Fleet (replicated serving)")
        lines.append("-" * 26)
        reasons = fleet.get("deaths_by_reason")
        lines.append(
            f"  replica deaths {fleet.get('replica_deaths', 0)}"
            + (f" ({', '.join(f'{r}={n}' for r, n in reasons.items())})"
               if reasons else "")
            + f"  failovers {fleet.get('failovers', 0)}"
            + (f" ({fleet['tenants_failed_over']} tenant(s) remapped)"
               if fleet.get("tenants_failed_over") is not None else ""))
        lives = fleet.get("replica_lifetimes_s")
        if lives:
            lines.append(
                f"  dead-replica lifetimes {min(lives):.1f}"
                f"-{max(lives):.1f} s over {len(lives)} death(s)")
        walls = fleet.get("rollover_wall_s")
        lines.append(
            f"  rollovers {fleet.get('rollovers', 0)}"
            + (f" (walls {', '.join(f'{w:.1f}s' for w in walls)};"
               f" now serving generation {fleet.get('generation')})"
               if walls else ""))
        share = fleet.get("request_share")
        if share:
            counts = fleet.get("requests_by_replica", {})
            for rep, frac in share.items():
                lines.append(f"    replica {rep:<8s} "
                             f"{counts.get(rep, 0):>7d} request(s)  "
                             f"{frac:.1%}")

    slo = summary.get("slo")
    if slo:
        lines.append("")
        lines.append("SLO")
        lines.append("-" * 3)
        verdict = ("BURNING" if slo.get("burning")
                   else "ok" if slo.get("requests") else "ok (no traffic)")
        p99 = slo.get("p99_ms")
        lines.append(
            f"  target p99 {slo.get('target_p99_ms')} ms over "
            f"{slo.get('window_s')} s window: {verdict}")
        lines.append(
            f"  windowed p99 "
            + (f"{p99:.2f} ms" if isinstance(p99, (int, float))
               else "n/a")
            + f"  requests {slo.get('requests', 0)}  errors "
            f"{slo.get('errors', 0)} "
            f"(rate {slo.get('error_rate', 0.0):.4f}, budget "
            f"{slo.get('max_error_rate', 0.0):.4f})")

    roof = summary.get("roofline")
    if roof:
        lines.append("")
        lines.append("Roofline")
        lines.append("-" * 8)
        lines.append(f"  {'stage':<22s} {'lane':<14s} {'wall':>9s} "
                     f"{'MFU':>7s} {'BW':>7s} {'int.':>8s}  verdict")
        for r in roof:
            mfu, bw = r.get("mfu"), r.get("bw_frac")
            inten = r.get("intensity")
            wall = r.get("wall_s")
            verdict = str(r.get("bound") or "?")
            if r.get("perf_exempt"):
                verdict += " (perf-exempt)"
            if r.get("peak_source") and r.get("peak_source") != "datasheet":
                verdict += f" [{r['peak_source']}]"
            lines.append(
                "  "
                f"{str(r.get('stage'))[:22]:<22s} "
                f"{str(r.get('lane'))[:14]:<14s} "
                + (f"{wall:>8.3f}s" if isinstance(wall, (int, float))
                   else f"{'n/a':>9s}") + " "
                + (f"{100 * mfu:>6.2f}%" if isinstance(mfu, (int, float))
                   else f"{'n/a':>7s}") + " "
                + (f"{100 * bw:>6.2f}%" if isinstance(bw, (int, float))
                   else f"{'n/a':>7s}") + " "
                + (f"{inten:>8.2f}" if isinstance(inten, (int, float))
                   else f"{'n/a':>8s}")
                + f"  {verdict}")

    spans = summary.get("spans")
    if spans:
        lines.append("")
        lines.append("Trace spans (sampled)")
        lines.append("-" * 21)
        lines.append(f"  {spans['count']} span(s) across "
                     f"{spans['traces']} trace(s) — render waterfalls "
                     f"with `cnmf-tpu trace <run_dir>`")
        for name, v in spans.get("by_name", {}).items():
            lines.append(f"  {name:<28s} {v['count']:>6d} span(s) "
                         f"{v['wall_ms_total']:>10.1f} ms total")

    lines.append("")
    lines.append("Device memory")
    lines.append("-" * 13)
    if summary.get("memory_peak_bytes"):
        lines.append(
            f"  peak {_fmt_bytes(summary['memory_peak_bytes'])} "
            f"(at stage boundary: {summary.get('memory_peak_stage')})")
    else:
        lines.append("  no memory watermarks recorded (backend reports no "
                     "memory stats and no live buffers were sampled)")
    lines.append("")
    lines.append(f"{summary['n_events']} events across "
                 f"{len(event_files)} file(s)")
    return "\n".join(lines)


def _stage_waterfall(stages: dict) -> list[str]:
    if not stages:
        return ["  (no stage events)"]
    # top-level pipeline stages first, sub-stages (dotted/slashed) under
    top = {k: v for k, v in stages.items() if "." not in k and "/" not in k}
    total = sum(v["wall_s"] for v in top.values()) or \
        sum(v["wall_s"] for v in stages.values())
    width = 32
    out = []
    for name, v in sorted(stages.items(),
                          key=lambda kv: -kv[1]["wall_s"]):
        frac = v["wall_s"] / total if total > 0 else 0.0
        bar = "#" * max(1, int(round(min(frac, 1.0) * width))) \
            if v["wall_s"] > 0 else ""
        extra = ""
        if v.get("nbytes"):
            gbps = v["nbytes"] / v["wall_s"] / 1e9 if v["wall_s"] > 0 else 0
            extra = f"  {_fmt_bytes(v['nbytes'])} ({gbps:.2f} GB/s)"
        out.append(f"  {name:<36s} {v['wall_s']:>9.3f} s  "
                   f"{bar:<{width}s}{extra}")
    return out
