from .cnmf import cNMF, compute_tpm

__all__ = ["cNMF", "compute_tpm"]
