"""Transfer-guard smokes (ISSUE 7): the solver hot paths perform NO
implicit host transfers.

Always-on (independent of ``CNMF_TPU_SANITIZE``): each test stages its
inputs with explicit ``jax.device_put``, then runs the jitted solver —
compile and execute — entirely under ``jax.transfer_guard("disallow")``,
fetching results with explicit ``jax.device_get``. Any hidden
``np.asarray``/``.item()``/scalar round-trip inside the solver body
raises immediately. This is the runtime counterpart of the
``trace-host-sync`` lint rule: the rule catches the pattern lexically,
the guard catches whatever the AST heuristics cannot see.

Under ``CNMF_TPU_SANITIZE=1`` the conftest fixture additionally wraps
these tests (they are the designated ``sanitize`` subset) in the same
guard plus ``jax_debug_nans`` — nesting is harmless and the stricter
mode also covers fixture setup.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cnmf_torch_tpu.ops.nmf import nmf_fit_batch, nmf_fit_online
from cnmf_torch_tpu.parallel.rowshard import _rowshard_pass_jit


def _staged_lowrank(n, g, k, seed=0):
    rng = np.random.default_rng(seed)
    H = rng.gamma(2.0, 1.0, size=(n, k)).astype(np.float32)
    W = rng.gamma(2.0, 1.0, size=(k, g)).astype(np.float32)
    X = (H @ W + 0.01 * rng.random((n, g))).astype(np.float32)
    return X, H, W


def test_nmf_fit_batch_no_implicit_transfers():
    X, H0, W0 = _staged_lowrank(48, 32, 4)
    Xd = jax.device_put(X)
    Hd = jax.device_put(H0)
    Wd = jax.device_put(W0)
    with jax.transfer_guard("disallow"):
        H, W, err = nmf_fit_batch(Xd, Hd, Wd, beta=2.0,
                                  tol=jax.device_put(np.float32(1e-4)),
                                  max_iter=40)
        out = jax.device_get((H, W, err))
    assert all(np.isfinite(o).all() for o in out)


def test_nmf_fit_online_no_implicit_transfers():
    X, H0, _ = _staged_lowrank(64, 32, 4)
    chunk = 16
    Xc = X.reshape(4, chunk, 32)
    Hc0 = H0.reshape(4, chunk, 4)
    W0 = np.random.default_rng(1).gamma(
        2.0, 1.0, size=(4, 32)).astype(np.float32)
    Xcd, Hcd, Wd = map(jax.device_put, (Xc, Hc0, W0))
    told = jax.device_put(np.float32(1e-4))
    htold = jax.device_put(np.float32(1e-3))
    with jax.transfer_guard("disallow"):
        Hc, W, err = nmf_fit_online(Xcd, Hcd, Wd, beta=1.0, tol=told,
                                    h_tol=htold, chunk_max_iter=30,
                                    n_passes=6)
        out = jax.device_get((Hc, W, err))
    assert all(np.isfinite(o).all() for o in out)


def test_rowshard_pass_no_implicit_transfers():
    """One block-coordinate rowshard pass (the shard_map program the fused
    while_loop and the checkpointed driver both run) over the full
    device mesh."""
    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("cells",))
    n = 16 * len(devs)
    X, H0, W0 = _staged_lowrank(n, 24, 3, seed=2)
    row_sh = NamedSharding(mesh, P("cells", None))
    rep_sh = NamedSharding(mesh, P())
    Xd = jax.device_put(X, row_sh)
    Hd = jax.device_put(H0, row_sh)
    Wd = jax.device_put(W0, rep_sh)

    pass_fn = jax.jit(functools.partial(
        _rowshard_pass_jit, mesh=mesh, axis="cells", beta=2.0, h_tol=0.05,
        chunk_max_iter=50, l1_H=0.0, l2_H=0.0, l1_W=0.0, l2_W=0.0))
    with jax.transfer_guard("disallow"):
        H, W, err, A, B = pass_fn(Xd, Hd, Wd)
        out = jax.device_get((H, W, err, A, B))
    assert all(np.isfinite(o).all() for o in out)
    assert out[0].shape == (n, 3) and out[1].shape == (3, 24)


def test_sanitize_mode_designation():
    """CNMF_TPU_SANITIZE=1 designation: this file's tests carry the
    ``sanitize`` marker (conftest adds it by nodeid), so the opt-in mode
    wraps them in the guard + debug-NaN fixture."""
    import tests.conftest as c

    assert any("test_sanitize.py" in pat for pat in c.SANITIZE_GUARD_SUBSET)
    assert c.SANITIZE_NANS_SUBSET  # the solver hot-path tests stay listed


@pytest.mark.parametrize("value,expected", [("1", True), ("0", False),
                                            ("", False)])
def test_sanitize_knob_parses(monkeypatch, value, expected):
    from cnmf_torch_tpu.utils.envknobs import env_flag

    if value:
        monkeypatch.setenv("CNMF_TPU_SANITIZE", value)
    else:
        monkeypatch.delenv("CNMF_TPU_SANITIZE", raising=False)
    assert env_flag("CNMF_TPU_SANITIZE", False) is expected
