"""Tier-1 out-of-core ingestion smoke gate (scripts/verify_tier1.sh).

Runs the mini pipeline twice on the same seeds — once resident
(``CNMF_TPU_OOC=0``) and once with ``CNMF_TPU_OOC_BUDGET_BYTES`` forced
far below the fixture's matrix size, so prepare writes the row-slab
shard store and the rowsharded factorize streams every slab from disk —
and asserts:

  * the store exists with > 1 slab and the h5ad copy is SKIPPED under
    ``CNMF_TPU_OOC=1`` (the double-write satellite);
  * merged spectra AND consensus are BIT-identical to the resident run
    (store-backed staging places values, never sums them);
  * a ``shard_read``-injected torn slab is DETECTED by the reader's
    content-digest validation and healed by a disk re-read (telemetry
    ``fault`` kind ``shard_read_torn``), with the run still bit-identical;
  * every emitted event validates against the telemetry schema.

Exits nonzero on any violation, failing the gate.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["CNMF_TPU_TELEMETRY"] = "1"

_OOC_KNOBS = ("CNMF_TPU_OOC", "CNMF_TPU_OOC_BUDGET_BYTES",
              "CNMF_TPU_OOC_SLAB_ROWS", "CNMF_TPU_FAULT_SPEC")


def _pipeline(workdir: str, env: dict) -> "object":
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    prior = {k: os.environ.get(k) for k in _OOC_KNOBS}
    os.environ.update(env)
    try:
        rng = np.random.default_rng(3)
        usage = rng.dirichlet(np.ones(5) * 0.3, size=220)
        spectra = rng.gamma(0.3, 1.0, size=(5, 130)) * 40.0 / 130
        counts = rng.poisson(usage @ spectra * 300.0).astype(np.float64)
        counts[counts.sum(axis=1) == 0, 0] = 1.0
        df = pd.DataFrame(counts, index=[f"c{i}" for i in range(220)],
                          columns=[f"g{j}" for j in range(130)])
        counts_fn = os.path.join(workdir, "counts.df.npz")
        save_df_to_npz(df, counts_fn)

        obj = cNMF(output_dir=workdir, name="ooc")
        obj.prepare(counts_fn, components=[3], n_iter=4, seed=7,
                    num_highvar_genes=100)
        obj.factorize(rowshard=True)
        obj.combine()
        obj.consensus(k=3, density_threshold=2.0, show_clustering=False)
        return obj
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main() -> int:
    import numpy as np

    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    base_dir = tempfile.mkdtemp(prefix="ooc_smoke_base_")
    ooc_dir = tempfile.mkdtemp(prefix="ooc_smoke_ooc_")
    torn_dir = tempfile.mkdtemp(prefix="ooc_smoke_torn_")
    try:
        base = _pipeline(base_dir, {"CNMF_TPU_OOC": "0"})

        # fixture matrix ~220 x 100 f32 = 88 KB >> 16 KB budget: the
        # store MUST be written and factorize MUST stream slab-wise.
        # Slab rows pinned to 64 (the auto sizing floors at 256 rows so
        # production budgets never explode the slab count — on this mini
        # fixture that floor would collapse the store to one slab and the
        # smoke would prove nothing); 220/64 also leaves a RAGGED final
        # slab, the boundary case the staging parity must absorb.
        ooc_env = {"CNMF_TPU_OOC": "1",
                   "CNMF_TPU_OOC_BUDGET_BYTES": "16384",
                   "CNMF_TPU_OOC_SLAB_ROWS": "64"}
        ooc = _pipeline(ooc_dir, ooc_env)
        store_manifest = os.path.join(ooc.paths["shard_store"],
                                      "manifest.json")
        assert os.path.exists(store_manifest), "shard store not written"
        assert not os.path.exists(ooc.paths["normalized_counts"]), \
            "CNMF_TPU_OOC=1 must skip the h5ad normalized-counts copy"
        import json

        with open(store_manifest) as f:
            n_slabs = len(json.load(f)["slabs"])
        assert n_slabs > 1, f"budget should force multiple slabs ({n_slabs})"

        def _load(obj, key, *fmt):
            return np.load(obj.paths[key] % fmt, allow_pickle=True)["data"]

        for key, fmt in (("merged_spectra", (3,)),
                         ("consensus_spectra", (3, "2_0"))):
            a, b = _load(base, key, *fmt), _load(ooc, key, *fmt)
            assert np.array_equal(a, b), \
                f"{key}: store-backed run is not bit-identical to resident"
        ev_path = os.path.join(ooc_dir, "ooc", "cnmf_tmp",
                               "ooc.events.jsonl")
        validate_events_file(ev_path)
        evs = list(read_events(ev_path))
        assert any(e["t"] == "dispatch" and e.get("decision") == "ooc_ingest"
                   for e in evs), "no ooc_ingest dispatch event"
        assert any(e["t"] == "stream" and e.get("disk_nbytes")
                   for e in evs), "no disk-producer stream stats recorded"
        print("[ooc_smoke] store-backed run bit-identical to resident "
              f"({n_slabs} slabs, h5ad skipped) ... ok")

        # torn-slab containment: the injected corruption must be caught
        # by the digest check and healed by a clean re-read — output
        # still bit-identical, fault event on the record
        torn_env = dict(ooc_env,
                        CNMF_TPU_FAULT_SPEC="shard_read:context=slab")
        import warnings

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            torn = _pipeline(torn_dir, torn_env)
        heal_warn = [w for w in caught
                     if "re-reading from disk" in str(w.message)]
        assert heal_warn, "torn shard read was not detected/re-read"
        a = _load(base, "consensus_spectra", 3, "2_0")
        b = _load(torn, "consensus_spectra", 3, "2_0")
        assert np.array_equal(a, b), \
            "torn-then-healed run is not bit-identical"
        torn_ev = os.path.join(torn_dir, "ooc", "cnmf_tmp",
                               "ooc.events.jsonl")
        validate_events_file(torn_ev)
        assert any(e["t"] == "fault" and e.get("kind") == "shard_read_torn"
                   for e in read_events(torn_ev)), \
            "no shard_read_torn fault event"
        print("[ooc_smoke] torn slab detected, re-read, bit-identical "
              "output ... ok")
        return 0
    finally:
        for d in (base_dir, ooc_dir, torn_dir):
            shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
