"""Tutorial: Harmony batch correction + CITE-seq preprocessing -> cNMF.

The runnable equivalent of the reference's batch-correction vignette
(`Tutorials/Batch_correction_vignette.ipynb`, which downloads the Baron
pancreatic-islet atlas; here an islets-shaped dataset with planted programs
AND planted per-batch gene effects is simulated in-process, so the tutorial
is self-contained and asserts its own success).

What it shows, end to end:

1. build a multi-batch CITE-seq-style dataset (RNA counts + a small ADT
   panel) with per-batch multiplicative gene effects — the nuisance signal
   Harmony removes;
2. ``Preprocess.preprocess_for_cnmf``: QC -> TP10K -> seurat_v3 HVGs ->
   PCA -> Harmony -> gene-space MOE ridge correction -> ADT hstack, saving
   the three files ``cNMF.prepare`` consumes (counts, tpm, HVG list);
3. verify the correction actually mixed the batches (batch silhouette in
   PCA space drops);
4. the standard cNMF stages on the corrected matrix, and a check that the
   planted biological programs — not the batch effects — are recovered.

Run:  python examples/batch_correction_tutorial.py [output_dir]
Takes ~2-4 minutes on one TPU chip or a few CPU cores.
"""

import os
import sys
import tempfile

import numpy as np
import pandas as pd

try:
    import cnmf_torch_tpu  # noqa: F401
except ImportError:  # uninstalled source checkout: use the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def simulate_citeseq_batches(n_cells=4000, n_genes=2500, n_adt=12,
                             k_true=6, n_batches=3, seed=7):
    """Islets-shaped synthetic: cells are Dirichlet mixtures of k_true
    programs (shared biology), each batch applies its own multiplicative
    per-gene effect (technical nuisance), plus a small ADT antibody panel
    correlated with the programs (the CITE-seq surface)."""
    rng = np.random.default_rng(seed)
    programs = rng.gamma(0.3, 1.0, size=(k_true, n_genes))
    block = n_genes // k_true
    for k in range(k_true):
        programs[k, k * block:(k + 1) * block] *= 6.0
    programs /= programs.sum(axis=1, keepdims=True)
    usage = rng.dirichlet(np.full(k_true, 0.2), size=n_cells)
    batch = rng.integers(0, n_batches, size=n_cells)
    batch_fx = rng.gamma(25.0, 0.04, size=(n_batches, n_genes))
    depth = rng.integers(1500, 5000, size=(n_cells, 1)).astype(float)
    rate = (usage @ programs) * batch_fx[batch]
    counts = rng.poisson(rate * depth).astype(np.float32)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    # ADT panel: two antibodies per program, Poisson around usage signal
    adt_loadings = np.zeros((k_true, n_adt))
    for k in range(k_true):
        adt_loadings[k, (2 * k) % n_adt] = 1.0
        adt_loadings[k, (2 * k + 1) % n_adt] = 0.5
    adt = rng.poisson(usage @ adt_loadings * 50.0 + 5.0).astype(np.float32)
    return counts, adt, usage, programs, batch


def batch_silhouette(pcs, batch):
    """Mean silhouette of the batch labels in PC space — HIGH means batches
    separate (bad), near-zero/negative means they mix (good)."""
    from cnmf_torch_tpu.ops import silhouette_score

    return float(silhouette_score(pcs.astype(np.float32),
                                  np.asarray(batch, dtype=np.int32)))


def main(output_dir=None, n_cells=4000, n_genes=2500, n_iter=20, k_sel=None):
    import scipy.sparse as sp

    from cnmf_torch_tpu import Preprocess, cNMF
    from cnmf_torch_tpu.ops.pca import pca
    from cnmf_torch_tpu.utils.anndata_lite import AnnDataLite, read_h5ad

    output_dir = output_dir or tempfile.mkdtemp(prefix="cnmf_batchcorr_")
    os.makedirs(output_dir, exist_ok=True)
    k_true = 6
    counts, adt, usage_true, programs_true, batch = simulate_citeseq_batches(
        n_cells=n_cells, n_genes=n_genes, k_true=k_true)

    # one AnnData-style object holding RNA + ADT rows in var, tagged by a
    # feature-type column — the 10x CITE-seq convention preprocess splits on
    X = sp.csr_matrix(np.hstack([counts, adt]))
    var = pd.DataFrame(index=(
        [f"gene_{j}" for j in range(counts.shape[1])]
        + [f"adt_{j}" for j in range(adt.shape[1])]))
    var["feature_types"] = (["Gene Expression"] * counts.shape[1]
                            + ["Antibody Capture"] * adt.shape[1])
    obs = pd.DataFrame(
        {"batch": pd.Categorical([f"donor{b}" for b in batch])},
        index=[f"cell_{i}" for i in range(n_cells)])
    adata = AnnDataLite(X=X, obs=obs, var=var)
    print(f"simulated CITE-seq: {n_cells} cells x {counts.shape[1]} genes "
          f"+ {adt.shape[1]} ADTs, {len(set(batch))} batches, "
          f"{k_true} planted programs")

    # ------------------------------------------------------------------
    # Preprocess: QC -> TP10K -> HVG -> PCA -> Harmony -> MOE ridge -> ADT
    # ------------------------------------------------------------------
    base = os.path.join(output_dir, "islets_pre")
    pre = Preprocess(random_seed=14)
    pre.preprocess_for_cnmf(adata, feature_type_col="feature_types",
                            harmony_vars="batch", n_top_rna_genes=1500,
                            librarysize_targetsum=1e6,
                            save_output_base=base)
    counts_fn = base + ".Corrected.HVG.Varnorm.h5ad"
    tpm_fn = base + ".TP10K.h5ad"
    genes_fn = base + ".Corrected.HVGs.txt"
    print("preprocess artifacts:", counts_fn)

    # did Harmony actually mix the batches? Compare batch silhouette in PC
    # space before vs after correction: it must drop substantially.
    corrected = read_h5ad(counts_fn)
    corr_X = (corrected.X.toarray()
              if sp.issparse(corrected.X) else np.asarray(corrected.X))
    raw_tp10k = np.asarray(counts / counts.sum(1, keepdims=True) * 1e4,
                           np.float32)
    hvg_names = [g for g in corrected.var.index if g.startswith("gene_")]
    hvg_idx = [int(g.split("_")[1]) for g in hvg_names]
    raw_hvg = raw_tp10k[:, hvg_idx]
    n_pcs = 20
    pcs_raw = np.asarray(pca(raw_hvg, n_pcs)[0])
    pcs_corr = np.asarray(pca(corr_X[:, :len(hvg_idx)], n_pcs)[0])
    sil_raw = batch_silhouette(pcs_raw, batch)
    sil_corr = batch_silhouette(pcs_corr, batch)
    print(f"batch silhouette: raw={sil_raw:.3f} -> corrected={sil_corr:.3f}")
    assert sil_corr < sil_raw - 0.05 or sil_corr < 0.02, (
        "Harmony correction did not improve batch mixing")

    # ------------------------------------------------------------------
    # cNMF on the corrected matrix (three-file contract, README.md:88-92)
    # ------------------------------------------------------------------
    obj = cNMF(output_dir=output_dir, name="islets")
    k_sel = k_sel or k_true
    obj.prepare(counts_fn, components=[k_sel], n_iter=n_iter, seed=14,
                tpm_fn=tpm_fn, genes_file=genes_fn)
    obj.factorize()
    obj.combine()
    try:
        obj.consensus(k_sel, density_threshold=0.5, show_clustering=False)
        dt = "0_5"
    except RuntimeError:
        obj.consensus(k_sel, density_threshold=2.0, show_clustering=False)
        dt = "2_0"
    usage, scores, tpm_spectra, top_genes = obj.load_results(
        K=k_sel, density_threshold=float(dt.replace("_", ".")))
    print(f"consensus usages {usage.shape}; top genes:\n"
          f"{top_genes.iloc[:5, :].to_string()}")

    # planted-program recovery on the BIOLOGY, not the batch effects: each
    # planted program must correlate with a recovered RNA spectrum
    rna_cols = [g for g in tpm_spectra.index if g.startswith("gene_")]
    rec = tpm_spectra.loc[rna_cols].values.T            # (K, hvg)
    truth = programs_true[:, [int(g.split("_")[1]) for g in rna_cols]]
    corr = np.corrcoef(np.vstack([truth, rec]))[:k_true, k_true:]
    best = corr.max(axis=1)
    print("per-planted-program best correlation:", np.round(best, 3))
    assert (best > 0.8).sum() >= k_true - 1, (
        "planted programs were not recovered from the corrected data")
    print(f"OK: batch effects removed, programs recovered. "
          f"Artifacts in {output_dir}/islets/")
    return sil_raw, sil_corr, best


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
