"""The runnable walkthroughs are under CI: each example's main() is smoke-run
at reduced size (the reference's tutorials are notebooks with no automated
coverage at all, SURVEY.md §4), and the ground-truth recovery asserts inside
them — planted-program correlation, batch-mixing improvement — run as part
of the smoke, so a regression in any pipeline stage fails here."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))


def test_simulated_tutorial_smoke(tmp_path):
    """Planted-program recovery (r > 0.95) end-to-end — VERDICT r3 asked for
    the example's assert to live in the suite, not only in user runs."""
    import simulated_tutorial

    # full-size main() asserts r > 0.95 internally; ~60-90 s on the CPU mesh
    simulated_tutorial.main(str(tmp_path))


@pytest.mark.slow
def test_batch_correction_tutorial_smoke(tmp_path):
    """Harmony/CITE-seq walkthrough at reduced size: asserts batch mixing
    improves AND the planted biology (not the batch effects) is recovered."""
    import batch_correction_tutorial

    sil_raw, sil_corr, best = batch_correction_tutorial.main(
        str(tmp_path), n_cells=800, n_genes=600, n_iter=8)
    assert (best > 0.8).sum() >= 5


@pytest.mark.slow
def test_pbmc_tutorial_smoke(tmp_path):
    """PBMC-style h5ad walkthrough at reduced size (k-selection sweep + the
    documented two-pass consensus)."""
    import pbmc_tutorial

    best = pbmc_tutorial.main(str(tmp_path), n_cells=600, n_genes=900,
                              n_iter=6, ks=[9, 10, 11])
    assert (best[:8] > 0.8).all()


def test_seurat_vignette_smoke(tmp_path):
    """R/Seurat export walkthrough (the reference's R_vignette.Rmd flow):
    the 10x trio + baked-paths R script generate, and the script's own
    input-coherence asserts run inside main()."""
    import seurat_vignette

    r_path = seurat_vignette.main(str(tmp_path), n_cells=300, n_genes=400,
                                  n_iter=6, k=4)
    assert r_path.endswith(".seurat_import.R")
    text = open(r_path).read()
    # every read.table/ReadMtx path in the generated R code exists
    import re

    for p in re.findall(r'"(/[^"]+)"', text):
        assert os.path.exists(p), p
