import numpy as np
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu.ops import (
    column_mean_var,
    highvar_genes,
    normalize_total,
    ols_all_cols,
    row_sums,
    scale_columns,
)
from cnmf_torch_tpu.utils import AnnDataLite


@pytest.mark.parametrize("sparse", [True, False])
@pytest.mark.parametrize("ddof", [0, 1])
def test_column_mean_var_matches_numpy(counts_100x500, sparse, ddof):
    X = sp.csr_matrix(counts_100x500) if sparse else counts_100x500
    mean, var = column_mean_var(X, ddof=ddof)
    np.testing.assert_allclose(mean, counts_100x500.mean(axis=0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var, counts_100x500.var(axis=0, ddof=ddof), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("sparse", [True, False])
def test_column_mean_var_large_mean_stability(sparse):
    # TPM-scale columns (mean ~1e4, std ~10): the naive E[x^2]-E[x]^2 form
    # in fp32 returns 0-112 for a true variance of 100
    rng = np.random.default_rng(3)
    X = rng.normal(1e4, 10.0, size=(2000, 8))
    Xin = sp.csr_matrix(X) if sparse else X
    mean, var = column_mean_var(Xin, ddof=0)
    np.testing.assert_allclose(mean, X.mean(axis=0), rtol=1e-5)
    np.testing.assert_allclose(var, X.var(axis=0, ddof=0), rtol=1e-2)


def test_column_mean_var_blocked(counts_100x500):
    # block streaming must give the same answer as one shot
    X = sp.csr_matrix(counts_100x500)
    m1, v1 = column_mean_var(X)
    m2, v2 = column_mean_var(X, block_rows=17)
    np.testing.assert_allclose(m1, m2, rtol=1e-5)
    np.testing.assert_allclose(v1, v2, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("sparse", [True, False])
@pytest.mark.parametrize("block_rows", [None, 17])
def test_column_moments_staged_matches_unstaged(counts_100x500, sparse,
                                                block_rows):
    """The fused host-f64 moment engine (one-block AND blocked-accumulation
    modes) must agree with column_mean_var for both the raw matrix and the
    row-scaled (TPM) view — and with exact numpy f64."""
    from cnmf_torch_tpu.ops.stats import column_moments_staged

    X = sp.csr_matrix(counts_100x500) if sparse else counts_100x500
    totals = counts_100x500.sum(axis=1)
    scale = np.where(totals > 0, 1e6 / np.where(totals > 0, totals, 1.0), 1.0)
    kw = {} if block_rows is None else {"block_rows": block_rows}
    (rm, rv), (sm, sv) = column_moments_staged(X, row_scale=scale, **kw)
    # exact f64: tight bars vs numpy
    np.testing.assert_allclose(rm, counts_100x500.mean(axis=0), rtol=1e-12)
    np.testing.assert_allclose(rv, counts_100x500.var(axis=0), rtol=1e-9,
                               atol=1e-12)

    m_ref, v_ref = column_mean_var(X, ddof=0)
    np.testing.assert_allclose(rm, m_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(rv, v_ref, rtol=1e-4, atol=1e-5)

    tpm = counts_100x500 * scale[:, None]
    np.testing.assert_allclose(sm, tpm.mean(axis=0), rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(sv, tpm.var(axis=0), rtol=1e-4, atol=1e-2)

    (rm2, rv2), none = column_moments_staged(X, **kw)
    assert none is None
    np.testing.assert_allclose(rm2, m_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(rv2, v_ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("sparse", [True, False])
def test_scale_columns_precomputed_var(counts_100x500, sparse):
    X = sp.csr_matrix(counts_100x500) if sparse else counts_100x500
    ref, std_ref = scale_columns(X, ddof=1)
    var1 = counts_100x500.var(axis=0, ddof=1)
    got, std = scale_columns(X, ddof=1, precomputed_var=var1)
    a = ref.toarray() if sparse else ref
    b = got.toarray() if sparse else got
    np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-6)


def test_column_mean_var_matches_sklearn_standard_scaler(sparse_counts_100x500):
    # the reference's get_mean_var (cnmf.py:128-131) is StandardScaler-based
    from sklearn.preprocessing import StandardScaler

    scaler = StandardScaler(with_mean=False).fit(sparse_counts_100x500)
    mean, var = column_mean_var(sparse_counts_100x500, ddof=0)
    np.testing.assert_allclose(mean, scaler.mean_, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(var, scaler.var_, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("sparse", [True, False])
def test_row_sums(counts_100x500, sparse):
    X = sp.csr_matrix(counts_100x500) if sparse else counts_100x500
    np.testing.assert_allclose(row_sums(X), counts_100x500.sum(axis=1), rtol=1e-5)


def test_row_sums_with_empty_rows():
    X = sp.csr_matrix(np.array([[0, 0], [1, 2], [0, 0], [3, 0], [0, 0]], dtype=float))
    np.testing.assert_allclose(row_sums(X), [0, 3, 0, 3, 0])


@pytest.mark.parametrize("sparse", [True, False])
def test_normalize_total(counts_100x500, sparse):
    X = sp.csr_matrix(counts_100x500) if sparse else counts_100x500
    adata = AnnDataLite(X)
    tpm = normalize_total(adata, target_sum=1e6)
    got = np.asarray(tpm.X.todense()) if sp.issparse(tpm.X) else tpm.X
    sums = got.sum(axis=1)
    nonzero = counts_100x500.sum(axis=1) > 0
    np.testing.assert_allclose(sums[nonzero], 1e6, rtol=1e-4)


@pytest.mark.parametrize("sparse", [True, False])
def test_scale_columns_unit_variance(counts_100x500, sparse):
    X = sp.csr_matrix(counts_100x500) if sparse else counts_100x500
    scaled, std = scale_columns(X, ddof=1)
    got = np.asarray(scaled.todense()) if sp.issparse(scaled) else scaled
    expected_std = counts_100x500.std(axis=0, ddof=1)
    nz = expected_std > 0
    np.testing.assert_allclose(got[:, nz].std(axis=0, ddof=1), 1.0, rtol=1e-4)
    np.testing.assert_allclose(std, expected_std, rtol=1e-4, atol=1e-6)
    # zero-variance columns pass through unchanged (scanpy semantics)
    if (~nz).any():
        np.testing.assert_allclose(got[:, ~nz], counts_100x500[:, ~nz])


def _reference_hvg_math(X):
    """The reference's dense HVG math (cnmf.py:188-238), in pandas/numpy."""
    import pandas as pd

    mean = pd.Series(X.mean(axis=0).astype(float))
    var = pd.Series(X.var(ddof=0, axis=0).astype(float))
    fano = var / mean
    top_genes = mean.sort_values(ascending=False)[:20].index
    A = (np.sqrt(var) / mean)[top_genes].min()
    w_mean_low, w_mean_high = mean.quantile([0.10, 0.90])
    w_fano_low, w_fano_high = fano.quantile([0.10, 0.90])
    box = (fano > w_fano_low) & (fano < w_fano_high) & (mean > w_mean_low) & (mean < w_mean_high)
    B = np.sqrt(fano[box].median())
    expected_fano = (A ** 2) * mean + (B ** 2)
    fano_ratio = fano / expected_fano
    T = 1.0 + fano[box].std()
    return mean, var, fano, expected_fano, fano_ratio, A, B, T


@pytest.mark.parametrize("sparse", [True, False])
def test_highvar_genes_matches_reference_math(counts_100x500, sparse):
    X = sp.csr_matrix(counts_100x500) if sparse else counts_100x500
    stats, params = highvar_genes(X, numgenes=100)
    mean, var, fano, expected_fano, fano_ratio, A, B, T = _reference_hvg_math(counts_100x500)

    np.testing.assert_allclose(stats["mean"], mean, rtol=1e-4)
    np.testing.assert_allclose(stats["fano"].dropna(), fano.dropna(), rtol=1e-3)
    np.testing.assert_allclose(params["A"], A, rtol=1e-3)
    np.testing.assert_allclose(params["B"], B, rtol=1e-3)
    assert stats["high_var"].sum() == 100
    # the top-100 selection must match the reference ranking
    ref_top = set(fano_ratio.sort_values(ascending=False).index[:100])
    got_top = set(np.where(stats["high_var"].values)[0])
    overlap = len(ref_top & got_top)
    assert overlap >= 98  # fp32 vs fp64 may swap genes at the exact cutoff


def test_highvar_genes_threshold_mode(counts_100x500):
    stats, params = highvar_genes(counts_100x500)
    _, _, _, _, fano_ratio, _, _, T = _reference_hvg_math(counts_100x500)
    np.testing.assert_allclose(params["T"], T, rtol=1e-3)
    mean = counts_100x500.mean(axis=0)
    expected = (fano_ratio.values > params["T"]) & (mean > 0.5)
    got = stats["high_var"].values
    assert (expected == got).mean() > 0.99


@pytest.mark.parametrize("sparse", [True, False])
@pytest.mark.parametrize("normalize_y", [True, False])
@pytest.mark.parametrize("precision", ["float64", "float32"])
def test_ols_matches_reference(counts_100x500, sparse, normalize_y, precision):
    rng = np.random.default_rng(0)
    X = rng.random((100, 7))
    Y = sp.csr_matrix(counts_100x500) if sparse else counts_100x500

    beta = ols_all_cols(X, Y, batch_size=33, normalize_y=normalize_y,
                        precision=precision)

    Yd = counts_100x500.copy()
    if normalize_y:
        m = Yd.mean(axis=0)
        v = np.maximum(Yd.var(axis=0, ddof=0), 1e-12)
        Yd = (Yd - m) / np.sqrt(v)
    expected, *_ = np.linalg.lstsq(X.T @ X, X.T @ Yd, rcond=None)
    if precision == "float64":
        # must clear the reference's golden-file RMS bar (1e-4)
        rms = np.sqrt(np.mean((beta - expected) ** 2))
        assert rms < 1e-6
    else:
        # fp32 path: conditioning amplifies rounding; still close
        np.testing.assert_allclose(beta, expected, rtol=0.05, atol=0.01)


def test_scale_hvg_columns_device_matches_host():
    """The consensus final-refit's on-device HVG slice+scale must equal the
    host scale_columns path it replaced (models/cnmf.py final usage refit):
    same ddof-1 std convention, same zero-std handling per input kind."""
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.stats import (scale_columns,
                                          scale_hvg_columns_device)

    rng = np.random.default_rng(17)
    X = rng.gamma(1.0, 1.0, size=(60, 30)).astype(np.float32)
    X[:, 5] = 0.0  # a zero-variance column
    hvg_idx = np.array([2, 5, 7, 11, 19, 23])

    # sparse-input convention: zero std -> divide by 1
    host_scaled, _ = scale_columns(sp.csr_matrix(X[:, hvg_idx]), ddof=1,
                                   zero_std_to_one=True)
    # derive div exactly the way the production site does
    # (models/cnmf.py final usage refit): the tpm_stats artifact's ddof=0
    # std, Bessel-corrected to ddof=1 — this pins the reconstruction
    # identity, not just the device division
    n_rows = X.shape[0]
    std0 = X.std(axis=0, ddof=0).astype(np.float64)[hvg_idx]
    div = np.sqrt(std0 ** 2 * (n_rows / (n_rows - 1.0)))
    div[div == 0] = 1.0
    dev = np.asarray(scale_hvg_columns_device(jnp.asarray(X), hvg_idx, div))
    np.testing.assert_allclose(dev, host_scaled.toarray(), rtol=2e-6,
                               atol=1e-7)
