"""Warm serving tier (ISSUE 12): a persistent multi-tenant projection
daemon over published reference spectra.

The heavy-traffic scenario is not one lab running ``factorize`` once —
it is many users projecting their cells onto a published reference
(``fit_h`` refit) and expecting usage matrices back in milliseconds.
This package assembles the existing ingredients into that service:

  * ``reference.py`` — the reference spectra loaded once, device-
    resident with precomputed loop-invariant W products;
  * ``batcher.py`` — admission queue + micro-batching dispatcher:
    concurrent requests coalesce into ONE vmapped, shape-bucketed
    ``fit_h`` dispatch, bit-identical per request to solo
    ``refit_usage`` dispatch, with per-lane health grading and tenant
    quarantine;
  * ``daemon.py`` — stdlib HTTP/JSON front end (unix socket or
    127.0.0.1 TCP) + client, behind ``cnmf-tpu serve <run_dir>``;
  * ``fleet.py`` (ISSUE 20) — replicated fleet behind ``cnmf-tpu
    fleet``: consistent-hash tenant routing over N serve replicas,
    per-tenant admission quotas, chaos-tested failover with idempotent
    retries, and zero-downtime reference rollover.

Knobs: ``CNMF_TPU_SERVE_BATCH`` / ``_LINGER_MS`` / ``_BUCKETS`` /
``_TIMEOUT_S`` / ``_WARM_START`` / ``_DRAIN_S`` and the
``CNMF_TPU_FLEET_*`` family (see the README knob table).
Telemetry: ``serve_request`` / ``serve_batch`` / ``replica_death`` /
``failover`` / ``rollover`` events, rendered by ``cnmf-tpu report``;
sustained-load numbers via ``bench.py --tier serve`` and ``--tier
fleet``.
"""

from .batcher import (PoisonError, ProjectionService, QuarantinedError,
                      ServeError, ShedError)
from .daemon import (REQUEST_ID_HEADER, ServeClient, ServeDaemon,
                     default_socket_path, serve_forever)
from .fleet import (FleetClient, FleetDaemon, FleetRouter, HashRing,
                    SubprocessReplica, TokenBucket,
                    default_fleet_socket_path, fleet_forever)
from .reference import (ReferenceError, ResidentReference, find_references,
                        load_reference)

__all__ = [
    "ServeError",
    "ShedError",
    "PoisonError",
    "QuarantinedError",
    "ProjectionService",
    "REQUEST_ID_HEADER",
    "ServeClient",
    "ServeDaemon",
    "default_socket_path",
    "serve_forever",
    "FleetClient",
    "FleetDaemon",
    "FleetRouter",
    "HashRing",
    "SubprocessReplica",
    "TokenBucket",
    "default_fleet_socket_path",
    "fleet_forever",
    "ReferenceError",
    "ResidentReference",
    "find_references",
    "load_reference",
]
