"""Warm serving tier tests (ISSUE 12): resident reference, cross-request
micro-batching bit-parity with solo dispatch, poison quarantine, warm
starts, the HTTP daemon, and the serve telemetry surface."""

import os
import shutil
import threading

import numpy as np
import pandas as pd
import pytest

from cnmf_torch_tpu.ops.nmf import fit_h
from cnmf_torch_tpu.serving import (
    PoisonError,
    ProjectionService,
    QuarantinedError,
    ReferenceError,
    ResidentReference,
    ServeClient,
    ServeDaemon,
    ShedError,
    find_references,
    load_reference,
)
from cnmf_torch_tpu.serving.batcher import (
    batched_project,
    bucket_for,
    lane_buckets,
    lane_count,
    resolve_buckets,
)
from cnmf_torch_tpu.utils.profiling import latency_summary, percentile

K, G = 6, 90


def _reference(beta=2.0, chunk_size=5000, seed=0, g=G, k=K, **kw):
    rng = np.random.default_rng(seed)
    W = rng.gamma(0.3, 1.0, size=(k, g)).astype(np.float32)
    return ResidentReference(W, beta=beta, chunk_size=chunk_size,
                             chunk_max_iter=150, h_tol=0.05, l1_H=0.0,
                             **kw)


def _query(ref, n, seed):
    rng = np.random.default_rng(seed)
    u = rng.dirichlet(np.ones(ref.k) * 0.3, size=n)
    return (u @ ref.W * 40.0
            + rng.random((n, ref.n_genes)) * 0.01).astype(np.float32)


def _solo(ref, X, H_init=None):
    """The solo comparator: exactly cNMF.refit_usage's fit_h call."""
    return fit_h(X, ref.W, H_init=H_init, chunk_size=ref.chunk_size,
                 chunk_max_iter=ref.chunk_max_iter, h_tol=ref.h_tol,
                 l1_reg_H=ref.l1_H, l2_reg_H=0.0, beta=ref.beta)


# ---------------------------------------------------------------------------
# buckets / percentile units
# ---------------------------------------------------------------------------

def test_resolve_buckets_schedule_and_validation(monkeypatch):
    assert resolve_buckets(5000, "64,256,1024") == (64, 256, 1024, 5000)
    # buckets above the chunk size drop out; the chunk size caps the top
    assert resolve_buckets(200, "64,256,1024") == (64, 200)
    monkeypatch.setenv("CNMF_TPU_SERVE_BUCKETS", "32, 128")
    assert resolve_buckets(5000) == (32, 128, 5000)
    with pytest.raises(ValueError, match="CNMF_TPU_SERVE_BUCKETS"):
        resolve_buckets(5000, "64,two")
    with pytest.raises(ValueError, match=">= 1"):
        resolve_buckets(5000, "0,64")


def test_bucket_and_lane_helpers():
    buckets = (64, 256, 1024)
    assert bucket_for(1, buckets) == 64
    assert bucket_for(64, buckets) == 64
    assert bucket_for(65, buckets) == 256
    assert bucket_for(4096, buckets) == 1024  # clamped to top
    assert lane_buckets(8) == (1, 2, 4, 8)
    assert lane_buckets(6) == (1, 2, 4, 6)
    assert lane_buckets(1) == (1,)
    assert lane_count(100, 5000) == 1
    assert lane_count(5000, 5000) == 1
    assert lane_count(5001, 5000) == 2
    assert lane_count(150, 64) == 3


def test_percentile_helper():
    vals = list(range(1, 101))
    assert percentile(vals, 50) == pytest.approx(50.5)
    assert percentile(vals, 99) == pytest.approx(99.01)
    assert percentile([7.0], 95) == 7.0
    with pytest.raises(ValueError):
        percentile([], 50)


def test_latency_summary_shape():
    s = latency_summary([0.5, 1.5, 3.0, 30.0, 700.0])
    assert s["count"] == 5 and s["max"] == 700.0
    assert set(s["histogram"]) == {"<=1", "<=2", "<=5", "<=50", "<=1000"}
    assert sum(s["histogram"].values()) == 5
    assert latency_summary([]) == {"count": 0}


# ---------------------------------------------------------------------------
# batched dispatch: bit-parity with solo refit_usage dispatch
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("beta", [2.0, 1.0])
def test_batched_bit_identical_to_solo(beta):
    ref = _reference(beta=beta)
    with ProjectionService(ref, max_batch=8, linger_ms=60.0,
                           warm_start=False) as svc:
        queries = [_query(ref, n, seed) for n, seed in
                   ((33, 1), (100, 2), (256, 3))]
        results = [None] * len(queries)

        def go(i):
            results[i] = svc.project(queries[i], tenant=f"t{i}")

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for q, (H, meta) in zip(queries, results):
            assert np.array_equal(H, _solo(ref, q))
        stats = svc.stats()
    assert stats["ok"] == 3
    assert stats["cold_dispatches_after_warmup"] == 0


def test_multichunk_request_bit_identical():
    """A request taller than the chunk size splits into the SOLO chunk
    partition (one lane per chunk) and still reproduces solo dispatch
    bit-exactly."""
    ref = _reference(beta=2.0, chunk_size=64)
    with ProjectionService(ref, max_batch=8, linger_ms=0.0,
                           warm_start=False) as svc:
        X = _query(ref, 150, 7)  # 3 lanes of chunk 64
        H, meta = svc.project(X)
        assert meta["batch_lanes"] == 3
        assert np.array_equal(H, _solo(ref, X))


def test_two_racing_clients_land_in_one_batch():
    """The ISSUE's concurrency pin: two racing clients coalesce into ONE
    batched dispatch and each gets the bit-exact solo result."""
    ref = _reference(beta=2.0)
    with ProjectionService(ref, max_batch=4, linger_ms=120.0,
                           warm_start=False) as svc:
        Xa, Xb = _query(ref, 40, 11), _query(ref, 55, 12)
        out = {}

        def go(name, X):
            out[name] = svc.project(X, tenant=name)

        ta = threading.Thread(target=go, args=("a", Xa))
        tb = threading.Thread(target=go, args=("b", Xb))
        ta.start()
        tb.start()
        ta.join()
        tb.join()
        assert out["a"][1]["batch_requests"] == 2
        assert out["b"][1]["batch_requests"] == 2
        assert np.array_equal(out["a"][0], _solo(ref, Xa))
        assert np.array_equal(out["b"][0], _solo(ref, Xb))
        assert svc.stats()["multi_request_batches"] >= 1


def test_poison_quarantines_without_sinking_batchmates():
    ref = _reference(beta=2.0)
    with ProjectionService(ref, max_batch=4, linger_ms=120.0,
                           warm_start=False) as svc:
        good = _query(ref, 30, 21)
        bad = _query(ref, 20, 22)
        bad[5, 3] = np.nan
        out = {}

        def go_good():
            out["good"] = svc.project(good, tenant="fine")

        def go_bad():
            try:
                svc.project(bad, tenant="evil")
                out["bad"] = "no error"
            except PoisonError as exc:
                out["bad"] = exc

        tg, tb = (threading.Thread(target=go_good),
                  threading.Thread(target=go_bad))
        tg.start()
        tb.start()
        tg.join()
        tb.join()
        # the poison lane failed alone; its batchmate is bit-exact
        assert isinstance(out["bad"], PoisonError)
        H, meta = out["good"]
        assert meta["batch_requests"] == 2
        assert np.array_equal(H, _solo(ref, good))

        # strikes accumulate to quarantine; admission then rejects
        for _ in range(2):
            with pytest.raises(PoisonError):
                svc.project(bad, tenant="evil")
        with pytest.raises(QuarantinedError):
            svc.project(good, tenant="evil")
        # other tenants unaffected
        H2, _ = svc.project(good, tenant="fine")
        assert np.array_equal(H2, _solo(ref, good))


def test_admission_shed_paths():
    ref = _reference()
    svc = ProjectionService(ref, max_batch=1, linger_ms=0.0,
                            timeout_s=0.05, warm_start=False)
    # queue-full shed (dispatcher not running; bounded queue fills)
    svc._running = True
    for _ in range(svc._q.maxsize):
        svc._q.put_nowait(object())
    with pytest.raises(ShedError, match="queue full"):
        svc.submit(_query(ref, 5, 1))
    # deadline shed: an aged request is dropped with a clear error
    while not svc._q.empty():
        svc._q.get_nowait()
    req = svc.submit(_query(ref, 5, 2))
    req.t_enqueue -= 10.0
    assert svc._expired(req)
    with pytest.raises(ShedError, match="CNMF_TPU_SERVE_TIMEOUT_S"):
        req.wait(1.0)
    svc._running = False


def test_admission_validates_shape_and_accounts_rejections():
    ref = _reference(chunk_size=64)
    with ProjectionService(ref, max_batch=2, linger_ms=0.0,
                           warm_start=False) as svc:
        with pytest.raises(Exception, match="genes"):
            svc.submit(np.ones((4, ref.n_genes + 1), np.float32))
        with pytest.raises(Exception, match="matrix"):
            svc.submit(np.ones((0, ref.n_genes), np.float32))
        # oversized requests reject at admission (the warmed program
        # bucket schedule stays the ONLY shapes ever dispatched)
        with pytest.raises(Exception, match="split the matrix"):
            svc.submit(np.ones((64 * 2 + 1, ref.n_genes), np.float32))
        # rejected traffic is visible to the operator, not silent
        stats = svc.stats()
        assert stats["error"] == 3
        assert stats["requests"] == 3


# ---------------------------------------------------------------------------
# warm starts (ISSUE 12 satellite)
# ---------------------------------------------------------------------------

def test_warm_start_reuses_previous_usage_bit_identically():
    ref = _reference(beta=1.0)
    with ProjectionService(ref, max_batch=2, linger_ms=0.0,
                           warm_start=True) as svc:
        X = _query(ref, 48, 31)
        H1, meta1 = svc.project(X, tenant="repeat")
        assert meta1["warm_start"] is False
        H2, meta2 = svc.project(X, tenant="repeat")
        assert meta2["warm_start"] is True
        # the warm comparator is solo fit_h seeded with the previous H
        assert np.array_equal(H1, _solo(ref, X))
        assert np.array_equal(H2, _solo(ref, X, H_init=H1))
        # a different tenant stays cold
        H3, meta3 = svc.project(X, tenant="other")
        assert meta3["warm_start"] is False
        assert np.array_equal(H3, H1)
        # a DIFFERENT matrix of the same shape stays cold too: inheriting
        # another solve's usage would let its exact-zero entries (which
        # are absorbing under MU) pin genuinely-active components to
        # zero — warm starts are keyed by matrix content, not shape
        X_other = _query(ref, 48, 32)
        H4, meta4 = svc.project(X_other, tenant="repeat")
        assert meta4["warm_start"] is False
        assert np.array_equal(H4, _solo(ref, X_other))


def _iters_to_fixed_point(ref, X, H_init, target):
    """Smallest inner-iteration budget whose result equals ``target``
    bit-exactly — the deterministic 'how many iterations did this solve
    need' probe (fit_h's inner loop has no iteration output)."""
    for budget in (1, 2, 4, 8, 16, 32, 64, 128, 150):
        H = fit_h(X, ref.W, H_init=H_init, chunk_size=ref.chunk_size,
                  chunk_max_iter=budget, h_tol=ref.h_tol, beta=ref.beta)
        if np.array_equal(H, target):
            return budget
    return 10 ** 9


def test_warm_start_converges_in_fraction_of_iterations():
    """The satellite's convergence pin: a repeat projection from the
    previous usage needs a small fraction of the cold inner iterations."""
    ref = _reference(beta=2.0)
    X = _query(ref, 64, 41)
    H_cold = _solo(ref, X)
    cold_iters = _iters_to_fixed_point(ref, X, None, H_cold)
    H_warm_target = _solo(ref, X, H_init=H_cold)
    warm_iters = _iters_to_fixed_point(ref, X, H_cold, H_warm_target)
    assert cold_iters >= 8
    assert warm_iters * 4 <= cold_iters, (
        f"warm start took {warm_iters} iters vs cold {cold_iters}")


# ---------------------------------------------------------------------------
# resident reference
# ---------------------------------------------------------------------------

def test_serve_refuses_legacy_threefry():
    """The bit-identical-to-solo contract rests on the partitionable
    threefry's prefix property — a legacy-threefry pin must refuse at
    daemon start (the fit_h(k_pad) stance), never serve silently
    divergent projections."""
    import jax

    ref = _reference()
    jax.config.update("jax_threefry_partitionable", False)
    try:
        with pytest.raises(RuntimeError, match="threefry"):
            ProjectionService(ref, warm_start=False).start(warmup=False)
    finally:
        jax.config.update("jax_threefry_partitionable", True)


def test_reference_rejects_nonfinite_and_bad_shapes():
    W = np.ones((3, 10), np.float32)
    W[1, 2] = np.inf
    with pytest.raises(ReferenceError, match="nonfinite"):
        ResidentReference(W, beta=2.0, chunk_size=100, chunk_max_iter=10)
    with pytest.raises(ReferenceError, match="matrix"):
        ResidentReference(np.ones(5, np.float32), beta=2.0,
                          chunk_size=100, chunk_max_iter=10)


def test_reference_resident_products():
    import jax

    ref = _reference(beta=2.0).stage()
    assert isinstance(ref.Wd, jax.Array)
    assert np.array_equal(np.asarray(ref.WWT),
                          np.asarray(jax.jit(lambda w: w @ w.T)(ref.Wd)))
    ref_kl = _reference(beta=1.0).stage()
    assert ref_kl.WWT is None
    assert np.allclose(np.asarray(ref_kl.w_colsum), ref_kl.W.sum(axis=1))
    # stage() is idempotent
    assert ref.stage() is ref


# ---------------------------------------------------------------------------
# run-directory reference resolution + serve events (pipeline fixture)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serve_run(tmp_path_factory):
    """A consensus-complete mini run — the daemon's real input."""
    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    tmp = tmp_path_factory.mktemp("serve_run")
    rng = np.random.default_rng(5)
    usage = rng.dirichlet(np.ones(4) * 0.3, size=150)
    spectra = rng.gamma(0.3, 1.0, size=(4, 80)) * 40.0 / 80
    counts = rng.poisson(usage @ spectra * 250.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(150)],
                      columns=[f"g{j}" for j in range(80)])
    counts_fn = os.path.join(tmp, "counts.df.npz")
    save_df_to_npz(df, counts_fn)
    obj = cNMF(output_dir=str(tmp), name="srv")
    obj.prepare(counts_fn, components=[3], n_iter=6, seed=11,
                num_highvar_genes=60)
    obj.factorize()
    obj.combine()
    obj.consensus(k=3, density_threshold=2.0, show_clustering=False)
    return obj, os.path.join(str(tmp), "srv")


def test_load_reference_from_run_dir(serve_run):
    obj, run_dir = serve_run
    refs = find_references(run_dir)
    assert [r["k"] for r in refs] == [3]
    ref = load_reference(run_dir)
    assert ref.k == 3 and ref.n_genes == 60
    assert ref.genes is not None and len(ref.genes) == 60
    # explicit (k, dt) selection and clear failures
    assert load_reference(run_dir, k=3, density_threshold="2.0").k == 3
    with pytest.raises(ReferenceError, match="no consensus"):
        load_reference(run_dir, k=9)
    # ambiguity is loud: a second artifact forces an explicit pick
    second = refs[0]["path"].replace("dt_2_0", "dt_0_4")
    shutil.copyfile(refs[0]["path"], second)
    try:
        with pytest.raises(ReferenceError, match="multiple"):
            load_reference(run_dir)
        assert load_reference(run_dir,
                              density_threshold="0.4").k == 3
    finally:
        os.unlink(second)


def test_load_reference_from_shard_store(serve_run):
    """Atlas-scale reference: spectra in a digest-validated ShardStore."""
    obj, run_dir = serve_run
    from cnmf_torch_tpu.utils.shardstore import write_shard_store

    base = load_reference(run_dir)
    store_dir = os.path.join(run_dir, "cnmf_tmp", "ref.store")
    write_shard_store(store_dir, base.W,
                      var_names=[str(g) for g in base.genes])
    try:
        ref = load_reference(run_dir, spectra_path=store_dir)
        assert np.array_equal(ref.W, base.W)
        assert ref.genes == [str(g) for g in base.genes]
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)


def test_serve_matches_refit_usage_on_run_fixture(serve_run):
    """End-to-end acceptance pin: the daemon's batched projection is
    bit-identical to cNMF.refit_usage solo dispatch on the run's own
    consensus reference."""
    obj, run_dir = serve_run
    ref = load_reference(run_dir)
    rng = np.random.default_rng(17)
    X = rng.gamma(1.0, 1.0, size=(37, ref.n_genes)).astype(np.float32)
    with ProjectionService(ref, max_batch=4, linger_ms=0.0,
                           warm_start=False) as svc:
        H, _ = svc.project(X)
    spectra = pd.DataFrame(ref.W, columns=ref.genes)
    solo = obj.refit_usage(X, spectra)
    assert np.array_equal(H, np.asarray(solo))


def test_serve_events_schema_and_report(serve_run, tmp_path, monkeypatch):
    obj, run_dir = serve_run
    from cnmf_torch_tpu.utils.telemetry import (EventLog, read_events,
                                                render_report,
                                                summarize_events,
                                                validate_events_file)

    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    ev_dir = tmp_path / "evrun" / "cnmf_tmp"
    ev_dir.mkdir(parents=True)
    ev_path = str(ev_dir / "evrun.events.jsonl")
    events = EventLog(ev_path, manifest_extra={"run_name": "evrun"})

    ref = _reference(beta=2.0)
    with ProjectionService(ref, max_batch=4, linger_ms=80.0,
                           warm_start=False, events=events) as svc:
        Xa, Xb = _query(ref, 16, 61), _query(ref, 24, 62)
        outs = []
        ts = [threading.Thread(
            target=lambda X=X, t=t: outs.append(svc.project(X, tenant=t)))
            for X, t in ((Xa, "a"), (Xb, "b"))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        bad = Xa.copy()
        bad[0, 0] = np.nan
        with pytest.raises(PoisonError):
            svc.project(bad, tenant="evil")

    assert validate_events_file(ev_path) > 0
    evs = read_events(ev_path)
    kinds = {e["t"] for e in evs}
    assert {"manifest", "serve_request", "serve_batch"} <= kinds
    batch_sizes = [e["requests"] for e in evs if e["t"] == "serve_batch"]
    assert max(batch_sizes) > 1  # cross-request batching engaged
    s = summarize_events(evs)
    assert s["serving"]["requests"] == 3
    assert s["serving"]["by_status"] == {"ok": 2, "poison": 1}
    assert s["serving"]["multi_request_batches"] >= 1
    assert "p95" in s["serving"]["latency_ms"]
    report = render_report(str(tmp_path / "evrun"))
    assert "Serving (projection daemon)" in report
    assert "latency p50" in report


# ---------------------------------------------------------------------------
# the daemon (HTTP over unix socket / TCP)
# ---------------------------------------------------------------------------

def test_daemon_unix_socket_end_to_end(tmp_path):
    ref = _reference(beta=2.0)
    svc = ProjectionService(ref, max_batch=4, linger_ms=5.0,
                            warm_start=False)
    sock = str(tmp_path / "serve.sock")
    daemon = ServeDaemon(svc, socket_path=sock).start()
    try:
        cli = ServeClient(socket_path=sock)
        hz = cli.healthz()
        assert hz["ok"] and hz["reference"]["resident"]
        X = _query(ref, 21, 71)
        H_b64, meta = cli.project(X)
        assert np.array_equal(H_b64, _solo(ref, X))
        H_json, _ = cli.project(X, encoding="data")
        assert np.array_equal(H_json, H_b64)
        stats = cli.stats()
        assert stats["ok"] == 2
        assert cli.reference()["components"]
        # protocol errors are clear, not daemon crashes
        with pytest.raises(Exception, match="genes"):
            cli.project(np.ones((3, ref.n_genes + 2), np.float32))
        assert cli.shutdown()
    finally:
        daemon.close()
    assert not os.path.exists(sock)  # no orphaned socket


def test_daemon_tcp_loopback():
    ref = _reference(beta=2.0)
    svc = ProjectionService(ref, max_batch=2, linger_ms=0.0,
                            warm_start=False)
    daemon = ServeDaemon(svc, port=0).start()
    try:
        port = daemon.server.server_address[1]
        cli = ServeClient(port=port)
        X = _query(ref, 9, 81)
        H, _ = cli.project(X)
        assert np.array_equal(H, _solo(ref, X))
    finally:
        daemon.close()


def test_daemon_replaces_stale_socket(tmp_path):
    sock = str(tmp_path / "stale.sock")
    with open(sock, "w") as f:  # cnmf-lint: disable=artifact-nonatomic
        f.write("")
    ref = _reference()
    svc = ProjectionService(ref, linger_ms=0.0, warm_start=False)
    daemon = ServeDaemon(svc, socket_path=sock)
    daemon.start()
    try:
        assert ServeClient(socket_path=sock).healthz()["ok"]
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# sanitize: the serve hot path performs no implicit host transfers
# ---------------------------------------------------------------------------

def test_serve_program_no_implicit_transfers():
    """The batched projection dispatch — the daemon's per-request device
    work — compiles and executes entirely under
    ``jax.transfer_guard("disallow")`` with explicitly staged operands
    (the test_sanitize.py contract applied to the serving tier)."""
    import jax

    ref = _reference(beta=2.0).stage()
    X = _query(ref, 32, 91)
    H0 = np.zeros((2, 64, ref.k), np.float32)
    Xb = np.zeros((2, 64, ref.n_genes), np.float32)
    Xb[0, :32] = X
    Xd = jax.device_put(Xb)
    Hd = jax.device_put(H0)
    prog = batched_project()
    with jax.transfer_guard("disallow"):
        H, rel = prog(Xd, Hd, ref.Wd, ref.WWT, ref.w_colsum,
                      ref.h_tol_dev, beta=ref.beta,
                      max_iter=ref.chunk_max_iter,
                      l1=ref.l1_H, l2=0.0)
        out_h, out_rel = jax.device_get((H, rel))
    assert np.isfinite(out_h).all() and np.isfinite(out_rel).all()


def test_cli_serve_argument_validation(tmp_path):
    from cnmf_torch_tpu.cli import main as cli_main

    with pytest.raises(SystemExit):
        cli_main(["serve", str(tmp_path / "nope")])  # missing run dir
    run_dir = tmp_path / "cnmf_tmp"
    run_dir.mkdir()
    with pytest.raises(SystemExit):
        cli_main(["serve", str(tmp_path), "--socket", "/tmp/x.sock",
                  "--port", "1234"])  # mutually exclusive
    # an unprepared run dir is a one-line usage error, not a traceback
    with pytest.raises(SystemExit) as exc:
        cli_main(["serve", str(tmp_path)])
    assert exc.value.code == 2
