from .harmony import moe_correct_ridge, run_harmony
from .hvg import highvar_genes
from .kmeans import kmeans
from .metrics import local_density, pairwise_euclidean, silhouette_score
from .pca import pca
from .seurat_v3 import seurat_v3_hvg
from .nmf import (
    beta_divergence,
    beta_loss_to_float,
    fit_h,
    init_factors,
    nmf_fit_batch,
    nmf_fit_online,
    nndsvd_init,
    run_nmf,
)
from .recipe import SolverRecipe, resolve_recipe
from .sketch import ConsensusSketch, project_rows, resolve_consensus_sketch
from .ols import ols_all_cols
from .stats import column_mean_var, normalize_total, row_sums, scale_columns

__all__ = [
    "moe_correct_ridge",
    "run_harmony",
    "pca",
    "seurat_v3_hvg",
    "highvar_genes",
    "kmeans",
    "local_density",
    "pairwise_euclidean",
    "silhouette_score",
    "beta_divergence",
    "beta_loss_to_float",
    "fit_h",
    "init_factors",
    "nmf_fit_batch",
    "nmf_fit_online",
    "nndsvd_init",
    "run_nmf",
    "SolverRecipe",
    "resolve_recipe",
    "ConsensusSketch",
    "project_rows",
    "resolve_consensus_sketch",
    "ols_all_cols",
    "column_mean_var",
    "normalize_total",
    "row_sums",
    "scale_columns",
]
