import numpy as np

from cnmf_torch_tpu.ops import (
    kmeans,
    local_density,
    pairwise_euclidean,
    silhouette_score,
)


def _blobs(n_per=40, k=4, d=12, seed=0, spread=0.05):
    rng = np.random.default_rng(seed)
    centers = rng.random((k, d)) * 3
    X = np.concatenate([
        centers[i] + spread * rng.standard_normal((n_per, d)) for i in range(k)
    ]).astype(np.float32)
    labels = np.repeat(np.arange(k), n_per)
    return X, labels


def test_pairwise_euclidean_matches_sklearn():
    from sklearn.metrics import euclidean_distances

    X, _ = _blobs()
    D = pairwise_euclidean(X)
    np.testing.assert_allclose(D, euclidean_distances(X), rtol=1e-3, atol=2e-3)
    assert (np.diag(D) == 0).all()


def test_local_density_matches_reference_math():
    # the reference's argpartition construction (cnmf.py:1065-1070)
    X, _ = _blobs(n_per=30, k=3)
    n_neighbors = 9
    dens, D = local_density(X, n_neighbors)

    from sklearn.metrics import euclidean_distances

    topics_dist = euclidean_distances(X)
    order = np.argpartition(topics_dist, n_neighbors + 1)[:, : n_neighbors + 1]
    dist_to_nn = topics_dist[np.arange(topics_dist.shape[0])[:, None], order]
    expected = dist_to_nn.sum(1) / n_neighbors
    np.testing.assert_allclose(dens, expected, rtol=1e-3, atol=1e-4)


def test_kmeans_recovers_blobs():
    X, true = _blobs()
    labels, centers, inertia = kmeans(X, 4, n_init=10, seed=1)
    # perfect cluster recovery up to label permutation
    for c in range(4):
        members = labels[true == c]
        assert len(set(members.tolist())) == 1
    # determinism with the same seed
    labels2, _, inertia2 = kmeans(X, 4, n_init=10, seed=1)
    np.testing.assert_array_equal(labels, labels2)
    assert inertia == inertia2


def test_kmeans_inertia_close_to_sklearn():
    from sklearn.cluster import KMeans

    X, _ = _blobs(n_per=50, k=5, spread=0.4)
    _, _, inertia = kmeans(X, 5, n_init=10, seed=1)
    sk = KMeans(n_clusters=5, n_init=10, random_state=1).fit(X)
    assert inertia <= sk.inertia_ * 1.02


def test_silhouette_matches_sklearn():
    from sklearn.metrics import silhouette_score as sk_sil

    X, labels = _blobs(n_per=25, k=4, spread=0.5)
    ours = silhouette_score(X, labels, k=4)
    theirs = sk_sil(X, labels, metric="euclidean")
    np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-4)


def test_silhouette_with_kmeans_labels():
    from sklearn.metrics import silhouette_score as sk_sil

    X, _ = _blobs(n_per=30, k=3, spread=0.8)
    labels, _, _ = kmeans(X, 3, seed=1)
    ours = silhouette_score(X, labels, k=3)
    theirs = sk_sil(X, np.asarray(labels), metric="euclidean")
    np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-4)


def test_kmeans_masked_rows_have_zero_influence():
    """Masked k-means (the consensus density filter at static shape): rows
    with mask=0 must not affect seeding, centers, labels of kept rows, or
    inertia — swap the masked-out rows for different junk and everything
    about the kept rows is identical."""
    X, _ = _blobs(n_per=30, k=3, spread=0.5)
    rng = np.random.default_rng(7)
    junk_a = rng.normal(50.0, 5.0, size=(20, X.shape[1]))
    junk_b = rng.normal(-80.0, 1.0, size=(20, X.shape[1]))
    mask = np.concatenate([np.ones(X.shape[0]), np.zeros(20)]).astype(bool)

    la, ca, ia = kmeans(np.vstack([X, junk_a]), 3, seed=1, mask=mask)
    lb, cb, ib = kmeans(np.vstack([X, junk_b]), 3, seed=1, mask=mask)
    np.testing.assert_array_equal(la[mask], lb[mask])
    np.testing.assert_allclose(ca, cb, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ia, ib, rtol=1e-5)


def test_kmeans_masked_matches_subset_quality():
    """The masked clustering of the kept rows must be as good as clustering
    the subset directly (same data, same k): compare inertia."""
    X, _ = _blobs(n_per=30, k=3, spread=0.5)
    junk = np.full((15, X.shape[1]), 99.0)
    mask = np.concatenate([np.ones(X.shape[0]), np.zeros(15)]).astype(bool)
    _, _, inertia_masked = kmeans(np.vstack([X, junk]), 3, seed=1, mask=mask)
    _, _, inertia_subset = kmeans(X, 3, seed=1)
    assert inertia_masked <= inertia_subset * 1.05


def test_kmeans_packed_matches_per_k_program():
    """The packed K-selection kmeans (K_max/R_max-padded, traced k and
    n_rows) must reproduce the per-K unmasked program's labels exactly:
    the threefry prefix properties make the kmeans++ streams identical,
    and zero-padded rows/clusters contribute exact zeros everywhere."""
    import pytest

    for k, seed in [(3, 1), (5, 1), (3, 7)]:
        X, _ = _blobs(n_per=25, k=k, spread=0.3, seed=seed)
        R = X.shape[0]
        R_max, K_max = R + 37, 8
        Xp = np.zeros((R_max, X.shape[1]), np.float32)
        Xp[:R] = X
        l_ref, c_ref, i_ref = kmeans(X, k, seed=seed)
        l_pk, c_pk, i_pk = kmeans(Xp, k, seed=seed, n_rows=R, k_pad=K_max)
        np.testing.assert_array_equal(l_ref, l_pk[:R])
        np.testing.assert_allclose(c_ref, c_pk[:k], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(i_ref, i_pk, rtol=1e-5)
        # padded clusters never receive members and keep zero centers
        assert (l_pk[:R] < k).all()
        np.testing.assert_array_equal(c_pk[k:], 0.0)

    # k == K_max and R == R_max degenerate to the unpadded clustering
    X, _ = _blobs(n_per=20, k=4, spread=0.3)
    l_ref, _, _ = kmeans(X, 4, seed=1)
    l_pk, _, _ = kmeans(X.astype(np.float32), 4, seed=1,
                        n_rows=X.shape[0], k_pad=4)
    np.testing.assert_array_equal(l_ref, l_pk)

    with pytest.raises(ValueError):
        kmeans(X, 4, k_pad=8)  # n_rows missing
    with pytest.raises(ValueError):
        kmeans(X, 4, n_rows=10, k_pad=2)  # k > k_pad
    with pytest.raises(ValueError):
        kmeans(X, 4, n_rows=10, k_pad=8, mask=np.ones(X.shape[0]))


def test_silhouette_packed_matches_per_k_program():
    from cnmf_torch_tpu.ops import silhouette_score

    X, labels = _blobs(n_per=30, k=4, spread=0.4)
    R = X.shape[0]
    want = silhouette_score(X, labels, 4)
    Xp = np.zeros((R + 50, X.shape[1]), np.float32)
    Xp[:R] = X
    lp = np.zeros((R + 50,), np.int32)
    lp[:R] = labels
    got = silhouette_score(Xp, lp, n_rows=R, k_pad=9)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # padded rows' (arbitrary) labels must not influence the score
    lp[R:] = 3
    got2 = silhouette_score(Xp, lp, n_rows=R, k_pad=9)
    np.testing.assert_allclose(got2, want, rtol=1e-5)
