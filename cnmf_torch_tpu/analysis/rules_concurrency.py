"""Lock-discipline rule: module-level mutable state mutates under a lock.

The bug class this guards is real history: PR 1 found ``StageTimer``
rows torn by k-selection's concurrent stats threads, PR 3 found the
``trace()`` profiler latch racing the same way. The pattern both fixes
converged on — a module-level ``threading.Lock`` beside the state, every
mutation inside ``with lock:`` — is what this rule enforces going
forward.

``lock-discipline`` fires when a function mutates a module-level mutable
binding without a module-level lock held:

  * container mutation — ``NAME[k] = v``, ``NAME.append/update/pop/...``
    on a module-level dict/list/set;
  * rebinding — ``global NAME; NAME = ...`` (or augmented assignment) of
    any module-level binding (the check-then-act latch shape).

A mutation is clean when any enclosing ``with`` holds a module-level
``threading.Lock``/``RLock``. Instance state (``self._x`` under
``self._lock``) is out of scope — the rule targets process-wide state,
where "which thread gets there first" is the hazard. Genuinely
single-threaded latches (e.g. one-time CLI init) should carry an inline
``# cnmf-lint: disable=lock-discipline`` with a comment saying why.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding

MUTATORS = {"append", "add", "update", "pop", "clear", "extend", "remove",
            "discard", "setdefault", "popitem", "insert"}
LOCK_FACTORIES = {"threading.Lock", "threading.RLock",
                  "threading.Condition"}

HINT = ("guard the mutation with `with <module lock>:` (add a module-"
        "level threading.Lock next to the state), or suppress with a "
        "justification if provably single-threaded")


def _module_bindings(ctx: FileContext):
    """(mutable container names, lock names, all module-level names)."""
    mutable, locks, all_names = set(), set(), set()
    for stmt in ctx.tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name):
            targets = [stmt.target]
            value = stmt.value
        else:
            continue
        for t in targets:
            all_names.add(t.id)
            if isinstance(value, (ast.Dict, ast.List, ast.Set)):
                mutable.add(t.id)
            elif isinstance(value, ast.Call):
                resolved = ctx.resolve_call(value) or ""
                leaf = resolved.split(".")[-1]
                if resolved in LOCK_FACTORIES:
                    locks.add(t.id)
                elif leaf in ("dict", "list", "set", "OrderedDict",
                              "defaultdict", "deque", "Counter"):
                    mutable.add(t.id)
    return mutable, locks, all_names


def _shallow_walk(fn: ast.AST):
    """Walk ``fn``'s own body WITHOUT descending into nested function/
    lambda scopes — their bindings and mutations are analyzed on their
    own pass (``ast.walk`` would leak a nested ``x = ...`` into the outer
    function's local-shadow set, masking real findings)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _local_names(fn: ast.AST, global_decls: set[str]) -> set[str]:
    """Names bound locally in ``fn``'s own scope (params/assignments/
    for/with/comp targets) and NOT declared global — those shadow module
    state."""
    out = set(p.arg for p in (fn.args.posonlyargs + fn.args.args
                              + fn.args.kwonlyargs))
    if fn.args.vararg:
        out.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        out.add(fn.args.kwarg.arg)
    for node in _shallow_walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    # Store context only: the base of `_state[k] = v` is a
                    # Load of module state, not a local binding
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, ast.Store):
                        out.add(n.id)
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            out.add(node.target.id)
        elif isinstance(node, ast.NamedExpr) \
                and isinstance(node.target, ast.Name):
            out.add(node.target.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for n in ast.walk(item.optional_vars):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
        elif isinstance(node, ast.comprehension):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out - global_decls


def _under_lock(ctx: FileContext, node: ast.AST, locks: set[str]) -> bool:
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id in locks:
                    return True
    return False


def check(ctx: FileContext):
    findings: list[Finding] = []
    mutable, locks, module_names = _module_bindings(ctx)
    if not module_names:
        return findings

    funcs = [n for n in ast.walk(ctx.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        global_decls: set[str] = set()
        for node in _shallow_walk(fn):
            if isinstance(node, ast.Global):
                global_decls.update(node.names)
        local = _local_names(fn, global_decls)

        for node in _shallow_walk(fn):
            target_name, what = None, None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in mutable \
                            and t.value.id not in local:
                        target_name = t.value.id
                        what = "item assignment on module-level container"
                    elif isinstance(t, ast.Name) \
                            and t.id in global_decls \
                            and t.id in module_names:
                        target_name = t.id
                        what = "rebind of module-level binding"
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATORS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in mutable \
                    and node.func.value.id not in local:
                target_name = node.func.value.id
                what = f".{node.func.attr}() on module-level container"
            if target_name and not _under_lock(ctx, node, locks):
                lock_note = ("no module-level lock exists in this module"
                             if not locks else
                             "outside every module-level lock")
                findings.append(ctx.finding(
                    node, "lock-discipline",
                    f"{what} `{target_name}` {lock_note} — concurrent "
                    "callers race (the StageTimer/trace() bug class)",
                    HINT))
    return findings
