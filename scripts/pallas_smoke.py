"""Tier-1 Pallas parity smoke (ISSUE 16, wired in verify_tier1.sh).

Runs a mini ELL β=1 replicate sweep with the fused Pallas kernels off
(knob unset / ``0``) and forced on (``1`` — interpret mode on the CPU
gate) and asserts:

  * default-off byte-identity: the knob-unset and ``CNMF_TPU_PALLAS=0``
    sweeps resolve to the SAME cached ``_sweep_program`` entry (the
    omit-on-default kwarg convention), and ``nmf_fit_batch`` lowers to
    byte-identical text with the default vs an explicit
    ``use_pallas=False`` — a build with the kernel layer dormant is the
    build without it;
  * the forced-on lowering DIFFERS from the default (engagement is
    detectable even in interpret mode, where the lowered text contains
    no "pallas" strings);
  * objective parity: the Pallas sweep lands within the accel band of
    the jnp ELL oracle (the kernels change accumulation order — f32
    tolerance, not bit equality);
  * the engaged kernel is visible end-to-end: sweep telemetry payloads
    carry the ``kernel`` label (``ell-jnp`` / ``ell-pallas``) and the
    emitted dispatch + replicates events validate against the schema;
  * unknown knob words fail loudly, naming the knob.

Exit 0 on success; any assertion or schema failure exits nonzero and
fails the gate.
"""

import os
import sys
import tempfile

# package: sys.path[0] is scripts/, the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["CNMF_TPU_TELEMETRY"] = "1"
# the mini fixture is 92% sparse but too skinny for the auto width
# guard (8*width > g) — force the ELL lane; the smoke is ABOUT it
os.environ["CNMF_TPU_SPARSE_BETA"] = "1"
os.environ.pop("CNMF_TPU_PALLAS", None)

import numpy as np  # noqa: E402


def fixture(n=120, g=96, k=4, seed=3):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * 6.0 * 0.04).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    return X


def main() -> int:
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.nmf import nmf_fit_batch
    from cnmf_torch_tpu.ops.pallas import PALLAS_ENV, resolve_pallas
    from cnmf_torch_tpu.ops.sparse import csr_to_ell, ell_device_put
    from cnmf_torch_tpu.parallel import replicate_sweep
    from cnmf_torch_tpu.parallel.replicates import _sweep_program
    from cnmf_torch_tpu.utils.telemetry import (EventLog, replicate_records,
                                                validate_events_file)

    import scipy.sparse as sp

    X = fixture()
    density = float((X > 0).mean())
    assert density < 0.10, density  # the lane's win case, not a dense run
    Xcsr = sp.csr_matrix(X)  # the sweep builds ELL from sparse input only
    seeds = [1, 2, 3]
    tmp = tempfile.mkdtemp(prefix="pallas_smoke_")
    log = EventLog(os.path.join(tmp, "smoke.events.jsonl"))

    payloads = {}

    def run(label, knob):
        if knob is None:
            os.environ.pop(PALLAS_ENV, None)
        else:
            os.environ[PALLAS_ENV] = knob
        sink_box = []
        _, _, errs = replicate_sweep(Xcsr, seeds, 4, mode="batch",
                                     beta_loss="kullback-leibler",
                                     telemetry_sink=sink_box.append)
        assert np.isfinite(errs).all(), (label, errs)
        (pay,) = sink_box
        log.emit("dispatch", decision="pallas_kernel",
                 context={"kernel": pay.get("kernel"),
                          PALLAS_ENV: knob if knob is not None else ""})
        log.emit("replicates", k=pay["k"], beta=pay["beta"],
                 mode=pay["mode"], cap=int(pay["cap"]),
                 cadence=pay["cadence"], kernel=pay.get("kernel"),
                 records=replicate_records(pay))
        payloads[label] = (np.asarray(errs, np.float64), pay.get("kernel"))
        print(f"[pallas-smoke] {label:8s} kernel={pay.get('kernel'):10s} "
              f"errs={np.round(errs, 2)}")

    _sweep_program.cache_clear()
    run("unset", None)
    info_unset = _sweep_program.cache_info()
    run("off", "0")
    info_off = _sweep_program.cache_info()
    run("on", "1")

    # knob unset and knob=0 resolve to the SAME cached program entry
    # (the omit-on-default kwarg convention): byte-identical dispatch
    assert info_unset.misses == info_off.misses == 1, (info_unset, info_off)
    assert info_off.hits > info_unset.hits, (info_unset, info_off)
    np.testing.assert_array_equal(payloads["unset"][0], payloads["off"][0])

    # the engaged kernel is visible in the sweep telemetry payload
    assert payloads["unset"][1] == "ell-jnp", payloads["unset"][1]
    assert payloads["off"][1] == "ell-jnp", payloads["off"][1]
    assert payloads["on"][1] == "ell-pallas", payloads["on"][1]

    # objective parity: the fused kernels solve the same problem to the
    # same place (accumulation order differs — accel band, not bits)
    TOL = 2e-2
    rel = np.abs(payloads["on"][0] - payloads["unset"][0]) \
        / payloads["unset"][0]
    assert (rel < TOL).all(), (payloads["on"][0], payloads["unset"][0])
    print(f"[pallas-smoke] objective parity max rel {rel.max():.2e} "
          f"(band {TOL})")

    # lowering identity: default == explicit use_pallas=False,
    # and forced-on differs (engagement detectable in interpret mode,
    # where the lowered text contains no 'pallas' strings)
    Xe = ell_device_put(csr_to_ell(X))
    rng = np.random.default_rng(0)
    H0 = jnp.asarray(rng.random((X.shape[0], 4), np.float32) + 0.1)
    W0 = jnp.asarray(rng.random((4, X.shape[1]), np.float32) + 0.1)
    low = {
        kw if kw is not None else "default": nmf_fit_batch.lower(
            Xe, H0, W0, beta=1.0, max_iter=8,
            **({} if kw is None else {"use_pallas": kw})).as_text()
        for kw in (None, False, True)
    }
    assert low["default"] == low[False], "use_pallas=False must be the default"
    assert low["default"] != low[True], "forced-on must change the program"
    print(f"[pallas-smoke] lowering: default==off "
          f"({len(low['default'])} chars), on differs "
          f"({len(low[True])} chars)")

    # unknown knob words fail loudly, naming the knob
    os.environ[PALLAS_ENV] = "bogus"
    try:
        resolve_pallas()
    except ValueError as e:
        assert PALLAS_ENV in str(e), e
    else:
        raise AssertionError("bad knob word must raise")
    finally:
        os.environ.pop(PALLAS_ENV, None)

    # schema-valid stream: manifest + 3x(dispatch + replicates)
    n_events = validate_events_file(log.path)
    assert n_events >= 7, n_events
    print(f"[pallas-smoke] OK: {n_events} schema-valid events, kernels "
          f"{sorted({v[1] for v in payloads.values()})}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
