"""Headline benchmarks against BASELINE.md.

Three tiers, one JSON line (the driver's contract):

1. **North star** (BASELINE.json config 2): PBMC-10k-shaped
   factorize+combine+consensus, K=5..13 x n_iter=100, batch_size=5000 —
   the reference's primary metric ("PBMC-10k factorize+consensus
   wall-clock"). The reference publishes no number for it; `vs_baseline`
   extrapolates its only anchor (PBMC3k: 120 online-MU runs of 2,700x2,000
   in ~240 s on 4 CPU workers => 2.0 s/run) to this workload's 900 runs of
   10,000x2,000 (rows scale the online solver linearly: 2.0 x 10000/2700
   x 900 = 6,667 s), consensus excluded (conservative). Per-stage seconds
   come from the pipeline's own StageTimer ledger; compile overhead is
   reported separately from the warm factorize rate.
2. **PBMC3k anchor** (config 1 shape): the directly comparable 120-run
   sweep vs the published ~240 s.
3. **KL beta-loss** (config 3): the beta=1 kernel at K=9 x 100 replicates
   on the same matrix.

CAVEAT (stated in the output): counts are synthetic Poisson draws from a
low-rank GEP model with the PBMC shapes — the reference datasets are not
redistributable in this environment — and the reference comparator for the
north star is an extrapolation, not a measurement.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

import numpy as np

PBMC3K_BASELINE_SECONDS = 240.0   # 4 min, 4 CPU workers, 120 runs
NORTH_STAR_BASELINE_SECONDS = PBMC3K_BASELINE_SECONDS / 120 * (10000 / 2700) * 900


def synthetic_pbmc_like(n=2700, g=2000, k_true=12, seed=0, scale=400.0):
    """Structured counts with PBMC-like shape: sparse-ish Poisson draws from
    a low-rank GEP model, variance-scaled the way prepare() feeds the
    solver (unit-variance genes, no centering)."""
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k_true) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k_true, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * scale).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    std = X.std(axis=0, ddof=1)
    std[std == 0] = 1.0
    return X / std


def synthetic_counts_df(n, g, k_true=14, seed=3):
    import pandas as pd

    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k_true) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k_true, g)) * 40.0 / g
    counts = rng.poisson(usage @ spectra * 400.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    return pd.DataFrame(counts, index=[f"c{i}" for i in range(n)],
                        columns=[f"g{j}" for j in range(g)])


def read_stage_seconds(timings_tsv):
    stages = {}
    with open(timings_tsv) as f:
        next(f)
        for line in f:
            name, secs = line.split("\t")[:2]
            stages[name] = stages.get(name, 0.0) + float(secs)
    return stages


def bench_north_star():
    """PBMC-10k-shaped e2e: prepare -> factorize(K=5..13 x 100) -> combine
    -> consensus(k=9). Returns the headline seconds + stage breakdown."""
    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    workdir = tempfile.mkdtemp(prefix="bench_ns_")
    counts_fn = os.path.join(workdir, "counts.df.npz")
    save_df_to_npz(synthetic_counts_df(10000, 5000), counts_fn)

    obj = cNMF(output_dir=workdir, name="ns")
    obj.prepare(counts_fn, components=list(range(5, 14)), n_iter=100,
                seed=14, num_highvar_genes=2000, batch_size=5000)

    t0 = time.perf_counter()
    obj.factorize()
    factorize_cold = time.perf_counter() - t0

    t0 = time.perf_counter()
    obj.combine()
    combine_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    try:
        obj.consensus(k=9, density_threshold=0.5, show_clustering=False)
    except RuntimeError:
        # synthetic replicate spectra can be more dispersed than real PBMC
        # ones; keep the full consensus pipeline in the measurement
        obj.consensus(k=9, density_threshold=2.0, show_clustering=False)
    consensus_s = time.perf_counter() - t0

    # warm factorize: every (shape, config) program is now compiled, so this
    # is the steady-state solver rate; cold - warm ~= XLA compile overhead
    t0 = time.perf_counter()
    obj.factorize()
    factorize_warm = time.perf_counter() - t0

    stages = read_stage_seconds(
        os.path.join(workdir, "ns", "cnmf_tmp", "ns.timings.tsv"))
    shutil.rmtree(workdir)
    e2e = factorize_cold + combine_s + consensus_s
    return {
        "e2e_seconds": round(e2e, 3),
        "factorize_cold_seconds": round(factorize_cold, 3),
        "factorize_warm_seconds": round(factorize_warm, 3),
        "compile_overhead_seconds": round(factorize_cold - factorize_warm, 3),
        "combine_seconds": round(combine_s, 3),
        "consensus_seconds": round(consensus_s, 3),
        "prepare_seconds": round(stages.get("prepare", 0.0), 3),
    }


def bench_pbmc3k_anchor():
    import jax.numpy as jnp

    from cnmf_torch_tpu.parallel import default_mesh, replicate_sweep

    X = jnp.asarray(synthetic_pbmc_like())
    mesh = default_mesh()
    master = np.random.RandomState(14)
    ks = [5, 6, 7, 8, 9, 10]
    seeds_per_k = {k: master.randint(1, 2 ** 31 - 1, size=20).tolist()
                   for k in ks}
    for k in ks:  # compile
        replicate_sweep(X, [1] * 20, k, mode="online", online_chunk_size=5000,
                        online_chunk_max_iter=1000, mesh=mesh)
    t0 = time.perf_counter()
    pending = [(k,) + replicate_sweep(
        X, seeds_per_k[k], k, mode="online", online_chunk_size=5000,
        online_chunk_max_iter=1000, mesh=mesh, fetch=False)[::2]
        for k in ks]
    total_err = 0.0
    for k, spectra_d, errs_d in pending:
        assert np.asarray(spectra_d).shape == (20, k, 2000)
        total_err += float(np.sum(np.asarray(errs_d)))
    elapsed = time.perf_counter() - t0
    assert np.isfinite(total_err)
    return round(elapsed, 3)


def bench_kl(X_dev):
    from cnmf_torch_tpu.parallel import replicate_sweep

    seeds = np.random.RandomState(7).randint(1, 2 ** 31 - 1, size=100).tolist()
    replicate_sweep(X_dev, seeds[:4], 9, beta_loss="kullback-leibler",
                    mode="online", online_chunk_size=5000)  # compile
    t0 = time.perf_counter()
    _, _, errs = replicate_sweep(X_dev, seeds, 9,
                                 beta_loss="kullback-leibler", mode="online",
                                 online_chunk_size=5000)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(errs).all()
    return round(elapsed, 3)


def main():
    import jax.numpy as jnp

    ns = bench_north_star()
    anchor_s = bench_pbmc3k_anchor()
    kl_s = bench_kl(jnp.asarray(synthetic_pbmc_like(n=10000, seed=5)))

    print(json.dumps({
        "metric": "pbmc10k_factorize_consensus_e2e",
        "value": ns["e2e_seconds"],
        "unit": ("seconds (factorize K=5..13 x 100 online-MU runs of "
                 "10000x2000 incl. compiles, + combine + consensus k=9)"),
        "vs_baseline": round(NORTH_STAR_BASELINE_SECONDS / ns["e2e_seconds"], 2),
        "stages": ns,
        "pbmc3k_anchor": {
            "seconds": anchor_s,
            "vs_baseline": round(PBMC3K_BASELINE_SECONDS / anchor_s, 2),
            "baseline": "ref tutorial: ~240 s, 120 runs, 4 CPU workers",
        },
        "kl_factorize_k9_x100_seconds": kl_s,
        "caveats": ("synthetic PBMC-shaped counts (real datasets not "
                    "redistributable here); north-star baseline is the "
                    "reference's PBMC3k 2.0 s/run anchor extrapolated "
                    "linearly in rows and runs (6667 s), consensus excluded"),
    }))


if __name__ == "__main__":
    main()
