"""Multi-chip sharding tests on the virtual 8-device CPU mesh — the coverage
the reference lacks entirely (SURVEY.md §4): replicate-axis sharding must not
change results, and the row-sharded solver's psum'd statistics must agree
with the single-device kernel."""

import jax
import numpy as np
import pytest
import scipy.sparse as sp
from jax.sharding import Mesh

from cnmf_torch_tpu.ops.nmf import beta_divergence, fit_h, run_nmf
from cnmf_torch_tpu.parallel import (
    default_mesh,
    fit_h_rowsharded,
    nmf_fit_rowsharded,
    replicate_sweep,
    worker_filter,
)


@pytest.fixture(scope="module")
def mesh():
    m = default_mesh()
    if m is None:
        pytest.skip("needs >1 device (virtual CPU mesh)")
    return m


def _lowrank(n=96, g=64, k=4, seed=0, noise=0.01):
    rng = np.random.default_rng(seed)
    H = rng.gamma(1.0, 1.0, size=(n, k)).astype(np.float32)
    W = rng.gamma(1.0, 1.0, size=(k, g)).astype(np.float32)
    X = H @ W + noise * rng.random((n, g)).astype(np.float32)
    return X


def test_worker_filter_partition():
    tasks = list(range(10))
    shards = [list(worker_filter(tasks, i, 3)) for i in range(3)]
    assert shards[0] == [0, 3, 6, 9]
    assert shards[1] == [1, 4, 7]
    assert shards[2] == [2, 5, 8]
    assert sorted(sum(shards, [])) == tasks


def test_replicate_sweep_basic():
    X = _lowrank()
    seeds = [11, 22, 33]
    spectra, usages, errs = replicate_sweep(
        X, seeds, 4, mode="batch", batch_max_iter=100, mesh=None,
        return_usages=True)
    assert spectra.shape == (3, 4, 64)
    assert usages.shape == (3, 96, 4)
    assert (spectra >= 0).all() and np.isfinite(errs).all()
    # distinct seeds give distinct replicates; all reconstruct well
    assert not np.allclose(spectra[0], spectra[1])
    denom = (X ** 2).sum() / 2
    assert (errs / denom < 0.05).all()


def test_replicate_sweep_matches_run_nmf():
    """The batched sweep and the scalar nmf-torch-contract entry point must
    agree replicate-by-replicate (same seeds, same kernels)."""
    X = _lowrank(n=64, g=48, k=3)
    seeds = [5, 17]
    spectra, _, errs = replicate_sweep(X, seeds, 3, mode="batch",
                                       batch_max_iter=80, mesh=None)
    for r, s in enumerate(seeds):
        _, W, err = run_nmf(X, 3, mode="batch", batch_max_iter=80,
                            random_state=s)
        np.testing.assert_allclose(spectra[r], W, rtol=1e-4, atol=1e-5)
        assert abs(errs[r] - err) / err < 1e-3


def test_replicate_sweep_sharded_matches_unsharded(mesh):
    """Sharding the replicate axis over the mesh must be semantics-free,
    including the R % n_devices != 0 padding path."""
    X = _lowrank(n=80, g=50, k=3, seed=3)
    seeds = [101, 202, 303, 404, 505]  # 5 replicates on an 8-device mesh
    ref, _, ref_err = replicate_sweep(X, seeds, 3, mode="batch",
                                      batch_max_iter=60, mesh=None)
    got, _, got_err = replicate_sweep(X, seeds, 3, mode="batch",
                                      batch_max_iter=60, mesh=mesh)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_err, ref_err, rtol=1e-3)


def test_replicate_sweep_online_sharded(mesh):
    X = _lowrank(n=100, g=40, k=3, seed=9)
    seeds = list(range(1, 9))
    spectra, _, errs = replicate_sweep(
        X, seeds, 3, mode="online", online_chunk_size=32,
        online_chunk_max_iter=100, mesh=mesh)
    assert spectra.shape == (8, 3, 40)
    denom = (X ** 2).sum() / 2
    assert (errs / denom < 0.1).all()


@pytest.mark.parametrize("beta_loss",
                         ["frobenius", "kullback-leibler", "itakura-saito"])
def test_rowsharded_nmf_converges(mesh, beta_loss):
    X = _lowrank(n=100, g=48, k=4, seed=5) + 0.01
    # IS takes gamma=0.5-damped steps (mu_gamma) — give it more passes
    n_passes = 80 if beta_loss == "itakura-saito" else 30
    H, W, err = nmf_fit_rowsharded(X, 4, mesh, beta_loss=beta_loss,
                                   seed=42, n_passes=n_passes)
    assert H.shape == (100, 4) and W.shape == (4, 48)
    assert (H >= 0).all() and (W >= 0).all()
    if beta_loss == "frobenius":
        denom = (X ** 2).sum() / 2
        assert err / denom < 0.05
    else:
        # beta!=2 err should be far below the trivial (flat W) objective
        beta = {"kullback-leibler": 1.0, "itakura-saito": 0.0}[beta_loss]
        flat = float(beta_divergence(
            np.asarray(X), np.full((100, 4), X.mean() / 4, np.float32),
            np.ones((4, 48), np.float32), beta=beta))
        assert err < 0.1 * flat


def test_rowsharded_nmf_matches_seq_statistics(mesh):
    """Row-sharded vs single-device solve from the same init: the per-shard
    H blocks hit their h_tol stopping criterion at different iterations, so
    element-wise W parity is not expected (nonconvex trajectories diverge) —
    but both must converge to optima of equal quality."""
    X = _lowrank(n=64, g=32, k=3, seed=7)
    _, _, err1 = nmf_fit_rowsharded(X, 3, mesh, seed=11, n_passes=25)
    _, _, err2 = nmf_fit_rowsharded(
        X, 3, Mesh(np.asarray(jax.devices()[:1]), ("cells",)),
        seed=11, n_passes=25)
    assert abs(err1 - err2) / max(err2, 1e-9) < 2e-2


def test_fit_h_rowsharded_matches_single(mesh):
    X = _lowrank(n=72, g=40, k=3, seed=13)
    rng = np.random.default_rng(0)
    W = rng.gamma(1.0, 1.0, size=(3, 40)).astype(np.float32)
    H_ref = fit_h(X, W, chunk_size=72, h_tol=1e-4, chunk_max_iter=500)
    H_sh = fit_h_rowsharded(X, W, mesh, h_tol=1e-4, chunk_max_iter=500)
    # both solve the same convex subproblem to tolerance
    r_ref = np.linalg.norm(X - H_ref @ W)
    r_sh = np.linalg.norm(X - H_sh @ W)
    assert abs(r_ref - r_sh) / r_ref < 1e-2


@pytest.mark.parametrize("beta", [2.0, 1.0, 0.0])
def test_refit_w_matches_transpose_trick(beta):
    """refit_w_rowsharded solves the same convex W-subproblem the
    reference's transpose trick does (refit_usage(X.T, usage.T).T,
    cnmf.py:979-994) — equal-quality optima, no transposed buffers."""
    from cnmf_torch_tpu.parallel.rowshard import refit_w_rowsharded

    X = _lowrank(n=120, g=40, k=3, seed=31) + 0.01
    rng = np.random.default_rng(5)
    H = rng.gamma(1.0, 1.0, size=(120, 3)).astype(np.float32)
    W_direct = refit_w_rowsharded(X, H, beta=beta, h_tol=1e-4, max_iter=500,
                                  row_block=50)
    W_transpose = fit_h(X.T, H.T, chunk_size=40, h_tol=1e-4,
                        chunk_max_iter=500, beta=beta).T
    assert W_direct.shape == (3, 40) and (W_direct >= 0).all()
    r_direct = float(beta_divergence(X, H, W_direct, beta=beta))
    r_transpose = float(beta_divergence(X, H, W_transpose, beta=beta))
    assert abs(r_direct - r_transpose) / max(r_transpose, 1e-9) < 2e-2


def test_refit_w_sparse_stats_path():
    """beta=2 path must consume CSR via sparse matmuls (k-sized statistics),
    never a dense X."""
    from cnmf_torch_tpu.parallel.rowshard import refit_w_rowsharded

    X = sp.random(200, 30, density=0.2, random_state=7, format="csr",
                  dtype=np.float64)
    H = np.abs(np.random.default_rng(8).normal(size=(200, 4))).astype(
        np.float32)
    orig = sp.csr_matrix.toarray
    called = []
    sp.csr_matrix.toarray = lambda self, *a, **kw: (
        called.append(self.shape) or orig(self, *a, **kw))
    try:
        W = refit_w_rowsharded(X, H, beta=2.0)
    finally:
        sp.csr_matrix.toarray = orig
    assert W.shape == (4, 30) and not called


def test_fit_h_rowsharded_sparse_input(mesh):
    X = sp.random(50, 30, density=0.3, random_state=1, format="csr",
                  dtype=np.float64)
    W = np.abs(np.random.default_rng(2).normal(size=(2, 30))).astype(np.float32)
    H = fit_h_rowsharded(X, W, mesh)
    assert H.shape == (50, 2)
    assert (H >= 0).all()


# ---------------------------------------------------------------------------
# out-of-core streaming (atlas path, BASELINE config 5)
# ---------------------------------------------------------------------------

def test_stream_rows_to_mesh_matches_dense(mesh):
    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh

    X = sp.random(101, 24, density=0.2, random_state=3, format="csr")
    Xd, pad = stream_rows_to_mesh(X, mesh, mesh.axis_names[0])
    n_dev = int(np.prod(mesh.devices.shape))
    assert Xd.shape[0] % n_dev == 0 and pad == Xd.shape[0] - 101
    got = np.asarray(Xd)
    want = np.vstack([X.toarray(), np.zeros((pad, 24))]).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_rowsharded_never_densifies_full_matrix(mesh, monkeypatch):
    """The no-host-dense guarantee: a row-sharded solve on CSR input never
    materializes the FULL dense matrix on host. On the accelerator (csr)
    transport the CSR buffers ship to the devices and densify there
    (streaming._csr_densify) — no toarray at all; forced here because the
    CPU backend auto-selects the host slab-densify transport (covered by
    test_streaming.py's slab-bound test)."""
    from cnmf_torch_tpu.parallel.rowshard import prepare_rowsharded

    monkeypatch.setenv("CNMF_TPU_STREAM_TRANSPORT", "csr")
    n, g = 160, 32
    X = sp.random(n, g, density=0.15, random_state=9, format="csr")

    seen = []
    orig = sp.csr_matrix.toarray

    def spy(self, *a, **kw):
        seen.append(self.shape)
        return orig(self, *a, **kw)

    monkeypatch.setattr(sp.csr_matrix, "toarray", spy)
    Xd, n_orig = prepare_rowsharded(X, mesh)
    H, W, err = nmf_fit_rowsharded(Xd, 3, mesh, seed=5, n_passes=10,
                                   n_orig=n_orig)
    assert n_orig == n and H.shape == (n, 3) and np.isfinite(err)
    assert not seen, f"host densify happened: {seen}"
    # and the staged array is exactly the padded dense matrix
    np.testing.assert_allclose(
        np.asarray(Xd)[:n], X.toarray().astype(np.float32), atol=0)


def test_prepared_device_array_reused_across_ks(mesh):
    from cnmf_torch_tpu.parallel.rowshard import prepare_rowsharded

    X = _lowrank(n=80, g=40, k=4, seed=21)
    Xd, n_orig = prepare_rowsharded(X, mesh)
    for k in (3, 4):
        H, W, err = nmf_fit_rowsharded(Xd, k, mesh, seed=k, n_passes=15,
                                       n_orig=n_orig)
        assert H.shape == (80, k) and W.shape == (k, 40)
        assert np.isfinite(err)


@pytest.mark.parametrize("beta_loss", ["frobenius", "kullback-leibler"])
def test_pipeline_rowsharded_factorize(tmp_path, mesh, monkeypatch,
                                       beta_loss):
    """Pipeline-level atlas path: factorize -> combine -> consensus runs
    ENTIRELY row-sharded on sparse counts (threshold below the cell count):
    same artifact contract, and no code path ever densifies more than a
    shard-sized row block on host — including the three consensus refits
    (VERDICT r2: the reference's fit_H/refit densify walls,
    cnmf.py:329-330, 979-994). The KL variant additionally drives the
    STAGED beta != 2 spectra refit through the pipeline's own
    refit_spectra wiring (rowshard.refit_w_rowsharded with the default
    cells mesh)."""
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import load_df_from_npz
    from cnmf_torch_tpu.utils.anndata_lite import AnnDataLite, write_h5ad

    rng = np.random.default_rng(33)
    n, g, ktrue = 300, 220, 4
    usage = rng.dirichlet(np.ones(ktrue) * 0.4, size=n)
    spectra = rng.gamma(0.4, 1.0, size=(ktrue, g)) * 40.0 / g
    counts = rng.poisson(usage @ spectra * 150.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    counts_fn = str(tmp_path / "counts.h5ad")
    write_h5ad(counts_fn, AnnDataLite(
        X=sp.csr_matrix(counts),
        obs=pd.DataFrame(index=[f"c{i}" for i in range(n)]),
        var=pd.DataFrame(index=[f"g{j}" for j in range(g)])))

    obj = cNMF(output_dir=str(tmp_path), name="atlas",
               rowshard_threshold=n // 2)
    obj.prepare(counts_fn, components=[4], n_iter=7, seed=9,
                num_highvar_genes=150, beta_loss=beta_loss)

    # from here on, any host densify must be <= one device shard of rows
    n_dev = int(np.prod(mesh.devices.shape))
    max_block = -(-n // n_dev) + n_dev
    seen = []
    orig = sp.csr_matrix.toarray

    def spy(self, *a, **kw):
        seen.append(self.shape)
        return orig(self, *a, **kw)

    monkeypatch.setattr(sp.csr_matrix, "toarray", spy)

    # pin that the spectra refit actually routes through the row-sharded
    # W-solver (for KL: the staged beta != 2 path) — a silent fallback to
    # the sub-threshold transpose trick would pass every other assertion
    from cnmf_torch_tpu.parallel import rowshard as rs_mod

    refit_betas = []
    orig_refit_w = rs_mod.refit_w_rowsharded

    def refit_spy(X, H, beta=2.0, **kw):
        refit_betas.append(float(beta))
        return orig_refit_w(X, H, beta=beta, **kw)

    monkeypatch.setattr(rs_mod, "refit_w_rowsharded", refit_spy)

    obj.factorize(mesh=mesh)  # auto-engages: n >= threshold
    obj.combine()
    obj.consensus(4, density_threshold=2.0, show_clustering=False,
                  ols_batch_size=max_block)

    expected_beta = 2.0 if beta_loss == "frobenius" else 1.0
    assert expected_beta in refit_betas, (beta_loss, refit_betas)

    oversized = [s for s in seen if s[0] > max_block]
    assert not oversized, f"host densify beyond shard size: {oversized}"

    merged = load_df_from_npz(obj.paths["merged_spectra"] % 4)
    assert merged.shape == (7 * 4, 150)
    usages = load_df_from_npz(obj.paths["consensus_usages"] % (4, "2_0"))
    assert usages.shape == (n, 4) and np.isfinite(usages.values).all()
    tpm_spectra = load_df_from_npz(obj.paths["gene_spectra_tpm"] % (4, "2_0"))
    assert tpm_spectra.shape == (4, g)
    assert np.isfinite(tpm_spectra.values).all()


# ---------------------------------------------------------------------------
# device-memory budgeting for the sweep slices
# ---------------------------------------------------------------------------

def test_kl_budget_splits_crashed_shape():
    """Regression for the round-2 TPU crash (BENCH_r02: rc=1): the shape
    100 replicates x (10000 x 2000) under KL must NOT be admitted as one
    slice — beta != 2 materializes (chunk x genes) intermediates per
    replicate that the old factor-state-only budget ignored."""
    from cnmf_torch_tpu.parallel import auto_replicates_per_batch

    kl = auto_replicates_per_batch(10000, 2000, 9, beta=1.0, chunk=5000)
    assert kl < 100, "KL sweep must split into multiple device slices"
    # a slice's worth of beta!=2 intermediates stays under the 1 GiB budget
    per_rep = 3 * (10000 * 9 + 9 * 2000) + 10000 * 9 + 3 * 5000 * 2000
    assert kl * per_rep <= (1 << 28)
    # the Frobenius path works from k x k / k x g statistics and admits
    # far more replicates per slice
    fro = auto_replicates_per_batch(10000, 2000, 9, beta=2.0, chunk=5000)
    assert fro > kl
    # never starves the mesh
    assert auto_replicates_per_batch(10 ** 6, 2000, 9, beta=1.0,
                                     chunk=5000, n_dev=8) >= 8


def test_kl_sweep_sliced_matches_single_slice():
    """Slicing a KL sweep across device batches must be semantics-free."""
    X = _lowrank(n=60, g=30, k=3, seed=29) + 0.01
    seeds = [7, 8, 9, 10]
    ref, _, ref_err = replicate_sweep(
        X, seeds, 3, beta_loss="kullback-leibler", mode="batch",
        batch_max_iter=50)
    got, _, got_err = replicate_sweep(
        X, seeds, 3, beta_loss="kullback-leibler", mode="batch",
        batch_max_iter=50, replicates_per_batch=2)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_err, ref_err, rtol=1e-3)


# ---------------------------------------------------------------------------
# nndsvd replicate diversity (seeded nndsvdar fill)
# ---------------------------------------------------------------------------

def test_nndsvd_replicates_are_distinct():
    """init='nndsvd' must not collapse all replicates onto one deterministic
    trajectory (that would make consensus over replicates vacuous): the SVD
    base's exact zeros are filled per replicate from the ledger seed."""
    X = _lowrank(n=60, g=40, k=3, seed=17) + 0.01
    spectra, _, _ = replicate_sweep(X, [101, 202, 303], 3, init="nndsvd",
                                    mode="batch", batch_max_iter=80)
    assert not np.allclose(spectra[0], spectra[1])
    assert not np.allclose(spectra[1], spectra[2])


def test_nndsvd_batched_matches_sequential_path():
    """Same ledger seed => the batched sweep and run_nmf produce the same
    nndsvd-initialized replicate (both map nndsvd -> seeded nndsvdar)."""
    X = _lowrank(n=60, g=40, k=3, seed=19) + 0.01
    seed = 777
    spectra, _, _ = replicate_sweep(X, [seed], 3, init="nndsvd",
                                    mode="batch", batch_max_iter=60)
    _, W_seq, _ = run_nmf(X, 3, init="nndsvd", mode="batch",
                          batch_max_iter=60, random_state=seed)
    np.testing.assert_allclose(spectra[0], W_seq, rtol=2e-4, atol=2e-5)


def test_rowsharded_nndsvd_init(mesh):
    X = _lowrank(n=96, g=40, k=4, seed=23) + 0.01
    H, W, err = nmf_fit_rowsharded(X, 4, mesh, init="nndsvd", seed=11,
                                   n_passes=20)
    assert H.shape == (96, 4) and (W >= 0).all() and np.isfinite(err)
    denom = (X ** 2).sum() / 2
    assert err / denom < 0.05
    # distinct seeds -> distinct solutions (the init carries the seed)
    _, W2, _ = nmf_fit_rowsharded(X, 4, mesh, init="nndsvd", seed=12,
                                  n_passes=20)
    assert not np.allclose(W, W2)


def test_warm_sweep_programs_matches_sweep_slicing(mesh):
    """Warming with the SAME arguments as the subsequent sweep must compile
    the exact executables the sweep requests: after warming, the sweep call
    adds no new entries to the jitted program's dispatch cache."""
    from cnmf_torch_tpu.parallel.replicates import (
        _slice_specs,
        _sweep_program,
        warm_sweep_programs,
    )

    n, g = 64, 40
    n_dev = int(np.prod(mesh.devices.shape))
    counts = {3: 10, 4: 5}
    expect = set()
    for k, R in counts.items():
        _, slices = _slice_specs(n, g, k, R, 2.0, "batch", 5000, None, n_dev)
        for _s, _r, r_pad in slices:
            expect.add((k, r_pad))
    warmed = warm_sweep_programs(n, g, counts, beta_loss="frobenius",
                                 mode="batch", batch_max_iter=30, mesh=mesh)
    assert warmed == len(expect)

    # the non-tautological half of the contract: the sweep's subsequent
    # _sweep_program lookups must HIT the lru entries the warmer built (a
    # miss means the two paths derived different static arguments and the
    # warmer compiled executables the sweep will never use)
    ci0 = _sweep_program.cache_info()
    X = _lowrank(n=n, g=g, k=3, seed=2)
    spectra, _, errs = replicate_sweep(X, list(range(10)), 3, mode="batch",
                                       batch_max_iter=30, mesh=mesh)
    assert spectra.shape == (10, 3, g) and np.isfinite(errs).all()
    ci1 = _sweep_program.cache_info()
    assert ci1.misses == ci0.misses, (
        "sweep built programs the warmer did not prepare")
    assert ci1.hits > ci0.hits


def test_stream_csr_multislab_assembly(mesh, monkeypatch):
    """The multi-slab shard assembly (zeros buffer + donated slab writes) is
    the path atlas-scale shards take; exercise it by shrinking the slab size
    so every shard needs several scatters, and require bit-exact equality
    with the dense matrix — including a non-dividing row count."""
    import cnmf_torch_tpu.parallel.rowshard as rs
    import cnmf_torch_tpu.parallel.streaming as streaming

    monkeypatch.setattr(streaming, "DENSIFY_SLAB_ROWS", 7)
    X = sp.random(107, 23, density=0.21, random_state=12, format="csr")
    Xd, pad = rs.stream_rows_to_mesh(X, mesh, mesh.axis_names[0])
    got = np.asarray(Xd)
    assert got.shape[0] == 107 + pad
    np.testing.assert_array_equal(got[:107], X.toarray().astype(np.float32))
    assert not got[107:].any()


def test_refit_w_rejects_generic_beta():
    """Same contract as nmf_fit_rowsharded: a generic beta would silently
    run the IS statistics under the wrong divergence (review finding)."""
    from cnmf_torch_tpu.parallel.rowshard import refit_w_rowsharded

    X = _lowrank(n=20, g=10, k=2)
    H = np.abs(np.random.default_rng(0).normal(size=(20, 2))).astype(
        np.float32)
    with pytest.raises(ValueError, match="beta"):
        refit_w_rowsharded(X, H, beta=0.5)


def test_packed_sweep_bit_identical_to_per_k():
    """The packed K_max program must reproduce the per-K programs' spectra
    BIT-FOR-BIT at matched batch shapes: zero-padded components stay at
    exact zero under MU and trailing zeros never perturb a reduction.
    (Across different batch shapes XLA's reduction groupings differ at the
    f32 rounding level — a property the per-K path itself has between its
    own slice sizes.)"""
    import numpy as np

    from cnmf_torch_tpu.parallel import replicate_sweep, replicate_sweep_packed

    rng = np.random.default_rng(0)
    X = (rng.gamma(0.3, 1.0, size=(120, 40)) * 5).astype(np.float32)
    seeds = [11, 22, 33, 44, 55, 66, 77, 88]
    for mode in ("online", "batch"):
        per_k, _, errs_k = replicate_sweep(X, seeds, 5, mode=mode,
                                           online_chunk_size=50, n_passes=5)
        packed, _, errs_p = replicate_sweep_packed(
            X, [5] * 8, seeds, mode=mode, online_chunk_size=50, n_passes=5)
        np.testing.assert_array_equal(packed[:, :5], per_k, err_msg=mode)
        np.testing.assert_array_equal(errs_p, errs_k)

    # mixed-K sweep: padding exact-zero above each task's own K, close
    # agreement with per-K runs (batch shapes differ: 8 vs 4)
    ks = [3] * 4 + [7] * 4
    packed, _, _ = replicate_sweep_packed(X, ks, seeds, mode="online",
                                          online_chunk_size=50, n_passes=5)
    assert (packed[:4, 3:] == 0).all()
    per3, _, _ = replicate_sweep(X, seeds[:4], 3, mode="online",
                                 online_chunk_size=50, n_passes=5)
    np.testing.assert_allclose(packed[:4, :3], per3, rtol=5e-4, atol=1e-5)


def test_packed_factorize_consensus_matches_per_k(tmp_path):
    """factorize(packed) and factorize(packed=False) must yield the same
    consensus artifacts (VERDICT r3 ask #2)."""
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import load_df_from_npz, save_df_to_npz

    rng = np.random.default_rng(5)
    usage = rng.dirichlet(np.ones(4) * 0.3, size=90)
    spectra = rng.gamma(0.3, 1.0, size=(4, 150)) * 40.0 / 150
    counts = rng.poisson(usage @ spectra * 300.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(90)],
                      columns=[f"g{j}" for j in range(150)])
    fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, fn)

    results = {}
    for packed in (True, False):
        name = "packed" if packed else "perk"
        obj = cNMF(output_dir=str(tmp_path), name=name)
        obj.prepare(fn, components=[3, 4], n_iter=6, seed=14,
                    num_highvar_genes=100, batch_size=64, max_NMF_iter=200)
        obj.factorize(packed=packed)
        obj.combine()
        for k in (3, 4):
            obj.consensus(k, density_threshold=2.0, show_clustering=False,
                          build_ref=False)
            results[(name, k)] = load_df_from_npz(
                obj.paths["consensus_spectra"] % (k, "2_0"))
    for k in (3, 4):
        a, b = results[("packed", k)], results[("perk", k)]
        assert list(a.index) == list(b.index)
        np.testing.assert_allclose(a.values, b.values, rtol=1e-4, atol=1e-6,
                                   err_msg=f"k={k}")


@pytest.mark.parametrize("beta", [1.0, 0.0])
def test_refit_w_staged_matches_streamed(mesh, beta):
    """The staged (HBM-resident, one-dispatch) beta != 2 spectra refit must
    agree with the re-streaming fallback: same MU fixed-point iteration,
    same stopping rule, only the residency of X differs."""
    from cnmf_torch_tpu.parallel.rowshard import refit_w_rowsharded

    X = sp.csr_matrix(_lowrank(n=104, g=40, k=3, seed=13) + 0.01)
    rng = np.random.default_rng(9)
    H = rng.gamma(1.0, 1.0, size=(104, 3)).astype(np.float32)
    W_streamed = refit_w_rowsharded(X, H, beta=beta, h_tol=1e-4,
                                    max_iter=200, row_block=32, stage=False)
    W_staged = refit_w_rowsharded(X, H, beta=beta, h_tol=1e-4,
                                  max_iter=200, row_block=32, stage=True,
                                  mesh=mesh)
    assert np.allclose(W_staged, W_streamed, rtol=2e-4, atol=1e-6)


def test_refit_w_staged_accepts_device_resident_x(mesh):
    """Direct API callers may hold X device-resident already (the pipeline
    itself always crosses the rowshard threshold with a host matrix); the
    staged refit must consume a jax.Array without a host round trip."""
    from cnmf_torch_tpu.parallel.rowshard import refit_w_rowsharded

    Xh = _lowrank(n=64, g=24, k=3, seed=3) + 0.01
    rng = np.random.default_rng(4)
    H = rng.gamma(1.0, 1.0, size=(64, 3)).astype(np.float32)
    import jax.numpy as jnp

    W_dev = refit_w_rowsharded(jnp.asarray(Xh), H, beta=1.0, h_tol=1e-4,
                               max_iter=150, stage="auto")
    W_host = refit_w_rowsharded(Xh, H, beta=1.0, h_tol=1e-4, max_iter=150,
                                stage=False)
    assert np.allclose(W_dev, W_host, rtol=2e-4, atol=1e-6)


def test_budget_derives_from_device_memory_stats(monkeypatch):
    """The slice budget scales with the device's actual free HBM (VERDICT
    r4 item 5): a part reporting 32 GB free must admit more replicates per
    slice than the v5e-tuned 1 GiB fallback, stats-less runtimes (CPU, the
    tunneled TPU) must keep the fallback exactly, and the env override
    wins over both."""
    from cnmf_torch_tpu.parallel import auto_replicates_per_batch
    from cnmf_torch_tpu.parallel import replicates as reps

    class FakeDev:
        def __init__(self, stats):
            self._stats = stats

        def memory_stats(self):
            return self._stats

    # pin the environment: the baseline must be the fallback constant even
    # on hosts whose real device reports large free HBM or where the env
    # override is exported
    monkeypatch.delenv("CNMF_TPU_BUDGET_ELEMS", raising=False)
    monkeypatch.setattr(reps.jax, "devices", lambda: [FakeDev(None)])
    fallback = auto_replicates_per_batch(10000, 2000, 9, beta=1.0,
                                         chunk=5000)
    big = {"bytes_limit": 32 << 30, "bytes_in_use": 1 << 30}
    monkeypatch.setattr(reps.jax, "devices", lambda: [FakeDev(big)])
    scaled = auto_replicates_per_batch(10000, 2000, 9, beta=1.0, chunk=5000)
    assert scaled > fallback
    # 30% of free
    free = (32 << 30) - (1 << 30)
    assert reps._device_budget_elems() == (free * 3 // 10) // 4
    # a nearly-full device must shrink BELOW the fallback constant (the
    # old floor re-admitted the round-2 OOM class on contended HBM)
    tight = {"bytes_limit": 16 << 30, "bytes_in_use": 15 << 30}
    monkeypatch.setattr(reps.jax, "devices", lambda: [FakeDev(tight)])
    assert reps._device_budget_elems() == ((1 << 30) * 3 // 10) // 4
    assert reps._device_budget_elems() < reps._FALLBACK_BUDGET_ELEMS

    monkeypatch.setattr(reps.jax, "devices", lambda: [FakeDev({})])
    assert reps._device_budget_elems() == reps._FALLBACK_BUDGET_ELEMS
    monkeypatch.setattr(reps.jax, "devices", lambda: [FakeDev(None)])
    assert reps._device_budget_elems() == reps._FALLBACK_BUDGET_ELEMS

    monkeypatch.setenv("CNMF_TPU_BUDGET_ELEMS", str(1 << 20))
    assert reps._device_budget_elems() == 1 << 20


def test_kl_sweep_bf16_ratio_statistical_parity(monkeypatch):
    """The production online-KL sweep stores X chunks and WH/ratio
    intermediates in bf16 (f32 accumulation/state/objective — measured
    1.78x per MU iteration on v5e). The bar is the fit_H_online fp32
    contract held to STATISTICAL parity — equal-quality optima (the same
    bar the row-sharded solver tests use: nonconvex trajectories with
    early-stopping inner loops diverge under ANY perturbation, so
    element-wise W parity is not expected), deterministic across calls."""
    from cnmf_torch_tpu.ops.nmf import resolve_bf16_ratio
    from cnmf_torch_tpu.parallel import replicate_sweep

    assert resolve_bf16_ratio(1.0, "online") is True
    assert resolve_bf16_ratio(0.0, "online") is True
    assert resolve_bf16_ratio(2.0, "online") is False
    assert resolve_bf16_ratio(1.0, "batch") is False
    monkeypatch.setenv("CNMF_TPU_BF16_RATIO", "0")
    assert resolve_bf16_ratio(1.0, "online") is False
    assert resolve_bf16_ratio(1.0, "online", override=True) is True
    monkeypatch.delenv("CNMF_TPU_BF16_RATIO")

    from cnmf_torch_tpu.parallel.replicates import _sweep_program

    X = _lowrank(n=120, g=60, k=4, seed=9) + 0.05
    seeds = [3, 11, 27]
    # per-seed trajectory-divergence bounds measured per loss: ~1-3% for
    # KL (re-pinned after the jax_threefry_partitionable default changed
    # the init streams — seed 27 lands at 2.7% on CPU); up to ~4% for IS
    # (gamma=0.5-damped steps amplify path divergence; on the TPU fixture
    # bf16 was BETTER on every IS seed). The systematic-quality guard
    # below (mean < 1%) is the real bar.
    bound = {"kullback-leibler": 4e-2, "itakura-saito": 5e-2}
    for beta_loss in ("kullback-leibler", "itakura-saito"):
        kw = dict(beta_loss=beta_loss, mode="online", online_chunk_size=64)
        sp_bf, _, errs_bf = replicate_sweep(X, seeds, 4, **kw)
        sp_bf2, _, errs_bf2 = replicate_sweep(X, seeds, 4, **kw)
        np.testing.assert_array_equal(sp_bf, sp_bf2)  # deterministic

        monkeypatch.setenv("CNMF_TPU_BF16_RATIO", "0")
        _sweep_program.cache_clear()
        sp_f32, _, errs_f32 = replicate_sweep(X, seeds, 4, **kw)
        _sweep_program.cache_clear()
        monkeypatch.delenv("CNMF_TPU_BF16_RATIO")
        rel = (errs_bf - errs_f32) / np.abs(errs_f32)
        assert np.all(np.abs(rel) < bound[beta_loss]), (
            beta_loss, errs_bf, errs_f32)
        # and no systematic quality loss across replicates
        assert rel.mean() < 1e-2, (beta_loss, rel)
        assert (sp_bf >= 0).all()
