"""Tier-1 serve smoke gate (scripts/verify_tier1.sh, ISSUE 12).

Builds a consensus-complete mini run, starts the REAL daemon through the
CLI surface (``cnmf-tpu serve <run_dir> --socket ...`` in a subprocess),
fires concurrent clients plus one poison tenant at it, and asserts the
serving tier's contract end-to-end:

  * cross-request batching ENGAGED: telemetry ``serve_batch`` events
    record multi-request batches under concurrent load;
  * every successful projection is BIT-identical to solo
    ``cNMF.refit_usage`` dispatch against the same reference;
  * the poison request fails alone (clear client error + quarantine
    accounting) without sinking its batchmates;
  * every emitted event line is schema-valid;
  * clean shutdown: daemon exits 0, no orphaned socket or temp files.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.serving import (PoisonError, ServeClient,
                                        ServeError)
    from cnmf_torch_tpu.utils import save_df_to_npz
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    workdir = tempfile.mkdtemp(prefix="serve_smoke_")
    proc = None
    try:
        # -- fixture run (telemetry off: the events file should carry
        # the DAEMON's stream) --------------------------------------------
        rng = np.random.default_rng(8)
        usage = rng.dirichlet(np.ones(4) * 0.3, size=160)
        spectra = rng.gamma(0.3, 1.0, size=(4, 90)) * 40.0 / 90
        counts = rng.poisson(usage @ spectra * 260.0).astype(np.float64)
        counts[counts.sum(axis=1) == 0, 0] = 1.0
        df = pd.DataFrame(counts, index=[f"c{i}" for i in range(160)],
                          columns=[f"g{j}" for j in range(90)])
        counts_fn = os.path.join(workdir, "counts.df.npz")
        save_df_to_npz(df, counts_fn)

        obj = cNMF(output_dir=workdir, name="smoke")
        obj.prepare(counts_fn, components=[3], n_iter=6, seed=4,
                    num_highvar_genes=70)
        obj.factorize()
        obj.combine()
        obj.consensus(k=3, density_threshold=2.0, show_clustering=False)
        run_dir = os.path.join(workdir, "smoke")

        # -- daemon through the CLI surface --------------------------------
        sock = os.path.join(workdir, "serve.sock")
        env = dict(os.environ,
                   CNMF_TPU_TELEMETRY="1",
                   CNMF_TPU_SERVE_LINGER_MS="150",
                   CNMF_TPU_SERVE_WARM_START="0")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cnmf_torch_tpu", "serve", run_dir,
             "--socket", sock],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        cli = ServeClient(socket_path=sock, timeout=60.0)
        deadline = time.time() + 120
        while True:
            if proc.poll() is not None:
                print("serve smoke: daemon exited early:\n"
                      + (proc.stdout.read() or ""), file=sys.stderr)
                return 1
            try:
                if cli.healthz().get("ok"):
                    break
            except Exception:
                pass
            if time.time() > deadline:
                print("serve smoke: daemon never came up", file=sys.stderr)
                return 1
            time.sleep(0.25)

        # -- concurrent clients + one poison tenant ------------------------
        from cnmf_torch_tpu.serving import load_reference

        ref = load_reference(run_dir)
        queries = {f"tenant{i}": rng.gamma(
            1.0, 1.0, size=(12 + 9 * i, ref.n_genes)).astype(np.float32)
            for i in range(4)}
        poison = queries["tenant0"].copy()
        poison[1, 1] = np.nan
        results: dict = {}

        def client(tenant, X):
            try:
                results[tenant] = ServeClient(
                    socket_path=sock, timeout=60.0).project(X, tenant=tenant)
            except ServeError as exc:
                results[tenant] = exc

        threads = [threading.Thread(target=client, args=(t, X))
                   for t, X in queries.items()]
        threads.append(threading.Thread(
            target=client, args=("poison_tenant", poison)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        spectra_df = pd.DataFrame(ref.W, columns=ref.genes)
        for tenant, X in queries.items():
            got = results[tenant]
            if isinstance(got, Exception):
                print(f"serve smoke: {tenant} failed: {got}",
                      file=sys.stderr)
                return 1
            H, _meta = got
            solo = np.asarray(obj.refit_usage(X, spectra_df))
            if not np.array_equal(H, solo):
                print(f"serve smoke: {tenant} NOT bit-identical to solo "
                      f"refit_usage (max diff "
                      f"{np.abs(H - solo).max()})", file=sys.stderr)
                return 1
        if not isinstance(results["poison_tenant"], PoisonError):
            print("serve smoke: poison request did not fail as poison: "
                  f"{results['poison_tenant']!r}", file=sys.stderr)
            return 1

        stats = cli.stats()
        if stats["ok"] != len(queries) or stats["poison"] != 1:
            print(f"serve smoke: bad outcome counts: {stats}",
                  file=sys.stderr)
            return 1

        # -- clean shutdown ------------------------------------------------
        cli.shutdown()
        rc = proc.wait(timeout=60)
        out = proc.stdout.read() or ""
        proc = None
        if rc != 0:
            print(f"serve smoke: daemon exit code {rc}:\n{out}",
                  file=sys.stderr)
            return 1
        if os.path.exists(sock):
            print("serve smoke: orphaned socket file after shutdown",
                  file=sys.stderr)
            return 1
        orphans = [fn for fn in os.listdir(os.path.join(run_dir,
                                                        "cnmf_tmp"))
                   if fn.endswith((".sock", ".tmp"))
                   or fn.startswith(".tmp")]
        if orphans:
            print(f"serve smoke: orphaned temp files: {orphans}",
                  file=sys.stderr)
            return 1

        # -- telemetry: schema-valid, batching ENGAGED ---------------------
        ev_path = os.path.join(run_dir, "cnmf_tmp", "smoke.events.jsonl")
        n = validate_events_file(ev_path)
        evs = read_events(ev_path)
        batches = [e for e in evs if e["t"] == "serve_batch"]
        reqs = [e for e in evs if e["t"] == "serve_request"]
        if not batches or not reqs:
            print(f"serve smoke: missing serve events "
                  f"({ {e['t'] for e in evs} })", file=sys.stderr)
            return 1
        max_batch_requests = max(e["requests"] for e in batches)
        if max_batch_requests < 2:
            print(f"serve smoke: cross-request batching never engaged "
                  f"(max batch {max_batch_requests} request(s) across "
                  f"{len(batches)} batches)", file=sys.stderr)
            return 1
        statuses = {e["status"] for e in reqs}
        if not {"ok", "poison"} <= statuses:
            print(f"serve smoke: unexpected statuses {statuses}",
                  file=sys.stderr)
            return 1

        print(f"serve smoke: {len(queries)} tenants bit-identical to solo "
              f"refit_usage, poison isolated+accounted, max batch "
              f"{max_batch_requests} requests across {len(batches)} "
              f"dispatches, {n} schema-valid events, clean shutdown "
              f"(exit 0, no orphans)")
        return 0
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
