"""Fixed-width ELL encoding + sparsity-aware beta in {1, 0} MU statistics.

Single-cell count matrices are ~85-95% zeros, yet the dense beta != 2 (KL/IS)
MU chains in ``ops/nmf.py`` materialize WH and X/WH over all n x g entries —
the measured MFU gap between the KL kernel (0.038) and the Frobenius bundle
(0.42) is structural, not a kernel-tuning problem (COMPLETENESS.md "Remaining
perf levers": "further gains need different math"). The different math
(arXiv:1604.04026; arXiv:2202.09518) is that for beta=1 the MU numerator
``(X/WH) @ W^T`` only needs the ratio at X's nonzeros and the denominator
``sum_g W`` is data-independent; for beta=0 the numerator ``(X/WH^2) @ W^T``
is likewise supported on X's nonzeros (the ``1/WH`` denominator still needs
the dense WH, so the IS path there is a hybrid).

Encoding: **dual fixed-width ELL** — per-row ``(values, col_indices)``
padded to one static width for the H-side statistics, PLUS a transposed
index set (per-column row indices + a permutation into the flat row-major
value buffer) for the W-side statistics. Every shape is static, so the
encoding rides jit/vmap/scan/shard_map exactly like a dense array, and —
critically for both CPU and TPU — every kernel below is gathers and
reductions only: scatter-free (XLA scatter measured 2-6 s per (k, g)
numerator at the bench shape on CPU, ~50x the whole dense update).

Kernel shape (all four statistics + the objective): a ``lax.scan`` over the
k components, each step one flat gather from a small table (a W row / an H
column — k*g / n-sized, cache- or VMEM-class) at the stored indices plus a
fused multiply-reduce. Work per MU update is O(k * nnz_padded) instead of
the dense chain's O(k * n * g), with no (n, w, k) gather intermediate (the
einsum form measured 4x slower than the k-scan on CPU and holds k extra
copies of the ratio buffer).

Padding entries carry ``value 0`` (at column 0 row-side; at a sentinel
one-past-the-end flat position transpose-side), so every padded slot
contributes an exact +0.0 to every statistic — the same absorbing-zero
argument the packed K-sweeps use.

The bf16 ratio chain (``ops/nmf.py:resolve_bf16_ratio``) composes: stored
values, gathered tables, and the ratio live in bf16 with f32 reduction of
the numerators, mirroring the dense chain's memory-format relief.

This module is imported by ``ops/nmf.py`` and must not import it back —
the MU rate application (``_apply_rate``/``mu_gamma``) stays in nmf.py and
composes these statistics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

__all__ = [
    "EllMatrix",
    "csr_to_ell",
    "ell_chunk_rows",
    "ell_to_dense",
    "ell_device_put",
    "ell_row_width",
    "resolve_sparse_beta",
    "ell_w_table",
    "ell_wh_at_nz",
    "ell_kl_h_stats",
    "ell_kl_h_newton_stats",
    "ell_kl_w_numer",
    "ell_kl_w_stats",
    "ell_is_h_stats",
    "ell_is_w_stats",
    "ell_beta_err",
    "is_per_elem",
    "kl_nz_term",
    "SPARSE_DENSITY_THRESHOLD",
]

EPS = 1e-16  # matches ops.nmf.EPS (no import: nmf.py imports this module)

# auto-dispatch density ceiling. The inner-iteration cost ratio is
# ~(2k + 2) * width / (3 * g) (slab passes vs dense WH/ratio passes), so
# the win shrinks as width/g grows: measured warm 8-replicate KL sweeps
# at the bench shape (10000 x 2000, k=9) run 1.5x FASTER ELL at 95%
# sparsity (width 136) but 1.5x SLOWER at 88% (width 296). The default
# engages only where the win is comfortable — <=10% nonzeros AND
# width <= g/8 — real HVG count matrices are typically 90-95% zeros.
# CNMF_TPU_SPARSE_BETA overrides (see resolve_sparse_beta).
SPARSE_DENSITY_THRESHOLD = 0.10

# pad the ELL widths to a lane-friendly multiple so the gather / ratio
# arrays tile cleanly
_WIDTH_MULTIPLE = 8


@jax.tree_util.register_pytree_node_class
class EllMatrix:
    """Dual fixed-width ELL matrix.

    Row side (always present): ``vals (..., n, w)`` + ``cols (..., n, w)``
    — per-row nonzero values and column indices, padded with
    ``(0.0, column 0)``.

    Transpose side (present when W-side statistics are needed):
    ``rows_t (..., g, wt)`` — per-column row indices — and
    ``perm_t (..., g, wt)`` — the flat index of that nonzero in the
    row-major ``vals`` buffer (``row * w + slot``), padding pointing at the
    sentinel ``n * w`` (one past the end; kernels gather from the ratio
    buffer with one appended zero). ``None`` for H-only uses (``fit_h``).

    ``g`` is static aux data, so the encoding is a pytree that rides
    jit/vmap/scan/shard_map like an array; leading batch/chunk axes on all
    leaves are fine (``lax.scan`` over a chunked EllMatrix yields
    per-chunk EllMatrix slices)."""

    def __init__(self, vals, cols, g: int, rows_t=None, perm_t=None):
        self.vals = vals
        self.cols = cols
        self.rows_t = rows_t
        self.perm_t = perm_t
        self.g = int(g)

    @property
    def shape(self):
        return self.vals.shape[:-1] + (self.g,)

    @property
    def width(self) -> int:
        return int(self.vals.shape[-1])

    @property
    def t_width(self) -> int | None:
        return None if self.rows_t is None else int(self.rows_t.shape[-1])

    @property
    def dtype(self):
        return self.vals.dtype

    def astype(self, dtype):
        """Cast the stored values only (all index buffers stay int32) —
        the bf16 ratio chain's ``x.astype(bfloat16)`` works unchanged."""
        return EllMatrix(self.vals.astype(dtype), self.cols, self.g,
                         self.rows_t, self.perm_t)

    def tree_flatten(self):
        return (self.vals, self.cols, self.rows_t, self.perm_t), self.g

    @classmethod
    def tree_unflatten(cls, g, children):
        vals, cols, rows_t, perm_t = children
        return cls(vals, cols, g, rows_t, perm_t)

    def __repr__(self):
        return (f"EllMatrix(shape={self.shape}, width={self.width}, "
                f"t_width={self.t_width}, dtype={self.vals.dtype})")


def _pad_width(w: int) -> int:
    return max(_WIDTH_MULTIPLE, -(-max(w, 1) // _WIDTH_MULTIPLE)
               * _WIDTH_MULTIPLE)


def ell_row_width(X) -> int:
    """The fixed row-ELL width a matrix will encode at: max row nnz,
    padded to a lane-friendly multiple (dense inputs count nonzeros)."""
    if sp.issparse(X):
        if sp.isspmatrix_csr(X):
            nnz_per_row = np.diff(X.indptr)
        else:
            # getnnz(axis=1) counts without materializing a CSR copy —
            # callers probing a transposed view (refit_spectra's ELL
            # decision on X.T) must not pay an O(nnz) conversion just
            # to be told the answer is "dense"
            nnz_per_row = np.asarray(X.getnnz(axis=1)).reshape(-1)
    else:
        nnz_per_row = np.count_nonzero(np.asarray(X), axis=1)
    return _pad_width(int(nnz_per_row.max()) if nnz_per_row.size else 1)


def _row_ell_buffers(Xc: sp.csr_matrix, width: int, dtype):
    n, _ = Xc.shape
    row_nnz = np.diff(Xc.indptr)
    vals = np.zeros((n, int(width)), dtype=dtype)
    cols = np.zeros((n, int(width)), dtype=np.int32)
    if Xc.nnz:
        rows = np.repeat(np.arange(n), row_nnz)
        pos = np.arange(Xc.nnz) - np.repeat(Xc.indptr[:-1], row_nnz)
        vals[rows, pos] = Xc.data
        cols[rows, pos] = Xc.indices
    return vals, cols


def _transpose_buffers(Xc: sp.csr_matrix, width: int, t_width: int):
    """Per-column (rows_t, perm_t) for a row-ELL block: ``perm_t`` maps
    each transpose slot to its flat ``row * width + slot`` position in the
    block's row-major value buffer; padding -> sentinel ``n * width``."""
    n, g = Xc.shape
    row_nnz = np.diff(Xc.indptr)
    rows_t = np.zeros((g, int(t_width)), np.int32)
    perm_t = np.full((g, int(t_width)), n * int(width), np.int32)
    if Xc.nnz:
        pos_in_row = np.arange(Xc.nnz) - np.repeat(Xc.indptr[:-1], row_nnz)
        flatpos = np.repeat(np.arange(n), row_nnz) * int(width) + pos_in_row
        # route the flat positions through CSC to group them per column
        # (+1 keeps position 0 distinguishable from CSC's implicit zeros)
        P = sp.csr_matrix((flatpos + 1, Xc.indices, Xc.indptr),
                          shape=(n, g)).tocsc()
        col_nnz = np.diff(P.indptr)
        pos_in_col = np.arange(P.nnz) - np.repeat(P.indptr[:-1], col_nnz)
        cc = np.repeat(np.arange(g), col_nnz)
        rows_t[cc, pos_in_col] = P.indices
        perm_t[cc, pos_in_col] = P.data - 1
    return rows_t, perm_t


def _as_clean_csr(X) -> sp.csr_matrix:
    if sp.issparse(X):
        Xc = X.tocsr().copy()
        Xc.eliminate_zeros()
        return Xc
    return sp.csr_matrix(np.asarray(X))


def csr_to_ell(X, width: int | None = None, t_width: int | None = None,
               transpose: bool = True, dtype=np.float32) -> EllMatrix:
    """Host-side CSR (or dense) -> dual fixed-width ELL conversion.

    ``width`` / ``t_width`` pin the static widths (must cover the longest
    row / column — pass global maxima when sharding so every shard
    compiles one program); both default to the matrix's own maxima.
    ``transpose=False`` skips the W-side index set (H-only uses).
    Explicit zeros are dropped so the kernels' "stored value > 0 <=>
    data nonzero" invariant holds. Returns numpy-backed arrays; stage
    with :func:`ell_device_put`.
    """
    Xc = _as_clean_csr(X)
    n, g = Xc.shape
    max_row = int(np.diff(Xc.indptr).max()) if n else 0
    if width is None:
        width = _pad_width(max_row)
    elif width < max_row:
        raise ValueError(
            f"width={width} < max row nnz {max_row}: rows would truncate")
    vals, cols = _row_ell_buffers(Xc, width, dtype)
    rows_t = perm_t = None
    if transpose:
        max_col = int(np.diff(Xc.tocsc().indptr).max()) if g else 0
        if t_width is None:
            t_width = _pad_width(max_col)
        elif t_width < max_col:
            raise ValueError(f"t_width={t_width} < max col nnz {max_col}")
        rows_t, perm_t = _transpose_buffers(Xc, width, t_width)
    return EllMatrix(vals, cols, g, rows_t, perm_t)


def ell_chunk_rows(X, chunk_size: int, width: int | None = None,
                   dtype=np.float32):
    """Host-side chunked dual-ELL staging for the ONLINE solver: rows are
    zero-padded to a multiple of ``chunk_size`` and each chunk gets its
    own transpose index set (the online beta != 2 W step uses per-chunk
    statistics, so the column grouping must be per chunk). All widths are
    global maxima, so every chunk shares one static shape. Returns
    ``(EllMatrix with (C, chunk, w) row leaves and (C, g, wt) transpose
    leaves, pad)``.
    """
    Xc = _as_clean_csr(X)
    n, g = Xc.shape
    chunk_size = int(min(chunk_size, n))
    n_chunks = max(1, -(-n // chunk_size))
    pad = n_chunks * chunk_size - n
    if pad:
        Xc = sp.vstack(
            [Xc, sp.csr_matrix((pad, g), dtype=Xc.dtype)]).tocsr()
    if width is None:
        width = ell_row_width(Xc)
    blocks = [Xc[i * chunk_size:(i + 1) * chunk_size]
              for i in range(n_chunks)]
    t_width = _pad_width(max(
        int(np.diff(b.tocsc().indptr).max()) if g else 0 for b in blocks))
    vs, cs, rts, pts = [], [], [], []
    for b in blocks:
        v, c = _row_ell_buffers(b, width, dtype)
        rt, pt = _transpose_buffers(b, width, t_width)
        vs.append(v)
        cs.append(c)
        rts.append(rt)
        pts.append(pt)
    return EllMatrix(np.stack(vs), np.stack(cs), g,
                     np.stack(rts), np.stack(pts)), pad


def ell_to_dense(x: EllMatrix) -> np.ndarray:
    """Exact inverse of the row-side encoding (host numpy). Padding
    entries scatter +0.0 into column 0 — a no-op."""
    vals = np.asarray(x.vals)
    cols = np.asarray(x.cols)
    n = vals.shape[0]
    out = np.zeros((n, x.g), dtype=vals.dtype)
    np.add.at(out, (np.repeat(np.arange(n), vals.shape[1]), cols.ravel()),
              vals.ravel())
    return out


def ell_device_put(x: EllMatrix, sharding=None, stats=None) -> EllMatrix:
    """Stage the ELL buffers to device (optionally with a sharding that
    applies to every leaf — e.g. replicated ``P()`` for sweeps). The four
    leaves upload through the streaming pool
    (:func:`~cnmf_torch_tpu.parallel.streaming.stream_put_leaves`) so
    their transfers overlap instead of queueing serially."""
    from ..parallel.streaming import stream_put_leaves

    leaves = [(x.vals, np.float32), (x.cols, np.int32),
              (x.rows_t, np.int32), (x.perm_t, np.int32)]
    host = [None if a is None else np.asarray(a, dtype=dt)
            for a, dt in leaves]
    live = [i for i, a in enumerate(host) if a is not None]
    put = stream_put_leaves([host[i] for i in live], sharding, stats=stats)
    out = [None] * len(host)
    for i, d in zip(live, put):
        out[i] = d
    return EllMatrix(out[0], out[1], x.g, out[2], out[3])


def resolve_sparse_beta(beta: float, density: float | None = None,
                        width: int | None = None, g: int | None = None,
                        override=None,
                        threshold: float | None = None) -> bool:
    """Should a beta != 2 solve take the ELL path?

    Production default: ON for beta in {1, 0} when the matrix is at most
    ``SPARSE_DENSITY_THRESHOLD`` dense AND the fixed row width is at most
    an eighth of the gene count (the ragged-row guard: the cost model is
    width-driven — one dense-ish row pads every row's width and erodes
    the win; see the threshold's derivation above).
    ``CNMF_TPU_SPARSE_BETA`` env override: ``0`` forces dense, ``1``
    forces ELL (for any beta in {1, 0}), any value in (0, 1) replaces
    the density threshold (the width guard stays). An explicit
    ``override`` argument wins over the env. ``threshold`` replaces the
    static density crossover WITHOUT outranking the env — it is the
    planner's slot for the measured per-device crossover
    (``utils/autotune.py``; precedence pin > autotuned > static).
    """
    if beta not in (1.0, 0.0):
        return False
    if override is not None:
        return bool(override)
    threshold = (SPARSE_DENSITY_THRESHOLD if threshold is None
                 else float(threshold))
    from ..utils.envknobs import env_str

    env = env_str("CNMF_TPU_SPARSE_BETA", "")
    if env:
        try:
            t = float(env)
        except ValueError:
            raise ValueError(
                f"CNMF_TPU_SPARSE_BETA={env!r}: expected 0 (dense), "
                "1 (force ELL), or a density threshold in (0, 1)")
        if t <= 0.0:
            return False
        if t >= 1.0:
            return True
        threshold = t
    if density is None:
        return False
    if width is not None and g is not None and 8 * width > g:
        return False
    return float(density) <= threshold


# ---------------------------------------------------------------------------
# nonzero-only statistics kernels (unrolled k-slab gathers; scatter-free)
# ---------------------------------------------------------------------------
#
# Form chosen by measurement (CPU, 10000x2000 @ 88% sparsity, k=9):
#   * XLA scatter-based (k, g) numerators: 2-6 s/update — unusable;
#   * (n, w, k)-gather + einsum ('nwk,nk->nw'): batched tiny matvecs the
#     backend cannot vectorize — ~0.6x DENSE;
#   * lax.scan over k with flat gathers: accumulator re-materializes per
#     step — ~parity with dense;
#   * UNROLLED sum over k slabs of a pre-gathered (n, w, k) table: one
#     fused pass per statistic, 2.1x the dense chain per inner iteration
#     (exact to f32 tolerance) — this form.
# The table is loop-invariant whenever W is fixed (every inner H-solve,
# the objective scans, the per-chunk W step) — gathered ONCE per chunk
# solve and reused across all inner iterations (``ell_w_table``). When no
# table is supplied (the batch solver's alternating updates) the slabs
# are gathered inline, still unrolled.

def _take(table, idx):
    return jnp.take(table, idx, mode="clip")


def ell_w_table(W, cols, bf16: bool = False):
    """Pre-gathered ``(k, n, w)`` slab table (``W[c][cols]`` stacked) —
    build once per fixed-W solve. Component-major layout so every inner
    iteration reads CONTIGUOUS (n, w) slabs: the (n, w, k) gather layout
    reads stride-k inside the loop, which measured 3x slower per
    iteration. The k x g source table is cache/VMEM-class."""
    Wt = W.T.astype(jnp.bfloat16) if bf16 else W.T
    return jnp.take(Wt, cols, axis=0, mode="clip").transpose(2, 0, 1)


def _slab(W, cols, w_table, c):
    return w_table[c] if w_table is not None else _take(W[c], cols)


def _wh_at_nz(cols, H, W, w_table=None):
    """``(H @ W)`` at the stored coordinates: unrolled sum of k slab
    FMAs — the SDDMM form, fused by XLA into one pass. Accumulates in the
    operand dtype (bf16 under the ratio chain, exactly like the dense
    chain's WH matmul)."""
    k = H.shape[-1]
    acc = H[..., 0:1] * _slab(W, cols, w_table, 0)
    for c in range(1, k):
        acc = acc + H[..., c:c + 1] * _slab(W, cols, w_table, c)
    return acc


def ell_wh_at_nz(x: EllMatrix, H, W, w_table=None):
    """Public f32 SDDMM: ``wh[i, j] = H[i, :] @ W[:, cols[i, j]]``.
    ``w_table``: optional pre-gathered :func:`ell_w_table` (fixed-W
    loops re-use it across candidate evaluations)."""
    return _wh_at_nz(x.cols, H, W, w_table)


def _h_numer(cols, ratio, W, w_table=None):
    """``ratio @ W^T`` with ratio supported on the stored coordinates:
    one unrolled slab-reduce per component — f32 accumulation."""
    k = W.shape[0]
    outs = [jnp.sum((ratio * _slab(W, cols, w_table, c)).astype(
        jnp.float32), axis=-1) for c in range(k)]
    return jnp.stack(outs, axis=-1)


def _w_numer(x: EllMatrix, ratio, H):
    """``H^T @ R`` with R supported on the stored coordinates — the
    scatter-free transpose-side form: the ratio buffer is permuted into
    per-column groups (one static gather through ``perm_t``; padding hits
    the appended zero), then each component gathers its H column at
    ``rows_t`` and reduces, unrolled. f32 accumulation."""
    if x.rows_t is None:
        raise ValueError(
            "this EllMatrix has no transpose index set (rows_t/perm_t); "
            "encode with csr_to_ell(transpose=True) / ell_chunk_rows for "
            "W-side updates")
    r_flat = jnp.concatenate(
        [ratio.reshape(-1), jnp.zeros((1,), ratio.dtype)])
    r_t = _take(r_flat, x.perm_t)                    # (g, wt)
    k = H.shape[-1]
    outs = [jnp.sum((r_t * _take(H[..., c], x.rows_t)).astype(jnp.float32),
                    axis=-1) for c in range(k)]
    return jnp.stack(outs, axis=0)                   # (k, g)


def _cast_pair(x: EllMatrix, H, W, bf16: bool):
    if bf16:
        return (x.vals.astype(jnp.bfloat16), H.astype(jnp.bfloat16),
                W.astype(jnp.bfloat16))
    return x.vals, H, W


def ell_kl_h_stats(x: EllMatrix, H, W, bf16_ratio: bool = False,
                   w_table=None):
    """KL (beta=1) H-update statistics, nonzeros only.

    numer = (X/WH) @ W^T restricted to X's support (zero entries of X
    contribute an exact 0 to the dense numerator); denom = row-broadcast
    ``W.sum(axis=1)`` — data-independent, never touches X. With
    ``bf16_ratio`` the stored values, gathered tables, and the ratio live
    in bf16 with f32 numerator accumulation (the same memory-format
    relief as the dense chain in ``ops/nmf.py:_update_H``). Padding
    entries have value 0 => ratio 0 => exact +0.0 contributions.
    ``w_table``: pre-gathered :func:`ell_w_table` (loop-invariant inner
    solves); must be in the chain's compute dtype.
    """
    vals, Hc, Wc = _cast_pair(x, H, W, bf16_ratio)
    wh = _wh_at_nz(x.cols, Hc, Wc, w_table)
    ratio = vals / jnp.maximum(wh, jnp.asarray(EPS, wh.dtype))
    numer = _h_numer(x.cols, ratio, Wc, w_table)
    denom = jnp.broadcast_to(W.sum(axis=1)[None, :], H.shape)
    return numer, denom


def ell_kl_h_newton_stats(x: EllMatrix, H, W, w_table=None):
    """KL H-update statistics for the Diagonalized Newton recipe
    (arXiv:1301.3389), nonzeros only, strict f32: the MU numerator
    ``(X/WH) @ Wᵀ`` plus the diagonal Hessian
    ``hess[i,c] = Σ_j X_ij W_cj² / WH_ij²`` — supported on X's nonzeros
    exactly like the numerator, so the Newton lane costs one extra
    squared-slab reduce per component over the same gathers. The
    data-independent ``W.sum(axis=1)`` denominator is returned for the
    MU fallback candidate. ``w_table`` must be an f32
    :func:`ell_w_table` (the DNA recipe does not compose with the bf16
    ratio chain — curvature is cancellation-sensitive)."""
    wh = _wh_at_nz(x.cols, H, W, w_table)
    whm = jnp.maximum(wh, jnp.asarray(EPS, wh.dtype))
    ratio = x.vals / whm
    r2 = ratio / whm
    k = W.shape[0]
    numers, hesses = [], []
    for c in range(k):
        slab = _slab(W, x.cols, w_table, c)
        numers.append(jnp.sum((ratio * slab).astype(jnp.float32), axis=-1))
        hesses.append(jnp.sum((r2 * slab * slab).astype(jnp.float32),
                              axis=-1))
    numer = jnp.stack(numers, axis=-1)
    hess = jnp.stack(hesses, axis=-1)
    denom = jnp.broadcast_to(W.sum(axis=1)[None, :], H.shape)
    return numer, denom, hess


def ell_kl_w_numer(x: EllMatrix, H, W, bf16_ratio: bool = False,
                   w_table=None):
    """KL W-update numerator ``H^T @ (X/WH)`` via the transpose-side
    gathers (f32 accumulation)."""
    vals, Hc, Wc = _cast_pair(x, H, W, bf16_ratio)
    wh = _wh_at_nz(x.cols, Hc, Wc, w_table)
    ratio = vals / jnp.maximum(wh, jnp.asarray(EPS, wh.dtype))
    return _w_numer(x, ratio, Hc)


def ell_kl_w_stats(x: EllMatrix, H, W, bf16_ratio: bool = False,
                   w_table=None):
    """Full KL W-update statistics: transpose-gather numerator + the
    data-independent column-sum denominator."""
    numer = ell_kl_w_numer(x, H, W, bf16_ratio, w_table)
    denom = jnp.broadcast_to(H.sum(axis=0)[:, None], W.shape)
    return numer, denom


def ell_kl_w_stats_rows(x: EllMatrix, H, W, idx):
    """Sketched KL W-update statistics from a ROW SUBSAMPLE (ISSUE 11,
    the ``sketch`` recipe): numerator ``H[idx].T @ (X[idx]/WH[idx])``
    supported on the sampled rows' nonzeros only, scatter-accumulated
    per stored coordinate — the transpose index set enumerates ALL rows'
    nonzeros and cannot serve a traced subset, so the sketched lane pays
    one (m·w, k)-vector scatter-add instead; sublinear in n, which is
    the point. Denominator: the sampled rows' column sums (numerator and
    denominator MUST come from the same subsample — the MU rate is the
    ratio, so the common n/m scale cancels exactly). Padding entries
    carry value 0 => ratio 0 => exact +0.0 into column 0. f32.

    ``H`` is the FULL usage matrix; ``idx`` a traced (m,) row index
    vector (sampling with replacement is fine — a duplicated row just
    doubles its weight in both statistics)."""
    vals = jnp.take(x.vals, idx, axis=0)                 # (m, w)
    cols = jnp.take(x.cols, idx, axis=0)                 # (m, w)
    H_s = jnp.take(H, idx, axis=0)                       # (m, k)
    k = H.shape[-1]
    wh = _wh_at_nz(cols, H_s, W)
    ratio = vals / jnp.maximum(wh, jnp.asarray(EPS, wh.dtype))
    contrib = (H_s[:, None, :] * ratio[..., None]).astype(jnp.float32)
    numer_t = jnp.zeros((x.g, k), jnp.float32).at[cols.reshape(-1)].add(
        contrib.reshape(-1, k))
    denom = jnp.broadcast_to(H_s.sum(axis=0)[:, None], W.shape)
    return numer_t.T, denom


def _wh_dense(H, W, bf16: bool):
    if bf16:
        wh = jnp.matmul(H.astype(jnp.bfloat16), W.astype(jnp.bfloat16),
                        preferred_element_type=jnp.bfloat16)
        return jnp.maximum(wh, jnp.bfloat16(EPS))
    return jnp.maximum(H @ W, EPS)


def ell_is_h_stats(x: EllMatrix, H, W, bf16_ratio: bool = False,
                   w_table=None):
    """IS (beta=0) H-update statistics — the hybrid form.

    The IS denominator ``(1/WH) @ W^T`` is supported on ALL n x g entries,
    so WH is materialized densely (one MXU/BLAS matmul, as the dense chain
    does); the numerator ``(X/WH^2) @ W^T`` is supported only on X's
    nonzeros and runs as a take_along_axis gather of the dense WH plus the
    per-component table gathers — the dense X buffer and the dense
    X/WH^2 ratio pass are what this saves."""
    bf = bool(bf16_ratio)
    wh = _wh_dense(H, W, bf)
    inv = 1.0 / wh
    Wb = W.astype(jnp.bfloat16) if bf else W
    denom = jnp.matmul(inv, Wb.T, preferred_element_type=jnp.float32)
    inv_nz = jnp.take_along_axis(inv, x.cols, axis=-1, mode="clip")
    vals = x.vals.astype(wh.dtype)
    r2 = vals * inv_nz * inv_nz
    numer = _h_numer(x.cols, r2, Wb, w_table)
    return numer, denom


def ell_is_w_stats(x: EllMatrix, H, W, bf16_ratio: bool = False):
    """IS W-update statistics: dense ``H^T @ (1/WH)`` denominator +
    nonzero-only transpose-gather numerator (f32 accumulation)."""
    bf = bool(bf16_ratio)
    wh = _wh_dense(H, W, bf)
    inv = 1.0 / wh
    Hb = H.astype(jnp.bfloat16) if bf else H
    denom = jnp.matmul(Hb.T, inv, preferred_element_type=jnp.float32)
    inv_nz = jnp.take_along_axis(inv, x.cols, axis=-1, mode="clip")
    vals = x.vals.astype(wh.dtype)
    r2 = vals * inv_nz * inv_nz
    numer = _w_numer(x, r2, Hb)
    return numer, denom


# ---------------------------------------------------------------------------
# objective
# ---------------------------------------------------------------------------

def kl_nz_term(Xp, WHs):
    """Cancellation-safe KL per-element term for entries with X > 0:
    ``X * (u - log1p(u))`` with ``u = WH/X - 1``. Same two regimes as
    :func:`is_per_elem`: near convergence the log1p form keeps the O(u^2)
    terms; when ``WH/X`` underflows f32 (``u`` rounds to exactly -1.0,
    ``log1p(-1) = -inf`` — routinely hit on genuinely sparse data whose
    WH collapses at some nonzeros) the logs are split, which is finite
    and cancellation-free. Shared by the dense objective and
    :func:`ell_beta_err`."""
    ratio = WHs / Xp
    u = ratio - 1.0
    stable = u - jnp.log1p(jnp.maximum(u, -1.0 + EPS))
    tiny = u + jnp.log(Xp) - jnp.log(WHs)
    return Xp * jnp.where(ratio < 1e-6, tiny, stable)


def is_per_elem(Xs, WHs):
    """Cancellation-safe Itakura-Saito per-element divergence
    ``x/wh - log(x/wh) - 1`` for EPS-floored operands.

    Two regimes: near convergence (ratio ~ 1) the ``v - log1p(v)`` form
    keeps the O(v^2) terms f32 cancellation would lose; for near-zero
    ratios (EPS-floored zero counts of a sparse matrix) ``v`` rounds to
    exactly -1.0 in f32 (EPS = 1e-16 is far below f32 epsilon) and
    ``log1p(-1) = -inf`` — there the logs are split instead
    (``log(wh) - log(x)``), which is finite and has no cancellation
    (the operands differ by orders of magnitude by construction).
    Shared by the dense objective (``ops/nmf.py:_beta_div_dense``) and
    the ELL objective below so both report identical finite values on
    sparse data."""
    ratio = Xs / WHs
    v = ratio - 1.0
    stable = v - jnp.log1p(jnp.maximum(v, -1.0 + EPS))
    tiny = v + jnp.log(WHs) - jnp.log(Xs)
    return jnp.where(ratio < 1e-6, tiny, stable)


def ell_beta_err(x: EllMatrix, H, W, beta: float):
    """``D_beta(X || HW)`` from the ELL encoding, f32, matching
    ``ops/nmf.py:_beta_div_dense``'s cancellation-safe per-element forms
    exactly in exact arithmetic.

    beta=1 (KL): the dense sum splits as
    ``sum_{X>0} [X (u - log1p(u)) - WH] + sum_all WH`` with
    ``u = WH/X - 1``; the first term is supported on the nonzeros and
    ``sum_all WH = H.sum(0) . W.sum(1)`` is k-sized — fully sparse.

    beta=0 (IS): the divergence is supported on ALL entries (zero counts
    are EPS-floored, exactly as the dense form does), so WH is evaluated
    densely (the IS updates materialize it anyway) and the nonzero terms
    are corrected via a take_along_axis gather.
    """
    vals = x.vals.astype(jnp.float32)
    if beta == 1.0:
        wh_nz = _wh_at_nz(x.cols, H.astype(jnp.float32),
                          W.astype(jnp.float32))
        total_wh = jnp.sum(H.sum(axis=0) * W.sum(axis=1))
        nz = jnp.where(
            vals > 0,
            kl_nz_term(jnp.maximum(vals, EPS), jnp.maximum(wh_nz, EPS))
            - wh_nz,
            0.0)
        return jnp.sum(nz) + total_wh
    if beta == 0.0:
        WH = jnp.maximum(H @ W, EPS)
        eps = jnp.float32(EPS)
        base = jnp.sum(is_per_elem(eps, WH))
        wh_nz = jnp.take_along_axis(WH, x.cols, axis=-1, mode="clip")
        corr = jnp.where(
            vals > 0,
            is_per_elem(jnp.maximum(vals, EPS), wh_nz)
            - is_per_elem(eps, wh_nz),
            0.0)
        return base + jnp.sum(corr)
    raise NotImplementedError(
        f"ELL objective implements beta in {{1, 0}}, got {beta}")


# ---------------------------------------------------------------------------
# analytic cost hooks (ISSUE 19, obs/costmodel.py)
# ---------------------------------------------------------------------------

def ell_stats_cost(n: int, g: int, k: int, width: int,
                   t_width: int | None = None, beta: float = 1.0) -> dict:
    """Analytic flop/byte cost of ONE ELL KL MU iteration (h_stats +
    w_stats) of the slab kernels above, in XLA ``cost_analysis()``
    accounting on the jnp lane. Useful-work convention: XLA's CPU
    backend sometimes splits wide reductions into vectorized partials
    that add bookkeeping flops; those are not counted here (agreement
    is exact on shapes where the splitting does not engage, within
    ~15% otherwise). Host arithmetic only — no jax import.

    width    ELL row width of the (cells, genes) layout (h side)
    t_width  transposed width for the w side; defaults to the balanced
             estimate ``ceil(width * n / g)`` padded like _pad_width
    """
    n, g, k, w = int(n), int(g), int(k), int(width)
    if t_width is None:
        wt = -(-(w * n) // max(g, 1))
        wt = max(8, -(-wt // 8) * 8)
    else:
        wt = int(t_width)
    f = 4.0
    nw = n * w
    gwt = g * wt
    # h_stats: wh_at_nz (k-term FMA chain: 2k-1 per nz), ratio
    # (maximum + div), numer per component (mul: nw, reduce over w:
    # n*(w-1)), denom W row-sum (k*(g-1))
    h_flops = (nw * (2 * k - 1) + 2 * nw
               + k * (nw + n * (w - 1)) + k * (g - 1))
    # w_stats mirrors on the transposed layout; denom H col-sum
    w_flops = (nw * (2 * k - 1) + 2 * nw
               + k * (gwt + g * (wt - 1)) + (n - 1) * k)
    # bytes: XLA CPU's fusion decisions are shape-dependent, so the two
    # sides use the regime each pinned shape actually lowers to.
    # h side (slab-materialized regime): each of the 2k slab gathers in
    # wh_at_nz/_h_numer costs a slice copy (2*g*f) + gather output
    # (2*nw*f as in+out of the consuming fusion); ratio chain + numer
    # output + denom ride on top. Within 0.1% of cost_analysis at the
    # pinned (512, 256, 9, 0.05) shape.
    h_bytes = (2 * k * (3 * g * f + 2 * nw * f)
               + 3 * nw * f                          # vals,wh -> ratio
               + k * n * f)                          # numer output
    # w side (fused regime, engages for modest t_width): operand +
    # output traffic of the fused transpose-gather program — vals,
    # cols, W, H in; r_flat spill; perm_t/r_t/rows_t gather traffic;
    # numer + denom stats out. Within 2% of cost_analysis at the
    # pinned (256, 512, 9, 0.05) shape.
    w_bytes = (2 * nw * 4                            # vals + cols
               + k * g * f + n * k * f               # W, H operands
               + nw * f                              # r_flat spill
               + 3 * gwt * 4                         # perm_t, r_t, rows_t
               + k * g * f                           # numer output
               + n * k * f + k * g * f)              # denom in + out
    if beta not in (1.0,):
        # the IS (beta=0) lane goes through a hybrid dense-WH path; no
        # calibrated analytic model — report the KL figure as a floor.
        pass
    return {"flops": float(h_flops + w_flops),
            "bytes": float(h_bytes + w_bytes),
            "h_flops": float(h_flops), "w_flops": float(w_flops),
            "h_bytes": float(h_bytes), "w_bytes": float(w_bytes),
            "lane": "ell-jnp"}
