"""Shard-store transport backends: local POSIX vs remote object store.

ROADMAP item 3(b)'s last gap: ``ShardStore`` (``utils/shardstore.py``)
reads slabs with raw ``open``/``np.load`` on joined paths, so
prepare-once-read-anywhere only works over a shared filesystem.
Production atlases live in object stores, where the dominant failure
mode is not a torn file but a flaky network — the distributed-ingest
setting of arXiv 2202.09518 and the data-distribution layer MPI-FAUN
assumes (arXiv 1609.09154). This module is the transport seam:

  * :class:`StoreBackend` — the five-verb contract (``get``/``put``/
    ``exists``/``list``/``delete``) the shard store reads and writes
    through. Digest validation, the manifest-last protocol, and
    torn-read healing all stay ABOVE this seam, unchanged.
  * :class:`LocalBackend` — today's POSIX paths, byte-for-byte: reads
    are plain ``open``, writes land via ``atomic_artifact``. With
    ``CNMF_TPU_STORE_URI`` unset this is the only code that runs.
  * :class:`RemoteBackend` — HTTP GET/PUT/HEAD/DELETE against an
    object-store endpoint (the in-repo ``utils/netstore.py`` fixture
    stands in for GCS). Robustness is the headline: per-operation-class
    timeouts, bounded exponential backoff with DETERMINISTIC jitter
    (chaos runs replay exactly), hedged reads for tail latency
    (``CNMF_TPU_STORE_HEDGE_S``), and a crash-safe read-through local
    slab cache (LRU under ``CNMF_TPU_STORE_CACHE_BYTES``, entries landed
    via ``atomic_artifact`` + sha1 sidecar, revalidated on every hit).

Degradation contract: transient faults heal invisibly (telemetry
``fault`` events, kind ``store_net``); a fully-down remote serves
digest-valid cached objects with a LOUD once-per-run warning; an
object that can neither be fetched nor served from cache raises
:class:`RemoteStoreError` — deliberately a ``RuntimeError`` and NOT an
``OSError``, so it escapes the shard reader's torn-read retry ladder
(those re-reads would hit the same dead network) and propagates to the
resilience ledger / launcher respawn like ``TornShardError`` does.

Stdlib-only (urllib, no jax/numpy) so IO-layer callers import it for
free, matching ``shardstore.py``'s own constraint.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import warnings

from ..obs import metrics as obs_metrics
from ..obs import tracing as obs_tracing
from .anndata_lite import atomic_artifact
from .envknobs import env_float, env_int, env_str

__all__ = [
    "STORE_URI_ENV",
    "STORE_RETRIES_ENV",
    "STORE_BACKOFF_ENV",
    "STORE_TIMEOUT_ENV",
    "STORE_HEDGE_ENV",
    "STORE_CACHE_ENV",
    "RemoteStoreError",
    "StoreBackend",
    "LocalBackend",
    "RemoteBackend",
    "resolve_backend",
    "store_cache_dir",
    "backend_counter_snapshot",
    "backoff_delay",
    "store_retries",
    "store_backoff_s",
    "store_timeout_s",
    "store_hedge_s",
    "store_cache_bytes",
]

STORE_URI_ENV = "CNMF_TPU_STORE_URI"
STORE_RETRIES_ENV = "CNMF_TPU_STORE_RETRIES"
STORE_BACKOFF_ENV = "CNMF_TPU_STORE_BACKOFF_S"
STORE_TIMEOUT_ENV = "CNMF_TPU_STORE_TIMEOUT_S"
STORE_HEDGE_ENV = "CNMF_TPU_STORE_HEDGE_S"
STORE_CACHE_ENV = "CNMF_TPU_STORE_CACHE_BYTES"


class RemoteStoreError(RuntimeError):
    """A remote store operation failed after exhausting its retry budget
    and no digest-valid cached copy could serve it. NOT an ``OSError``:
    the shard reader's torn-read ladder must not burn its disk-reread
    budget against a dead network — this propagates to the resilience
    ledger (kind ``remote_store``) and the launcher respawn instead."""


def store_retries() -> int:
    """Network-transport retry budget per store operation
    (``CNMF_TPU_STORE_RETRIES``, default 3; 0 disables). Distinct from
    the shard-layer ``CNMF_TPU_SHARD_RETRIES``."""
    return env_int(STORE_RETRIES_ENV, 3, lo=0)


def store_backoff_s() -> float:
    """Backoff base seconds (``CNMF_TPU_STORE_BACKOFF_S``, default
    0.05): attempt N waits ``base * 2^(N-1) * (1 + 0.5*jitter)``."""
    return env_float(STORE_BACKOFF_ENV, 0.05, lo=0.0)


def store_timeout_s() -> float:
    """Per-request socket timeout for slab transfers
    (``CNMF_TPU_STORE_TIMEOUT_S``, default 30); metadata operations use
    the tighter ``max(1, timeout/4)``."""
    return env_float(STORE_TIMEOUT_ENV, 30.0, lo=0.001)


def store_hedge_s() -> float:
    """Hedged-read trigger (``CNMF_TPU_STORE_HEDGE_S``): a GET still
    unanswered after this many seconds issues a second identical
    request and the first valid response wins. 0 (default) = off."""
    return env_float(STORE_HEDGE_ENV, 0.0, lo=0.0)


def store_cache_bytes() -> int:
    """Read-through cache budget (``CNMF_TPU_STORE_CACHE_BYTES``,
    default 1 GiB; 0 disables caching entirely)."""
    return env_int(STORE_CACHE_ENV, 1 << 30, lo=0)


def backoff_delay(name: str, attempt: int, base: float | None = None) -> float:
    """Delay before retry ``attempt`` (1-based) of an operation on
    ``name``: exponential in the attempt with a DETERMINISTIC jitter
    derived from ``(name, attempt)`` — different objects decorrelate
    (no thundering herd against a recovering endpoint) while any given
    chaos run replays with identical timing."""
    if base is None:
        base = store_backoff_s()
    seed = hashlib.sha1(("%s:%d" % (name, attempt)).encode()).digest()
    jitter = int.from_bytes(seed[:4], "big") / 2.0 ** 32
    return float(base) * (2.0 ** (attempt - 1)) * (1.0 + 0.5 * jitter)


class _Counters:
    """Thread-safe per-backend operation counters, folded into
    ``StreamStats`` (``parallel/streaming.py``) and the telemetry
    Ingestion table by snapshot-before/delta-after around each
    streaming pass."""

    FIELDS = ("retries", "healed", "hedges", "hedges_won",
              "cache_hits", "cache_misses", "degraded_reads")

    def __init__(self):
        self._lock = threading.Lock()
        for f in self.FIELDS:
            setattr(self, f, 0)

    def bump(self, key: str, n: int = 1):
        with self._lock:
            setattr(self, key, getattr(self, key) + int(n))
        # mirror into the live metrics registry (no-op when the metrics
        # knob is off) — the same numbers a scrape sees mid-run that the
        # post-hoc Ingestion table reports per pass
        obs_metrics.counter_inc("cnmf_store_%s_total" % key, n)

    def snapshot(self) -> dict:
        with self._lock:
            return {f: int(getattr(self, f)) for f in self.FIELDS}


def backend_counter_snapshot(obj):
    """Counter snapshot of a store's backend when it is remote, else
    None — the ``StreamStats.fold_store_counters`` input. Accepts a
    ``ShardStore`` (has ``.backend``) or a backend directly."""
    bk = getattr(obj, "backend", obj)
    if bk is None or getattr(bk, "kind", "local") != "remote":
        return None
    return bk.counters.snapshot()


class StoreBackend:
    """Transport contract the shard store reads/writes through. Object
    names are flat (``manifest.json``, ``names.npz``, ``slab_*.npz``);
    the ``op`` hints (``slab``/``meta``/``manifest``) select the
    timeout class on remote transports and are ignored locally."""

    kind = "abstract"

    def __init__(self):
        self.counters = _Counters()

    def get(self, name, *, op="slab", refresh=False, events=None) -> bytes:
        raise NotImplementedError

    def put(self, name, data, *, op="slab", events=None) -> None:
        raise NotImplementedError

    def exists(self, name, *, events=None) -> bool:
        raise NotImplementedError

    def list(self, *, events=None) -> list:
        raise NotImplementedError

    def delete(self, name, *, events=None) -> None:
        raise NotImplementedError

    def describe(self, name) -> str:
        """Human-readable location of ``name`` for error messages."""
        raise NotImplementedError


class LocalBackend(StoreBackend):
    """Today's POSIX store directory, byte-for-byte: ``get`` is a plain
    read (the shard reader's digest/retry ladder above handles torn
    reads exactly as before), ``put`` lands via ``atomic_artifact``."""

    kind = "local"

    def __init__(self, root: str):
        super().__init__()
        self.root = os.fspath(root)

    def get(self, name, *, op="slab", refresh=False, events=None) -> bytes:
        with open(os.path.join(self.root, name), "rb") as f:
            return f.read()

    def put(self, name, data, *, op="slab", events=None) -> None:
        os.makedirs(self.root, exist_ok=True)
        path = os.path.join(self.root, name)
        with atomic_artifact(path) as tmp:
            with open(tmp, "wb") as f:
                f.write(bytes(data))

    def exists(self, name, *, events=None) -> bool:
        return os.path.exists(os.path.join(self.root, name))

    def list(self, *, events=None) -> list:
        if not os.path.isdir(self.root):
            return []
        return sorted(os.listdir(self.root))

    def delete(self, name, *, events=None) -> None:
        try:
            os.unlink(os.path.join(self.root, name))
        except FileNotFoundError:
            pass

    def describe(self, name) -> str:
        return os.path.join(self.root, name)


# once-per-run degraded-service warning, keyed by endpoint: a down
# remote serving from cache must be LOUD exactly once, not once per slab
_degraded_lock = threading.Lock()
_degraded_warned: set = set()


def _reset_degraded_warnings():
    """Test/smoke hook: re-arm the once-per-run degraded warning."""
    with _degraded_lock:
        _degraded_warned.clear()


class RemoteBackend(StoreBackend):
    """HTTP object-store transport with fault containment (module
    docstring has the full contract). ``base`` is the object prefix URL
    (no trailing slash); ``cache_dir`` hosts the read-through cache
    (None or ``CNMF_TPU_STORE_CACHE_BYTES=0`` disables it)."""

    kind = "remote"

    def __init__(self, base: str, cache_dir: str | None = None):
        super().__init__()
        self.base = base.rstrip("/")
        self.cache_dir = None if cache_dir is None else os.fspath(cache_dir)

    # -- request plumbing ----------------------------------------------

    def _url(self, name) -> str:
        return self.base + "/" + urllib.parse.quote(str(name))

    def _timeout(self, op: str) -> float:
        t = store_timeout_s()
        # metadata (manifest/HEAD/LIST) answers in one RTT — a down
        # remote should be detected at metadata speed, not slab speed
        return t if op == "slab" else min(t, max(1.0, t / 4.0))

    def _request(self, method, name, url, data=None, op="slab") -> bytes:
        from ..runtime import faults

        action = faults.maybe_netfault(op=method.lower(), context=str(name))
        req = urllib.request.Request(url, data=data, method=method)
        if data is not None:
            req.add_header("Content-Type", "application/octet-stream")
        with urllib.request.urlopen(req, timeout=self._timeout(op)) as resp:
            body = resp.read()
        if action == "tear" and body:
            # injected torn response: flip one mid-body byte so the
            # shard reader's content-digest validation must catch it
            torn = bytearray(body)
            torn[len(torn) // 2] ^= 0xFF
            body = bytes(torn)
        return body

    def _emit_fault(self, events, context: dict):
        if events is None:
            return
        try:
            events.emit("fault", kind="store_net", context=context)
        except Exception:
            pass

    def _warn_degraded(self, detail: str):
        with _degraded_lock:
            if self.base in _degraded_warned:
                return
            _degraded_warned.add(self.base)
        warnings.warn(
            "remote store %s is unreachable after retries; DEGRADED to "
            "the local read-through cache (%s). Served objects are "
            "digest-validated, but writes and uncached reads will fail "
            "until the endpoint recovers" % (self.base, detail),
            RuntimeWarning, stacklevel=3)

    def _with_retries(self, fn, *, op, name, events=None):
        retries = store_retries()
        attempt = 0
        while True:
            attempt += 1
            try:
                out = fn()
            except urllib.error.HTTPError as exc:
                # HTTPError FIRST (it is an OSError subclass): 404 is an
                # answer, not a fault — no retry, caller semantics decide
                if exc.code == 404:
                    raise FileNotFoundError(
                        "%s: object %r not found (HTTP 404)"
                        % (self.base, str(name)))
                err = exc
            except (TimeoutError, OSError) as exc:
                err = exc
            else:
                if attempt > 1:
                    # transient fault healed invisibly — count it and
                    # leave telemetry evidence (report: "recovered")
                    self.counters.bump("healed")
                    self._emit_fault(events, {
                        "op": str(op), "object": str(name),
                        "attempt": attempt, "healed": True})
                return out
            self._emit_fault(events, {
                "op": str(op), "object": str(name),
                "attempt": attempt, "error": str(err)})
            if attempt > retries:
                raise RemoteStoreError(
                    "%s: %s %r failed after %d attempt(s): %s — remote "
                    "store unreachable (tune %s / %s, or unset %s to go "
                    "back to local paths)"
                    % (self.base, str(op), str(name), attempt, err,
                       STORE_RETRIES_ENV, STORE_TIMEOUT_ENV,
                       STORE_URI_ENV)) from err
            self.counters.bump("retries")
            time.sleep(backoff_delay(str(name), attempt))

    def _fetch(self, name, op) -> bytes:
        """One GET, hedged: if the primary request is still unanswered
        after ``CNMF_TPU_STORE_HEDGE_S``, race a second identical
        request and take the first completion (on a failure, wait for
        the other — a flaky primary must not waste a healthy hedge).
        Requests run on ephemeral daemon threads; an abandoned loser
        drains into an unreferenced queue and exits — nothing lingers,
        nothing blocks interpreter shutdown."""
        hedge = store_hedge_s()
        url = self._url(name)
        if hedge <= 0.0:
            return self._request("GET", name, url, op=op)
        import queue

        results: queue.Queue = queue.Queue()

        def _run(tag):
            try:
                results.put((tag, True,
                             self._request("GET", name, url, op=op)))
            except BaseException as exc:
                results.put((tag, False, exc))

        threading.Thread(target=_run, args=("primary",),
                         name="cnmf-store-get", daemon=True).start()
        try:
            tag, ok, val = results.get(timeout=hedge)
        except queue.Empty:
            self.counters.bump("hedges")
            threading.Thread(target=_run, args=("hedge",),
                             name="cnmf-store-hedge", daemon=True).start()
            tag, ok, val = results.get()
            if not ok:
                tag2, ok2, val2 = results.get()
                if ok2:
                    tag, ok, val = tag2, ok2, val2
            if ok and tag == "hedge":
                self.counters.bump("hedges_won")
        if not ok:
            raise val
        return val

    # -- the five verbs ------------------------------------------------

    def _cache_on(self) -> bool:
        return self.cache_dir is not None and store_cache_bytes() > 0

    def get(self, name, *, op="slab", refresh=False, events=None) -> bytes:
        """Read-through: a digest-valid cached entry serves without
        touching the network; misses fetch (with retries + hedging) and
        land in the cache. ``refresh=True`` bypasses the cache — the
        shard reader sets it after a digest mismatch, so a poisoned
        cache entry heals from the remote instead of looping."""
        cache_on = self._cache_on()
        if cache_on and not refresh:
            data = self._cache_get(name)
            if data is not None:
                self.counters.bump("cache_hits")
                with _degraded_lock:
                    endpoint_down = self.base in _degraded_warned
                if endpoint_down:
                    # the endpoint already proved unreachable this run:
                    # cache hits are now degraded service, not luck —
                    # the report's "degraded reads" must count them
                    self.counters.bump("degraded_reads")
                return data
            self.counters.bump("cache_misses")
        # store-I/O hop of a sampled batch-run trace (the launcher
        # plants the process context in worker env) — plus the live GET
        # latency histogram
        t_get = time.perf_counter()
        try:
            with obs_tracing.span(
                    events, obs_tracing.child(obs_tracing.process_context()),
                    "store.get", object=str(name), op=str(op)):
                data = self._with_retries(
                    lambda: self._fetch(name, op),
                    op="get", name=name, events=events)
            obs_metrics.observe("cnmf_store_get_ms",
                                (time.perf_counter() - t_get) * 1e3)
        except RemoteStoreError:
            if cache_on and not refresh:
                # a copy may have landed since the miss (another worker
                # shares the cache dir) — last chance before failing
                data = self._cache_get(name)
                if data is not None:
                    self.counters.bump("degraded_reads")
                    self._warn_degraded("read %r from cache" % str(name))
                    self._emit_fault(events, {
                        "op": "get", "object": str(name), "degraded": True})
                    return data
            raise
        if cache_on:
            self._cache_put(name, data)
        return data

    def put(self, name, data, *, op="slab", events=None) -> None:
        """Retried PUT. Deliberately NOT write-through: reads must
        exercise (and be accounted against) the network path, and the
        cache only ever holds bytes the remote actually served."""
        data = bytes(data)
        self._with_retries(
            lambda: self._request("PUT", name, self._url(name),
                                  data=data, op=op),
            op="put", name=name, events=events)

    def exists(self, name, *, events=None) -> bool:
        try:
            self._with_retries(
                lambda: self._request("HEAD", name, self._url(name),
                                      op="meta"),
                op="head", name=name, events=events)
            return True
        except FileNotFoundError:
            return False
        except RemoteStoreError:
            if self._cache_on() and self._cache_get(name) is not None:
                self._warn_degraded("presence of %r from cache" % str(name))
                self._emit_fault(events, {
                    "op": "head", "object": str(name), "degraded": True})
                return True
            raise

    def list(self, *, events=None) -> list:
        try:
            body = self._with_retries(
                lambda: self._request("GET", "list", self.base + "/?list=1",
                                      op="meta"),
                op="list", name="list", events=events)
            return sorted(str(s) for s in json.loads(body.decode("utf-8")))
        except RemoteStoreError:
            if self._cache_on() and os.path.isdir(self.cache_dir):
                names = sorted(
                    urllib.parse.unquote(fn)
                    for fn in os.listdir(self.cache_dir)
                    if not fn.endswith(".sha1") and ".tmp-" not in fn)
                self._warn_degraded("listing cached objects only")
                self._emit_fault(events, {"op": "list", "degraded": True})
                return names
            raise

    def delete(self, name, *, events=None) -> None:
        try:
            self._with_retries(
                lambda: self._request("DELETE", name, self._url(name),
                                      op="meta"),
                op="delete", name=name, events=events)
        except FileNotFoundError:
            pass
        finally:
            self._cache_drop(name)

    def describe(self, name) -> str:
        return self._url(name)

    # -- crash-safe read-through cache ---------------------------------
    #
    # one file per object under cache_dir (URL-quoted name) plus a
    # ``.sha1`` sidecar holding the content digest. Both land via
    # atomic_artifact, so a crash mid-write leaves only pid-suffixed
    # temps (swept by --clean / the fresh-run orphan sweep) — a hit
    # recomputes the sha1 and discards any entry that disagrees with
    # its sidecar (partial write, bit rot, tampering), so the cache can
    # NEVER serve bytes the remote did not once serve.

    def _cache_path(self, name) -> str:
        return os.path.join(self.cache_dir,
                            urllib.parse.quote(str(name), safe=""))

    def _cache_get(self, name):
        path = self._cache_path(name)
        try:
            with open(path, "rb") as f:
                data = f.read()
            with open(path + ".sha1") as f:
                want = f.read().strip()
        except OSError:
            return None
        if hashlib.sha1(data).hexdigest() != want:
            self._cache_drop(name)
            return None
        try:
            os.utime(path)  # LRU recency bump
        except OSError:
            pass
        return data

    def _cache_put(self, name, data: bytes):
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            path = self._cache_path(name)
            with atomic_artifact(path + ".sha1") as tmp:
                with open(tmp, "w") as f:
                    f.write(hashlib.sha1(data).hexdigest())
            with atomic_artifact(path) as tmp:
                with open(tmp, "wb") as f:
                    f.write(data)
            self._evict(keep=os.path.basename(path))
        except OSError:
            # the cache is an optimization: a full/read-only disk must
            # never fail the read that was trying to populate it
            pass

    def _cache_drop(self, name):
        if self.cache_dir is None:
            return
        path = self._cache_path(name)
        for p in (path, path + ".sha1"):
            try:
                os.unlink(p)
            except OSError:
                pass

    def _evict(self, keep: str):
        """LRU sweep to the byte budget (entry bytes; sidecars ride
        along), oldest-read first, never evicting ``keep`` (the entry
        just written must survive its own landing)."""
        budget = store_cache_bytes()
        entries = []
        total = 0
        for fn in os.listdir(self.cache_dir):
            if fn.endswith(".sha1") or ".tmp-" in fn:
                continue
            p = os.path.join(self.cache_dir, fn)
            try:
                st = os.stat(p)
            except OSError:
                continue
            total += st.st_size
            entries.append((st.st_mtime, st.st_size, fn))
        if total <= budget:
            return
        for _, size, fn in sorted(entries):
            if fn == keep:
                continue
            p = os.path.join(self.cache_dir, fn)
            for victim in (p, p + ".sha1"):
                try:
                    os.unlink(victim)
                except OSError:
                    pass
            total -= size
            if total <= budget:
                return


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def store_cache_dir(store_dir) -> str:
    """The read-through cache directory for a store path: beside it,
    ``<store>.cache`` — matched by the launcher ``--clean`` sweep and
    worker 0's fresh-run orphan sweep."""
    return os.fspath(store_dir) + ".cache"


def resolve_backend(store_dir, uri: str | None = None) -> StoreBackend:
    """Backend for ``store_dir`` from the store URI (argument wins, else
    ``CNMF_TPU_STORE_URI``): empty → :class:`LocalBackend` on the path
    itself (byte-for-byte today's behavior); ``file:///base`` relocates
    the store under ``base/<leaf>``; ``http(s)://host[:port]/prefix`` →
    :class:`RemoteBackend` under ``prefix/<leaf>`` with the cache beside
    ``store_dir``. ``<leaf>`` is the store directory's basename, so
    multiple stores (a run's main store, the serving tier's second
    open) namespace apart under one endpoint."""
    store_dir = os.fspath(store_dir)
    raw = env_str(STORE_URI_ENV, "") if uri is None else uri
    raw = (raw or "").strip()
    if not raw:
        return LocalBackend(store_dir)
    parts = urllib.parse.urlsplit(raw)
    scheme = parts.scheme.lower()
    leaf = os.path.basename(os.path.normpath(store_dir)) or "store"
    if scheme == "file":
        return LocalBackend(os.path.join(parts.path or "/", leaf))
    if scheme in ("http", "https"):
        base = raw.rstrip("/") + "/" + urllib.parse.quote(leaf)
        return RemoteBackend(base, cache_dir=store_cache_dir(store_dir))
    raise ValueError(
        "%s=%r: expected empty (local paths), file:///base/dir, or "
        "http(s)://host[:port]/prefix" % (STORE_URI_ENV, raw))
