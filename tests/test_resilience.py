"""Fault-tolerant sweep execution (ISSUE 5): quarantine + reseeded retry,
torn-artifact-proof resume, launcher self-healing, and the fault-injection
harness itself.

The integration tests inject faults through ``CNMF_TPU_FAULT_SPEC``
(runtime/faults.py) — the same deterministic harness the chaos smoke gate
uses — so every recovery path here exercises the production code, not a
mock of it."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pandas as pd
import pytest

from cnmf_torch_tpu import cNMF, load_df_from_npz, save_df_to_npz
from cnmf_torch_tpu.runtime import faults, resilience

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# unit: seed derivation, spec parsing, health grading
# ---------------------------------------------------------------------------

def test_derive_retry_seed_deterministic_and_masked():
    assert resilience.derive_retry_seed(1234567, 1) == (1234567 ^ 1)
    assert resilience.derive_retry_seed(1234567, 2) == (1234567 ^ 2)
    # stays in the ledger's 31-bit seed domain even at the boundary
    assert 0 <= resilience.derive_retry_seed(0x7FFFFFFF, 3) <= 0x7FFFFFFF
    with pytest.raises(ValueError):
        resilience.derive_retry_seed(7, 0)


def test_parse_fault_spec():
    clauses = faults.parse_fault_spec(
        "nonfinite:k=5,iter=2;kill:stage=factorize,worker=1;"
        "torn:artifact=iter_spectra;upload")
    assert [c.kind for c in clauses] == ["nonfinite", "kill", "torn",
                                        "upload"]
    assert clauses[0].params == {"k": 5, "iter": 2}
    assert clauses[1].params == {"stage": "factorize", "worker": 1}
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.parse_fault_spec("explode:k=1")
    with pytest.raises(ValueError, match="key=value"):
        faults.parse_fault_spec("kill:stage")


def test_lane_health_grades_err_latch_and_spectra():
    errs = np.asarray([1.0, np.nan, np.inf, 2.0])
    h = resilience.lane_health(errs)
    assert h.tolist() == [True, False, False, True]
    # the telemetry latch catches a transient nonfinite that recovered
    h2 = resilience.lane_health(errs,
                                nonfinite=[True, False, False, False])
    assert h2.tolist() == [False, False, False, True]
    spectra = np.ones((4, 2, 3), np.float32)
    spectra[3, 1, 2] = np.nan
    h3 = resilience.lane_health(errs, spectra=spectra)
    assert h3.tolist() == [True, False, False, False]


def test_maybe_poison_lanes_matches_and_copies(monkeypatch):
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=4,iter=1")
    spectra = np.ones((3, 2, 2), np.float32)
    errs = np.ones(3)
    sp2, er2 = faults.maybe_poison_lanes(4, [0, 1, 2], spectra, errs)
    assert np.isnan(sp2[1]).all() and np.isnan(er2[1])
    assert np.isfinite(spectra).all() and np.isfinite(errs).all()  # copies
    # wrong K, wrong attempt: untouched (and same objects back)
    sp3, _ = faults.maybe_poison_lanes(5, [0, 1, 2], spectra, errs)
    assert sp3 is spectra
    sp4, _ = faults.maybe_poison_lanes(4, [0, 1, 2], spectra, errs,
                                       attempt=1)
    assert sp4 is spectra
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)
    sp5, _ = faults.maybe_poison_lanes(4, [0, 1, 2], spectra, errs)
    assert sp5 is spectra


def test_maybe_poison_lanes_honors_controls(tmp_path, monkeypatch):
    """`after`/`limit`/`once` apply to the nonfinite hook like every
    other fault hook — a chaos spec meant to poison one sweep must not
    poison every matching sweep in every process."""
    spectra = np.ones((2, 2, 2), np.float32)
    errs = np.ones(2)
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=3,after=1")
    first, _ = faults.maybe_poison_lanes(3, [0, 1], spectra, errs)
    assert first is spectra  # hit 1 skipped by after=1
    second, _ = faults.maybe_poison_lanes(3, [0, 1], spectra, errs)
    assert np.isnan(second).all()
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=3,limit=1")
    a, _ = faults.maybe_poison_lanes(3, [0, 1], spectra, errs)
    b, _ = faults.maybe_poison_lanes(3, [0, 1], spectra, errs)
    assert np.isnan(a).all() and b is spectra
    sentinel = tmp_path / "poison.done"
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       f"nonfinite:k=3,once={sentinel}")
    c, _ = faults.maybe_poison_lanes(3, [0, 1], spectra, errs)
    d, _ = faults.maybe_poison_lanes(3, [0, 1], spectra, errs)
    assert np.isnan(c).all() and d is spectra and sentinel.exists()


def test_upload_fault_raises_from_staging(monkeypatch):
    from cnmf_torch_tpu.parallel.streaming import stream_to_device

    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "upload:context=stream_to_device")
    with pytest.raises(RuntimeError, match="injected fault: upload"):
        stream_to_device(np.ones((4, 4), np.float32))
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)
    assert np.asarray(
        stream_to_device(np.ones((2, 2), np.float32))).shape == (2, 2)


def test_maybe_kill_sigkills_once_per_sentinel(tmp_path, monkeypatch):
    """The kill fault is a real SIGKILL, and the `once` sentinel ensures a
    respawned worker does not re-kill itself (run in a subprocess — the
    harness must not take the test runner down)."""
    script = tmp_path / "killme.py"
    script.write_text(
        "import sys\n"
        f"sys.path.insert(0, {REPO_ROOT!r})\n"
        "from cnmf_torch_tpu.runtime import faults\n"
        "faults.maybe_kill('factorize', 0)\n"
        "print('alive')\n")
    sentinel = tmp_path / "kill.done"
    env = dict(os.environ, CNMF_TPU_FAULT_SPEC=
               f"kill:stage=factorize,worker=0,once={sentinel}")
    p1 = subprocess.run([sys.executable, str(script)], env=env,
                        capture_output=True, timeout=120)
    assert p1.returncode == -signal.SIGKILL, p1.stderr.decode()
    assert sentinel.exists()
    p2 = subprocess.run([sys.executable, str(script)], env=env,
                        capture_output=True, timeout=120)
    assert p2.returncode == 0 and b"alive" in p2.stdout


# ---------------------------------------------------------------------------
# unit: atomic writes + torn-artifact detection
# ---------------------------------------------------------------------------

def test_save_df_to_npz_atomic_failure_preserves_old_file(tmp_path,
                                                          monkeypatch):
    fn = tmp_path / "a.df.npz"
    df = pd.DataFrame(np.ones((2, 3)), index=["a", "b"],
                      columns=["x", "y", "z"])
    save_df_to_npz(df, fn, compress=False)
    before = fn.read_bytes()

    def boom(fh, **kwargs):
        fh.write(b"partial garbage")
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)
    with pytest.raises(OSError, match="disk full"):
        save_df_to_npz(df * 2, fn, compress=False)
    monkeypatch.undo()
    # the reader-visible file is the OLD complete artifact, untouched,
    # and the failed temp file is cleaned up
    assert fn.read_bytes() == before
    assert not [f for f in os.listdir(tmp_path) if ".tmp-" in f]
    pd.testing.assert_frame_equal(load_df_from_npz(fn), df)


def test_write_h5ad_atomic_no_temp_leftovers(tmp_path):
    from cnmf_torch_tpu.utils.anndata_lite import (AnnDataLite, read_h5ad,
                                                   write_h5ad)

    fn = tmp_path / "m.h5ad"
    write_h5ad(str(fn), AnnDataLite(np.ones((3, 4), np.float64)))
    assert not [f for f in os.listdir(tmp_path) if ".tmp-" in f]
    assert read_h5ad(str(fn)).shape == (3, 4)


def test_probe_and_load_detect_torn_artifacts(tmp_path):
    fn = str(tmp_path / "s.df.npz")
    df = pd.DataFrame(np.ones((3, 5)), index=np.arange(1, 4),
                      columns=[f"g{j}" for j in range(5)])
    save_df_to_npz(df, fn, compress=False)
    assert resilience.probe_spectra_file(fn, k=3, n_genes=5) is None
    # wrong expectations are torn-equivalent
    assert "component rows" in resilience.probe_spectra_file(fn, k=4)
    assert "gene columns" in resilience.probe_spectra_file(fn, k=3,
                                                           n_genes=9)
    # nonfinite values must not be trusted either
    dfn = df.copy()
    dfn.iloc[1, 2] = np.nan
    save_df_to_npz(dfn, fn, compress=False)
    assert "nonfinite" in resilience.probe_spectra_file(fn, k=3)
    # a truncated zip (SIGKILL mid-write on the pre-atomic layer)
    save_df_to_npz(df, fn, compress=False)
    size = os.path.getsize(fn)
    with open(fn, "r+b") as f:
        f.truncate(size // 3)
    assert "unreadable" in resilience.probe_spectra_file(fn, k=3)
    with pytest.raises(resilience.TornArtifactError):
        resilience.load_spectra_checked(fn, k=3)
    assert resilience.probe_spectra_file(str(tmp_path / "no.npz")) \
        == "missing"


def test_torn_injection_hits_matching_artifact_once(tmp_path, monkeypatch):
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "torn:artifact=spectra,limit=1")
    df = pd.DataFrame(np.ones((2, 3)), index=[1, 2], columns=list("abc"))
    fn1 = str(tmp_path / "x.spectra.k_3.iter_0.df.npz")
    fn2 = str(tmp_path / "x.spectra.k_3.iter_1.df.npz")
    save_df_to_npz(df, fn1, compress=False)
    save_df_to_npz(df, fn2, compress=False)
    assert resilience.probe_spectra_file(fn1, k=2) is not None  # torn
    assert resilience.probe_spectra_file(fn2, k=2) is None      # limit=1


# ---------------------------------------------------------------------------
# combine validation + quarantine exclusion
# ---------------------------------------------------------------------------

def _fabricate_run(tmp_path, name, k=3, n_iter=4, g=20):
    """A run directory with a hand-built ledger + replicate artifacts, so
    combine-layer behavior is testable without a factorize pass."""
    obj = cNMF(output_dir=str(tmp_path), name=name)
    rp = pd.DataFrame({
        "n_components": [k] * n_iter, "iter": list(range(n_iter)),
        "nmf_seed": [100 + i for i in range(n_iter)],
        "completed": [False] * n_iter})
    save_df_to_npz(rp, obj.paths["nmf_replicate_parameters"])
    genes = [f"g{j}" for j in range(g)]
    with open(obj.paths["nmf_genes_list"], "w") as f:
        f.write("\n".join(genes))
    rng = np.random.default_rng(0)
    for it in range(n_iter):
        df = pd.DataFrame(rng.random((k, g)), index=np.arange(1, k + 1),
                          columns=genes)
        save_df_to_npz(df, obj.paths["iter_spectra"] % (k, it),
                       compress=False)
    return obj


def test_combine_treats_corrupt_like_missing_under_skip(tmp_path):
    obj = _fabricate_run(tmp_path, "torncomb")
    fn = obj.paths["iter_spectra"] % (3, 2)
    with open(fn, "r+b") as f:
        f.truncate(os.path.getsize(fn) // 3)
    # without the flag: a clear torn-artifact error, not a zipfile
    # traceback from deep inside pandas
    with pytest.raises(resilience.TornArtifactError,
                       match="skip_missing_files"):
        obj.combine_nmf(3, skip_missing_files=False)
    merged = obj.combine_nmf(3, skip_missing_files=True)
    assert merged.shape == (3 * 3, 20)  # torn replicate dropped
    assert not any(lbl.startswith("iter2_") for lbl in merged.index)


def test_combine_skips_quarantined_without_flag(tmp_path):
    obj = _fabricate_run(tmp_path, "quarcomb")
    os.remove(obj.paths["iter_spectra"] % (3, 1))
    with open(obj.paths["resilience_ledger"] % 0, "w") as f:
        json.dump({"schema": 1, "retries": [],
                   "quarantined": [{"k": 3, "iter": 1, "seed": 101,
                                    "attempts": 2},
                                   {"k": 3, "iter": 0, "seed": 100,
                                    "attempts": 2}]}, f)
    # quarantined replicates are deliberately absent: no skip flag
    # needed. But a quarantine record only suppresses the invalid
    # artifact it explains — iter 0's artifact is VALID on disk (a stale
    # record from an older run / different worker topology), so it is
    # trusted and included.
    merged = obj.combine_nmf(3)
    assert merged.shape == (3 * 3, 20)
    assert any(lbl.startswith("iter0_") for lbl in merged.index)
    assert not any(lbl.startswith("iter1_") for lbl in merged.index)


# ---------------------------------------------------------------------------
# launcher self-healing (unit, monkeypatched worker command)
# ---------------------------------------------------------------------------

def test_sweep_stale_ledgers_removes_out_of_range_workers(tmp_path):
    obj = _fabricate_run(tmp_path, "sweepled")
    for w in (0, 3):
        with open(obj.paths["resilience_ledger"] % w, "w") as f:
            json.dump({"schema": 1, "retries": [], "quarantined": []}, f)
    resilience.sweep_stale_ledgers(obj.paths["resilience_ledger"], 2)
    assert os.path.exists(obj.paths["resilience_ledger"] % 0)  # in range
    assert not os.path.exists(obj.paths["resilience_ledger"] % 3)


def test_launcher_worker_timeout_kills_and_reports(tmp_path, monkeypatch):
    from cnmf_torch_tpu import launcher

    monkeypatch.setattr(
        launcher, "_worker_cmd",
        lambda od, nm, extra: [sys.executable, "-c",
                               "import time; time.sleep(60)"])
    monkeypatch.setenv("CNMF_TPU_WORKER_TIMEOUT", "0.5")
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "0")
    t0 = time.monotonic()
    with pytest.warns(RuntimeWarning, match="CNMF_TPU_WORKER_TIMEOUT"):
        failed, unhealthy = launcher._run_subprocess_workers(
            str(tmp_path), "x", 1, [], dict(os.environ))
    assert failed == {0} and unhealthy == set()
    assert time.monotonic() - t0 < 30  # the hung worker was killed


def test_launcher_respawns_dead_worker_with_resume_flag(tmp_path,
                                                        monkeypatch):
    from cnmf_torch_tpu import launcher

    flaky = tmp_path / "flaky.py"
    sentinel = tmp_path / "first_attempt"
    flaky.write_text(
        "import os, sys\n"
        f"p = {str(sentinel)!r}\n"
        "if os.path.exists(p):\n"
        "    sys.exit(0)\n"
        "open(p, 'w').close()\n"
        "sys.exit(5)\n")  # generic crash (3 is the reserved unhealthy code)
    spawned = []

    def fake_cmd(od, nm, extra):
        spawned.append(list(extra))
        return [sys.executable, str(flaky)]

    monkeypatch.setattr(launcher, "_worker_cmd", fake_cmd)
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "1")
    monkeypatch.setenv("CNMF_TPU_WORKER_BACKOFF_S", "0.05")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    with pytest.warns(RuntimeWarning, match="respawning onto its "
                                            "unfinished ledger shard"):
        failed, unhealthy = launcher._run_subprocess_workers(
            str(tmp_path), "x", 1, [], dict(os.environ))
    assert failed == set() and unhealthy == set()  # respawn succeeded
    assert len(spawned) == 2
    assert "--skip-completed-runs" not in spawned[0]
    assert "--skip-completed-runs" in spawned[1]  # resumes its own shard


def test_launcher_unhealthy_exit_is_fatal_not_respawned(tmp_path,
                                                        monkeypatch):
    """A worker below the min-healthy-frac floor exits with the distinct
    code: the launcher must neither respawn it (the derived retry seeds
    are deterministic — it would fail identically) nor degrade around it
    with skip-missing combine."""
    from cnmf_torch_tpu import launcher

    spawned = []

    def fake_cmd(od, nm, extra):
        spawned.append(list(extra))
        return [sys.executable, "-c",
                f"import sys; sys.exit({resilience.UNHEALTHY_EXIT_CODE})"]

    monkeypatch.setattr(launcher, "_worker_cmd", fake_cmd)
    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "2")
    monkeypatch.delenv("CNMF_TPU_WORKER_TIMEOUT", raising=False)
    failed, unhealthy = launcher._run_subprocess_workers(
        str(tmp_path), "x", 1, [], dict(os.environ))
    assert unhealthy == {0} and failed == set()
    assert len(spawned) == 1  # no respawn burned on a policy failure


# ---------------------------------------------------------------------------
# integration: quarantine + reseeded retry through factorize
# ---------------------------------------------------------------------------

def _prepare_mini(tmp_path, name, components=(3,), n_iter=3, seed=1):
    counts = np.random.default_rng(2).binomial(
        40, 0.02, size=(60, 100)).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    counts_fn = str(tmp_path / f"{name}_counts.df.npz")
    save_df_to_npz(df, counts_fn)
    obj = cNMF(output_dir=str(tmp_path), name=name)
    obj.prepare(counts_fn, components=list(components), n_iter=n_iter,
                seed=seed, num_highvar_genes=50, batch_size=64,
                max_NMF_iter=50)
    return obj, counts_fn


def test_factorize_retries_nonfinite_lane_with_derived_seed(tmp_path,
                                                            monkeypatch):
    """An injected NaN lane is detected by the always-on health pass,
    rerun with seed XOR 1, recorded in the resilience ledger, and emitted
    as schema-valid fault telemetry — and the retried artifact lands."""
    obj, _ = _prepare_mini(tmp_path, "retry")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=3,iter=1")
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    obj.factorize()
    assert os.path.exists(obj.paths["iter_spectra"] % (3, 1))
    with open(obj.paths["resilience_ledger"] % 0) as f:
        ledger = json.load(f)
    assert ledger["quarantined"] == []
    (rec,) = ledger["retries"]
    assert rec["k"] == 3 and rec["iter"] == 1 and rec["healthy"]
    assert rec["attempt"] == 1
    assert rec["derived_seed"] == resilience.derive_retry_seed(
        rec["seed"], 1) == (rec["seed"] ^ 1)
    # the retried lane's artifact is a genuinely different draw from the
    # poisoned seed's would-have-been spectra, and is finite
    vals = load_df_from_npz(obj.paths["iter_spectra"] % (3, 1)).values
    assert np.isfinite(vals).all()

    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    ev_path = os.path.join(str(tmp_path), "retry", "cnmf_tmp",
                           "retry.events.jsonl")
    validate_events_file(ev_path)  # fault events are schema-valid
    kinds = [e["kind"] for e in read_events(ev_path) if e["t"] == "fault"]
    assert "nonfinite_replicate" in kinds and "retry" in kinds
    # consensus proceeds on the healthy + recovered set
    merged = obj.combine_nmf(3)
    assert merged.shape[0] == 3 * 3


def test_factorize_quarantines_and_degrades_above_floor(tmp_path,
                                                        monkeypatch):
    obj, _ = _prepare_mini(tmp_path, "quar")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=3,iter=0")
    monkeypatch.setenv(resilience.MAX_RETRIES_ENV, "0")
    monkeypatch.setenv(resilience.MIN_HEALTHY_FRAC_ENV, "0.5")
    with pytest.warns(RuntimeWarning, match="quarantined"):
        obj.factorize()
    assert not os.path.exists(obj.paths["iter_spectra"] % (3, 0))
    with open(obj.paths["resilience_ledger"] % 0) as f:
        ledger = json.load(f)
    assert [(q["k"], q["iter"]) for q in ledger["quarantined"]] == [(3, 0)]
    # combine excludes the quarantined replicate WITHOUT any skip flag
    merged = obj.combine_nmf(3)
    assert merged.shape[0] == 2 * 3
    # resume is idempotent after an accepted degraded run: the
    # quarantined lane is deliberately absent, so a resume has nothing
    # to do and the quarantine ledger survives
    obj.factorize(skip_completed_runs=True)
    assert os.path.exists(obj.paths["resilience_ledger"] % 0)
    assert merged.shape[0] == obj.combine_nmf(3).shape[0]
    # raising CNMF_TPU_MAX_RETRIES un-finalizes the quarantine: the lane
    # reruns on resume (clean now), heals, and the ledger clears — the
    # remedy the quarantine warning prescribes actually works
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)
    monkeypatch.setenv(resilience.MAX_RETRIES_ENV, "2")
    obj.factorize(skip_completed_runs=True)
    assert not os.path.exists(obj.paths["resilience_ledger"] % 0)
    assert obj.combine_nmf(3).shape[0] == 3 * 3
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=3,iter=0")
    monkeypatch.setenv(resilience.MAX_RETRIES_ENV, "0")
    with pytest.warns(RuntimeWarning, match="quarantined"):
        obj.factorize()  # restore the quarantined state
    monkeypatch.delenv(faults.FAULT_SPEC_ENV)
    monkeypatch.delenv(resilience.MAX_RETRIES_ENV)
    # a clean re-run supersedes the quarantine: the stale ledger is
    # removed, so combine must not silently drop the now-healthy lane
    obj.factorize()
    assert not os.path.exists(obj.paths["resilience_ledger"] % 0)
    merged = obj.combine_nmf(3)
    assert merged.shape[0] == 3 * 3


def test_factorize_hard_fails_below_min_healthy_frac(tmp_path, monkeypatch):
    obj, _ = _prepare_mini(tmp_path, "floor")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=3,iter=0")
    monkeypatch.setenv(resilience.MAX_RETRIES_ENV, "0")
    # default floor 0.8 > 2/3 healthy -> loud failure, not silent degrade
    monkeypatch.delenv(resilience.MIN_HEALTHY_FRAC_ENV, raising=False)
    with pytest.warns(RuntimeWarning, match="quarantined"):
        with pytest.raises(resilience.UnhealthySweepError,
                           match="too few healthy replicates"):
            obj.factorize()
    # the CLI maps the floor violation to the distinct exit code the
    # launcher treats as fatal (no respawn, no skip-missing fallback)
    from cnmf_torch_tpu import cli

    with pytest.warns(RuntimeWarning, match="quarantined"):
        with pytest.raises(SystemExit) as exc_info:
            cli.main(["factorize", "--output-dir", str(tmp_path),
                      "--name", "floor"])
    assert exc_info.value.code == resilience.UNHEALTHY_EXIT_CODE
    # resume must not bypass the floor: the quarantined lane carries into
    # the accounting, so even the nothing-to-rerun path re-fails instead
    # of exiting 0 and letting combine run on the below-floor sweep
    with pytest.raises(resilience.UnhealthySweepError):
        obj.factorize(skip_completed_runs=True)


def test_resume_credits_existing_healthy_replicates(tmp_path, monkeypatch):
    """The min-healthy-frac floor is judged against the K's FULL replicate
    count: a resume that reruns 1 of 4 lanes and quarantines it is 3/4
    healthy (degrade), not 0/1 (spurious hard failure)."""
    obj, _ = _prepare_mini(tmp_path, "credit", n_iter=4)
    obj.factorize(batched=False)
    os.remove(obj.paths["iter_spectra"] % (3, 2))
    monkeypatch.setenv(faults.FAULT_SPEC_ENV, "nonfinite:k=3,iter=2")
    monkeypatch.setenv(resilience.MAX_RETRIES_ENV, "0")
    monkeypatch.setenv(resilience.MIN_HEALTHY_FRAC_ENV, "0.7")
    with pytest.warns(RuntimeWarning, match="quarantined"):
        obj.factorize(batched=False, skip_completed_runs=True)
    with open(obj.paths["resilience_ledger"] % 0) as f:
        ledger = json.load(f)
    assert [(q["k"], q["iter"]) for q in ledger["quarantined"]] == [(3, 2)]
    merged = obj.combine_nmf(3)
    assert merged.shape[0] == 3 * 3


def test_resume_reruns_torn_artifact(tmp_path):
    """--skip-completed-runs must validate, not just stat: a truncated
    artifact is rerun (atomically overwritten), not trusted."""
    obj, _ = _prepare_mini(tmp_path, "tornres")
    obj.factorize()
    fn = obj.paths["iter_spectra"] % (3, 1)
    good = load_df_from_npz(fn).values
    with open(fn, "r+b") as f:
        f.truncate(os.path.getsize(fn) // 3)
    with pytest.warns(RuntimeWarning, match="failed validation"):
        obj.factorize(skip_completed_runs=True)
    repaired = load_df_from_npz(fn).values
    assert np.isfinite(repaired).all()
    # the whole-K rerun reproduces the uninterrupted sweep bit-for-bit
    np.testing.assert_array_equal(repaired, good)


def test_guard_records_shard_faults_in_ledger(tmp_path):
    """Exhausted shard uploads / stalls flow into the PR-4 resilience
    ledger (ISSUE 6): record_shard_fault books the fault, emits a
    schema-valid telemetry event, and finalize persists it alongside the
    quarantine records."""
    class Rec:
        def __init__(self):
            self.events = []

        def emit(self, t, **kw):
            self.events.append(dict(kw, t=t))

    rec = Rec()
    ledger_path = str(tmp_path / "resilience.w0.json")
    guard = resilience.ReplicateGuard(events=rec, ledger_path=ledger_path)
    guard.record_shard_fault("shard_stall",
                             {"stage": "rowshard_stage_x", "error": "hung"})
    guard.finalize()
    with open(ledger_path) as f:
        ledger = json.load(f)
    assert ledger["shard_faults"] == [
        {"stage": "rowshard_stage_x", "error": "hung",
         "kind": "shard_stall"}]
    (ev,) = rec.events
    assert ev["t"] == "fault" and ev["kind"] == "shard_stall"

    from cnmf_torch_tpu.utils.telemetry import validate_event

    validate_event({"v": 1, "t": "fault", "ts": 0.0, "kind": ev["kind"],
                    "context": ev["context"]})


def test_stall_clause_parses_and_limits():
    """The new `stall` fault kind parses like the others (seconds stays a
    float-able string) and defaults to one injection per clause."""
    (clause,) = faults.parse_fault_spec("stall:context=stream,seconds=0.05")
    assert clause.kind == "stall"
    assert clause.params["context"] == "stream"
    assert float(clause.params["seconds"]) == 0.05

    import time

    os.environ[faults.FAULT_SPEC_ENV] = "stall:context=abc,seconds=0.05"
    try:
        t0 = time.monotonic()
        assert faults.maybe_stall(context="xyz") == 0.0   # no context match
        assert faults.maybe_stall(context="abc123") == 0.05
        assert faults.maybe_stall(context="abc123") == 0.0  # limit 1
        assert time.monotonic() - t0 < 1.0
    finally:
        del os.environ[faults.FAULT_SPEC_ENV]


# ---------------------------------------------------------------------------
# integration: kill–resume parity through the launcher
# ---------------------------------------------------------------------------

def test_kill_resume_parity_end_to_end(tmp_path, monkeypatch):
    """SIGKILL a subprocess-engine worker mid-factorize (fault harness),
    let the launcher respawn it onto its unfinished shard, and assert the
    resumed run's merged spectra AND consensus artifacts match an
    uninterrupted run bit-for-bit (sweep-granular resume keeps batch
    composition identical)."""
    from cnmf_torch_tpu.launcher import run_pipeline

    counts = np.random.default_rng(1).binomial(
        40, 0.02, size=(60, 100)).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, counts_fn)

    monkeypatch.setenv("CNMF_TPU_WORKER_RESPAWNS", "2")
    monkeypatch.setenv("CNMF_TPU_WORKER_BACKOFF_S", "0.1")
    common = dict(components=[3, 4], n_iter=3, total_workers=1, seed=4,
                  numgenes=50, k_selection=False)
    run_pipeline(counts_fn, str(tmp_path), "uninterrupted",
                 env_extra={"CNMF_SIM_CPU_DEVICES": "2"}, **common)

    sentinel = tmp_path / "kill.done"
    run_pipeline(counts_fn, str(tmp_path), "killed",
                 env_extra={"CNMF_SIM_CPU_DEVICES": "2",
                            "CNMF_TPU_FAULT_SPEC":
                            "kill:stage=factorize,worker=0,"
                            f"once={sentinel}"},
                 **common)
    assert sentinel.exists()  # the SIGKILL fired in the first worker

    for k in (3, 4):
        a = load_df_from_npz(os.path.join(
            str(tmp_path), "uninterrupted", "cnmf_tmp",
            f"uninterrupted.spectra.k_{k}.merged.df.npz"))
        b = load_df_from_npz(os.path.join(
            str(tmp_path), "killed", "cnmf_tmp",
            f"killed.spectra.k_{k}.merged.df.npz"))
        np.testing.assert_array_equal(a.values, b.values)
        assert list(a.index) == list(b.index)

    # consensus over the resumed artifacts is bit-identical too
    outs = []
    for name in ("uninterrupted", "killed"):
        obj = cNMF(output_dir=str(tmp_path), name=name)
        # local_neighborhood_size widened: 9 merged spectra at k=3 give
        # int(0.3 * 9 / 3) = 0 neighbors under the default
        obj.consensus(3, density_threshold=2.0,
                      local_neighborhood_size=0.7, show_clustering=False,
                      build_ref=False)
        outs.append({key: load_df_from_npz(
            obj.paths[key] % (3, "2_0")).values
            for key in ("consensus_spectra", "consensus_usages")})
    for key in outs[0]:
        np.testing.assert_array_equal(outs[0][key], outs[1][key])
