import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu.ops import (
    beta_divergence,
    beta_loss_to_float,
    fit_h,
    nndsvd_init,
    run_nmf,
)
from cnmf_torch_tpu.ops.nmf import init_factors, nmf_fit_batch


def _synthetic(n=120, g=80, k=5, seed=0, noise=0.01):
    rng = np.random.default_rng(seed)
    H = rng.gamma(2.0, 1.0, size=(n, k)).astype(np.float32)
    W = rng.gamma(2.0, 1.0, size=(k, g)).astype(np.float32)
    X = H @ W + noise * rng.random((n, g)).astype(np.float32)
    return X, H, W


def test_beta_loss_names():
    assert beta_loss_to_float("frobenius") == 2.0
    assert beta_loss_to_float("kullback-leibler") == 1.0
    assert beta_loss_to_float("itakura-saito") == 0.0
    assert beta_loss_to_float(1.5) == 1.5
    with pytest.raises(ValueError):
        beta_loss_to_float("nope")


def test_beta_divergence_trace_identity_matches_dense():
    X, H, W = _synthetic()
    d_trace = float(beta_divergence(jnp.asarray(X), jnp.asarray(H), jnp.asarray(W), beta=2.0))
    d_dense = 0.5 * np.sum((X - H @ W) ** 2)
    np.testing.assert_allclose(d_trace, d_dense, rtol=1e-3)


def test_beta_divergence_matches_sklearn():
    from sklearn.decomposition._nmf import _beta_divergence as sk_beta

    X, H, W = _synthetic()
    for beta in (2.0, 1.0, 0.0):
        ours = float(beta_divergence(jnp.asarray(X), jnp.asarray(H), jnp.asarray(W), beta=beta))
        # sklearn's frobenius convention is also 0.5 * ||.||^2_F via square_root=False
        theirs = sk_beta(X.astype(np.float64), H.astype(np.float64), W.astype(np.float64), beta)
        np.testing.assert_allclose(ours, theirs, rtol=5e-3)


@pytest.mark.parametrize("beta", [2.0, 1.0, 0.0])
def test_mu_monotone_decrease(beta):
    X, _, _ = _synthetic(noise=0.2)
    Xj = jnp.asarray(X)
    key = jax.random.key(0)
    H0, W0 = init_factors(Xj, 5, "random", key)
    errs = [float(beta_divergence(Xj, H0, W0, beta=beta))]
    H, W = H0, W0
    from cnmf_torch_tpu.ops.nmf import _update_H, _update_W

    for _ in range(25):
        H = _update_H(Xj, H, W, beta, 0.0, 0.0)
        W = _update_W(Xj, H, W, beta, 0.0, 0.0)
        errs.append(float(beta_divergence(Xj, H, W, beta=beta)))
    errs = np.array(errs)
    # allow tiny fp32 wiggle; MU is monotone in exact arithmetic
    assert np.all(np.diff(errs) <= np.abs(errs[:-1]) * 1e-4 + 1e-5)
    assert errs[-1] < 0.5 * errs[0]


@pytest.mark.parametrize("mode", ["batch", "online"])
@pytest.mark.parametrize("beta_loss", ["frobenius", "kullback-leibler"])
def test_run_nmf_recovers_low_rank(mode, beta_loss):
    X, _, _ = _synthetic(n=150, g=60, k=4, noise=0.0)
    # stochastic-MU online KL needs more passes than block-coordinate
    # frobenius to reach the same residual (slow tail of KL MU updates).
    # Noiseless exact recovery also wants the TIGHT inner tolerance: the
    # default coarse-to-fine schedule targets noisy count matrices (where
    # it is both faster and better-converged, see resolve_online_schedule);
    # on exact low-rank data its loose floor plateaus above this test's
    # recovery bar, so the knob is pinned here.
    n_passes = 200 if beta_loss == "kullback-leibler" else 40
    H, W, err = run_nmf(X, n_components=4, beta_loss=beta_loss, mode=mode,
                        tol=1e-6, random_state=7, online_chunk_size=64,
                        n_passes=n_passes, batch_max_iter=400,
                        online_h_tol=1e-3)
    assert H.shape == (150, 4)
    assert W.shape == (4, 60)
    assert (H >= 0).all() and (W >= 0).all()
    rel = np.linalg.norm(X - H @ W) / np.linalg.norm(X)
    assert rel < 0.05


def test_run_nmf_comparable_to_sklearn():
    from sklearn.decomposition import NMF

    X, _, _ = _synthetic(n=100, g=50, k=6, noise=0.05)
    H, W, err = run_nmf(X, n_components=6, mode="batch", tol=1e-6,
                        batch_max_iter=600, random_state=3)
    ours = np.linalg.norm(X - H @ W)

    sk = NMF(n_components=6, solver="mu", init="random", tol=1e-6,
             max_iter=600, random_state=3)
    Hs = sk.fit_transform(X)
    theirs = np.linalg.norm(X - Hs @ sk.components_)
    assert ours <= theirs * 1.05  # within 5% of sklearn's final residual


def test_run_nmf_sparse_input_and_seed_determinism():
    X, _, _ = _synthetic(noise=0.1)
    Xs = sp.csr_matrix(np.where(X > np.median(X), X, 0))
    H1, W1, e1 = run_nmf(Xs, n_components=3, random_state=11, mode="online",
                         online_chunk_size=50)
    H2, W2, e2 = run_nmf(Xs, n_components=3, random_state=11, mode="online",
                         online_chunk_size=50)
    np.testing.assert_array_equal(W1, W2)
    H3, _, _ = run_nmf(Xs, n_components=3, random_state=12, mode="online",
                       online_chunk_size=50)
    assert not np.allclose(H1, H3)


def test_run_nmf_l2_regularization_shrinks_spectra():
    X, _, _ = _synthetic(noise=0.1)
    _, W0, _ = run_nmf(X, n_components=4, random_state=0, mode="batch")
    _, W1, _ = run_nmf(X, n_components=4, random_state=0, mode="batch",
                       alpha_W=5.0, l1_ratio_W=0.0)
    assert np.linalg.norm(W1) < np.linalg.norm(W0)


def test_nndsvd_init_quality():
    X, _, _ = _synthetic(n=90, g=70, k=5, noise=0.0)
    H, W = nndsvd_init(jnp.asarray(X), 5, variant="nndsvda")
    H, W = np.asarray(H), np.asarray(W)
    assert (H >= 0).all() and (W >= 0).all()
    base = np.linalg.norm(X - X.mean())
    assert np.linalg.norm(X - H @ W) < np.linalg.norm(X)
    # nndsvd init should beat the error of a random init before any updates
    Hr, Wr = init_factors(jnp.asarray(X), 5, "random", jax.random.key(0))
    assert (np.linalg.norm(X - H @ W)
            < np.linalg.norm(X - np.asarray(Hr) @ np.asarray(Wr)))


def test_nndsvd_gram_rank_deficient_no_blowup():
    """k > rank(X): clipped eigenvalues must NOT seed ~1e10-scale factors
    (X@V noise / EPS). Rank-overflow components zero out so the seeded fill
    takes over, mirroring the full-SVD path."""
    from cnmf_torch_tpu.ops.nmf import nndsvd_init_gram

    rng = np.random.default_rng(4)
    # exactly rank-2 nonnegative matrix; ask for k=5
    X = (rng.random((40, 2)) @ rng.random((2, 30))).astype(np.float32)
    H, W = nndsvd_init_gram(jnp.asarray(X), 5, variant="nndsvdar",
                            key=jax.random.key(0))
    H, W = np.asarray(H), np.asarray(W)
    assert np.isfinite(H).all() and np.isfinite(W).all()
    assert H.max() < 100 * max(X.max(), 1.0)
    assert W.max() < 100 * max(X.max(), 1.0)
    # overflow components carry the small seeded fill, not zeros (absorbing
    # under MU) and not noise-driven garbage
    assert (H > 0).any() and (W > 0).any()


def test_run_nmf_nndsvd_end_to_end():
    X, _, _ = _synthetic(n=80, g=40, k=3, noise=0.0)
    H, W, err = run_nmf(X, n_components=3, init="nndsvd", mode="batch", tol=1e-6)
    rel = np.linalg.norm(X - H @ W) / np.linalg.norm(X)
    assert rel < 0.05


def test_fit_h_matches_nnls_solution():
    # with W fixed and frobenius loss the H subproblem is convex; the chunked
    # MU solver should approach scipy's per-row NNLS solution
    import scipy.optimize

    X, _, Wtrue = _synthetic(n=40, g=30, k=4, noise=0.0)
    H = fit_h(X, Wtrue, chunk_size=16, chunk_max_iter=2000, h_tol=1e-6)
    expected = np.stack([
        scipy.optimize.nnls(Wtrue.T, X[i])[0] for i in range(X.shape[0])
    ])
    np.testing.assert_allclose(H, expected, rtol=0.05, atol=0.05)


def test_fit_h_one_pass_semantics_and_init_clamp():
    X, Htrue, Wtrue = _synthetic(n=30, g=20, k=3, noise=0.0)
    # negative entries in H_init must be clamped to 0 (cnmf.py:345)
    H_init = Htrue.copy()
    H_init[0, 0] = -5.0
    H = fit_h(X, Wtrue, H_init=H_init, chunk_size=30, chunk_max_iter=500, h_tol=1e-5)
    assert (H >= 0).all()
    # zeros are absorbing under MU: the clamped entry stays exactly 0
    # (same behavior as the reference's torch H-solver, cnmf.py:345, 372)
    assert H[0, 0] == 0.0
    rel = np.linalg.norm(X[1:] - H[1:] @ Wtrue) / np.linalg.norm(X[1:])
    assert rel < 0.02


def test_vmapped_replicates_differ_and_converge():
    # the replicate axis: one compiled program, many seeds
    X, _, _ = _synthetic(n=60, g=40, k=4, noise=0.05)
    Xj = jnp.asarray(X)
    keys = jax.random.split(jax.random.key(0), 6)
    inits = [init_factors(Xj, 4, "random", k) for k in keys]
    H0 = jnp.stack([h for h, _ in inits])
    W0 = jnp.stack([w for _, w in inits])
    fit = jax.vmap(lambda h, w: nmf_fit_batch(Xj, h, w, beta=2.0, tol=1e-5,
                                              max_iter=300))
    H, W, errs = fit(H0, W0)
    assert W.shape == (6, 4, 40)
    base = 0.5 * np.sum((X - X.mean()) ** 2)
    assert np.all(np.asarray(errs) < 0.1 * base)
    # different seeds land in (generally) different local optima
    assert not np.allclose(np.asarray(W[0]), np.asarray(W[1]))


@pytest.mark.parametrize("beta_loss", ["kullback-leibler", "itakura-saito"])
def test_online_schedule_default_matches_tight_inner_quality(beta_loss):
    """The beta != 2 online default is a LOOSE inner tolerance with more W
    passes (ops/nmf.py: resolve_online_schedule) — measured 49x faster on
    TPU than tight inner solves. This pins the quality half of that trade:
    the default schedule's final objective must not be worse than the tight
    (h_tol=1e-3, 20-pass) schedule's by more than 5%."""
    from cnmf_torch_tpu.ops.nmf import resolve_online_schedule

    beta = beta_loss_to_float(beta_loss)
    h_tol, n_passes, h_tol_start = resolve_online_schedule(beta)
    assert (h_tol, n_passes, h_tol_start) == (1e-2, 60, 0.1)
    # beta=2 keeps the 20-pass cap with a CONSTANT 3e-3 inner tolerance
    # (measured faster end-to-end than coarse-to-fine for the cheap
    # k-sized inner solves); beta!=2 defaults are coarse-to-fine; pinned
    # knobs always run constant
    assert resolve_online_schedule(2.0) == (3e-3, 20, None)
    assert resolve_online_schedule(2.0, 1e-3) == (1e-3, 20, None)

    X, _, _ = _synthetic(n=200, g=80, k=4, noise=0.05)
    _, _, err_default = run_nmf(X, n_components=4, beta_loss=beta_loss,
                                mode="online", random_state=3,
                                online_chunk_size=64)
    _, _, err_tight = run_nmf(X, n_components=4, beta_loss=beta_loss,
                              mode="online", random_state=3,
                              online_chunk_size=64, online_h_tol=1e-3,
                              n_passes=20)
    assert np.isfinite(err_default) and np.isfinite(err_tight)
    assert err_default <= err_tight * 1.05


def test_bundled_batch_solver_matches_vmapped():
    """nmf_fit_batch_bundled packs replicate bundles into ~128-wide MXU
    contractions; the masked cross-replicate Gram terms are exact zeros
    (a single packed update is bit-identical at production shapes on TPU),
    but XLA picks shape-dependent contraction tilings, so across a full
    solve the pinned contract is tight element-wise agreement plus
    identical freeze/stopping behavior."""
    from cnmf_torch_tpu.ops.nmf import (init_factors, nmf_fit_batch,
                                        nmf_fit_batch_bundled)

    X, _, _ = _synthetic(n=120, g=80, k=4, noise=0.1)
    Xj = jnp.asarray(X)
    R, k = 11, 5  # R deliberately NOT a bundle multiple (pads internally)
    inits = [init_factors(Xj, k, "random", jax.random.key(s))
             for s in range(R)]
    H0 = jnp.stack([h for h, _ in inits])
    W0 = jnp.stack([w for _, w in inits])

    Hv, Wv, ev = jax.vmap(
        lambda h, w: nmf_fit_batch(Xj, h, w, beta=2.0, tol=1e-4,
                                   max_iter=60))(H0, W0)
    Hb, Wb, eb = nmf_fit_batch_bundled(Xj, H0, W0, tol=1e-4, max_iter=60)
    assert Hb.shape == (R, 120, k) and Wb.shape == (R, k, 80)
    np.testing.assert_allclose(np.asarray(Hv), np.asarray(Hb),
                               rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Wv), np.asarray(Wb),
                               rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ev), np.asarray(eb), rtol=1e-5)


def test_halsvar_solver():
    """algo='halsvar' (nmf-torch's HALS family, SURVEY §2.3 row 1):
    converges on the Frobenius objective to at least MU quality, and its
    contract guards reject the combinations it doesn't cover."""
    X, _, _ = _synthetic(n=100, g=60, k=4, noise=0.02)
    H, W, err = run_nmf(X, n_components=4, algo="halsvar", mode="batch",
                        tol=1e-6, batch_max_iter=400, random_state=5)
    assert (H >= 0).all() and (W >= 0).all()
    rel = np.linalg.norm(X - H @ W) / np.linalg.norm(X)
    assert rel < 0.05
    _, _, err_mu = run_nmf(X, n_components=4, algo="mu", mode="batch",
                           tol=1e-6, batch_max_iter=400, random_state=5)
    assert err <= err_mu * 1.05  # HALS at least matches MU's optimum

    # L2 on W shrinks spectra under HALS too
    _, W_reg, _ = run_nmf(X, n_components=4, algo="halsvar", mode="batch",
                          alpha_W=5.0, l1_ratio_W=0.0, random_state=5)
    assert np.linalg.norm(W_reg) < np.linalg.norm(W)

    with pytest.raises(ValueError):
        run_nmf(X, 4, algo="halsvar", beta_loss="kullback-leibler",
                mode="batch")
    with pytest.raises(ValueError):
        run_nmf(X, 4, algo="halsvar", beta_loss="kullback-leibler",
                mode="online")
    with pytest.raises(NotImplementedError):
        run_nmf(X, 4, algo="bpp")


def test_halsvar_online_matches_batch_objective():
    """Online-mode HALS (VERDICT r4 item 7, completing nmf-torch's solver
    matrix minus NNLS-BPP): per-chunk HALS usage sweeps with accumulated
    (A, B) statistics must reach the batch HALS objective on a fixture
    small enough for both to converge, and must beat/match online MU."""
    X, _, _ = _synthetic(n=160, g=60, k=4, noise=0.02)
    X = X / X.std(axis=0, ddof=1)  # prepare()'s varnorm scaling
    # one online W update per pass vs 400 batch sweeps: compare at an
    # explicit pass budget generous enough for both online solvers
    kw = dict(online_chunk_size=64, tol=1e-7, n_passes=120, random_state=5)
    H, W, err = run_nmf(X, n_components=4, algo="halsvar", mode="online",
                        **kw)
    assert (H >= 0).all() and (W >= 0).all()
    assert H.shape == (160, 4) and W.shape == (4, 60)
    _, _, err_batch = run_nmf(X, n_components=4, algo="halsvar",
                              mode="batch", tol=1e-6, batch_max_iter=400,
                              random_state=5)
    assert np.isfinite(err) and err <= err_batch * 1.10
    _, _, err_mu = run_nmf(X, n_components=4, algo="mu", mode="online", **kw)
    assert err <= err_mu * 1.05

    # determinism across calls
    H2, W2, err2 = run_nmf(X, n_components=4, algo="halsvar", mode="online",
                           **kw)
    np.testing.assert_array_equal(H, H2)
    assert err == err2


def test_run_nmf_fp_precision_contract():
    """nmf-torch's ``fp_precision`` kwarg (cnmf.py:757-771 surface): 'float'
    is the default fp32 path, 'double' runs the batch solve genuinely in
    float64 under x64, and anything else is rejected loudly instead of
    silently ignored."""
    X, _, _ = _synthetic(n=80, g=50, k=4, noise=0.02)
    H, W, err = run_nmf(X, 4, mode="batch", fp_precision="double",
                        batch_max_iter=150, random_state=7)
    assert H.dtype == np.float64 and W.dtype == np.float64
    Hf, Wf, err_f = run_nmf(X, 4, mode="batch", fp_precision="float",
                            batch_max_iter=150, random_state=7)
    assert Hf.dtype == np.float32
    # same seed/schedule: the double solve tracks the float one closely but
    # is a genuinely different precision (exact equality would mean the
    # kwarg is still ignored)
    assert abs(err - err_f) / err_f < 1e-3
    assert err != err_f
    # x64 mode must not leak out of the call
    assert jnp.asarray(1.0).dtype == jnp.float32

    Hd, Wd, _ = run_nmf(X, 4, mode="batch", algo="halsvar",
                        fp_precision="double", batch_max_iter=100,
                        random_state=7)
    assert Hd.dtype == np.float64 and Wd.dtype == np.float64

    with pytest.raises(NotImplementedError):
        run_nmf(X, 4, mode="online", fp_precision="double")
    with pytest.raises(ValueError):
        run_nmf(X, 4, fp_precision="half")


def test_fit_h_k_pad_matches_unpadded():
    """fit_h's packed entry (k_pad): zero-padded W rows and the flat-prefix
    uniform init must reproduce the per-K solve in the real columns and
    return exact-zero padded columns internally (sliced off)."""
    X, _, _ = _synthetic(n=150, g=70, k=5, noise=0.02)
    W = np.random.default_rng(3).gamma(1.0, 1.0, size=(5, 70)).astype(
        np.float32) + 0.05
    for beta in (2.0, 1.0):
        want = fit_h(X, W, chunk_size=64, chunk_max_iter=200, beta=beta)
        got = fit_h(X, W, chunk_size=64, chunk_max_iter=200, beta=beta,
                    k_pad=9)
        assert got.shape == want.shape == (150, 5)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-6)
    # k_pad == k is the identity configuration
    same = fit_h(X, W, chunk_size=64, chunk_max_iter=200, k_pad=5)
    np.testing.assert_array_equal(same, fit_h(X, W, chunk_size=64,
                                              chunk_max_iter=200))
    with pytest.raises(ValueError):
        fit_h(X, W, k_pad=3)
