"""Pipelined staging engine (parallel/streaming.py): parity against direct
device_put, bounded in-flight depth, the serial depth=1 fallback, pooled
slab-buffer reuse, and the StageTimer throughput columns.

Everything runs on the simulated multi-device CPU mesh from conftest
(``--xla_force_host_platform_device_count``), so multi-device round-robin
staging is exercised without hardware.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp
from jax.sharding import Mesh

import cnmf_torch_tpu.parallel.streaming as streaming
from cnmf_torch_tpu.parallel.streaming import (
    SlabBufferPool,
    StreamStats,
    nnz_bucket,
    run_pipeline,
    stream_put_leaves,
    stream_to_device,
)


@pytest.fixture()
def mesh():
    return Mesh(np.asarray(jax.devices()[:4]), ("cells",))


def _skewed_csr(n=97, g=31, seed=5):
    """A CSR with one pathologically dense row block, many empty rows, and
    a ragged tail — the slab-skew shape the bucketing exists for."""
    rng = np.random.default_rng(seed)
    X = sp.random(n, g, density=0.08, random_state=int(seed),
                  format="lil")
    X[3, :] = rng.random(g) + 0.5          # dense row -> skewed slab nnz
    X[n - 1, :] = 0.0                      # empty last row (ragged shard)
    X[n // 2, :] = 0.0                     # empty middle row
    return sp.csr_matrix(X).astype(np.float32)


# ---------------------------------------------------------------------------
# engine primitives
# ---------------------------------------------------------------------------

def test_run_pipeline_commits_in_order_and_bounds_depth():
    seen, in_flight, max_in_flight = [], [0], [0]
    import threading

    lock = threading.Lock()

    def prep(i):
        with lock:
            in_flight[0] += 1
            max_in_flight[0] = max(max_in_flight[0], in_flight[0])
        return i * i

    def commit(i, payload):
        with lock:
            in_flight[0] -= 1
        seen.append((i, payload))

    run_pipeline(range(20), prep, commit, depth=3, threads=2)
    assert seen == [(i, i * i) for i in range(20)]
    assert max_in_flight[0] <= 3


def test_run_pipeline_serial_fallbacks():
    for kw in ({"depth": 1, "threads": 4}, {"depth": 8, "threads": 0}):
        seen = []
        run_pipeline(range(5), lambda i: -i, lambda i, p: seen.append(p),
                     **kw)
        assert seen == [0, -1, -2, -3, -4]


def test_run_pipeline_propagates_prep_errors():
    def prep(i):
        if i == 3:
            raise RuntimeError("boom")
        return i

    with pytest.raises(RuntimeError, match="boom"):
        run_pipeline(range(8), prep, lambda i, p: None, depth=2, threads=2)


def test_nnz_bucket():
    assert nnz_bucket(0, 10_000) == 1024          # floor
    assert nnz_bucket(1025, 10_000) == 2048       # next power of two
    assert nnz_bucket(900_000, 10_000) == 10_000  # capped at global max
    assert nnz_bucket(5, 100) == 100              # cap below floor

def test_slab_buffer_pool_zeroes_stale_tail():
    pool = SlabBufferPool()
    b = pool.take((8,), np.float32)
    SlabBufferPool.fill(b, np.array([1, 2, 3, 4, 5], np.float32))
    pool.give(b)
    b2 = pool.take((8,), np.float32)
    assert b2 is b  # actually reused
    out = SlabBufferPool.fill(b2, np.array([9, 9], np.float32))
    np.testing.assert_array_equal(out, [9, 9, 0, 0, 0, 0, 0, 0])
    assert pool.allocated == 1


def test_stream_knobs_env(monkeypatch):
    monkeypatch.setenv(streaming.THREADS_ENV, "3")
    monkeypatch.setenv(streaming.DEPTH_ENV, "7")
    assert streaming.stream_threads() == 3
    assert streaming.stream_depth() == 7
    # bytes budget clamps depth
    monkeypatch.setenv(streaming.BYTES_ENV, str(100))
    assert streaming.stream_depth(slab_bytes=60) == 1
    monkeypatch.delenv(streaming.DEPTH_ENV)
    monkeypatch.setenv(streaming.BYTES_ENV, str(1 << 40))
    assert streaming.stream_depth(slab_bytes=1) == 7  # 2 x threads + 1


def test_stream_knob_validation(monkeypatch):
    """Bad knob values reject at parse time with a one-line message naming
    the knob — not a confusing downstream error (ISSUE 6 satellite)."""
    cases = [
        (streaming.DEPTH_ENV, "0", streaming.stream_depth),
        (streaming.DEPTH_ENV, "soon", streaming.stream_depth),
        (streaming.THREADS_ENV, "-1", streaming.stream_threads),
        (streaming.THREADS_ENV, "many", streaming.stream_threads),
        (streaming.SHARD_RETRIES_ENV, "-2", streaming.shard_retries),
        (streaming.STALL_ENV, "-1", streaming.stream_stall_s),
        (streaming.STALL_ENV, "later", streaming.stream_stall_s),
    ]
    for env, val, fn in cases:
        monkeypatch.setenv(env, val)
        with pytest.raises(ValueError, match=env):
            fn()
        monkeypatch.delenv(env)
    monkeypatch.setenv(streaming.BYTES_ENV, "lots")
    with pytest.raises(ValueError, match=streaming.BYTES_ENV):
        streaming.stream_depth(slab_bytes=100)
    monkeypatch.delenv(streaming.BYTES_ENV)
    # valid settings still parse (0 threads = serial is legal; depth 1 =
    # serial is legal)
    monkeypatch.setenv(streaming.THREADS_ENV, "0")
    assert streaming.stream_threads() == 0
    monkeypatch.setenv(streaming.DEPTH_ENV, "1")
    assert streaming.stream_depth() == 1


# ---------------------------------------------------------------------------
# shard-granular retry + stall watchdog (ISSUE 6)
# ---------------------------------------------------------------------------

class _Events:
    def __init__(self):
        self.events = []

    def emit(self, t, **kw):
        self.events.append(dict(kw, t=t))


def test_shard_retry_recovers_transient_failures(monkeypatch):
    monkeypatch.setenv(streaming.SHARD_BACKOFF_ENV, "0")
    fails = {"n": 0}

    def prep(i):
        if i == 3 and fails["n"] < 2:
            fails["n"] += 1
            raise RuntimeError("transient wire fault")
        return i

    seen = []
    events = _Events()
    with pytest.warns(RuntimeWarning, match="retrying"):
        run_pipeline(range(6), prep, lambda i, p: seen.append(p),
                     depth=2, threads=2, fault_context="test", events=events)
    assert seen == list(range(6))
    retry_kinds = [e["kind"] for e in events.events if e["t"] == "fault"]
    assert retry_kinds == ["shard_retry", "shard_retry"]


def test_shard_retry_exhaustion_raises(monkeypatch):
    from cnmf_torch_tpu.parallel.streaming import ShardUploadError

    monkeypatch.setenv(streaming.SHARD_BACKOFF_ENV, "0")
    monkeypatch.setenv(streaming.SHARD_RETRIES_ENV, "1")

    def prep(i):
        if i == 1:
            raise RuntimeError("permanent")
        return i

    events = _Events()
    with pytest.warns(RuntimeWarning):
        with pytest.raises(ShardUploadError,
                           match=streaming.SHARD_RETRIES_ENV):
            run_pipeline(range(4), prep, lambda i, p: None, depth=2,
                         threads=2, fault_context="test", events=events)
    kinds = [e["kind"] for e in events.events if e["t"] == "fault"]
    assert kinds == ["shard_retry", "shard_upload_failed"]
    # serial fallback applies the same retry policy
    monkeypatch.setenv(streaming.SHARD_RETRIES_ENV, "0")
    with pytest.raises(ShardUploadError):
        run_pipeline(range(4), prep, lambda i, p: None, depth=1, threads=0)


def test_stall_watchdog_converts_hang(monkeypatch):
    import time

    from cnmf_torch_tpu.parallel.streaming import ShardStallError

    monkeypatch.setenv(streaming.STALL_ENV, "0.3")

    def prep(i):
        if i == 0:
            time.sleep(2.0)
        return i

    events = _Events()
    t0 = time.monotonic()
    with pytest.raises(ShardStallError, match=streaming.STALL_ENV):
        run_pipeline(range(4), prep, lambda i, p: None, depth=2, threads=2,
                     fault_context="test", events=events)
    assert time.monotonic() - t0 < 1.5   # failed at the watchdog, not the hang
    assert any(e["t"] == "fault" and e["kind"] == "shard_stall"
               for e in events.events)


def test_stall_watchdog_excludes_retry_backoff(monkeypatch):
    """The two containment knobs compose: per-attempt heartbeats (with the
    backoff window stamped forward) keep legitimate retry/backoff time out
    of the stall budget, so a slab that recovers via retries is never
    misreported as hung even when its total retry time exceeds
    CNMF_TPU_STREAM_STALL_S."""
    import time

    monkeypatch.setenv(streaming.STALL_ENV, "0.6")
    monkeypatch.setenv(streaming.SHARD_RETRIES_ENV, "2")
    monkeypatch.setenv(streaming.SHARD_BACKOFF_ENV, "0.4")
    fails = {"n": 0}

    def prep(i):
        if i == 1 and fails["n"] < 2:
            fails["n"] += 1
            time.sleep(0.3)   # slow attempt + 0.4/0.8s backoffs: ~2.2s total
            raise RuntimeError("transient")
        return i

    seen = []
    with pytest.warns(RuntimeWarning):
        run_pipeline(range(4), prep, lambda i, p: seen.append(p),
                     depth=2, threads=2, fault_context="t")
    assert seen == [0, 1, 2, 3]


def test_abandoned_worker_skips_fresh_prep_after_stall(monkeypatch):
    """A worker thread that wakes from a hang after the stall watchdog
    abandoned the pipeline must not start fresh prep work: nothing will
    ever commit it, and it races whatever re-stage replaced the call.
    (The injected ``stall`` clause sleeps before prep, so the waking
    zombie used to densify its slab seconds later — inside whichever
    unrelated test happened to be running by then.)"""
    import time

    from cnmf_torch_tpu.parallel.streaming import ShardStallError

    monkeypatch.setenv("CNMF_TPU_FAULT_SPEC",
                       "stall:context=zomb,seconds=0.8")
    monkeypatch.setenv(streaming.STALL_ENV, "0.2")
    ran = []

    def prep(i):
        ran.append(i)
        return i

    with pytest.raises(ShardStallError):
        run_pipeline(range(4), prep, lambda i, p: None, depth=2, threads=1,
                     fault_context="zomb")
    n_at_abort = len(ran)
    time.sleep(1.2)   # past the injected wake
    assert len(ran) == n_at_abort, "abandoned worker started fresh prep"


def test_stall_fault_injection_through_staging(mesh, monkeypatch):
    """The `stall` chaos clause (runtime/faults.py) fires inside a real
    staging call and the watchdog converts it into ShardStallError within
    its deadline; clearing the spec restores normal staging."""
    import time

    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh
    from cnmf_torch_tpu.parallel.streaming import ShardStallError

    monkeypatch.setattr(streaming, "DENSIFY_SLAB_ROWS", 8)
    monkeypatch.setenv("CNMF_TPU_FAULT_SPEC", "stall:context=stream,seconds=3")
    monkeypatch.setenv(streaming.STALL_ENV, "0.3")
    monkeypatch.setenv(streaming.THREADS_ENV, "2")
    X = _skewed_csr(n=64, g=16, seed=2)
    t0 = time.monotonic()
    with pytest.raises(ShardStallError):
        stream_rows_to_mesh(X, mesh, "cells")
    assert time.monotonic() - t0 < 2.0
    monkeypatch.delenv("CNMF_TPU_FAULT_SPEC")
    monkeypatch.delenv(streaming.STALL_ENV)
    Xd, pad = stream_rows_to_mesh(X, mesh, "cells")
    np.testing.assert_array_equal(np.asarray(Xd)[:64], X.toarray())


# ---------------------------------------------------------------------------
# staged-array parity (bit-exact vs direct device_put)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("transport", ["csr", "dense"])
def test_stream_csr_sharded_parity_skewed_and_ragged(mesh, monkeypatch,
                                                     transport):
    # tiny slabs force multi-slab shards, skew forces mixed nnz buckets
    # (csr transport) / many slab densifies (dense transport)
    monkeypatch.setenv(streaming.TRANSPORT_ENV, transport)
    monkeypatch.setattr(streaming, "DENSIFY_SLAB_ROWS", 5)
    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh

    X = _skewed_csr()
    stats = StreamStats()
    Xd, pad = stream_rows_to_mesh(X, mesh, "cells", stats=stats)
    want = np.vstack([X.toarray(),
                      np.zeros((pad, X.shape[1]), np.float32)])
    np.testing.assert_array_equal(np.asarray(Xd), want)
    assert stats.slabs > 4 and stats.nbytes > 0
    assert stats.wall_s > 0


def test_stream_dense_sharded_parity(mesh, monkeypatch):
    monkeypatch.setattr(streaming, "DENSIFY_SLAB_ROWS", 7)
    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh

    X = np.random.default_rng(0).random((53, 12)).astype(np.float64)
    Xd, pad = stream_rows_to_mesh(X, mesh, "cells")
    want = np.vstack([X.astype(np.float32),
                      np.zeros((pad, 12), np.float32)])
    np.testing.assert_array_equal(np.asarray(Xd), want)


def test_stream_parity_depth1_serial_path(mesh, monkeypatch):
    """depth=1 must be the exact serial fallback — same bits, no threads."""
    monkeypatch.setenv(streaming.DEPTH_ENV, "1")
    monkeypatch.setattr(streaming, "DENSIFY_SLAB_ROWS", 5)
    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh

    X = _skewed_csr(seed=7)
    Xd, pad = stream_rows_to_mesh(X, mesh, "cells")
    want = np.vstack([X.toarray(),
                      np.zeros((pad, X.shape[1]), np.float32)])
    np.testing.assert_array_equal(np.asarray(Xd), want)


def test_stream_ell_parity(mesh):
    from cnmf_torch_tpu.ops.sparse import ell_to_dense
    from cnmf_torch_tpu.parallel.rowshard import stream_ell_to_mesh

    X = _skewed_csr(n=41, g=17, seed=9)
    stats = StreamStats()
    E, pad = stream_ell_to_mesh(X, mesh, "cells", stats=stats)
    got = ell_to_dense(
        type(E)(np.asarray(E.vals), np.asarray(E.cols), E.g, None, None))
    np.testing.assert_array_equal(got[:41], X.toarray())
    assert not got[41:].any()
    assert stats.nbytes > 0 and stats.slabs == 4


def test_stream_ell_depth1_matches_pipelined(mesh, monkeypatch):
    from cnmf_torch_tpu.parallel.rowshard import stream_ell_to_mesh

    X = _skewed_csr(n=37, g=13, seed=11)
    E1, _ = stream_ell_to_mesh(X, mesh, "cells")
    monkeypatch.setenv(streaming.DEPTH_ENV, "1")
    E2, _ = stream_ell_to_mesh(X, mesh, "cells")
    for a, b in [(E1.vals, E2.vals), (E1.cols, E2.cols),
                 (E1.rows_t, E2.rows_t), (E1.perm_t, E2.perm_t)]:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_stream_to_device_csr_transport_no_host_densify(monkeypatch):
    """On the csr transport (accelerators) the single-device staging path
    (cNMF._stage_dense, replicate-sweep staging) never calls toarray —
    densification happens on device."""
    monkeypatch.setenv(streaming.TRANSPORT_ENV, "csr")
    seen = []
    orig = sp.csr_matrix.toarray

    def spy(self, *a, **kw):
        seen.append(self.shape)
        return orig(self, *a, **kw)

    monkeypatch.setattr(sp.csr_matrix, "toarray", spy)
    monkeypatch.setattr(streaming, "DENSIFY_SLAB_ROWS", 9)
    X = _skewed_csr(n=61, g=19, seed=3)
    Xd = stream_to_device(X)
    assert not seen, f"host densify happened: {seen}"
    np.testing.assert_array_equal(np.asarray(Xd), X.toarray())
    assert Xd.shape == (61, 19) and Xd.dtype == jnp.float32


def test_stream_dense_transport_slab_bounded(monkeypatch):
    """The host slab-densify transport (auto on CPU backends) never
    materializes the full matrix — every toarray is slab-sized."""
    monkeypatch.setenv(streaming.TRANSPORT_ENV, "dense")
    monkeypatch.setattr(streaming, "DENSIFY_SLAB_ROWS", 9)
    seen = []
    orig = sp.csr_matrix.toarray

    def spy(self, *a, **kw):
        seen.append(self.shape)
        return orig(self, *a, **kw)

    monkeypatch.setattr(sp.csr_matrix, "toarray", spy)
    X = _skewed_csr(n=61, g=19, seed=3)
    Xd = stream_to_device(X)
    assert seen and all(r <= 9 for r, _ in seen), seen
    np.testing.assert_array_equal(np.asarray(Xd), orig(X))


def test_csr_transport_selection(monkeypatch):
    cpu = jax.devices()  # simulated mesh devices are the cpu backend
    assert streaming._csr_transport(cpu) == "dense"
    monkeypatch.setenv(streaming.TRANSPORT_ENV, "csr")
    assert streaming._csr_transport(cpu) == "csr"
    monkeypatch.setenv(streaming.TRANSPORT_ENV, "dense")
    assert streaming._csr_transport(cpu) == "dense"


def test_stream_to_device_dense_parity():
    X = np.random.default_rng(2).random((30, 9))
    Xd = stream_to_device(X)
    np.testing.assert_array_equal(np.asarray(Xd), X.astype(np.float32))


def test_stream_put_leaves_order_and_placement():
    arrs = [np.arange(6, dtype=np.float32).reshape(2, 3),
            np.arange(4, dtype=np.int32)]
    out = stream_put_leaves(arrs, None)
    assert all(isinstance(d, jax.Array) for d in out)
    np.testing.assert_array_equal(np.asarray(out[0]), arrs[0])
    np.testing.assert_array_equal(np.asarray(out[1]), arrs[1])


def test_ell_device_put_streams_leaves():
    from cnmf_torch_tpu.ops.sparse import csr_to_ell, ell_device_put

    X = _skewed_csr(n=23, g=11, seed=13)
    E = csr_to_ell(X)
    Ed = ell_device_put(E)
    for host, dev in [(E.vals, Ed.vals), (E.cols, Ed.cols),
                      (E.rows_t, Ed.rows_t), (E.perm_t, Ed.perm_t)]:
        assert isinstance(dev, jax.Array)
        np.testing.assert_array_equal(np.asarray(dev), np.asarray(host))


# ---------------------------------------------------------------------------
# instrumentation
# ---------------------------------------------------------------------------

def test_stream_stats_overlap_fraction():
    s = StreamStats()
    s.add(host_prep_s=1.0, h2d_s=1.0, device_s=1.0)
    s.wall_s = 3.0
    assert s.overlap_fraction == 0.0          # fully serial
    s.wall_s = 1.0
    assert s.overlap_fraction == pytest.approx(2.0 / 3.0)  # perfect overlap
    assert StreamStats().overlap_fraction == 0.0


def test_stage_timer_bytes_columns(tmp_path):
    from cnmf_torch_tpu.utils.profiling import StageTimer

    tsv = os.path.join(tmp_path, "t.timings.tsv")
    t = StageTimer(tsv)
    with t.stage("upload", nbytes=2_000_000_000):
        pass
    t.record("stream/h2d", 2.0, nbytes=4_000_000_000, slabs=3)
    t.record("stream/host_prep", 0.5)
    with open(tsv) as f:
        header = f.readline().strip().split("\t")
        rows = [ln.strip("\n").split("\t") for ln in f]
    assert header[:4] == ["stage", "wall_seconds", "bytes", "gb_per_s"]
    by_name = {r[0]: r for r in rows}
    assert by_name["stream/h2d"][2] == "4000000000"
    assert float(by_name["stream/h2d"][3]) == pytest.approx(2.0)
    assert by_name["stream/host_prep"][2] == ""      # no bytes -> blank
    assert "slabs=3" in by_name["stream/h2d"][6]
    # the bench parser contract: columns [:2] are (stage, wall_seconds)
    for r in rows:
        float(r[1])


def test_stream_stats_record_to_timer(tmp_path):
    from cnmf_torch_tpu.utils.profiling import StageTimer

    s = StreamStats()
    s.add(host_prep_s=0.2, h2d_s=0.4, nbytes=1000, slabs=2)
    s.wall_s = 0.5
    tsv = os.path.join(tmp_path, "s.timings.tsv")
    s.record_to(StageTimer(tsv), "stage_dense:tpm")
    with open(tsv) as f:
        names = [ln.split("\t")[0] for ln in f][1:]
    assert names == ["stage_dense:tpm/host_prep", "stage_dense:tpm/h2d",
                     "stage_dense:tpm/device", "stage_dense:tpm/wall"]
