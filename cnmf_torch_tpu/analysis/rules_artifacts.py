"""Artifact-atomicity rule: library writes go through ``atomic_artifact``.

``--skip-completed-runs``, launcher respawn, and ``combine`` all probe
the run directory and trust what they find; a worker SIGKILLed mid-write
must therefore never leave a half-written file under a final name. The
package-wide invariant (PR 4) is the temp-file + ``os.replace`` dance in
``utils/anndata_lite.atomic_artifact`` — this rule keeps new write sites
from quietly regressing it.

``artifact-nonatomic`` flags ``open(path, "w"/"a"/"x"/...)``,
``np.save``/``np.savez*``, pandas ``.to_csv``/``.to_pickle``/
``.to_hdf``/``.to_parquet``, and ``.savefig`` calls that are NOT
lexically inside a ``with atomic_artifact(...)`` block (writes to the
yielded temp path are exactly how the pattern is used). ``write_h5ad``
and the ``save_df_to_*`` helpers are atomic internally and not flagged.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding

WRITE_FUNCS = {"numpy.save", "numpy.savez", "numpy.savez_compressed"}
WRITE_METHODS = {"to_csv", "to_pickle", "to_hdf", "to_parquet", "savefig"}
WRITE_MODES = ("w", "a", "x", "+")

HINT = ("wrap the write in `with atomic_artifact(target) as tmp:` "
        "(utils/anndata_lite.py) and write to tmp")


def _open_write_mode(call: ast.Call) -> str | None:
    """The mode literal of an ``open`` call when it writes; None for
    reads / non-literal modes."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and any(ch in mode.value for ch in WRITE_MODES):
        return mode.value
    return None


def check(ctx: FileContext):
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve_call(node)
        msg = None
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            mode = _open_write_mode(node)
            if mode is not None:
                msg = f"`open(..., {mode!r})` writes a final path directly"
        elif resolved in WRITE_FUNCS:
            msg = f"`{resolved}` writes a final path directly"
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in WRITE_METHODS:
            msg = f"`.{node.func.attr}(...)` writes a final path directly"
        if msg and not ctx.in_atomic_with(node):
            findings.append(ctx.finding(
                node, "artifact-nonatomic",
                msg + " — a crash mid-write leaves a torn artifact that "
                      "resume/combine may trust", HINT))
    return findings
