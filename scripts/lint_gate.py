#!/usr/bin/env python
"""Tier-1 lint gate: the package must produce ZERO unbaselined findings.

Runs ``cnmf-tpu lint`` over ``cnmf_torch_tpu/`` (all rule families plus
the README knob-table drift check) against the checked-in baseline
(``cnmf_torch_tpu/analysis/baseline.json`` — shipped empty) and echoes a
one-line per-family count next to the telemetry/chaos smoke lines in
``scripts/verify_tier1.sh``. Never imports jax — this step costs well
under a second.

Exit 0: clean. Exit 1: findings (printed). Anything else: engine error.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    os.chdir(REPO)
    from cnmf_torch_tpu.analysis.engine import (DEFAULT_BASELINE,
                                                format_text, lint_paths)

    result = lint_paths(["cnmf_torch_tpu"], baseline_path=DEFAULT_BASELINE)
    fams = " ".join(f"{fam}={n}" for fam, n in
                    sorted(result.family_counts().items()))
    print(f"LINT_GATE: {fams} baselined={len(result.baselined)} "
          f"suppressed={result.suppressed} files={result.files}")
    if result.findings:
        print(format_text(result))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
