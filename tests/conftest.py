import os

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
# exercised without TPU hardware (SURVEY.md §4 "what the reference lacks").
# NOTE: this environment pre-imports jax at interpreter startup (axon
# sitecustomize) with jax_platforms='axon,cpu', so env vars are too late —
# the config must be updated through jax.config before any backend is
# initialized. Override with CNMF_TEST_PLATFORM=tpu to run on hardware.
import jax  # noqa: E402

if os.environ.get("CNMF_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import scipy.sparse as sp  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture()
def counts_100x500():
    """The reference's synthetic smoke fixture: binomial counts with seed 42
    (test_prepare.py:10-14)."""
    np.random.seed(42)
    return np.random.binomial(100, 0.01, size=(100, 500)).astype(np.float64)


@pytest.fixture()
def sparse_counts_100x500(counts_100x500):
    return sp.csr_matrix(counts_100x500)
