// Fast parallel MatrixMarket coordinate-body parser.
//
// Native data-loader core for the 10x mtx path (the reference delegates
// matrix loading to scipy at its loader boundary,
// /root/reference/src/cnmf/cnmf.py:520-522 via scanpy). The body parse is
// the cold-start hot spot for multi-hundred-MB coordinate files, so it runs
// here as a two-phase multi-threaded pass over the raw buffer:
//
//   phase 1: split the buffer at line boundaries into per-thread chunks and
//            count entry lines per chunk (comments/blank lines skipped);
//   phase 2: exclusive prefix sums give each chunk its output offset, then
//            all chunks parse concurrently straight into the caller's
//            arrays — no locks, no allocations, deterministic order.
//
// Contract: buf[0..len) is the body (entries only, comments allowed), each
// entry "row col [value]" 1-indexed, one per line. Returns the number of
// entries parsed, or -(byte offset + 1) of the first malformed entry.
// pattern==1 means no value column (implicit 1.0). n_threads<=0 selects
// hardware concurrency.

#include <algorithm>
#include <atomic>
#include <charconv>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Chunk {
    const char* begin;
    const char* end;
    long long n_entries = 0;   // phase-1 count
    long long offset = 0;      // phase-2 output offset
    long long bad_at = -1;     // byte offset of first malformed entry
};

inline bool is_entry_line(const char* p, const char* line_end) {
    while (p < line_end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    return p < line_end && *p != '%';
}

void count_chunk(Chunk& ch) {
    const char* p = ch.begin;
    long long n = 0;
    while (p < ch.end) {
        const char* nl = (const char*)memchr(p, '\n', ch.end - p);
        const char* line_end = nl ? nl : ch.end;
        if (is_entry_line(p, line_end)) ++n;
        p = nl ? nl + 1 : ch.end;
    }
    ch.n_entries = n;
}

inline const char* skip_ws(const char* p, const char* end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    return p;
}

void parse_chunk(const char* buf, Chunk& ch, int32_t* rows, int32_t* cols,
                 double* vals, int pattern) {
    // std::from_chars: locale-free, ~3x strtod throughput (the float parse
    // dominates the whole load for real-valued matrices)
    const char* p = ch.begin;
    long long i = ch.offset;
    while (p < ch.end) {
        const char* nl = (const char*)memchr(p, '\n', ch.end - p);
        const char* line_end = nl ? nl : ch.end;
        if (is_entry_line(p, line_end)) {
            long long r = 0, c = 0;
            p = skip_ws(p, line_end);
            auto res = std::from_chars(p, line_end, r);
            // reject indices outside [1, INT32_MAX]: a silent int32 wrap
            // would deposit the value at a bogus in-bounds coordinate
            if (res.ec != std::errc() || r < 1 || r > INT32_MAX) {
                ch.bad_at = p - buf; return;
            }
            p = skip_ws(res.ptr, line_end);
            res = std::from_chars(p, line_end, c);
            if (res.ec != std::errc() || c < 1 || c > INT32_MAX) {
                ch.bad_at = p - buf; return;
            }
            p = res.ptr;
            double v = 1.0;
            if (!pattern) {
                p = skip_ws(p, line_end);
                // from_chars rejects a leading '+' that strtod accepts;
                // MatrixMarket writers never emit it, but tolerate it
                if (p < line_end && *p == '+') ++p;
                auto fres = std::from_chars(p, line_end, v);
                if (fres.ec != std::errc()) { ch.bad_at = p - buf; return; }
            }
            rows[i] = (int32_t)(r - 1);
            cols[i] = (int32_t)(c - 1);
            vals[i] = v;
            ++i;
        }
        p = nl ? nl + 1 : ch.end;
    }
}

}  // namespace

extern "C" {

long long mtx_parse_body(const char* buf, long long len, int32_t* rows,
                         int32_t* cols, double* vals, long long max_entries,
                         int pattern, int n_threads) {
    if (len <= 0) return 0;
    unsigned hw = std::thread::hardware_concurrency();
    int T = n_threads > 0 ? n_threads : (hw ? (int)hw : 4);
    // small bodies: threading overhead dominates
    if (len < (1 << 20)) T = 1;
    T = (int)std::max<long long>(1, std::min<long long>(T, len / 4096 + 1));

    // split at line boundaries
    std::vector<Chunk> chunks;
    chunks.reserve(T);
    const char* pos = buf;
    const char* end = buf + len;
    long long target = len / T;
    for (int t = 0; t < T && pos < end; ++t) {
        const char* stop = (t == T - 1) ? end
                                        : std::min(end, pos + target);
        if (stop < end) {
            const char* nl = (const char*)memchr(stop, '\n', end - stop);
            stop = nl ? nl + 1 : end;
        }
        chunks.push_back({pos, stop});
        pos = stop;
    }

    // phase 1: count
    {
        std::vector<std::thread> ts;
        for (auto& ch : chunks)
            ts.emplace_back(count_chunk, std::ref(ch));
        for (auto& th : ts) th.join();
    }
    long long total = 0;
    for (auto& ch : chunks) {
        ch.offset = total;
        total += ch.n_entries;
    }
    // distinct sentinel beyond any valid -(byte offset + 1)
    if (total > max_entries) return -(len + 2);

    // phase 2: parse into place
    {
        std::vector<std::thread> ts;
        for (auto& ch : chunks)
            ts.emplace_back(parse_chunk, buf, std::ref(ch), rows, cols, vals,
                            pattern);
        for (auto& th : ts) th.join();
    }
    for (auto& ch : chunks)
        if (ch.bad_at >= 0) return -(ch.bad_at + 1);
    return total;
}

}  // extern "C"
