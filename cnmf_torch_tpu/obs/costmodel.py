"""Roofline cost model (ISSUE 19): the performance-truth layer.

Analytic per-stage / per-kernel-lane cost accounting — flops, bytes
moved through the solver, collective bytes per pass — instantiated
directly from the resolved :class:`~cnmf_torch_tpu.runtime.planner.
ExecutionPlan` (via :meth:`cost_inputs`) plus the per-dispatch problem
shape. The closed forms follow the MPI-FAUN accounting (arXiv
1609.09154: per-iteration flop/word/collective-word counts for
distributed MU/HALS schedules) and the out-of-memory NMF slab-loop
accounting (arXiv 2202.09518); each lane's formula lives NEXT TO its
kernel (``ops/nmf.py:dense_update_cost``, ``ops/sparse.py:
ell_stats_cost``, ``ops/pallas:pallas_stats_cost``, ``parallel/
grid2d.py:grid_pass_cost``) and is cross-validated against
``jit(f).lower(...).compile().cost_analysis()`` on pinned shapes by
tests/test_costmodel.py — flops exact, bytes within the 10% band.

Joining a prediction with a measured wall yields the roofline verdict
(:func:`roofline`): achieved MFU, achieved bandwidth fraction,
arithmetic intensity against the machine balance point, and the
compute- vs memory-bound call. Runs on hardware without a datasheet
entry (this CPU gate, Pallas interpret mode) get nominal peaks and a
``perf_exempt`` flag — the verdict renders, but the perf gate and
benchdiff never compare it.

Host-side only: importing this module never imports jax, and nothing
here runs inside a traced computation — with ``CNMF_TPU_PERF_MODEL``
unset compiled programs are byte-identical (pinned by test).
"""

from __future__ import annotations

import math

__all__ = ["CHIP_PEAKS", "chip_peaks", "lane_cost", "plan_cost",
           "serve_project_cost", "roofline", "xla_cost",
           "perf_model_enabled", "PERF_MODEL_ENV"]

PERF_MODEL_ENV = "CNMF_TPU_PERF_MODEL"

# (peak dense-matmul flops/s, peak HBM bytes/s) per device kind —
# datasheet bf16 numbers, same table the bench MFU tier reports against
# (bench.py:_CHIP_PEAKS). Keys match jax's `device_kind` strings.
CHIP_PEAKS = {
    "TPU v4": (275e12, 1.2e12),
    "TPU v5 lite": (394e12, 0.819e12),
    "TPU v5": (459e12, 2.765e12),
    "TPU v5p": (459e12, 2.765e12),
    "TPU v6 lite": (918e12, 1.64e12),
}

# nominal single-core CPU envelope used when the device has no
# datasheet entry: ~50 GFLOP/s f32 and ~20 GB/s effective stream
# bandwidth. Deliberately round numbers — rows built on them carry
# peak_source="nominal-cpu" + perf_exempt=True and are never gated.
_NOMINAL_CPU = (50e9, 20e9)


def perf_model_enabled() -> bool:
    """Whether factorize/serve should emit ``perf_model`` events.
    Host-side flag only — it gates event emission, never lowering."""
    from ..utils.envknobs import env_flag

    return env_flag(PERF_MODEL_ENV, False)


def chip_peaks(device_kind: str | None) -> dict:
    """Peak envelope for a device kind: ``{flops, bw, source}`` where
    source is ``datasheet`` for known TPUs and ``nominal-cpu``
    otherwise (the accompanying roofline rows become perf-exempt)."""
    if device_kind:
        for name, (pf, pb) in CHIP_PEAKS.items():
            if device_kind == name or device_kind.startswith(name):
                return {"flops": pf, "bw": pb, "source": "datasheet"}
    return {"flops": _NOMINAL_CPU[0], "bw": _NOMINAL_CPU[1],
            "source": "nominal-cpu"}


# ---------------------------------------------------------------------------
# per-lane analytic cost
# ---------------------------------------------------------------------------

def lane_cost(lane: str, n: int, g: int, k: int, *, beta: float = 1.0,
              ell_width: int | None = None, t_width: int | None = None,
              bf16_ratio: bool = False,
              grid_shape: list | None = None,
              grid_blocks: int | None = None) -> dict:
    """Cost of ONE update iteration (H + W) on a kernel lane, per the
    formula owned by that lane's module. ``lane`` is a kernel label as
    carried by dispatch/replicates events (``vmapped``, ``vmapped-bf16``,
    ``bundled``, ``dense-jnp``, ``ell-jnp``, ``ell-pallas``,
    ``grid2d``). Returns ``{flops, bytes, lane, ...}``; grid lanes add
    ``collective_bytes``. Degenerate windows (n==0, g==0, k==0, or a
    zero-width ELL slab) cost exactly zero — callers never special-case
    empty work."""
    n, g, k = int(n), int(g), int(k)
    if n <= 0 or g <= 0 or k <= 0 or (
            lane in ("ell-jnp", "ell-pallas") and not ell_width):
        return {"flops": 0.0, "bytes": 0.0, "lane": lane,
                "degenerate": True}
    if lane == "grid2d":
        from ..parallel.grid2d import grid_pass_cost

        gs = grid_shape or [1, 1]
        n_dev = max(1, int(gs[0]) * int(gs[1]))
        rows_loc = -(-n // max(int(gs[0]), 1))
        g_loc = -(-g // max(int(gs[1]), 1))
        nblk = max(1, int(grid_blocks or 1))
        return grid_pass_cost(rows_loc, g_loc, k, beta,
                              nblk_h=nblk, nblk_w=nblk, n_dev=n_dev)
    if lane == "ell-pallas":
        from ..ops.pallas import pallas_stats_cost

        return pallas_stats_cost(n, g, k, int(ell_width),
                                 t_width=t_width, beta=beta)
    if lane == "ell-jnp":
        from ..ops.sparse import ell_stats_cost

        return ell_stats_cost(n, g, k, int(ell_width),
                              t_width=t_width, beta=beta)
    # dense lanes (vmapped / vmapped-bf16 / bundled / dense-jnp)
    from ..ops.nmf import dense_update_cost

    c = dense_update_cost(n, g, k, beta, bf16_ratio=bf16_ratio,
                          bundled=(lane == "bundled"))
    c["lane"] = lane
    return c


def plan_cost(plan_inputs: dict, n: int, g: int, k: int,
              lane: str | None = None) -> dict:
    """Instantiate the per-iteration cost for a resolved plan
    (``ExecutionPlan.cost_inputs()`` or an equal dict) at a problem
    shape. ``lane`` overrides the plan's kernel label when the caller
    knows which lane actually dispatched (e.g. the rowshard solver's
    per-job kernel)."""
    p = dict(plan_inputs or {})
    resolved = lane or str(p.get("kernel") or "vmapped")
    if p.get("layout") == "grid2d" or resolved == "grid2d":
        resolved = "grid2d"
    return lane_cost(
        resolved, n, g, k,
        beta=float(p.get("beta", 1.0)),
        ell_width=p.get("ell_width"),
        bf16_ratio=bool(p.get("bf16_ratio")),
        grid_shape=p.get("grid_shape"),
        grid_blocks=p.get("grid_blocks"))


def serve_project_cost(b: int, n: int, g: int, k: int, *,
                       beta: float = 2.0, iters: int = 1) -> dict:
    """Cost of one batched serve dispatch (``serving/batcher.py``
    ``batched_project``): an H-only fit on a padded ``(b, n, g)`` lane
    batch with the reference Gram precomputed (beta=2) or the ratio
    chain (beta=1), times ``iters`` inner iterations. Serving assumes
    the iteration CAP (the while loop's actual trip count is
    data-dependent and not observable host-side) — events built on
    this carry ``iters_assumed_cap``."""
    b, n, g, k, iters = int(b), int(n), int(g), int(k), max(int(iters), 1)
    if b <= 0 or n <= 0 or g <= 0 or k <= 0:
        return {"flops": 0.0, "bytes": 0.0, "lane": "serve-project",
                "degenerate": True}
    f = 4.0
    if beta == 2.0:
        flops = b * (2 * n * g * k + 2 * n * k * k + 3 * n * k)
        bytes_ = b * ((n * g + k * g + n * k) * f
                      + (n * k + k * k + n * k) * f
                      + 4 * n * k * f)
    else:
        flops = b * (4 * n * g * k + 2 * n * g + k * (g - 1) + 3 * n * k)
        bytes_ = b * ((n * k + k * g + n * g) * f + 3 * n * g * f
                      + (n * g + k * g + n * k) * f + 4 * n * k * f)
    return {"flops": float(flops * iters), "bytes": float(bytes_ * iters),
            "lane": "serve-project"}


# ---------------------------------------------------------------------------
# roofline verdict
# ---------------------------------------------------------------------------

def roofline(flops: float, nbytes: float, wall_s: float,
             peaks: dict | None = None, *,
             perf_exempt: bool = False) -> dict:
    """Join predicted work with a measured wall: achieved MFU, achieved
    bandwidth fraction, arithmetic intensity vs the machine balance
    point, and the bound verdict. ``peaks`` is :func:`chip_peaks`
    output (nominal-cpu assumed when absent). Zero/degenerate work or a
    non-positive wall yields the ``"idle"`` verdict rather than a
    division error."""
    peaks = peaks or chip_peaks(None)
    pf, pb = float(peaks["flops"]), float(peaks["bw"])
    src = str(peaks.get("source", "nominal-cpu"))
    exempt = bool(perf_exempt or src != "datasheet")
    flops, nbytes = float(flops), float(nbytes)
    out = {"peak_source": src, "perf_exempt": exempt}
    if wall_s is None or wall_s <= 0 or (flops <= 0 and nbytes <= 0):
        out.update(mfu=None, bw_frac=None, intensity=None, bound="idle")
        return out
    mfu = flops / wall_s / pf
    bw = nbytes / wall_s / pb
    balance = pf / pb                       # flops per byte at the ridge
    intensity = flops / nbytes if nbytes > 0 else math.inf
    bound = "compute-bound" if intensity >= balance else "memory-bound"
    out.update(mfu=round(mfu, 6), bw_frac=round(bw, 6),
               intensity=round(intensity, 4) if math.isfinite(intensity)
               else None,
               balance=round(balance, 4), bound=bound)
    return out


# ---------------------------------------------------------------------------
# XLA cross-validation
# ---------------------------------------------------------------------------

def xla_cost(fn, *args, static_argnames=None, **kwargs) -> dict:
    """``jit(fn).lower(...).compile().cost_analysis()`` normalized to
    ``{flops, bytes}``. Some backends return a per-computation LIST of
    dicts (first entry = entry computation); flop-free programs (bare
    gathers) omit the ``flops`` key entirely — both normalized here so
    tests and calibration probes share one code path. Requires jax;
    only ever called from tests/probes, never from the hot path."""
    import jax

    ca = (jax.jit(fn, static_argnames=static_argnames)
          .lower(*args, **kwargs).compile().cost_analysis())
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    ca = ca or {}
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed", 0.0))}
