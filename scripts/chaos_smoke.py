"""Tier-1 chaos smoke gate (scripts/verify_tier1.sh).

Runs the mini pipeline once per injected fault class (runtime/faults.py)
and asserts the pipeline completes with correct degraded-mode accounting:

  1. ``nonfinite`` — a NaN replicate lane is quarantined by the health
     pass and retried with the derived seed (``seed XOR attempt``); the
     resilience ledger records it and the telemetry ``fault`` events are
     schema-valid.
  2. ``kill`` — a subprocess-engine worker is SIGKILLed mid-factorize,
     the launcher respawns it onto its unfinished ledger shard, and the
     resumed run's merged spectra + consensus match an uninterrupted run
     bit-for-bit.
  3. ``torn`` — a truncated artifact is detected (never trusted) by
     combine, and ``--skip-completed-runs`` regenerates it.
  4. ``stall`` — a hung shard upload trips the ``CNMF_TPU_STREAM_STALL_S``
     watchdog as a ``ShardStallError`` within its deadline instead of
     hanging the staging call forever.
  5. ``kill:stage=pass`` — a rowsharded factorize worker is SIGKILLed
     mid-pass (after a checkpoint write lands); the launcher respawns it
     and the relaunch RESUMES from the pass checkpoint (asserted via the
     telemetry ``checkpoint resume`` event, i.e. NOT from scratch) with
     merged spectra bit-identical to an uninterrupted run.
  6. ``torn:artifact=ckpt`` — a truncated pass checkpoint is detected on
     resume, discarded, and the replicate restarts from scratch,
     reproducing the clean result.
  7. ``hostloss`` — a simulated host (2 of a worker's 4 devices) dies
     mid-sweep at a replicate's post-checkpoint boundary; the elastic
     controller re-plans the mesh over the survivors, re-stages X, and
     the run COMPLETES degraded with merged spectra and consensus
     bit-identical to an uninterrupted run (the interrupted replicate
     finishes from its checkpointed state, H under the byte budget) —
     proven via ``host_loss``/``remesh``/``checkpoint resume`` telemetry
     events, with zero leaked threads or checkpoint files.
  8. ``straggler`` — one of two launcher workers is made pathologically
     slow; the ``CNMF_TPU_STRAGGLER_S`` deadline fires after the first
     clean finisher, the straggler is killed (telemetry ``straggler``)
     and its shard adopted by the fleet (``worker_steal``), and every
     replicate still lands — containment instead of a wedged sweep.

Exits nonzero on any violated invariant, failing the gate.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

FAULT_ENV = "CNMF_TPU_FAULT_SPEC"


def _counts_file(workdir: str):
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu.utils.io import save_df_to_npz

    rng = np.random.default_rng(5)
    counts = rng.binomial(40, 0.02, size=(60, 100)).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    fn = os.path.join(workdir, "counts.df.npz")
    save_df_to_npz(df, fn)
    return fn


def _prepare(workdir: str, counts_fn: str, name: str, components=(3, 4),
             n_iter: int = 3):
    from cnmf_torch_tpu import cNMF

    obj = cNMF(output_dir=workdir, name=name)
    obj.prepare(counts_fn, components=list(components), n_iter=n_iter,
                seed=4, num_highvar_genes=50, batch_size=64, max_NMF_iter=50)
    return obj


def scenario_nonfinite(workdir: str, counts_fn: str) -> None:
    from cnmf_torch_tpu.runtime import resilience
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    obj = _prepare(workdir, counts_fn, "nonfin")
    os.environ[FAULT_ENV] = "nonfinite:k=4,iter=1"
    os.environ["CNMF_TPU_TELEMETRY"] = "1"
    try:
        obj.factorize()
    finally:
        del os.environ[FAULT_ENV]
        del os.environ["CNMF_TPU_TELEMETRY"]
    with open(obj.paths["resilience_ledger"] % 0) as f:
        ledger = json.load(f)
    assert ledger["quarantined"] == [], ledger
    (rec,) = ledger["retries"]
    assert rec["k"] == 4 and rec["iter"] == 1 and rec["healthy"], rec
    assert rec["derived_seed"] == resilience.derive_retry_seed(
        rec["seed"], rec["attempt"]), rec
    assert os.path.exists(obj.paths["iter_spectra"] % (4, 1))
    ev_path = os.path.join(workdir, "nonfin", "cnmf_tmp",
                           "nonfin.events.jsonl")
    validate_events_file(ev_path)  # raises on any malformed line
    kinds = [e["kind"] for e in read_events(ev_path) if e["t"] == "fault"]
    assert "nonfinite_replicate" in kinds and "retry" in kinds, kinds
    merged = obj.combine_nmf(4)
    assert merged.shape[0] == 3 * 4, merged.shape
    print("chaos smoke [nonfinite]: quarantined lane retried with derived "
          "seed %d (= %d ^ 1); %d schema-valid fault events"
          % (rec["derived_seed"], rec["seed"], len(kinds)))


def scenario_kill(workdir: str, counts_fn: str) -> None:
    import numpy as np

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.launcher import run_pipeline
    from cnmf_torch_tpu.utils.io import load_df_from_npz

    os.environ["CNMF_TPU_WORKER_RESPAWNS"] = "2"
    os.environ["CNMF_TPU_WORKER_BACKOFF_S"] = "0.1"
    common = dict(components=[3, 4], n_iter=3, total_workers=1, seed=4,
                  numgenes=50, k_selection=False)
    try:
        run_pipeline(counts_fn, workdir, "clean",
                     env_extra={"CNMF_SIM_CPU_DEVICES": "2"}, **common)
        sentinel = os.path.join(workdir, "kill.done")
        run_pipeline(counts_fn, workdir, "killed",
                     env_extra={"CNMF_SIM_CPU_DEVICES": "2",
                                FAULT_ENV: "kill:stage=factorize,worker=0,"
                                           f"once={sentinel}"},
                     **common)
    finally:
        del os.environ["CNMF_TPU_WORKER_RESPAWNS"]
        del os.environ["CNMF_TPU_WORKER_BACKOFF_S"]
    assert os.path.exists(sentinel), "kill fault never fired"
    for k in (3, 4):
        a = load_df_from_npz(os.path.join(
            workdir, "clean", "cnmf_tmp",
            f"clean.spectra.k_{k}.merged.df.npz")).values
        b = load_df_from_npz(os.path.join(
            workdir, "killed", "cnmf_tmp",
            f"killed.spectra.k_{k}.merged.df.npz")).values
        assert np.array_equal(a, b), f"merged spectra diverge at k={k}"
    outs = []
    for name in ("clean", "killed"):
        obj = cNMF(output_dir=workdir, name=name)
        obj.consensus(3, density_threshold=2.0,
                      local_neighborhood_size=0.7, show_clustering=False,
                      build_ref=False)
        outs.append({key: load_df_from_npz(obj.paths[key] % (3, "2_0")).values
                     for key in ("consensus_spectra", "consensus_usages")})
    for key, a in outs[0].items():
        assert np.array_equal(a, outs[1][key]), f"{key} diverges"
    print("chaos smoke [kill]: worker SIGKILLed, respawned onto its shard; "
          "resumed consensus bit-identical to the uninterrupted run")


def scenario_torn(workdir: str, counts_fn: str) -> None:
    import numpy as np

    from cnmf_torch_tpu.runtime import resilience
    from cnmf_torch_tpu.utils.io import load_df_from_npz

    obj = _prepare(workdir, counts_fn, "torn")
    os.environ[FAULT_ENV] = "torn:artifact=iter_1,limit=1"
    try:
        obj.factorize()
    finally:
        del os.environ[FAULT_ENV]
    # find the torn artifact: exactly one replicate file fails validation
    torn = [(k, it) for k in (3, 4) for it in range(3)
            if os.path.exists(obj.paths["iter_spectra"] % (k, it))
            and resilience.probe_spectra_file(
                obj.paths["iter_spectra"] % (k, it), k=k) is not None]
    assert len(torn) == 1, torn
    # combine detects it (treated like missing under the skip flag) ...
    try:
        obj.combine_nmf(torn[0][0])
        raise AssertionError("combine trusted a torn artifact")
    except resilience.TornArtifactError:
        pass
    merged = obj.combine_nmf(torn[0][0], skip_missing_files=True)
    assert merged.shape[0] == 2 * torn[0][0], merged.shape
    # ... and resume regenerates it rather than trusting it
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        obj.factorize(skip_completed_runs=True)
    assert resilience.probe_spectra_file(
        obj.paths["iter_spectra"] % torn[0], k=torn[0][0]) is None
    merged = obj.combine_nmf(torn[0][0])
    assert merged.shape[0] == 3 * torn[0][0]
    assert np.isfinite(
        load_df_from_npz(obj.paths["iter_spectra"] % torn[0]).values).all()
    print("chaos smoke [torn]: truncated artifact detected at combine and "
          "regenerated by --skip-completed-runs (k=%d iter=%d)" % torn[0])


def scenario_stall(workdir: str, counts_fn: str) -> None:
    """A hung shard transfer must fail within CNMF_TPU_STREAM_STALL_S as a
    diagnosable ShardStallError, not hang the whole staging call (and,
    downstream, the mesh) forever."""
    import time

    import jax
    import numpy as np
    import scipy.sparse as sp
    from jax.sharding import Mesh

    import cnmf_torch_tpu.parallel.streaming as streaming
    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh
    from cnmf_torch_tpu.parallel.streaming import ShardStallError

    X = sp.random(64, 16, density=0.2, format="csr", dtype=np.float32,
                  random_state=0)
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("cells",))
    saved_rows = streaming.DENSIFY_SLAB_ROWS
    streaming.DENSIFY_SLAB_ROWS = 8            # multi-slab staging
    os.environ[FAULT_ENV] = "stall:context=stream,seconds=3"
    os.environ["CNMF_TPU_STREAM_STALL_S"] = "0.5"
    os.environ["CNMF_TPU_STREAM_THREADS"] = "2"
    t0 = time.monotonic()
    try:
        try:
            stream_rows_to_mesh(X, mesh, "cells")
            raise AssertionError("stalled upload did not trip the watchdog")
        except ShardStallError:
            pass
        dt = time.monotonic() - t0
        assert dt < 2.5, f"watchdog fired late ({dt:.1f}s)"
    finally:
        streaming.DENSIFY_SLAB_ROWS = saved_rows
        for key in (FAULT_ENV, "CNMF_TPU_STREAM_STALL_S",
                    "CNMF_TPU_STREAM_THREADS"):
            os.environ.pop(key, None)
    # with the spec cleared, the same staging call succeeds
    Xd, _pad = stream_rows_to_mesh(X, mesh, "cells")
    assert np.array_equal(np.asarray(Xd)[:64], X.toarray())
    print("chaos smoke [stall]: hung shard upload failed as ShardStallError "
          "in %.2fs (watchdog 0.5s, injected hang 3s)" % dt)


def scenario_ckpt_kill(workdir: str, counts_fn: str) -> None:
    """Mid-pass kill + checkpoint resume through the LAUNCHER: a rowsharded
    worker dies via kill:stage=pass (fires after a checkpoint write), the
    launcher respawns it with --skip-completed-runs, and the relaunch
    resumes from the checkpoint — proven by the telemetry `checkpoint
    resume` event (pass counter >= 1, i.e. not from scratch) — with merged
    spectra bit-identical to an uninterrupted run."""
    import numpy as np

    from cnmf_torch_tpu.launcher import run_pipeline
    from cnmf_torch_tpu.utils.io import load_df_from_npz
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    os.environ["CNMF_TPU_WORKER_RESPAWNS"] = "2"
    os.environ["CNMF_TPU_WORKER_BACKOFF_S"] = "0.1"
    common = dict(components=[3], n_iter=2, total_workers=1, seed=4,
                  numgenes=50, k_selection=False,
                  factorize_flags=["--rowshard"])
    try:
        run_pipeline(counts_fn, workdir, "ckclean", **common)
        sentinel = os.path.join(workdir, "ckpt_kill.done")
        run_pipeline(counts_fn, workdir, "ckkill",
                     env_extra={"CNMF_TPU_TELEMETRY": "1",
                                FAULT_ENV: "kill:stage=pass,after=3,"
                                           f"once={sentinel}"},
                     **common)
    finally:
        del os.environ["CNMF_TPU_WORKER_RESPAWNS"]
        del os.environ["CNMF_TPU_WORKER_BACKOFF_S"]
    assert os.path.exists(sentinel), "pass-stage kill never fired"
    ev_path = os.path.join(workdir, "ckkill", "cnmf_tmp",
                           "ckkill.events.jsonl")
    validate_events_file(ev_path)              # raises on malformed lines
    resumes = [e for e in read_events(ev_path)
               if e["t"] == "checkpoint" and e["action"] == "resume"]
    assert resumes, "relaunched worker did not resume from the checkpoint"
    assert int(resumes[0]["context"]["pass_idx"]) >= 1
    a = load_df_from_npz(os.path.join(
        workdir, "ckclean", "cnmf_tmp",
        "ckclean.spectra.k_3.merged.df.npz")).values
    b = load_df_from_npz(os.path.join(
        workdir, "ckkill", "cnmf_tmp",
        "ckkill.spectra.k_3.merged.df.npz")).values
    assert np.array_equal(a, b), "resumed spectra diverge from clean run"
    import glob

    assert not glob.glob(os.path.join(workdir, "ckkill", "cnmf_tmp",
                                      "*.ckpt.*"))
    print("chaos smoke [ckpt-kill]: worker SIGKILLed mid-pass, relaunch "
          "resumed from checkpoint pass %d (not from scratch); merged "
          "spectra bit-identical to the uninterrupted run"
          % int(resumes[0]["context"]["pass_idx"]))


def scenario_torn_ckpt(workdir: str, counts_fn: str) -> None:
    """A pass checkpoint truncated mid-write is detected on resume,
    discarded (surfaced as a torn_artifact fault event), and the
    replicate restarts from scratch — reproducing the clean run's
    artifact exactly, never trusting damaged state."""
    import warnings

    import numpy as np

    from cnmf_torch_tpu.runtime import checkpoint as ck
    from cnmf_torch_tpu.utils.anndata_lite import read_h5ad
    from cnmf_torch_tpu.utils.io import load_df_from_npz
    from cnmf_torch_tpu.utils.telemetry import read_events

    obj = _prepare(workdir, counts_fn, "tornck", components=[3], n_iter=2)
    os.environ["CNMF_TPU_TELEMETRY"] = "1"
    try:
        obj.factorize(rowshard=True)
        orig = load_df_from_npz(obj.paths["iter_spectra"] % (3, 1)).values
        os.unlink(obj.paths["iter_spectra"] % (3, 1))
        # craft a mid-run checkpoint for the now-missing replicate, then
        # tear it (the state a SIGKILL during the atomic rename's write
        # phase — or a corrupt filesystem — would leave)
        norm = read_h5ad(obj.paths["normalized_counts"])
        run_params = load_df_from_npz(obj.paths["nmf_replicate_parameters"])
        row = run_params[(run_params.n_components == 3)
                         & (run_params.iter == 1)].iloc[0]
        path = obj.paths["pass_checkpoint"] % (3, 1)
        g = int(norm.X.shape[1])
        rng = np.random.default_rng(0)
        ck.save_pass_checkpoint(
            path, k=3, it=1, seed=int(row["nmf_seed"]), attempt=0,
            digest=ck.input_digest(norm.X), beta=2.0, pass_idx=3,
            err_prev=np.float32(5.0), err=np.float32(4.0),
            trace=np.zeros(4, np.float32),
            W=np.abs(rng.normal(size=(3, g))).astype(np.float32),
            A=np.zeros((3, g), np.float32), B=np.zeros((3, 3), np.float32))
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 3))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            obj.factorize(rowshard=True, skip_completed_runs=True)
        assert not os.path.exists(path), "torn checkpoint not discarded"
        regen = load_df_from_npz(obj.paths["iter_spectra"] % (3, 1)).values
        assert np.array_equal(regen, orig), \
            "from-scratch restart diverged from the clean replicate"
        ev_path = os.path.join(workdir, "tornck", "cnmf_tmp",
                               "tornck.events.jsonl")
        torn_faults = [
            e for e in read_events(ev_path)
            if e["t"] == "fault" and e["kind"] == "torn_artifact"
            and "ckpt" in str(e["context"].get("path", ""))]
        assert torn_faults, "torn checkpoint not surfaced as a fault event"
    finally:
        del os.environ["CNMF_TPU_TELEMETRY"]
    print("chaos smoke [torn-ckpt]: truncated pass checkpoint detected on "
          "resume, discarded, replicate regenerated from scratch "
          "bit-identically")


def scenario_hostloss(workdir: str, counts_fn: str) -> None:
    """Elastic degraded-mesh execution (ISSUE 8): a simulated host (2 of
    a 4-device worker mesh) dies mid-sweep at the second replicate's
    post-checkpoint boundary. The worker re-plans the mesh over the 2
    survivors, re-stages X, resumes the in-flight replicate from its
    pass checkpoint (zero further passes needed — H rode the checkpoint
    under its byte budget), and the run completes with merged spectra
    AND consensus bit-identical to an uninterrupted run."""
    import glob
    import threading

    import numpy as np

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.launcher import run_pipeline
    from cnmf_torch_tpu.utils.io import load_df_from_npz
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    common = dict(components=[3], n_iter=2, total_workers=1, seed=4,
                  numgenes=50, k_selection=False,
                  factorize_flags=["--rowshard"])
    run_pipeline(counts_fn, workdir, "elclean",
                 env_extra={"CNMF_SIM_CPU_DEVICES": "4"}, **common)
    run_pipeline(counts_fn, workdir, "elloss",
                 env_extra={"CNMF_SIM_CPU_DEVICES": "4",
                            "CNMF_TPU_TELEMETRY": "1",
                            FAULT_ENV:
                                "hostloss:context=replicate,after=1,count=2"},
                 **common)

    ev_path = os.path.join(workdir, "elloss", "cnmf_tmp",
                           "elloss.events.jsonl")
    validate_events_file(ev_path)               # raises on malformed lines
    ev = read_events(ev_path)
    kinds = [e["kind"] for e in ev if e["t"] == "fault"]
    assert "host_loss" in kinds and "remesh" in kinds, kinds
    remesh = next(e for e in ev if e["t"] == "fault"
                  and e["kind"] == "remesh")
    assert (remesh["context"]["from_devices"],
            remesh["context"]["to_devices"]) == (4, 2), remesh
    resumes = [e for e in ev
               if e["t"] == "checkpoint" and e["action"] == "resume"]
    assert resumes and int(resumes[0]["context"]["pass_idx"]) >= 1, \
        "degraded continuation did not resume from the pass checkpoint"

    a = load_df_from_npz(os.path.join(
        workdir, "elclean", "cnmf_tmp",
        "elclean.spectra.k_3.merged.df.npz")).values
    b = load_df_from_npz(os.path.join(
        workdir, "elloss", "cnmf_tmp",
        "elloss.spectra.k_3.merged.df.npz")).values
    assert np.array_equal(a, b), \
        "degraded run's merged spectra diverge from the clean run"
    outs = []
    for name in ("elclean", "elloss"):
        obj = cNMF(output_dir=workdir, name=name)
        obj.consensus(3, density_threshold=2.0,
                      local_neighborhood_size=0.7, show_clustering=False,
                      build_ref=False)
        outs.append(load_df_from_npz(
            obj.paths["consensus_spectra"] % (3, "2_0")).values)
    assert np.array_equal(outs[0], outs[1]), "consensus diverges"
    # zero leaks: checkpoints discarded, no cnmf worker threads survive
    # (worker processes were waited by run_pipeline itself)
    assert not glob.glob(os.path.join(workdir, "elloss", "cnmf_tmp",
                                      "*.ckpt.*"))
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("cnmf-")]
    assert not leaked, leaked
    print("chaos smoke [hostloss]: host died mid-sweep, mesh re-planned "
          "4->2 devices, replicate resumed from checkpoint pass %d; merged "
          "spectra + consensus bit-identical to the uninterrupted run"
          % int(resumes[0]["context"]["pass_idx"]))


def scenario_straggler(workdir: str, counts_fn: str) -> None:
    """Launcher straggler containment (ISSUE 8): one of two workers is
    made pathologically slow (injected ``straggler`` clause); after the
    fast worker finishes, the ``CNMF_TPU_STRAGGLER_S`` deadline kills
    the straggler and its shard is adopted by the fleet — every
    replicate lands, asserted via telemetry, instead of the sweep
    waiting out the slow shard."""
    import threading
    import time

    import numpy as np

    from cnmf_torch_tpu.launcher import run_pipeline
    from cnmf_torch_tpu.utils.io import load_df_from_npz
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    sentinel = os.path.join(workdir, "straggle.once")
    # launcher-side knobs live in THIS process; the fault spec rides the
    # worker env. The injected sleep (120 s) dwarfs the whole gate: only
    # containment can finish this scenario. Straggler conviction is
    # evidence-based, so liveness must be on: the straggler stamps once
    # at its sweep boundary, then goes silent inside the injected sleep
    # — exactly the stale-heartbeat + past-deadline combination the
    # containment requires. Prior env values are restored afterwards.
    knobs = {"CNMF_TPU_STRAGGLER_S": "2", "CNMF_TPU_HEARTBEAT_S": "0.5",
             "CNMF_TPU_WORKER_RESPAWNS": "1",
             "CNMF_TPU_WORKER_BACKOFF_S": "0.1", "CNMF_TPU_TELEMETRY": "1"}
    saved = {key: os.environ.get(key) for key in knobs}
    os.environ.update(knobs)
    t0 = time.monotonic()
    try:
        run_pipeline(counts_fn, workdir, "strag", components=[3, 4],
                     n_iter=3, total_workers=2, seed=4, numgenes=50,
                     k_selection=False,
                     env_extra={FAULT_ENV:
                                "straggler:worker=1,context=factorize,"
                                f"seconds=120,once={sentinel}"})
    finally:
        for key, old in saved.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old
    wall = time.monotonic() - t0
    assert wall < 110, f"straggler was not contained ({wall:.0f}s)"
    assert os.path.exists(sentinel), "straggler fault never fired"

    ev_path = os.path.join(workdir, "strag", "cnmf_tmp",
                           "strag.events.jsonl")
    validate_events_file(ev_path)
    kinds = [e["kind"] for e in read_events(ev_path) if e["t"] == "fault"]
    assert "straggler" in kinds, kinds
    assert "worker_steal" in kinds, kinds
    # the adopted shard finished: every replicate of both Ks landed
    for k in (3, 4):
        merged = load_df_from_npz(os.path.join(
            workdir, "strag", "cnmf_tmp",
            f"strag.spectra.k_{k}.merged.df.npz")).values
        assert merged.shape[0] == 3 * k, (k, merged.shape)
        assert np.isfinite(merged).all()
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("cnmf-")]
    assert not leaked, leaked
    print("chaos smoke [straggler]: slow worker killed %.0fs in by the "
          "2s deadline, shard adopted by the fleet; all replicates "
          "landed" % wall)


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="chaos_smoke_")
    try:
        counts_fn = _counts_file(workdir)
        scenario_nonfinite(workdir, counts_fn)
        scenario_kill(workdir, counts_fn)
        scenario_torn(workdir, counts_fn)
        scenario_stall(workdir, counts_fn)
        scenario_ckpt_kill(workdir, counts_fn)
        scenario_torn_ckpt(workdir, counts_fn)
        scenario_hostloss(workdir, counts_fn)
        scenario_straggler(workdir, counts_fn)
        print("chaos smoke: all fault classes recovered")
        return 0
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
