"""Trace-safety rules: nothing host-side may hide inside a traced scope.

A traced scope is a function the XLA tracer will run: decorated with
``@jit``/``@partial(jax.jit, ...)``/``@shard_map``, or passed (by name,
as an inline lambda, or wrapped in ``functools.partial``) to a tracer
call — ``jax.jit``, ``vmap``/``pmap``,
``lax.while_loop``/``fori_loop``/``scan``/``cond``/``switch``/``map``,
``shard_map``, ``pl.pallas_call``, ``checkpoint``/``remat``, ``grad``.
Detection is lexical and per-file (a helper that is only ever traced via
an import in another module is out of reach — the rule is a tripwire for
the patterns that actually bite, not a whole-program dataflow analysis).

Pallas kernel bodies (the function handed to ``pl.pallas_call``) are
traced scopes like any other: the host-sync and nondet rules apply
inside them — a ``.item()`` or ``time.time()`` in a kernel body is just
as wrong as in a jitted solver. ``pl.load``/``pl.store`` are explicitly
exempt from the scatter/host-access heuristics (see
``PALLAS_REF_CALLS``): they are in-kernel VMEM ref accesses — part of
the traced program itself — not device->host traffic.

  * ``trace-host-sync`` — ``.item()``/``.tolist()``/
    ``.block_until_ready()``, ``np.asarray``/``np.array``,
    ``jax.device_get``, and ``float()``/``int()``/``bool()`` on traced
    values. Each is a device->host sync: inside a jitted body it either
    fails at trace time or (worse) silently forces a per-dispatch flush.
    ``int(x.shape[0])``-style shape/size/ndim/len expressions are static
    under tracing and exempt.
  * ``trace-nondet`` — ``time.*`` clocks and ``random``/``np.random``
    draws inside a traced scope: they freeze a trace-time value into the
    compiled program, so reruns and resumed runs silently diverge
    (reproducibility is a ledger guarantee here; RNG must flow through
    seeded ``jax.random`` keys).
  * ``trace-branch`` — Python ``if``/``while`` on a traced parameter:
    concretization either raises at trace time or, via a static argnum
    the author forgot, recompiles per value. Parameters named in
    ``static_argnames``/``static_argnums`` are exempt (branching on
    statics is the supported pattern — e.g. the ``telemetry`` flag on the
    solvers).
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding, dotted_name

# tracer entry points: a function-valued argument of any of these is a
# traced scope (index-precision deliberately not attempted — a lambda or
# local function handed to any argument slot of these is traced or about
# to be)
TRACER_CALLS = {
    "jax.jit", "jax.pmap", "jax.vmap",
    "jax.lax.while_loop", "jax.lax.fori_loop", "jax.lax.scan",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.pallas.pallas_call",
    "jax.checkpoint", "jax.remat", "jax.grad", "jax.value_and_grad",
}

# in-kernel VMEM ref accesses (``pl.load(ref, idx)`` / ``pl.store(ref,
# idx, val)``): deliberately exempt from the host-sync and any future
# scatter heuristics — a ref access inside a Pallas kernel body IS the
# traced program, not device->host traffic
PALLAS_REF_CALLS = {"jax.experimental.pallas.load",
                    "jax.experimental.pallas.store"}

HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready",
                   "copy_to_host_async"}
HOST_SYNC_CALLS = {"numpy.asarray", "numpy.array", "numpy.asanyarray",
                   "numpy.ascontiguousarray", "jax.device_get"}
CAST_BUILTINS = {"float", "int", "bool"}

NONDET_CALLS = {"time.time", "time.time_ns", "time.perf_counter",
                "time.perf_counter_ns", "time.monotonic",
                "time.monotonic_ns"}
NONDET_PREFIXES = ("random.", "numpy.random.")


def _tracer_name(ctx: FileContext, node: ast.AST) -> bool:
    name = ctx.imports.resolve(dotted_name(node))
    if name in TRACER_CALLS:
        return True
    # the package re-exports shard_map through utils.jax_compat's version
    # shim, and pallas is imported under an alias (``import pallas as
    # pl``) — any import path with either leaf is the tracer
    return name is not None and name.split(".")[-1] in ("shard_map",
                                                        "pallas_call")


def _partial_tracer(ctx: FileContext, call: ast.Call) -> bool:
    """``partial(jax.jit, static_argnames=...)`` used as a decorator."""
    name = ctx.resolve_call(call)
    if name not in ("functools.partial", "partial"):
        return False
    return bool(call.args) and _tracer_name(ctx, call.args[0])


def _static_names_from_call(call: ast.Call, fn: ast.AST) -> set[str]:
    """static_argnames / static_argnums keywords -> parameter names."""
    out: set[str] = set()
    params = _param_names(fn)
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    if 0 <= n.value < len(params):
                        out.add(params[n.value])
    return out


def _param_names(fn: ast.AST) -> list[str]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
        return []
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]


def _collect_traced_scopes(ctx: FileContext) -> dict[ast.AST, set[str]]:
    """Map traced function/lambda node -> set of STATIC parameter names."""
    scopes: dict[ast.AST, set[str]] = {}
    # local function definitions by name (last definition wins)
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _tracer_name(ctx, dec):
                    scopes.setdefault(node, set())
                elif isinstance(dec, ast.Call):
                    if _tracer_name(ctx, dec.func):  # @jit(static_...)
                        scopes.setdefault(node, set()).update(
                            _static_names_from_call(dec, node))
                    elif _partial_tracer(ctx, dec):
                        scopes.setdefault(node, set()).update(
                            _static_names_from_call(dec, node))
        elif isinstance(node, ast.Call) and _tracer_name(ctx, node.func):
            statics_call = node
            # function-valued operands arrive positionally AND by keyword
            # (lax.while_loop(cond, body_fun=body, ...) is standard style)
            candidates = list(node.args) + [kw.value for kw in node.keywords
                                            if kw.arg is not None]
            for arg in candidates:
                target = None
                if isinstance(arg, ast.Lambda):
                    target = arg
                elif isinstance(arg, ast.Name) and arg.id in defs:
                    target = defs[arg.id]
                elif isinstance(arg, ast.Call) \
                        and ctx.resolve_call(arg) in ("functools.partial",
                                                      "partial") \
                        and arg.args \
                        and isinstance(arg.args[0], ast.Name) \
                        and arg.args[0].id in defs:
                    # pallas_call(functools.partial(body, k=k), ...) —
                    # the kernel-body idiom binds statics via partial
                    target = defs[arg.args[0].id]
                if target is not None:
                    scopes.setdefault(target, set()).update(
                        _static_names_from_call(statics_call, target))
    return scopes


def _traced_value_uses(ctx: FileContext, test: ast.AST):
    """Name nodes in a branch test whose VALUE is traced — occurrences
    that only probe trace-time-static facts (``isinstance(x, ...)``,
    ``x.shape``/``.ndim``/``.size``/``.dtype``, ``len(x)``) don't
    concretize and are skipped."""
    for n in ast.walk(test):
        if not isinstance(n, ast.Name):
            continue
        static = False
        cur = n
        for anc in ctx.ancestors(n):
            if isinstance(anc, ast.Attribute) and anc.value is cur \
                    and anc.attr in ("shape", "ndim", "size", "dtype"):
                static = True
                break
            if isinstance(anc, ast.Call) and isinstance(anc.func, ast.Name) \
                    and anc.func.id in ("isinstance", "len", "type"):
                static = True
                break
            if anc is test:
                break
            cur = anc
        if not static:
            yield n


def _mentions_static_shape(node: ast.AST) -> bool:
    """``int(x.shape[0])`` / ``float(len(xs))`` / dtype probes are
    trace-time constants, not syncs."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim",
                                                       "size", "dtype"):
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return True
    return False


def _walk_scope(scope: ast.AST, scope_ids: set[int]):
    """Walk ``scope``'s subtree but stop at NESTED traced scopes — each
    traced scope gets exactly one pass, with its own (closure-aware)
    parameter sets. Nested plain functions stay in the enclosing walk:
    they are traced by closure when the traced scope calls them."""
    body = scope.body if isinstance(scope.body, list) else [scope.body]
    stack = [n for n in body if id(n) not in scope_ids]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if id(child) not in scope_ids:
                stack.append(child)


def check(ctx: FileContext):
    findings: list[Finding] = []
    scopes = _collect_traced_scopes(ctx)
    scope_ids = set(map(id, scopes))

    for scope, statics in scopes.items():
        # traced values visible here: this scope's params plus every
        # ENCLOSING traced scope's params (closure capture — the dominant
        # solver shape is `def body(carry)` inside a jitted function),
        # each minus that scope's own static names
        traced_params = set(_param_names(scope)) - statics
        all_statics = set(statics)
        for anc in ctx.ancestors(scope):
            if anc in scopes:
                traced_params |= set(_param_names(anc)) - scopes[anc]
                all_statics |= scopes[anc]
        all_statics -= traced_params  # a traced binding wins over a
        #                               same-named outer static
        for node in _walk_scope(scope, scope_ids):
            f = _check_node(ctx, node, traced_params, all_statics)
            if f is not None:
                findings.append(f)
    return findings


def _check_node(ctx: FileContext, node: ast.AST, traced_params: set[str],
                statics: set[str]) -> Finding | None:
    if isinstance(node, ast.Call):
        resolved = ctx.resolve_call(node)
        if resolved in PALLAS_REF_CALLS:
            return None
        # host syncs
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in HOST_SYNC_ATTRS:
            return ctx.finding(
                node, "trace-host-sync",
                f"`.{node.func.attr}()` forces a device->host sync inside "
                "a traced scope",
                "compute on-device with jnp, or hoist the fetch out of "
                "the jitted body")
        if resolved in HOST_SYNC_CALLS:
            return ctx.finding(
                node, "trace-host-sync",
                f"`{resolved}` materializes a host array inside a traced "
                "scope",
                "use jnp equivalents inside jit; convert at the "
                "dispatch boundary")
        if isinstance(node.func, ast.Name) \
                and node.func.id in CAST_BUILTINS and node.args:
            arg = node.args[0]
            if not isinstance(arg, ast.Constant) \
                    and not _mentions_static_shape(arg) \
                    and not (isinstance(arg, ast.Name)
                             and arg.id in statics):
                return ctx.finding(
                    node, "trace-host-sync",
                    f"`{node.func.id}(...)` on a traced value "
                    "concretizes (host sync) inside a traced scope",
                    "keep it an array (jnp.asarray / astype), or mark "
                    "the argument static")
        # nondeterminism
        if resolved in NONDET_CALLS or (
                resolved and resolved.startswith(NONDET_PREFIXES)):
            return ctx.finding(
                node, "trace-nondet",
                f"`{resolved}` inside a traced scope freezes a "
                "trace-time value into the compiled program "
                "(nondeterministic across runs/resumes)",
                "thread seeded jax.random keys (or pass the value in as "
                "an argument)")
    elif isinstance(node, (ast.If, ast.While)):
        hit = sorted({n.id for n in _traced_value_uses(ctx, node.test)
                      if n.id in traced_params})
        if hit:
            kw = "if" if isinstance(node, ast.If) else "while"
            return ctx.finding(
                node, "trace-branch",
                f"Python `{kw}` on traced value(s) {', '.join(hit)} — "
                "concretization error at trace time, or a silent "
                "per-value recompile",
                "use lax.cond/jnp.where, or list the parameter in "
                "static_argnames")
    return None
