"""Process-local metrics registry with text exposition (ISSUE 18).

One registry per process, one lock per registry: counters, gauges, and
fixed-log-bucket histograms (the bucket edges are
``utils/profiling.HIST_EDGES`` so a scraped histogram and the post-hoc
report's ``latency_summary`` agree bucket-for-bucket). The registry is
deliberately tiny — no label cardinality explosions, no per-sample
allocation beyond a dict entry — because every publisher (batcher
dispatch, store-backend fetches, streaming slabs, rowshard passes,
launcher respawns) sits on a hot-ish host path.

Publication is gated on ``CNMF_TPU_METRICS``: the module-level helpers
(:func:`counter_inc`, :func:`gauge_set`, :func:`observe`) are no-ops
when the knob is off, so an un-knobbed run records nothing and scrapes
render an explicit "disabled" banner. :class:`MetricsRegistry` methods
themselves are ungated so tests can drive a private registry directly.

Exposition is the de-facto text format (``# TYPE`` comments +
``name{label="v"} value`` samples; histograms expose cumulative
``_bucket{le=...}`` series plus ``_sum``/``_count``), parse-backable via
:func:`parse_exposition`. Snapshots of the same state land in the run
telemetry JSONL as ``metrics_snapshot`` events through the existing
``EventLog`` (same O_APPEND single-write discipline).
"""

from __future__ import annotations

import threading

from ..utils.envknobs import env_flag
from ..utils.profiling import HIST_EDGES

__all__ = [
    "METRICS_ENV", "MetricsRegistry", "metrics_enabled",
    "default_registry", "reset_default_registry", "counter_inc",
    "gauge_set", "observe", "render_text", "parse_exposition",
    "emit_snapshot", "Snapshotter",
]

METRICS_ENV = "CNMF_TPU_METRICS"

_COUNTER = "counter"
_GAUGE = "gauge"
_HISTOGRAM = "histogram"


def metrics_enabled() -> bool:
    """True when ``CNMF_TPU_METRICS`` is on. Checked at every
    publication site (like ``telemetry_enabled``), so long-lived
    processes and tests can toggle it without rebuilding objects."""
    return env_flag(METRICS_ENV, False)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Histogram:
    """Cumulative fixed-edge histogram cell: per-bucket counts (one
    overflow bucket), sum, count. Mutated only under the owning
    registry's lock."""

    __slots__ = ("buckets", "sum", "count")

    def __init__(self):
        self.buckets = [0] * (len(HIST_EDGES) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        for i, edge in enumerate(HIST_EDGES):
            if value <= edge:
                self.buckets[i] += 1
                break
        else:
            self.buckets[-1] += 1
        self.sum += value
        self.count += 1


class MetricsRegistry:
    """Thread-safe instrument store. ``(name, kind)`` is the instrument;
    each distinct label set is a series under it. Mixing kinds under one
    name raises — the exposition format cannot represent it."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, {label_key: value | _Histogram})
        self._instruments: dict = {}

    def _series(self, name: str, kind: str, labels: dict):
        inst = self._instruments.get(name)
        if inst is None:
            inst = (kind, {})
            self._instruments[name] = inst
        elif inst[0] != kind:
            raise ValueError(
                "metric %r already registered as %s, not %s"
                % (name, inst[0], kind))
        return inst[1], _label_key(labels)

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError("counter %r increment must be >= 0" % name)
        with self._lock:
            series, key = self._series(name, _COUNTER, labels)
            series[key] = series.get(key, 0.0) + float(value)

    def set(self, name: str, value: float, **labels) -> None:
        with self._lock:
            series, key = self._series(name, _GAUGE, labels)
            series[key] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        with self._lock:
            series, key = self._series(name, _HISTOGRAM, labels)
            cell = series.get(key)
            if cell is None:
                cell = series[key] = _Histogram()
            cell.observe(float(value))

    def snapshot(self) -> dict:
        """JSON-safe copy of the whole registry, the payload of a
        ``metrics_snapshot`` telemetry event. Histograms keep the
        report's ``latency_summary`` bucket labels (``<=%g`` / ``>%g``,
        NON-cumulative) so the two surfaces read identically."""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            for name in sorted(self._instruments):
                kind, series = self._instruments[name]
                for key in sorted(series):
                    label = name if not key else "%s{%s}" % (
                        name, ",".join("%s=%s" % kv for kv in key))
                    if kind == _COUNTER:
                        out["counters"][label] = series[key]
                    elif kind == _GAUGE:
                        out["gauges"][label] = series[key]
                    else:
                        cell = series[key]
                        hist = {}
                        for i, edge in enumerate(HIST_EDGES):
                            if cell.buckets[i]:
                                hist["<=%g" % edge] = cell.buckets[i]
                        if cell.buckets[-1]:
                            hist[">%g" % HIST_EDGES[-1]] = cell.buckets[-1]
                        out["histograms"][label] = {
                            "count": cell.count, "sum": cell.sum,
                            "buckets": hist}
        return out

    def render_text(self) -> str:
        """Text exposition: ``# TYPE`` per instrument, samples sorted by
        (name, labels) so scrapes diff cleanly; histogram buckets are
        CUMULATIVE with an explicit ``+Inf`` bucket."""
        lines = []
        with self._lock:
            for name in sorted(self._instruments):
                kind, series = self._instruments[name]
                lines.append("# TYPE %s %s" % (name, kind))
                for key in sorted(series):
                    if kind == _HISTOGRAM:
                        cell = series[key]
                        acc = 0
                        for i, edge in enumerate(HIST_EDGES):
                            acc += cell.buckets[i]
                            lines.append("%s_bucket%s %d" % (
                                name, _fmt_labels(key, le="%g" % edge),
                                acc))
                        acc += cell.buckets[-1]
                        lines.append("%s_bucket%s %d" % (
                            name, _fmt_labels(key, le="+Inf"), acc))
                        lines.append("%s_sum%s %s" % (
                            name, _fmt_labels(key), _fmt_value(cell.sum)))
                        lines.append("%s_count%s %d" % (
                            name, _fmt_labels(key), cell.count))
                    else:
                        lines.append("%s%s %s" % (
                            name, _fmt_labels(key),
                            _fmt_value(series[key])))
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()


def _fmt_value(v: float) -> str:
    f = float(v)
    return "%d" % f if f == int(f) and abs(f) < 1e15 else repr(f)


def _escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_labels(key: tuple, **extra) -> str:
    pairs = list(key) + sorted(extra.items())
    if not pairs:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, _escape(v)) for k, v in pairs)


def parse_exposition(text: str) -> dict:
    """Parse exposition text back into ``{(name, labels_tuple): value}``
    plus a ``types`` side table — the round-trip half of the format the
    tests and the obs smoke gate assert with."""
    samples: dict = {}
    types: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        name_part, _, value_part = line.rpartition(" ")
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            body = rest.rstrip("}")
            labels = []
            for item in _split_labels(body):
                k, _, v = item.partition("=")
                labels.append((k, _unescape(v.strip('"'))))
            key = (name, tuple(labels))
        else:
            key = (name_part, ())
        samples[key] = float(value_part)
    return {"samples": samples, "types": types}


def _split_labels(body: str):
    out, cur, in_str, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\" and in_str:
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_str = not in_str
            cur.append(ch)
            continue
        if ch == "," and not in_str:
            out.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _unescape(v: str) -> str:
    return (v.replace(r'\"', '"').replace(r"\n", "\n")
            .replace(r"\\", "\\"))


# ---------------------------------------------------------------------------
# process-default registry + gated helpers (the publisher API)
# ---------------------------------------------------------------------------

_REGISTRY_LOCK = threading.Lock()
_DEFAULT_REGISTRY: list = []  # 0-or-1 element; rebound under the lock


def default_registry() -> MetricsRegistry:
    """The one process-wide registry every publisher shares — serve
    batcher, store backend, streaming engine, launcher, netstore server
    all land in the same scrape."""
    with _REGISTRY_LOCK:
        if not _DEFAULT_REGISTRY:
            _DEFAULT_REGISTRY.append(MetricsRegistry())
        return _DEFAULT_REGISTRY[0]


def reset_default_registry() -> None:
    """Tests only: drop all recorded series."""
    with _REGISTRY_LOCK:
        if _DEFAULT_REGISTRY:
            _DEFAULT_REGISTRY[0].reset()


def counter_inc(name: str, value: float = 1.0, **labels) -> None:
    """Gated counter bump on the default registry — a no-op (no lock,
    no allocation) when ``CNMF_TPU_METRICS`` is off."""
    if metrics_enabled():
        default_registry().inc(name, value, **labels)


def gauge_set(name: str, value: float, **labels) -> None:
    if metrics_enabled():
        default_registry().set(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    if metrics_enabled():
        default_registry().observe(name, value, **labels)


_DISABLED_BANNER = ("# cnmf-tpu metrics disabled "
                    "(set CNMF_TPU_METRICS=1 to enable)\n")


def render_text() -> str:
    """Exposition for the default registry — the ``GET /metrics`` body
    on both the serve daemon and the object-store server."""
    if not metrics_enabled():
        return _DISABLED_BANNER
    return default_registry().render_text()


# ---------------------------------------------------------------------------
# metrics_snapshot events
# ---------------------------------------------------------------------------

def emit_snapshot(events, registry=None, slo=None) -> bool:
    """Append one ``metrics_snapshot`` event (full registry state, plus
    the current SLO evaluation when the caller has one) to the run's
    telemetry JSONL. Requires BOTH telemetry and metrics on; returns
    whether an event was written."""
    if events is None or not getattr(events, "enabled", False):
        return False
    if not metrics_enabled():
        return False
    reg = default_registry() if registry is None else registry
    events.emit("metrics_snapshot", metrics=reg.snapshot(), slo=slo)
    return True


class Snapshotter:
    """Background snapshot loop for long-lived processes (the serve
    daemon): one ``metrics_snapshot`` per ``interval_s`` plus a final
    one at :meth:`stop`, so even a short-lived daemon leaves at least
    one snapshot in its event stream."""

    def __init__(self, events, interval_s: float = 30.0, registry=None,
                 slo_fn=None):
        self._events = events
        self._interval = max(1.0, float(interval_s))
        self._registry = registry
        self._slo_fn = slo_fn
        self._stop = threading.Event()
        self._thread = None

    def _slo(self):
        return self._slo_fn() if self._slo_fn is not None else None

    def _run(self):
        while not self._stop.wait(self._interval):
            emit_snapshot(self._events, registry=self._registry,
                          slo=self._slo())

    def start(self) -> "Snapshotter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="cnmf-metrics-snapshot",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        emit_snapshot(self._events, registry=self._registry,
                      slo=self._slo())
