"""Batched ordinary least squares over row blocks.

JAX equivalent of ``efficient_ols_all_cols``
(``/root/reference/src/cnmf/cnmf.py:56-126``): solves
``Beta = (X^T X)^{-1} X^T Y`` for every column of ``Y`` simultaneously by
accumulating the k x k and k x g sufficient statistics over row blocks, with
optional *global* z-scoring of ``Y``'s columns applied blockwise so a sparse
``Y`` is densified only one block at a time. Used to produce the
"gene_spectra_score" z-score GEP matrix (``cnmf.py:1132``).

The accumulation is two MXU matmuls per block; under ``shard_map`` the same
kernel row-shards across devices with a ``psum`` over the block axis (see
``cnmf_torch_tpu.parallel``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from .stats import column_mean_var

__all__ = ["ols_all_cols"]


@jax.jit
def _block_stats(xb, yb):
    return xb.T @ xb, xb.T @ yb


@jax.jit
def _block_stats_normalized(xb, yb, meanY, inv_stdY):
    yb = (yb - meanY) * inv_stdY
    return xb.T @ xb, xb.T @ yb


def ols_all_cols(X, Y, batch_size: int = 65536, normalize_y: bool = False,
                 precision: str = "float64") -> np.ndarray:
    """OLS coefficients (n_predictors x n_targets).

    ``X``: dense (n x k) predictors. ``Y``: dense or CSR (n x g) targets.
    ``normalize_y`` z-scores Y's columns with *global* population moments
    (ddof=0, matching ``get_mean_var``; zero variances floored at 1e-12,
    cnmf.py:94-96) while densifying only one row block at a time.

    ``precision='float64'`` (default) runs the accumulation in host float64,
    matching the reference's all-float64 path (cnmf.py:99-100) — the normal
    equations amplify fp32 rounding by cond(X^T X), which breaks the
    RMS<1e-4 parity bar. ``'float32'`` streams blocks through fp32 MXU
    matmuls for atlas-scale inputs where that tradeoff is acceptable.
    """
    n, k = X.shape
    nY, g = Y.shape
    if n != nY:
        raise ValueError("X and Y must have the same number of rows.")

    if precision == "float64":
        return _ols_f64_host(X, Y, batch_size, normalize_y)
    dtype = jnp.float32

    if normalize_y:
        meanY, varY = column_mean_var(Y, ddof=0)
        varY = np.maximum(varY, 1e-12)
        meanY_d = jnp.asarray(meanY, dtype=dtype)
        inv_stdY_d = jnp.asarray(1.0 / np.sqrt(varY), dtype=dtype)

    # per-block products run as fp32 MXU matmuls; cross-block accumulation
    # and the k x k solve happen in float64 on host (k and g are small) so
    # conditioning does not amplify fp32 rounding — the reference accumulates
    # and solves entirely in float64 (cnmf.py:99-100, 125)
    XtX = np.zeros((k, k), dtype=np.float64)
    XtY = np.zeros((k, g), dtype=np.float64)
    X = np.asarray(X)
    for start in range(0, n, batch_size):
        stop = min(start + batch_size, n)
        xb = jnp.asarray(X[start:stop], dtype=dtype)
        yb = Y[start:stop]
        if sp.issparse(yb):
            yb = yb.toarray()
        yb = jnp.asarray(yb, dtype=dtype)
        if normalize_y:
            bXtX, bXtY = _block_stats_normalized(xb, yb, meanY_d, inv_stdY_d)
        else:
            bXtX, bXtY = _block_stats(xb, yb)
        XtX += np.asarray(bXtX, dtype=np.float64)
        XtY += np.asarray(bXtY, dtype=np.float64)

    # k x k normal-equation solve; lstsq for rank-deficiency robustness,
    # as in the reference (cnmf.py:125)
    beta, _, _, _ = np.linalg.lstsq(XtX, XtY, rcond=None)
    return beta


def _ols_f64_host(X, Y, batch_size: int, normalize_y: bool) -> np.ndarray:
    n, k = X.shape
    g = Y.shape[1]
    if normalize_y:
        # float64 moments from a blockwise pass (sparse Y never densified)
        s1 = np.zeros(g)
        s2 = np.zeros(g)
        for start in range(0, n, batch_size):
            yb = Y[start:start + batch_size]
            if sp.issparse(yb):
                s1 += np.asarray(yb.sum(axis=0)).ravel()
                s2 += np.asarray(yb.multiply(yb).sum(axis=0)).ravel()
            else:
                yb = np.asarray(yb, dtype=np.float64)
                s1 += yb.sum(axis=0)
                s2 += (yb * yb).sum(axis=0)
        meanY = s1 / n
        varY = np.maximum(s2 / n - meanY ** 2, 1e-12)
        inv_stdY = 1.0 / np.sqrt(varY)

    XtX = np.zeros((k, k))
    XtY = np.zeros((k, g))
    xsum = np.zeros(k)
    X = np.asarray(X, dtype=np.float64)
    for start in range(0, n, batch_size):
        stop = min(start + batch_size, n)
        xb = X[start:stop]
        yb = Y[start:stop]
        XtX += xb.T @ xb
        if sp.issparse(yb):
            # csr.T @ dense multiplies sparsely, O(nnz * k) — no densify
            XtY += np.asarray((yb.T @ xb).T, dtype=np.float64)
        else:
            XtY += xb.T @ np.asarray(yb, dtype=np.float64)
        if normalize_y:
            xsum += xb.sum(axis=0)
    if normalize_y:
        # centering identity: X^T((Y - mean) * inv_std) =
        # (X^T Y - (X^T 1) mean^T) * inv_std — exact in float64, so the
        # z-scored (n x g) copy the reference materializes per block
        # (cnmf.py:108-110) is never built for dense OR sparse Y. Measured
        # on the north-star consensus (10000 x 5000 dense TPM): the warm
        # OLS stage dropped 3.8 s -> 1.1 s.
        XtY = (XtY - np.outer(xsum, meanY)) * inv_stdY[None, :]
    beta, _, _, _ = np.linalg.lstsq(XtX, XtY, rcond=None)
    return beta
