"""Roofline cost model tests (ISSUE 19): the analytic per-lane
flop/byte predictions (`obs/costmodel.py` + the per-kernel hooks) are
cross-validated against XLA's own `cost_analysis()` on pinned shapes —
dense beta=2, the ELL KL statistics on both sides (each pinned at the
shape whose fusion regime its byte model encodes), the Pallas lane
label, and one 2-D grid pass on a 2x2 mesh — all within the 10%
acceptance band. Plus degenerate guards (empty window, zero-width
slab), roofline verdict math, the perf_model event end-to-end from a
real factorize, and the byte-identity guarantee: CNMF_TPU_PERF_MODEL
is host-side only, so set-vs-unset compiled programs are equal."""

import numpy as np
import pandas as pd
import pytest

from cnmf_torch_tpu.obs import costmodel as cm
from cnmf_torch_tpu.utils import telemetry as tel

TOL = 0.10  # the ISSUE 19 acceptance band vs cost_analysis()


def _within(pred, actual, tol=TOL):
    assert actual > 0, f"cost_analysis returned {actual}"
    rel = abs(pred - actual) / actual
    assert rel <= tol, (f"prediction {pred:.0f} vs XLA {actual:.0f} "
                        f"off by {100 * rel:.1f}% (> {100 * tol:.0f}%)")


# ---------------------------------------------------------------------------
# dense beta=2 vs cost_analysis (pinned shape)
# ---------------------------------------------------------------------------

def test_dense_beta2_within_band_of_xla():
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.nmf import (_update_H, _update_W,
                                        dense_update_cost)

    n, g, k = 512, 256, 9
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.random((n, g)).astype(np.float32))
    H = jnp.asarray(rng.random((n, k)).astype(np.float32) + 0.1)
    W = jnp.asarray(rng.random((k, g)).astype(np.float32) + 0.1)
    ch = cm.xla_cost(lambda X, H, W: _update_H(X, H, W, 2.0, 0.0, 0.0),
                     X, H, W)
    cw = cm.xla_cost(lambda X, H, W: _update_W(X, H, W, 2.0, 0.0, 0.0),
                     X, H, W)
    m = dense_update_cost(n, g, k, 2.0)
    _within(m["flops"], ch["flops"] + cw["flops"])
    _within(m["bytes"], ch["bytes"] + cw["bytes"])
    assert m["lane"] == "vmapped"
    assert dense_update_cost(n, g, k, 2.0, bundled=True)["lane"] == \
        "bundled"


# ---------------------------------------------------------------------------
# ELL KL statistics vs cost_analysis — each side at the pinned shape
# whose XLA fusion regime its byte model encodes
# ---------------------------------------------------------------------------

def _ell_fixture(n, g, k=9, density=0.05):
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.sparse import csr_to_ell

    rng = np.random.default_rng(0)
    X = ((rng.random((n, g)) < density)
         * rng.random((n, g))).astype(np.float32)
    E = csr_to_ell(X)
    H = jnp.abs(jnp.asarray(rng.random((n, k), dtype=np.float32)))
    W = jnp.abs(jnp.asarray(rng.random((k, g), dtype=np.float32)))
    return E, H, W


def test_ell_kl_h_side_within_band_of_xla():
    from cnmf_torch_tpu.ops.sparse import ell_kl_h_stats, ell_stats_cost

    n, g, k = 512, 256, 9
    E, H, W = _ell_fixture(n, g, k)
    ca = cm.xla_cost(ell_kl_h_stats, E, H, W)
    m = ell_stats_cost(n, g, k, E.width, t_width=E.t_width)
    _within(m["h_flops"], ca["flops"])
    _within(m["h_bytes"], ca["bytes"])
    assert m["lane"] == "ell-jnp"


def test_ell_kl_w_side_within_band_of_xla():
    from cnmf_torch_tpu.ops.sparse import ell_kl_w_stats, ell_stats_cost

    n, g, k = 256, 512, 9
    E, H, W = _ell_fixture(n, g, k)
    ca = cm.xla_cost(ell_kl_w_stats, E, H, W)
    m = ell_stats_cost(n, g, k, E.width, t_width=E.t_width)
    _within(m["w_flops"], ca["flops"])
    _within(m["w_bytes"], ca["bytes"])


def test_pallas_lane_label_and_interpret_exemption():
    from cnmf_torch_tpu.ops.pallas import pallas_interpret, pallas_stats_cost
    from cnmf_torch_tpu.ops.sparse import ell_stats_cost

    c = pallas_stats_cost(512, 256, 9, 32)
    assert c["lane"] == "ell-pallas"
    # same useful flops as the jnp ELL lane, strictly fewer bytes (the
    # fused kernel never spills the slab-sized intermediates)
    ref = ell_stats_cost(512, 256, 9, 32)
    assert c["flops"] == ref["flops"]
    assert c["bytes"] < ref["bytes"]
    # on this CPU gate the kernels run in interpret mode: the cost is
    # still produced, but marked perf-exempt, never compared
    assert c["perf_exempt"] == bool(pallas_interpret())


# ---------------------------------------------------------------------------
# grid2d pass vs cost_analysis on a 2x2 mesh (per-device program)
# ---------------------------------------------------------------------------

def test_grid2d_pass_within_band_of_xla():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from cnmf_torch_tpu.parallel.grid2d import _grid_pass_jit, grid_pass_cost

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 simulated devices")
    n, g, k = 256, 256, 5
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.gamma(1.0, 1.0, (n, g)).astype(np.float32))
    H = jnp.asarray(rng.random((n, k), np.float32) + 0.1)
    W = jnp.asarray(rng.random((k, g), np.float32) + 0.1)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("cells", "genes"))
    ca = _grid_pass_jit.lower(X, H, W, mesh, 2.0, jnp.float32(1e-4), 3,
                              0.0, 0.0, 0.0, 0.0).compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    m = grid_pass_cost(n // 2, g // 2, k)
    _within(m["flops"], float(ca["flops"]))
    _within(m["bytes"], float(ca["bytes accessed"]))
    assert m["calibrated"] is True and m["lane"] == "grid2d"


def test_grid2d_collective_bytes_cross_check():
    from cnmf_torch_tpu.parallel.grid2d import (coll_bytes_per_pass,
                                                grid_pass_cost)

    m = grid_pass_cost(128, 128, 5, nblk_h=2, nblk_w=2, n_dev=4)
    assert m["collective_bytes"] == coll_bytes_per_pass(
        128, 128, 5, 2.0, nblk_h=2, nblk_w=2, n_dev=4)
    assert m["collective_bytes"] > 0


# ---------------------------------------------------------------------------
# lane_cost / plan_cost dispatch + degenerate guards
# ---------------------------------------------------------------------------

def test_lane_cost_degenerate_guards():
    # empty window, zero-K, and a zero-nnz (width-0) ELL slab all cost
    # exactly zero and say so, instead of emitting nonsense rooflines
    for kwargs in ({"n": 0, "g": 64, "k": 5},
                   {"n": 64, "g": 64, "k": 0},
                   {"n": 64, "g": 64, "k": 5}):
        c = cm.lane_cost("vmapped", **kwargs) if 0 in kwargs.values() \
            else cm.lane_cost("ell-jnp", **kwargs, ell_width=0)
        assert c == {"flops": 0.0, "bytes": 0.0, "lane": c["lane"],
                     "degenerate": True}
    assert cm.serve_project_cost(0, 64, 64, 5)["degenerate"] is True


def test_plan_cost_dispatches_by_plan_inputs():
    from cnmf_torch_tpu.runtime.planner import ExecutionPlan

    plan = ExecutionPlan(kernel="vmapped", beta=2.0)
    pi = plan.cost_inputs()
    assert pi["kernel"] == "vmapped" and pi["beta"] == 2.0
    c = cm.plan_cost(pi, 512, 256, 9)
    assert c["lane"] == "vmapped" and c["flops"] > 0
    # grid layout forces the grid lane regardless of the kernel label
    cg = cm.plan_cost({"kernel": "vmapped", "beta": 2.0,
                       "layout": "grid2d", "grid_shape": [2, 2]},
                      256, 256, 5)
    assert cg["lane"] == "grid2d" and "collective_bytes" in cg


# ---------------------------------------------------------------------------
# roofline verdict math + peaks
# ---------------------------------------------------------------------------

def test_chip_peaks_lookup_and_nominal_fallback():
    v4 = cm.chip_peaks("TPU v4")
    assert v4 == {"flops": 275e12, "bw": 1.2e12, "source": "datasheet"}
    assert cm.chip_peaks("TPU v5p")["flops"] == 459e12
    for unknown in (None, "", "cpu", "Tesla V100"):
        p = cm.chip_peaks(unknown)
        assert p["source"] == "nominal-cpu"


def test_roofline_verdicts():
    peaks = {"flops": 100e12, "bw": 1e12, "source": "datasheet"}
    # balance point = 100 flops/byte: intensity above => compute-bound
    r = cm.roofline(2e12, 1e9, 1.0, peaks)
    assert r["bound"] == "compute-bound" and not r["perf_exempt"]
    assert r["mfu"] == pytest.approx(0.02)
    r = cm.roofline(1e12, 5e11, 1.0, peaks)
    assert r["bound"] == "memory-bound"
    assert r["bw_frac"] == pytest.approx(0.5)
    # degenerate work or a dead clock is "idle", never a div-by-zero
    assert cm.roofline(0.0, 0.0, 1.0, peaks)["bound"] == "idle"
    assert cm.roofline(1e9, 1e6, 0.0, peaks)["bound"] == "idle"
    # nominal peaks always exempt, regardless of the flag
    assert cm.roofline(1e9, 1e6, 1.0, None)["perf_exempt"] is True
    assert cm.roofline(1e9, 1e6, 1.0, peaks,
                       perf_exempt=True)["perf_exempt"] is True


# ---------------------------------------------------------------------------
# perf_model event end-to-end + report rendering
# ---------------------------------------------------------------------------

def _mini_counts(n=160, g=90, seed=5):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(4) * 0.3, size=n)
    spectra = rng.gamma(0.3, 1.0, size=(4, g)) * 40.0 / g
    counts = rng.poisson(usage @ spectra * 260.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    return pd.DataFrame(counts, index=[f"c{i}" for i in range(n)],
                        columns=[f"g{j}" for j in range(g)])


def test_perf_model_event_end_to_end(tmp_path, monkeypatch):
    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
    monkeypatch.setenv(cm.PERF_MODEL_ENV, "1")
    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(_mini_counts(), counts_fn)
    obj = cNMF(output_dir=str(tmp_path), name="pm")
    obj.prepare(counts_fn, components=[3], n_iter=4, seed=7,
                num_highvar_genes=70)
    obj.factorize()

    ev_path = tmp_path / "pm" / "cnmf_tmp" / "pm.events.jsonl"
    tel.validate_events_file(str(ev_path))
    events = tel.read_events(str(ev_path))
    pms = [e for e in events if e["t"] == "perf_model"]
    assert pms, "factorize with the knob on must emit a perf_model event"
    pm = pms[0]
    assert pm["stage"].startswith("factorize")
    assert pm["predicted"]["flops"] > 0 and pm["predicted"]["bytes"] > 0
    assert pm["measured"]["wall_s"] > 0 and pm["measured"]["passes"] >= 1
    roof = pm["roofline"]
    assert roof["bound"] in ("compute-bound", "memory-bound", "idle")
    # this gate runs on CPU: nominal peaks => exempt, never compared
    assert roof["peak_source"] == "nominal-cpu"
    assert roof["perf_exempt"] is True

    summary = tel.summarize_events(events)
    rows = summary["roofline"]
    assert rows and rows[0]["lane"] == pm["lane"]
    assert rows[0]["mfu"] is None or rows[0]["mfu"] >= 0
    report = tel.render_report(str(tmp_path / "pm"))
    assert "Roofline" in report
    assert pm["lane"] in report


def test_perf_model_event_not_emitted_when_knob_unset(tmp_path,
                                                      monkeypatch):
    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
    monkeypatch.delenv(cm.PERF_MODEL_ENV, raising=False)
    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(_mini_counts(), counts_fn)
    obj = cNMF(output_dir=str(tmp_path), name="off")
    obj.prepare(counts_fn, components=[3], n_iter=3, seed=7,
                num_highvar_genes=70)
    obj.factorize()
    events = tel.read_events(
        str(tmp_path / "off" / "cnmf_tmp" / "off.events.jsonl"))
    assert not [e for e in events if e["t"] == "perf_model"]


def test_validate_event_rejects_malformed_perf_model():
    good = {"v": tel.SCHEMA_VERSION, "t": "perf_model", "ts": 1.0,
            "stage": "factorize", "lane": "vmapped",
            "predicted": {"flops": 1e9, "bytes": 1e8},
            "measured": {"wall_s": 0.5, "passes": 3},
            "roofline": {"bound": "memory-bound"}}
    tel.validate_event(good)
    for breakage in ({"predicted": "fast"},
                     {"predicted": {"flops": "many", "bytes": 1.0}},
                     {"measured": {"passes": 3}},
                     {"roofline": {"bound": 7}}):
        with pytest.raises(ValueError):
            tel.validate_event({**good, **breakage})


# ---------------------------------------------------------------------------
# the house rule: the knob is host-side only — byte-identical programs
# ---------------------------------------------------------------------------

def test_compiled_programs_byte_identical_with_perf_model_on(monkeypatch):
    import jax
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.nmf import nmf_fit_batch, random_init

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.gamma(1.0, 1.0, (60, 30)).astype(np.float32))
    H0, W0 = random_init(jax.random.key(0), 60, 30, 3, jnp.mean(X))

    def lowered():
        return nmf_fit_batch.lower(X, H0, W0, beta=2.0,
                                   max_iter=10).as_text()

    base = lowered()
    monkeypatch.setenv(cm.PERF_MODEL_ENV, "1")
    from cnmf_torch_tpu.obs.regress import GATE_BAND_ENV, GATE_N_ENV
    monkeypatch.setenv(GATE_BAND_ENV, "0.1")
    monkeypatch.setenv(GATE_N_ENV, "7")
    assert lowered() == base
