"""Tier-1 telemetry smoke gate (scripts/verify_tier1.sh).

Runs the mini pipeline with CNMF_TPU_TELEMETRY=1 and validates EVERY
emitted event against the schema (utils/telemetry.py — the one schema
definition), then renders the `cnmf report` view. Exits nonzero on any
malformed event, missing event class, or report failure, failing the gate.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

# runnable as `python scripts/telemetry_smoke.py` without installing the
# package: sys.path[0] is scripts/, the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["CNMF_TPU_TELEMETRY"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.cli import main as cli_main
    from cnmf_torch_tpu.utils import save_df_to_npz
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    workdir = tempfile.mkdtemp(prefix="telemetry_smoke_")
    try:
        rng = np.random.default_rng(3)
        usage = rng.dirichlet(np.ones(5) * 0.3, size=220)
        spectra = rng.gamma(0.3, 1.0, size=(5, 130)) * 40.0 / 130
        counts = rng.poisson(usage @ spectra * 300.0).astype(np.float64)
        counts[counts.sum(axis=1) == 0, 0] = 1.0
        df = pd.DataFrame(counts, index=[f"c{i}" for i in range(220)],
                          columns=[f"g{j}" for j in range(130)])
        counts_fn = os.path.join(workdir, "counts.df.npz")
        save_df_to_npz(df, counts_fn)

        obj = cNMF(output_dir=workdir, name="smoke")
        obj.prepare(counts_fn, components=[3, 4], n_iter=10, seed=7,
                    num_highvar_genes=100)
        obj.factorize()
        obj.combine()
        obj.consensus(k=3, density_threshold=2.0, show_clustering=False)

        ev_path = os.path.join(workdir, "smoke", "cnmf_tmp",
                               "smoke.events.jsonl")
        n = validate_events_file(ev_path)  # raises on any malformed line
        counts_by_type: dict = {}
        for ev in read_events(ev_path):
            counts_by_type[ev["t"]] = counts_by_type.get(ev["t"], 0) + 1
        required = {"manifest": 1, "dispatch": 1, "stage": 3,
                    "replicates": 2, "memory": 1}
        for t, minimum in required.items():
            if counts_by_type.get(t, 0) < minimum:
                print(f"telemetry smoke: expected >= {minimum} {t!r} "
                      f"event(s), got {counts_by_type.get(t, 0)} "
                      f"(all: {counts_by_type})", file=sys.stderr)
                return 1

        # the report CLI must render the stream without error
        cli_main(["report", os.path.join(workdir, "smoke")])
        print(f"telemetry smoke: {n} schema-valid events "
              f"({counts_by_type}); report rendered")
        return 0
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
