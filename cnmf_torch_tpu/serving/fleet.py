"""Replicated serving fleet (ISSUE 20): tenant-aware routing, failover,
and zero-downtime reference rollover over N ``serve`` daemon replicas.

One warm daemon (``daemon.py``) is the single-host story; the
millions-of-users scenario (ROADMAP item 2) is horizontal — many
replicas that individually die, hang, and roll their reference forward
without the fleet ever dropping a request. This module is the stdlib
HTTP router/load-balancer in front of that fleet:

  * **Tenant routing** — a consistent-hash ring (:class:`HashRing`,
    sha1, 64 vnodes per replica) pins each tenant to one replica, so
    the tenant's warm-start usage cache and the replica's AOT program
    buckets stay hot; adding or removing a replica remaps only ~1/N of
    the tenants (pinned by ``tests/test_fleet.py``).
  * **Admission** — per-tenant token-bucket quotas
    (``CNMF_TPU_FLEET_TENANT_QPS``) shed a hot tenant with HTTP 429
    BEFORE it consumes replica queue space, and the 3-strike poison
    quarantine is fleet-scoped: strikes are counted at the router, so a
    poisoned tenant stays quarantined across failovers instead of
    re-learning the lesson per replica.
  * **Failover** — replica health via subprocess liveness, ``/healthz``
    polling, and heartbeat stamps (``runtime/elastic.py``); a dead
    replica is detected at the supervision tick, its tenants remap to
    the survivors (ring removal), and it respawns after the launcher's
    deterministic exponential backoff (``launcher.respawn_delay``). A
    WEDGED replica (alive but unresponsive — SIGSTOP in the chaos
    drill) is convicted only on ``CNMF_TPU_FLEET_WEDGE_POLLS``
    consecutive ``/healthz`` failures WITH a stale/absent heartbeat,
    then SIGKILLed and respawned. Router retries ride idempotent
    request ids (``daemon.REQUEST_ID_HEADER``): at most one solve per
    id, so a retry after a mid-request death can never double-solve,
    and one hedged attempt (``CNMF_TPU_FLEET_HEDGE_MS``) bounds the
    p99 paid for a momentarily slow replica.
  * **Rollover** — ``POST /rollover {"spectra": <path>}`` serves a new
    reference with zero downtime: a fresh replica set warms against
    the new spectra (published through the remote ShardStore when
    ``CNMF_TPU_STORE_URI`` is set — the PR-13 distribution channel),
    the ring swaps atomically once every fresh replica is healthy, and
    the old generation drains (the daemon's ``/shutdown`` drain —
    every accepted request finishes) before it exits. No request
    observes an error or a mixed-reference reply.

Chaos clauses ``replicadeath`` / ``replicawedge``
(``runtime/faults.py``) let the tier-1 fleet smoke kill and wedge
replicas on demand; telemetry lands as ``replica_death`` /
``failover`` / ``rollover`` events plus router-side ``serve_request``
events carrying the serving replica, rendered by ``cnmf-tpu report``.
"""

from __future__ import annotations

import itertools
import hashlib
import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time

from ..launcher import respawn_delay
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..runtime import faults
from ..runtime.elastic import Heartbeat
from ..utils.envknobs import env_flag, env_float, env_int
from .batcher import POISON_QUARANTINE_STRIKES, ServeError
from .daemon import (REQUEST_ID_HEADER, ServeClient, _TCPHTTPServer,
                     _UnixHTTPServer, _UnixHTTPConnection)
from http.server import BaseHTTPRequestHandler

__all__ = [
    "HashRing",
    "TokenBucket",
    "FleetRouter",
    "FleetDaemon",
    "FleetClient",
    "SubprocessReplica",
    "fleet_forever",
    "default_fleet_socket_path",
]

# vnodes per replica on the consistent-hash ring: enough that tenant
# load spreads evenly across a handful of replicas, few enough that
# ring rebuilds stay trivially cheap
FLEET_VNODES = 64


def default_fleet_socket_path(run_dir: str) -> str:
    name = os.path.basename(os.path.normpath(run_dir))
    return os.path.join(run_dir, "cnmf_tmp", name + ".fleet.sock")


# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------

def _hash64(key: str) -> int:
    return int.from_bytes(hashlib.sha1(key.encode("utf-8")).digest()[:8],
                          "big")


class HashRing:
    """Consistent-hash ring over opaque node ids.

    Each node owns :data:`FLEET_VNODES` points; a key routes to the
    first point clockwise from its own hash. Removing a node remaps
    ONLY the keys that routed to it (they fall to the next point
    clockwise); adding a node steals ~1/N of the keyspace. That
    stability is the whole reason for the structure: a replica death
    must not reshuffle every tenant's warm-start cache onto a cold
    replica."""

    def __init__(self, nodes=()):
        self._points: list = []  # sorted [(hash, node)]
        self._nodes: set = set()
        for node in nodes:
            self.add(node)

    def add(self, node):
        if node in self._nodes:
            return
        self._nodes.add(node)
        for v in range(FLEET_VNODES):
            self._points.append((_hash64(f"{node}#{v}"), node))
        self._points.sort()

    def remove(self, node):
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._points = [p for p in self._points if p[1] != node]

    def nodes(self) -> set:
        return set(self._nodes)

    def __len__(self):
        return len(self._nodes)

    def route(self, key: str):
        """The key's home node, or ``None`` on an empty ring."""
        if not self._points:
            return None
        h = _hash64(str(key))
        lo, hi = 0, len(self._points)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._points[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        return self._points[lo % len(self._points)][1]

    def candidates(self, key: str) -> list:
        """Every node, ordered by ring distance from the key: the
        failover sequence (element 0 is :meth:`route`'s answer; retries
        walk clockwise so every router agrees on the fallback order)."""
        if not self._points:
            return []
        h = _hash64(str(key))
        lo, hi = 0, len(self._points)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._points[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        out, seen = [], set()
        n = len(self._points)
        for i in range(n):
            node = self._points[(lo + i) % n][1]
            if node not in seen:
                seen.add(node)
                out.append(node)
        return out


# ---------------------------------------------------------------------------
# per-tenant token buckets
# ---------------------------------------------------------------------------

class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s up to ``burst`` capacity;
    :meth:`allow` spends one token or answers False. ``clock`` is
    injectable so tests drive time deterministically."""

    def __init__(self, rate: float, burst: float | None = None,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst) if burst else max(1.0, 2.0 * self.rate)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def allow(self) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


# ---------------------------------------------------------------------------
# replicas (subprocess engine)
# ---------------------------------------------------------------------------

class SubprocessReplica:
    """One ``serve`` daemon subprocess: the real replica engine.

    The router only touches the duck interface (``start`` / ``alive`` /
    ``kill`` / ``healthz`` / ``forward`` / ``heartbeat_age`` /
    ``shutdown``) so unit tests substitute in-process fakes; everything
    process-shaped lives here."""

    def __init__(self, run_dir: str, slot: int, ordinal: int,
                 generation: int, spectra_path: str | None = None,
                 k: int | None = None, density_threshold=None,
                 replica_telemetry: bool | None = None):
        self.run_dir = run_dir
        self.slot = int(slot)
        self.ordinal = int(ordinal)
        self.generation = int(generation)
        self.spectra_path = spectra_path
        self.k = k
        self.density_threshold = density_threshold
        name = os.path.basename(os.path.normpath(run_dir))
        tmp = os.path.join(run_dir, "cnmf_tmp")
        self.socket_path = os.path.join(
            tmp, f"{name}.fleet.r{self.ordinal}.sock")
        self.log_path = os.path.join(
            tmp, f"{name}.fleet.r{self.ordinal}.log")
        self.heartbeat_path = os.path.join(
            tmp, f"{name}.serve.heartbeat.{self.ordinal}.json")
        self._telemetry = (env_flag("CNMF_TPU_FLEET_REPLICA_TELEMETRY",
                                    False)
                           if replica_telemetry is None
                           else bool(replica_telemetry))
        self.proc: subprocess.Popen | None = None
        self.started_at: float | None = None
        self.requests = 0  # router-side per-replica share counter

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def start(self):
        cmd = [sys.executable, "-m", "cnmf_torch_tpu", "serve",
               self.run_dir, "--socket", self.socket_path,
               "--replica-index", str(self.ordinal)]
        if self.k is not None:
            cmd += ["-k", str(self.k)]
        if self.density_threshold is not None:
            cmd += ["--local-density-threshold",
                    str(self.density_threshold)]
        if self.spectra_path is not None:
            cmd += ["--spectra", self.spectra_path]
        env = dict(os.environ)
        if not self._telemetry:
            # N replicas of one run dir would otherwise multi-count
            # serve_request in the merged report; the router's own
            # stream carries per-request outcomes
            env["CNMF_TPU_TELEMETRY"] = "0"
        # heartbeats are the wedge-conviction evidence — make sure the
        # replica actually stamps them unless the operator pinned a rate
        env.setdefault("CNMF_TPU_HEARTBEAT_S", "0.5")
        # an append-only crash log, not an artifact anyone parses — torn
        # tails are expected after SIGKILL chaos
        log = open(self.log_path, "ab")  # cnmf-lint: disable=artifact-nonatomic
        try:
            self.proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                         env=env)
        finally:
            log.close()
        self.started_at = time.monotonic()
        return self

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def uptime_s(self) -> float:
        if self.started_at is None:
            return 0.0
        return time.monotonic() - self.started_at

    def kill(self, wedge: bool = False):
        """SIGKILL the replica (``wedge=True`` SIGSTOPs instead — the
        fault hooks' alive-but-unresponsive profile)."""
        if self.proc is None:
            return
        try:
            self.proc.send_signal(signal.SIGSTOP if wedge
                                  else signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass

    def reap(self, timeout: float = 10.0):
        if self.proc is None:
            return
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.kill()
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass

    def _connect(self, timeout: float):
        return _UnixHTTPConnection(self.socket_path, timeout=timeout)

    def forward(self, method: str, path: str, body: bytes | None = None,
                headers: dict | None = None, timeout: float = 180.0):
        """Raw pass-through to the replica: ``(status, body_bytes)``.
        Raises ``OSError`` family on transport failure (dead socket,
        refused connect, read timeout) — the router's failover signal."""
        conn = self._connect(timeout)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def healthz(self, timeout: float = 5.0) -> dict:
        status, blob = self.forward("GET", "/healthz", timeout=timeout)
        if status != 200:
            raise ServeError(f"replica {self.ordinal}: healthz HTTP "
                             f"{status}")
        return json.loads(blob)

    def heartbeat_age(self) -> float | None:
        """Seconds since the replica's last heartbeat stamp, or ``None``
        when it never stamped."""
        rec = Heartbeat.read(self.heartbeat_path)
        if rec is None:
            return None
        return max(0.0, time.time() - float(rec.get("ts", 0.0)))

    def shutdown(self, grace_s: float = 60.0):
        """Drain-stop: ``POST /shutdown`` (the daemon finishes every
        accepted request before its batcher stops), bounded wait, then
        SIGKILL if it overstays."""
        try:
            self.forward("POST", "/shutdown", timeout=10.0)
        except OSError:
            pass
        if self.proc is not None:
            try:
                self.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                self.kill()
                self.reap(5.0)
        self._cleanup()

    def _cleanup(self):
        for path in (self.socket_path, self.heartbeat_path):
            try:
                if os.path.exists(path):
                    os.unlink(path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------

class _Slot:
    """One replica position: the ring membership unit the supervisor
    manages. ``replica`` is live (or warming) or ``None`` while down;
    ``attempts`` counts deaths against the respawn budget."""

    __slots__ = ("index", "replica", "in_ring", "attempts", "down_until",
                 "healthz_fails")

    def __init__(self, index: int):
        self.index = int(index)
        self.replica = None
        self.in_ring = False
        self.attempts = 0
        self.down_until = 0.0
        self.healthz_fails = 0


class FleetRouter:
    """Spawns, supervises, and routes over N serve replicas.

    ``replica_factory(slot, ordinal, generation, spectra_path)`` builds
    one replica (default: :class:`SubprocessReplica` over ``run_dir``);
    tests inject in-process fakes. :meth:`handle_project` /
    :meth:`handle_rollover` are plain ``(status, payload)`` functions so
    router behavior is unit-testable without any HTTP server."""

    def __init__(self, run_dir: str | None = None, *,
                 replicas: int | None = None,
                 spectra_path: str | None = None, k: int | None = None,
                 density_threshold=None, events=None,
                 replica_factory=None, forward_timeout_s: float = 180.0):
        self.run_dir = run_dir
        self.n_replicas = (env_int("CNMF_TPU_FLEET_REPLICAS", 2, lo=1)
                           if replicas is None else max(1, int(replicas)))
        self.events = events
        self.forward_timeout_s = float(forward_timeout_s)
        self.health_s = env_float("CNMF_TPU_FLEET_HEALTH_S", 0.5, lo=0.05)
        self.wedge_polls = env_int("CNMF_TPU_FLEET_WEDGE_POLLS", 3, lo=1)
        self.respawn_budget = env_int("CNMF_TPU_FLEET_RESPAWNS", 3, lo=0)
        self.warm_timeout_s = env_float("CNMF_TPU_FLEET_WARM_TIMEOUT_S",
                                        300.0, lo=1.0)
        self.retries = env_int("CNMF_TPU_FLEET_RETRIES", 2, lo=0)
        self.hedge_ms = env_float("CNMF_TPU_FLEET_HEDGE_MS", 0.0, lo=0.0)
        self.tenant_qps = env_float("CNMF_TPU_FLEET_TENANT_QPS", 0.0,
                                    lo=0.0)
        self.tenant_burst = env_float("CNMF_TPU_FLEET_TENANT_BURST", 0.0,
                                      lo=0.0)
        self.backoff_s = env_float("CNMF_TPU_WORKER_BACKOFF_S", 0.5,
                                   lo=0.0)
        if replica_factory is None:
            if run_dir is None:
                raise ValueError("need run_dir or replica_factory")

            def replica_factory(slot, ordinal, generation, spectra):
                return SubprocessReplica(
                    run_dir, slot, ordinal, generation,
                    spectra_path=spectra, k=k,
                    density_threshold=density_threshold)

        self._factory = replica_factory
        self._spectra_path = spectra_path
        self._ordinals = itertools.count(0)
        # ring + slots + generation swap together under one lock: a
        # request either sees the whole old generation or the whole new
        # one, never a mix
        self._ring_lock = threading.Lock()
        self._ring = HashRing()
        self._slots = [_Slot(i) for i in range(self.n_replicas)]
        self._by_node: dict = {}  # ordinal -> replica (ring members)
        self._generation = 0
        self._rollover_lock = threading.Lock()
        # fleet-scoped admission state
        self._tenant_lock = threading.Lock()
        self._tenant_home: dict = {}
        self._strikes: dict = {}
        self._quarantined: set = set()
        self._buckets: dict = {}
        self._slo = obs_slo.tracker_from_env()
        self._stats = {"requests": 0, "ok": 0, "shed": 0, "poison": 0,
                       "quarantined": 0, "error": 0, "retries": 0,
                       "hedged": 0, "failovers": 0, "replica_deaths": 0,
                       "rollovers": 0}
        self._stats_lock = threading.Lock()
        self._req_seq = itertools.count(1)
        self._running = False
        self._supervisor: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------

    def start(self, supervise: bool = True):
        """Spawn the initial replica set, wait until every one answers
        ``/healthz`` (bounded by ``CNMF_TPU_FLEET_WARM_TIMEOUT_S``),
        and start the supervision loop."""
        self._running = True
        fresh = []
        for slot in self._slots:
            rep = self._factory(slot.index, next(self._ordinals),
                                self._generation, self._spectra_path)
            rep.start()
            slot.replica = rep
            fresh.append((slot, rep))
        deadline = time.monotonic() + self.warm_timeout_s
        for slot, rep in fresh:
            self._wait_healthy(rep, deadline)
            with self._ring_lock:
                self._ring.add(rep.ordinal)
                self._by_node[rep.ordinal] = rep
                slot.in_ring = True
        if supervise:
            t = threading.Thread(target=self._supervise_loop,
                                 name="cnmf-fleet-supervise", daemon=True)
            self._supervisor = t
            t.start()
        return self

    def _wait_healthy(self, rep, deadline: float):
        while True:
            if not rep.alive():
                raise ServeError(
                    f"replica {rep.ordinal} exited while warming "
                    f"(see its log)")
            try:
                rep.healthz(timeout=2.0)
                return
            except (OSError, ValueError, ServeError):
                pass
            if time.monotonic() >= deadline:
                raise ServeError(
                    f"replica {rep.ordinal} not healthy within "
                    f"CNMF_TPU_FLEET_WARM_TIMEOUT_S="
                    f"{self.warm_timeout_s:g} s")
            time.sleep(0.1)

    def close(self):
        """Stop supervision, then drain-stop every replica."""
        self._running = False
        if self._supervisor is not None:
            self._supervisor.join(timeout=2 * self.health_s + 5.0)
            self._supervisor = None
        with self._ring_lock:
            reps = [s.replica for s in self._slots
                    if s.replica is not None]
            for s in self._slots:
                if s.replica is not None:
                    self._ring.remove(s.replica.ordinal)
                    self._by_node.pop(s.replica.ordinal, None)
                s.replica = None
                s.in_ring = False
        for rep in reps:
            rep.shutdown()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- supervision ---------------------------------------------------

    def _supervise_loop(self):
        while self._running:
            try:
                self._tick()
            except Exception:  # pragma: no cover - supervision must live
                pass
            time.sleep(self.health_s)

    def _tick(self):
        now = time.monotonic()
        for slot in self._slots:
            rep = slot.replica
            if rep is None:
                if (slot.attempts <= self.respawn_budget
                        and now >= slot.down_until and self._running):
                    self._respawn(slot)
                continue
            # injectable chaos (runtime/faults.py): kill or wedge a real
            # subprocess so the detection paths below run against the
            # genuine article, not a simulation of one
            if faults.maybe_replicadeath(context="fleet",
                                         worker=slot.index):
                rep.kill()
            elif faults.maybe_replicawedge(context="fleet",
                                           worker=slot.index):
                rep.kill(wedge=True)
            if not rep.alive():
                self._pronounce_dead(slot, "exit")
                continue
            if not slot.in_ring:
                # warming respawn: join the ring on first healthy poll
                try:
                    rep.healthz(timeout=2.0)
                except (OSError, ValueError, ServeError):
                    continue
                with self._ring_lock:
                    self._ring.add(rep.ordinal)
                    self._by_node[rep.ordinal] = rep
                    slot.in_ring = True
                slot.healthz_fails = 0
                continue
            try:
                rep.healthz(timeout=max(2.0, 4 * self.health_s))
                slot.healthz_fails = 0
            except (OSError, ValueError, ServeError):
                slot.healthz_fails += 1
                # conviction needs BOTH kinds of evidence: healthz can
                # time out on a merely busy replica, but a busy replica
                # keeps stamping heartbeats from its dispatch loop — a
                # wedge (SIGSTOP, GIL spin) fails both
                hb_age = rep.heartbeat_age()
                hb_stale = hb_age is None or hb_age > max(
                    3.0, 4 * self.health_s)
                if slot.healthz_fails >= self.wedge_polls and hb_stale:
                    rep.kill()
                    if hasattr(rep, "reap"):
                        rep.reap(5.0)
                    self._pronounce_dead(slot, "wedge")

    def _pronounce_dead(self, slot, reason: str):
        rep = slot.replica
        with self._ring_lock:
            was_in_ring = slot.in_ring
            if was_in_ring:
                self._ring.remove(rep.ordinal)
                self._by_node.pop(rep.ordinal, None)
            slot.replica = None
            slot.in_ring = False
            slot.healthz_fails = 0
        with self._tenant_lock:
            displaced = sum(1 for home in self._tenant_home.values()
                            if home == rep.ordinal)
        with self._stats_lock:
            self._stats["replica_deaths"] += 1
            if was_in_ring:
                self._stats["failovers"] += 1
        if self.events is not None:
            self.events.emit("replica_death", replica=slot.index,
                             reason=reason, ordinal=rep.ordinal,
                             pid=rep.pid,
                             uptime_s=round(rep.uptime_s(), 3),
                             requests_served=rep.requests)
            if was_in_ring:
                self.events.emit("failover", replica=slot.index,
                                 tenants=displaced,
                                 survivors=len(self._ring))
        if hasattr(rep, "_cleanup"):
            rep._cleanup()
        slot.attempts += 1
        if slot.attempts <= self.respawn_budget:
            slot.down_until = time.monotonic() + respawn_delay(
                self.backoff_s, slot.attempts, slot.index)
        elif self.events is not None:
            # terminal: the slot stays down until a rollover rebuilds
            # the fleet — surfaced as its own death record so the
            # report's reason breakdown shows the budget ran out
            self.events.emit("replica_death", replica=slot.index,
                             reason="respawns_exhausted",
                             attempts=slot.attempts)

    def _respawn(self, slot):
        rep = self._factory(slot.index, next(self._ordinals),
                            self._generation, self._spectra_path)
        try:
            rep.start()
        except Exception:
            slot.attempts += 1
            slot.down_until = time.monotonic() + respawn_delay(
                self.backoff_s, slot.attempts, slot.index)
            if self.events is not None:
                self.events.emit("replica_death", replica=slot.index,
                                 reason="spawn_failed",
                                 ordinal=rep.ordinal)
            return
        slot.replica = rep
        slot.in_ring = False  # joins the ring on first healthy poll

    # -- request path --------------------------------------------------

    def handle_project(self, body: bytes, headers: dict
                       ) -> tuple[int, dict | bytes]:
        """Route one ``/project`` body: admission (quarantine, quota),
        consistent-hash candidates, bounded transport-failure retry with
        the same idempotency id, optional hedge. Returns ``(http_status,
        reply)`` where reply is raw bytes (pass-through) or a dict the
        caller JSON-encodes."""
        try:
            payload = json.loads(body or b"{}")
        except (ValueError, json.JSONDecodeError) as exc:
            return 400, {"ok": False, "status": "error",
                         "error": f"bad JSON body: {exc}"}
        tenant = str(payload.get("tenant", "default"))
        shape = payload.get("shape")
        n_cells = (int(shape[0]) if isinstance(shape, (list, tuple))
                   and shape else len(payload.get("data") or ()))
        with self._tenant_lock:
            quarantined = tenant in self._quarantined
        if quarantined:
            self._account(tenant, n_cells, "quarantined", None)
            return 403, {"ok": False, "status": "quarantined",
                         "error": f"tenant {tenant!r} is quarantined at "
                                  f"the fleet router after "
                                  f"{POISON_QUARANTINE_STRIKES} poison "
                                  f"inputs"}
        if self.tenant_qps > 0 and not self._bucket(tenant).allow():
            self._account(tenant, n_cells, "shed", None)
            return 429, {"ok": False, "status": "shed",
                         "error": f"tenant {tenant!r} is over its "
                                  f"admission quota "
                                  f"(CNMF_TPU_FLEET_TENANT_QPS="
                                  f"{self.tenant_qps:g}); retry with "
                                  f"backoff"}
        request_id = (headers.get(REQUEST_ID_HEADER)
                      or payload.get("request_id"))
        if request_id is None:
            # stamp one so OUR retries and hedges are idempotent even
            # for clients that did not opt in
            request_id = f"fleet-{os.getpid()}-{next(self._req_seq)}"
        fwd_headers = {"Content-Type": "application/json",
                       REQUEST_ID_HEADER: str(request_id)}
        trace = headers.get("X-CNMF-Trace")
        if trace:
            fwd_headers["X-CNMF-Trace"] = trace

        t0 = time.perf_counter()
        last_exc: Exception | None = None
        tried: set = set()
        for attempt in range(1 + self.retries):
            with self._ring_lock:
                order = [self._by_node[n]
                         for n in self._ring.candidates(tenant)
                         if n in self._by_node]
            order = [r for r in order if r.ordinal not in tried]
            if not order:
                break
            primary = order[0]
            backup = order[1] if len(order) > 1 else None
            with self._tenant_lock:
                self._tenant_home[tenant] = primary.ordinal
            try:
                status, blob, served_by = self._attempt(
                    primary, backup, body, fwd_headers)
            except OSError as exc:
                last_exc = exc
                tried.add(primary.ordinal)
                with self._stats_lock:
                    self._stats["retries"] += 1
                # deterministic bounded backoff before walking the ring
                time.sleep(min(0.25, 0.02 * (attempt + 1)))
                continue
            if status == 200:
                blob = self._stamp_generation(blob, served_by)
            self._finish(tenant, n_cells, status, blob, served_by,
                         (time.perf_counter() - t0) * 1e3)
            return status, blob
        self._account(tenant, n_cells, "error", None)
        self._slo_record((time.perf_counter() - t0) * 1e3, ok=False)
        return 503, {"ok": False, "status": "error",
                     "error": f"no replica reachable for tenant "
                              f"{tenant!r} after {1 + self.retries} "
                              f"attempt(s): {last_exc}"}

    def _stamp_generation(self, blob: bytes, served_by) -> bytes:
        """Stamp the serving replica's reference generation into the
        reply ``meta`` — during a rollover it is the client-visible
        answer to "which reference solved this?"."""
        try:
            reply = json.loads(blob)
            meta = reply.get("meta")
            if not isinstance(meta, dict):
                meta = reply["meta"] = {}
            meta["generation"] = served_by.generation
            return json.dumps(reply).encode("ascii")
        except (ValueError, TypeError, AttributeError):
            return blob

    def _attempt(self, primary, backup, body: bytes, headers: dict):
        """One routed attempt, optionally hedged: after
        ``CNMF_TPU_FLEET_HEDGE_MS`` without a reply the next distinct
        candidate gets a duplicate (same idempotency id — at most one
        solve) and the first answer wins."""
        if self.hedge_ms <= 0 or backup is None:
            status, blob = primary.forward(
                "POST", "/project", body, headers,
                timeout=self.forward_timeout_s)
            primary.requests += 1
            return status, blob, primary

        results: queue.Queue = queue.Queue()

        def run(rep):
            try:
                results.put((rep, rep.forward(
                    "POST", "/project", body, headers,
                    timeout=self.forward_timeout_s)))
            except Exception as exc:
                results.put((rep, exc))

        threading.Thread(target=run, args=(primary,), daemon=True).start()
        hedged = False
        outstanding = 1
        try:
            rep, out = results.get(timeout=self.hedge_ms / 1e3)
            outstanding -= 1
        except queue.Empty:
            hedged = True
            with self._stats_lock:
                self._stats["hedged"] += 1
            threading.Thread(target=run, args=(backup,),
                             daemon=True).start()
            outstanding += 1
            rep, out = results.get()
            outstanding -= 1
        if isinstance(out, Exception) and hedged and outstanding:
            # the loser may still deliver — prefer any real reply over
            # surfacing the first transport error
            rep, out = results.get()
            outstanding -= 1
        if isinstance(out, Exception):
            raise out if isinstance(out, OSError) else OSError(str(out))
        rep.requests += 1
        return out[0], out[1], rep

    def _finish(self, tenant: str, n_cells: int, status: int,
                blob: bytes, served_by, total_ms: float):
        """Account a replica's verdict fleet-side: counters, SLO,
        telemetry, and the fleet-scoped poison strikes."""
        if status == 200:
            self._account(tenant, n_cells, "ok", served_by,
                          total_ms=round(total_ms, 3))
            self._slo_record(total_ms, ok=True)
            return
        verdict = "error"
        try:
            verdict = str(json.loads(blob).get("status", "error"))
        except (ValueError, AttributeError):
            pass
        if verdict == "poison":
            with self._tenant_lock:
                strikes = self._strikes.get(tenant, 0) + 1
                self._strikes[tenant] = strikes
                if strikes >= POISON_QUARANTINE_STRIKES:
                    self._quarantined.add(tenant)
        elif verdict == "quarantined":
            # the replica already convicted this tenant — adopt the
            # verdict fleet-wide so its failover target never re-learns
            with self._tenant_lock:
                self._quarantined.add(tenant)
        self._account(tenant, n_cells, verdict, served_by)
        self._slo_record(total_ms, ok=False)

    def _account(self, tenant: str, n_cells: int, status: str,
                 served_by, **fields):
        key = status if status in ("ok", "shed", "poison", "quarantined",
                                   "error") else "error"
        with self._stats_lock:
            self._stats["requests"] += 1
            self._stats[key] += 1
        obs_metrics.counter_inc("cnmf_fleet_requests_total", status=key)
        if self.events is not None:
            if served_by is not None:
                fields["replica"] = served_by.slot
                fields["ordinal"] = served_by.ordinal
            self.events.emit("serve_request", tenant=tenant,
                             n_cells=int(n_cells), status=status,
                             **fields)

    def _bucket(self, tenant: str) -> TokenBucket:
        with self._tenant_lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = TokenBucket(self.tenant_qps,
                                     self.tenant_burst or None)
                self._buckets[tenant] = bucket
            return bucket

    def _slo_record(self, latency_ms: float, ok: bool):
        if self._slo is not None:
            self._slo.record(latency_ms, ok=ok)

    # -- rollover ------------------------------------------------------

    def handle_rollover(self, payload: dict) -> tuple[int, dict]:
        """Zero-downtime reference rollover: warm a fresh replica set
        against the new spectra, swap the ring atomically, drain-stop
        the old generation. The old generation keeps serving until the
        instant of the swap; on ANY warm failure it keeps serving,
        untouched."""
        spectra = payload.get("spectra")
        if not spectra:
            return 400, {"ok": False, "error":
                         "rollover needs {\"spectra\": <path or shard "
                         "store>}"}
        if not self._rollover_lock.acquire(blocking=False):
            return 409, {"ok": False, "error":
                         "a rollover is already in progress"}
        t0 = time.monotonic()
        try:
            new_gen = self._generation + 1
            fresh = []
            try:
                for i in range(self.n_replicas):
                    rep = self._factory(i, next(self._ordinals), new_gen,
                                        spectra)
                    rep.start()
                    fresh.append(rep)
                deadline = time.monotonic() + self.warm_timeout_s
                for rep in fresh:
                    self._wait_healthy(rep, deadline)
            except Exception as exc:
                for rep in fresh:
                    rep.kill()
                    if hasattr(rep, "reap"):
                        rep.reap(5.0)
                    if hasattr(rep, "_cleanup"):
                        rep._cleanup()
                return 500, {"ok": False, "error":
                             f"rollover aborted (old reference still "
                             f"serving): {exc}"}
            # atomic swap: requests admitted after this block route to
            # the new generation; requests already forwarded ride their
            # open connections and the old daemons' shutdown drain
            with self._ring_lock:
                old = [s.replica for s in self._slots
                       if s.replica is not None]
                self._ring = HashRing(r.ordinal for r in fresh)
                self._by_node = {r.ordinal: r for r in fresh}
                self._slots = [_Slot(i) for i in range(self.n_replicas)]
                for slot, rep in zip(self._slots, fresh):
                    slot.replica = rep
                    slot.in_ring = True
                self._generation = new_gen
                self._spectra_path = spectra  # respawns load the new ref
            for rep in old:
                rep.shutdown()
            wall = time.monotonic() - t0
            with self._stats_lock:
                self._stats["rollovers"] += 1
            if self.events is not None:
                self.events.emit("rollover", generation=new_gen,
                                 wall_s=round(wall, 3),
                                 replicas=self.n_replicas,
                                 spectra=str(spectra))
            return 200, {"ok": True, "generation": new_gen,
                         "wall_s": round(wall, 3),
                         "replicas": self.n_replicas}
        finally:
            self._rollover_lock.release()

    # -- introspection -------------------------------------------------

    def healthz(self) -> tuple[int, dict]:
        with self._ring_lock:
            up = len(self._ring)
            total = len(self._slots)
            gen = self._generation
        reply = {"ok": up > 0, "generation": gen, "replicas_up": up,
                 "replicas": total}
        if self._slo is not None:
            verdict = self._slo.evaluate()
            reply["slo"] = verdict
            reply["degraded"] = bool(verdict.get("burning"))
        return (200 if up > 0 else 503), reply

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        with self._ring_lock:
            out["generation"] = self._generation
            out["replicas_up"] = len(self._ring)
            out["replicas"] = [
                {"slot": s.index,
                 "ordinal": (s.replica.ordinal
                             if s.replica is not None else None),
                 "in_ring": s.in_ring,
                 "pid": (s.replica.pid if s.replica is not None
                         else None),
                 "requests": (s.replica.requests
                              if s.replica is not None else 0),
                 "respawn_attempts": s.attempts}
                for s in self._slots]
        with self._tenant_lock:
            out["quarantined_tenants"] = sorted(self._quarantined)
            out["tenants"] = len(self._tenant_home)
        if self._slo is not None:
            out["slo"] = self._slo.evaluate()
        return out

    def metrics_text(self) -> str:
        return obs_metrics.render_text()


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D401 - BaseHTTP override
        pass

    @property
    def router(self) -> FleetRouter:
        return self.server.router

    def _reply(self, code: int, obj):
        body = (obj if isinstance(obj, bytes)
                else json.dumps(obj).encode("utf-8"))
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, text: str):
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            self._reply(*self.router.healthz())
        elif self.path == "/stats":
            self._reply(200, {"ok": True, "stats": self.router.stats()})
        elif self.path == "/metrics":
            self._reply_text(200, self.router.metrics_text())
        else:
            self._reply(404, {"ok": False,
                              "error": f"no route {self.path!r}"})

    def do_POST(self):
        if self.path == "/shutdown":
            self._reply(200, {"ok": True, "stopping": True})
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length) if length else b""
        if self.path == "/project":
            status, reply = self.router.handle_project(
                body, dict(self.headers.items()))
            self._reply(status, reply)
        elif self.path == "/rollover":
            try:
                payload = json.loads(body or b"{}")
            except (ValueError, json.JSONDecodeError) as exc:
                self._reply(400, {"ok": False, "error": str(exc)})
                return
            self._reply(*self.router.handle_rollover(payload))
        else:
            self._reply(404, {"ok": False,
                              "error": f"no route {self.path!r}"})


class FleetDaemon:
    """The router behind one HTTP endpoint — the fleet's single front
    door (unix socket default, 127.0.0.1 TCP with ``port``). The same
    drain-accounted server classes as the serve daemon: close() stops
    accepting, lets accepted requests finish, then stops the router."""

    def __init__(self, router: FleetRouter,
                 socket_path: str | None = None, port: int | None = None):
        self.router = router
        self.socket_path = None
        if port is not None:
            self.server = _TCPHTTPServer(("127.0.0.1", int(port)),
                                         _FleetHandler)
        else:
            if socket_path is None:
                raise ValueError("need socket_path or port")
            if os.path.exists(socket_path):
                os.unlink(socket_path)
            self.server = _UnixHTTPServer(socket_path, _FleetHandler)
            self.socket_path = socket_path
        self.server.daemon_threads = True
        self.server.router = router
        self._thread = None
        self._closed = False

    @property
    def address(self) -> str:
        if self.socket_path:
            return self.socket_path
        host, port = self.server.server_address[:2]
        return f"{host}:{port}"

    def start(self):
        self.router.start()
        t = threading.Thread(target=self.server.serve_forever,
                             name="cnmf-fleet-http", daemon=True)
        self._thread = t
        t.start()
        return self

    def serve_forever(self):
        try:
            self.server.serve_forever()
        finally:
            self.close()

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.server.shutdown()
        drain_s = env_float("CNMF_TPU_SERVE_DRAIN_S", 30.0, lo=0.0)
        self.server.wait_drained(drain_s)
        self.router.close()
        self.server.server_close()
        if self.socket_path and os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class FleetClient(ServeClient):
    """The serve client plus the fleet's control surface."""

    def rollover(self, spectra: str) -> dict:
        """Trigger a zero-downtime reference rollover; returns the
        router's verdict (raises :class:`ServeError` on failure)."""
        status, data = self._request("POST", "/rollover",
                                     {"spectra": str(spectra)})
        if status != 200 or not data.get("ok"):
            raise ServeError(data.get("error", f"rollover: HTTP "
                                               f"{status}"))
        return data


def fleet_forever(run_dir: str, replicas: int | None = None,
                  k: int | None = None, density_threshold=None,
                  spectra_path: str | None = None,
                  socket_path: str | None = None,
                  port: int | None = None):
    """The ``cnmf-tpu fleet <run_dir>`` entry: spawn + front N serve
    replicas until SIGINT/SIGTERM (clean close: replicas drain-stopped,
    sockets removed)."""
    from ..utils.telemetry import EventLog
    from .reference import load_reference

    name = os.path.basename(os.path.normpath(run_dir))
    events = EventLog(
        os.path.join(run_dir, "cnmf_tmp", name + ".fleet.events.jsonl"),
        manifest_extra={"run_name": name, "role": "fleet"})
    # resolve the reference NOW so a bad run_dir/k/spectra fails fast
    # here instead of N times in replica logs
    load_reference(run_dir, k=k, density_threshold=density_threshold,
                   spectra_path=spectra_path)
    router = FleetRouter(run_dir, replicas=replicas,
                         spectra_path=spectra_path, k=k,
                         density_threshold=density_threshold,
                         events=events)
    if port is None and socket_path is None:
        socket_path = default_fleet_socket_path(run_dir)
    daemon = FleetDaemon(router, socket_path=socket_path, port=port)

    def _stop(signum, frame):
        threading.Thread(target=daemon.server.shutdown,
                         daemon=True).start()

    prev = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            prev[sig] = signal.signal(sig, _stop)
        except ValueError:  # non-main thread (tests)
            pass
    print(f"cnmf-tpu fleet: spawning {router.n_replicas} serve "
          f"replica(s) for {name} ...")
    try:
        router.start()
        print(f"cnmf-tpu fleet: routing on {daemon.address} "
              f"(generation {router._generation}, "
              f"{len(router._ring)} replica(s) up)")
        t = threading.Thread(target=daemon.server.serve_forever,
                             name="cnmf-fleet-http", daemon=True)
        daemon._thread = t
        t.start()
        while t.is_alive():
            t.join(timeout=1.0)
    finally:
        daemon.close()
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except ValueError:
                pass
    return 0
