"""Perf-regression observatory tests (ISSUE 19, obs/regress.py): the
cnmf-bench snapshot schema (build/validate/save/load round-trip, pinned
so bench.py --json-out output stays machine-readable across rounds),
metric extraction + direction classification, and the noise-aware diff:
green on identical results, red on a 2x lane slowdown, improvements
counted separately, min-of-N sample estimators, and the fingerprint key
exempting cross-hardware comparisons. Plus the benchdiff CLI exit
semantics the perf gate scripts rely on."""

import copy
import json
import subprocess
import sys

import pytest

from cnmf_torch_tpu.obs import regress as rg

RAW = {
    "serve": {"qps": 500.0, "latency_ms": {"p50": 10.0, "p99": 20.0,
                                           "count": 360,
                                           "histogram": {"<=10": 160}},
              "vs_baseline": 0.5, "requests": 360, "ok": True,
              "latency_samples_kept": 390},
    "kl": {"wall_seconds": 4.0, "mfu": 0.02, "error": None},
}


def _snap(raw=None, fingerprint="fp-a", created=1000.0, label=None):
    return rg.build_snapshot(raw if raw is not None else RAW,
                             fingerprint=fingerprint, created=created,
                             label=label)


# ---------------------------------------------------------------------------
# schema: extraction, validation, round-trip
# ---------------------------------------------------------------------------

def test_extract_metrics_direction_classification():
    m = rg.extract_metrics(RAW["serve"])
    assert m["qps"]["direction"] == "higher"
    assert m["latency_ms.p50"]["direction"] == "lower"
    assert m["latency_ms.p99"]["direction"] == "lower"
    # no gate metric from: vs_baseline ratios, bare counts/config ints,
    # histogram bucket occupancy, reservoir honesty counters, booleans
    for absent in ("vs_baseline", "requests", "ok", "latency_ms.count",
                   "latency_ms.histogram.<=10", "latency_samples_kept"):
        assert absent not in m
    mk = rg.extract_metrics(RAW["kl"])
    assert mk["wall_seconds"]["direction"] == "lower"
    assert mk["mfu"]["direction"] == "higher"


def test_snapshot_round_trip(tmp_path):
    snap = _snap(label="round-trip")
    path = rg.save_snapshot(snap, str(tmp_path / "deep" / "snap.json"))
    loaded = rg.load_snapshot(path)
    assert loaded == snap
    assert loaded["schema"] == rg.BENCH_SCHEMA
    assert loaded["schema_version"] == rg.BENCH_SCHEMA_VERSION
    assert loaded["label"] == "round-trip"
    # the raw ad-hoc payload survives verbatim next to the typed metrics
    assert loaded["tiers"]["serve"]["raw"]["latency_ms"]["p50"] == 10.0


def test_validate_rejects_malformed_docs():
    good = _snap()
    for breakage in (
            {"schema": "something-else"},
            {"schema_version": 99},
            {"fingerprint": None},
            {"tiers": {"kl": {"metrics": "fast"}}},
            {"tiers": {"kl": {"metrics": {"wall_seconds": {
                "value": "4", "direction": "lower"}}}}},
            {"tiers": {"kl": {"metrics": {"wall_seconds": {
                "value": 4.0, "direction": "sideways"}}}}},
            {"tiers": {"kl": {"metrics": {"wall_seconds": {
                "value": 4.0, "direction": "lower",
                "samples": [1.0, "x"]}}}}},
    ):
        with pytest.raises(ValueError):
            rg.validate_bench({**good, **breakage})
    with pytest.raises(ValueError):
        rg.validate_bench([good])


def test_error_tier_is_perf_exempt():
    snap = _snap({"kl": {"wall_seconds": 4.0, "error": "timeout"},
                  "serve": {"qps": 10.0, "perf_exempt": True}})
    assert snap["tiers"]["kl"]["perf_exempt"] is True
    assert snap["tiers"]["serve"]["perf_exempt"] is True


# ---------------------------------------------------------------------------
# noise-aware diff
# ---------------------------------------------------------------------------

def test_diff_green_on_identical():
    d = rg.diff_snapshots(_snap(), _snap(), band=0.1)
    assert d["ok"] is True and d["regressions"] == 0
    assert all(r["verdict"] in ("ok", "exempt") for r in d["rows"])
    assert "=> OK" in rg.render_diff(d)


def test_diff_red_on_2x_lane_slowdown():
    new = copy.deepcopy(RAW)
    new["kl"]["wall_seconds"] = 8.0  # the injected 2x
    d = rg.diff_snapshots(_snap(), _snap(new), band=0.6)
    red = [r for r in d["rows"] if r["verdict"] == "regressed"]
    assert d["ok"] is False and d["regressions"] == 1
    assert red[0]["tier"] == "kl" and red[0]["metric"] == "wall_seconds"
    assert red[0]["rel"] == pytest.approx(1.0)
    assert "=> RED" in rg.render_diff(d)


def test_diff_direction_for_higher_better_metrics():
    worse = copy.deepcopy(RAW)
    worse["serve"]["qps"] = 100.0  # throughput collapse = regression
    d = rg.diff_snapshots(_snap(), _snap(worse), band=0.6)
    assert {(r["tier"], r["metric"]) for r in d["rows"]
            if r["verdict"] == "regressed"} == {("serve", "qps")}
    better = copy.deepcopy(RAW)
    better["serve"]["qps"] = 2000.0
    d2 = rg.diff_snapshots(_snap(), _snap(better), band=0.6)
    assert d2["ok"] is True and d2["improvements"] == 1


def test_diff_min_of_n_samples_absorb_noise():
    base, new = _snap(), _snap()
    m = new["tiers"]["kl"]["metrics"]["wall_seconds"]
    # one quiet sample among noisy ones: min-of-N keeps the lane green
    m["samples"] = [9.0, 4.1, 12.0]
    m["value"] = 9.0
    rg.validate_bench(new)
    d = rg.diff_snapshots(base, new, band=0.2)
    row = [r for r in d["rows"] if r["metric"] == "wall_seconds"][0]
    assert row["new"] == 4.1 and row["verdict"] == "ok"
    # higher-is-better uses max-of-N
    assert rg._effective({"value": 1.0, "direction": "higher",
                          "samples": [1.0, 3.0, 2.0]}) == 3.0


def test_diff_fingerprint_mismatch_exempts_everything():
    new = copy.deepcopy(RAW)
    new["kl"]["wall_seconds"] = 400.0
    d = rg.diff_snapshots(_snap(), _snap(new, fingerprint="fp-b"),
                          band=0.1)
    assert d["ok"] is True and d["fingerprint_match"] is False
    assert all(r["verdict"] in ("exempt", "missing") for r in d["rows"])
    assert "fingerprints differ" in rg.render_diff(d)


def test_diff_missing_tier_and_metric_reported_not_gated():
    base = _snap({"kl": {"wall_seconds": 4.0},
                  "serve": {"qps": 500.0}})
    new = _snap({"kl": {"wall_seconds": 4.0, "compile_seconds": 1.0}})
    d = rg.diff_snapshots(base, new, band=0.1)
    verdicts = {(r["tier"], r["metric"]): r["verdict"] for r in d["rows"]}
    assert verdicts[("serve", "*")] == "missing"
    assert verdicts[("kl", "compile_seconds")] == "missing"
    assert d["ok"] is True


def test_gate_band_and_n_knobs(monkeypatch):
    assert rg.gate_band() == rg.DEFAULT_BAND
    assert rg.gate_n() == rg.DEFAULT_N
    monkeypatch.setenv(rg.GATE_BAND_ENV, "0.25")
    monkeypatch.setenv(rg.GATE_N_ENV, "5")
    assert rg.gate_band() == 0.25
    assert rg.gate_n() == 5
    d = rg.diff_snapshots(_snap(), _snap())
    assert d["band"] == 0.25


def test_zero_baseline_edge():
    base = _snap({"kl": {"wall_seconds": 0.0}})
    same = _snap({"kl": {"wall_seconds": 0.0}})
    worse = _snap({"kl": {"wall_seconds": 1.0}})
    assert rg.diff_snapshots(base, same, band=0.1)["ok"] is True
    d = rg.diff_snapshots(base, worse, band=0.1)
    assert d["ok"] is False
    row = [r for r in d["rows"] if r["metric"] == "wall_seconds"][0]
    assert row["rel"] is None  # inf is reported as unrepresentable


# ---------------------------------------------------------------------------
# benchdiff CLI exit semantics (what scripts/perf_gate.py relies on)
# ---------------------------------------------------------------------------

def test_benchdiff_cli_exit_codes(tmp_path):
    a = rg.save_snapshot(_snap(), str(tmp_path / "a.json"))
    worse = copy.deepcopy(RAW)
    worse["kl"]["wall_seconds"] = 8.0
    b = rg.save_snapshot(_snap(worse), str(tmp_path / "b.json"))

    green = subprocess.run(
        [sys.executable, "-m", "cnmf_torch_tpu", "benchdiff", a, a],
        capture_output=True, text=True, timeout=120)
    assert green.returncode == 0, green.stderr
    assert "=> OK" in green.stdout

    red = subprocess.run(
        [sys.executable, "-m", "cnmf_torch_tpu", "benchdiff", a, b,
         "--band", "0.6", "--json"],
        capture_output=True, text=True, timeout=120)
    assert red.returncode == 1, red.stderr
    doc = json.loads(red.stdout)
    assert doc["ok"] is False and doc["regressions"] == 1
