"""Replicate-sweep execution — the reference's worker processes as one XLA program.

The reference runs ``n_iter x |K|`` independent NMF replicates as separate OS
processes, statically sharded by ``worker_filter`` and communicating through
files (``/root/reference/src/cnmf/cnmf.py:53-54, 744-749, 839-892``). Here the
replicate axis becomes a ``vmap`` dimension of one jit-compiled solver call,
and device parallelism is a ``jax.sharding`` annotation over a 1-D mesh: XLA
partitions the batched program across chips, with the data matrix replicated
(it is shared, read-only input for every replicate) and the factor states
sharded along the replicate axis. "combine" becomes an all-gather the runtime
inserts when the host fetches the sharded spectra — no per-iteration files.

K changes array shapes, so the sweep compiles once per K (SURVEY.md §7:
per-K jit is the safe first cut); seeds only change data, never shapes.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.nmf import (
    _chunk_rows,
    beta_loss_to_float,
    init_factors,
    nmf_fit_batch,
    nmf_fit_online,
    random_init,
    split_regularization,
)

__all__ = ["replicate_sweep", "worker_filter", "default_mesh"]


def worker_filter(iterable, worker_index: int, total_workers: int):
    """Round-robin task partition, contract-identical to the reference
    (``cnmf.py:53-54``): worker i takes every task whose position is
    congruent to i modulo total_workers."""
    return (p for i, p in enumerate(iterable)
            if (i - worker_index) % total_workers == 0)


def default_mesh(axis_name: str = "replicates") -> Mesh | None:
    """1-D mesh over all local devices; None when a single device makes
    sharding annotations pure overhead."""
    devices = jax.devices()
    if len(devices) <= 1:
        return None
    return Mesh(np.asarray(devices), (axis_name,))


def _stacked_inits(X, k: int, seeds, init: str):
    """Per-replicate (H0, W0) stacks from the ledger's seed list.

    ``init='random'`` vmaps the seeded init over replicate keys. The nndsvd
    family is deterministic given X (as in the reference's solver, where
    ``random_state`` does not perturb nndsvd), so it is computed once and
    broadcast — replicate diversity then comes only from MU tie-breaking,
    mirroring the reference's behavior for that init.
    """
    n, g = X.shape
    if init == "random":
        x_mean = jnp.mean(X)
        keys = jnp.stack([jax.random.key(int(s) & 0x7FFFFFFF) for s in seeds])
        return jax.vmap(lambda key: random_init(key, n, g, k, x_mean))(keys)
    H0, W0 = init_factors(X, k, init, jax.random.key(int(seeds[0]) & 0x7FFFFFFF))
    R = len(seeds)
    return (jnp.broadcast_to(H0, (R, n, k)), jnp.broadcast_to(W0, (R, k, g)))


def replicate_sweep(X, seeds, k: int, beta_loss="frobenius", init: str = "random",
                    mode: str = "online", tol: float = 1e-4,
                    online_chunk_size: int = 5000,
                    online_chunk_max_iter: int = 1000,
                    batch_max_iter: int = 500, n_passes: int = 20,
                    alpha_W: float = 0.0, l1_ratio_W: float = 0.0,
                    alpha_H: float = 0.0, l1_ratio_H: float = 0.0,
                    mesh: Mesh | None = None, return_usages: bool = False,
                    replicates_per_batch: int | None = None):
    """Run ``len(seeds)`` NMF replicates at one K as a batched XLA program.

    Returns ``(spectra (R, k, g), usages (R, n, k) | None, errs (R,))`` as
    numpy arrays, in ledger seed order — the in-memory equivalent of the
    reference's per-(k, iter) spectra files (``cnmf.py:888-892``).

    ``mesh``: optional 1-D device mesh; the replicate axis is sharded across
    it (R is padded to a mesh multiple; pad replicates are computed and
    dropped). ``replicates_per_batch`` bounds device memory by running the
    sweep in host-level slices (each slice is still one XLA call).
    """
    if sp.issparse(X):
        X = X.toarray()
    X = jnp.asarray(np.asarray(X), dtype=jnp.float32)
    n, g = X.shape
    k = int(k)
    beta = beta_loss_to_float(beta_loss)
    seeds = list(seeds)
    R = len(seeds)
    if R == 0:
        return (np.zeros((0, k, g), np.float32),
                np.zeros((0, n, k), np.float32) if return_usages else None,
                np.zeros((0,), np.float32))

    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)

    if mode == "batch":
        def solve(H0, W0):
            return nmf_fit_batch(
                X, H0, W0, beta=beta, tol=float(tol),
                max_iter=int(batch_max_iter),
                l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W)
    elif mode == "online":
        chunk = int(min(online_chunk_size, n))

        def solve(H0, W0):
            Xc, Hc, _ = _chunk_rows(X, H0, chunk)
            Hc, W, err = nmf_fit_online(
                Xc, Hc, W0, beta=beta, tol=float(tol),
                chunk_max_iter=int(online_chunk_max_iter),
                n_passes=int(n_passes),
                l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W)
            return Hc.reshape(-1, k)[:n], W, err
    else:
        raise ValueError(f"unknown mode {mode!r}")

    sweep = jax.vmap(solve)

    n_dev = 1 if mesh is None else math.prod(mesh.devices.shape)
    if replicates_per_batch is None:
        # bound per-slice device footprint: each replicate holds an n x k
        # usage state plus solver temporaries of the same order; keep the
        # whole slice (inputs + X + outputs) well under a single-chip HBM
        budget_elems = 1 << 28  # ~1 GiB of fp32 state per slice
        per_rep = 3 * (n * k + k * g) + n * k
        replicates_per_batch = max(n_dev, int(budget_elems // max(per_rep, 1)))
    # slices must stay mesh-multiples so every shard stays busy
    replicates_per_batch = max(n_dev, (replicates_per_batch // n_dev) * n_dev)

    spectra_out = np.empty((R, k, g), dtype=np.float32)
    usages_out = np.empty((R, n, k), dtype=np.float32) if return_usages else None
    errs_out = np.empty((R,), dtype=np.float32)

    for start in range(0, R, replicates_per_batch):
        sl = seeds[start:start + replicates_per_batch]
        H0, W0 = _stacked_inits(X, k, sl, init)
        r = len(sl)
        pad = (-r) % n_dev
        if pad:
            # tile modulo r: works even when the slice is smaller than the
            # mesh (pad replicates recompute existing seeds and are dropped)
            idx = jnp.arange(r + pad) % r
            H0 = H0[idx]
            W0 = W0[idx]
        if mesh is not None:
            ax = mesh.axis_names[0]
            H0 = jax.device_put(H0, NamedSharding(mesh, P(ax, None, None)))
            W0 = jax.device_put(W0, NamedSharding(mesh, P(ax, None, None)))
        H, W, err = sweep(H0, W0)
        spectra_out[start:start + r] = np.asarray(W)[:r]
        if return_usages:
            usages_out[start:start + r] = np.asarray(H)[:r]
        errs_out[start:start + r] = np.asarray(err)[:r]

    return spectra_out, usages_out, errs_out
