"""Fused Pallas kernels for the ELL β=1 inner loop (ISSUE 16).

Parity bars: every fused statistic matches its jnp ELL oracle at f32
tolerance (the kernels change accumulation order only), the bf16 ratio
variants stay within the bf16 band, and the default-off knob compiles
byte-identical programs to a build without the kernel layer. On this
CPU suite every ``pallas_call`` runs in interpret mode — the same
dispatch surface a TPU run takes, minus the Mosaic lowering."""

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from cnmf_torch_tpu.ops import pallas_kl as pk
from cnmf_torch_tpu.ops.nmf import (_update_H, _update_W, nmf_fit_batch,
                                    nmf_fit_online)
from cnmf_torch_tpu.ops.pallas import (PALLAS_ENV, kernel_label,
                                       pallas_available, pallas_interpret,
                                       resolve_pallas)
from cnmf_torch_tpu.ops.recipe import SolverRecipe
from cnmf_torch_tpu.ops.sparse import (csr_to_ell, ell_beta_err,
                                       ell_chunk_rows, ell_device_put,
                                       ell_kl_h_newton_stats,
                                       ell_kl_h_stats, ell_kl_w_numer,
                                       ell_kl_w_stats, ell_wh_at_nz)


def _fixture(n, g, k, density=0.08, seed=0, zero_rows=0):
    """Sparse counts + positive factors. ``zero_rows`` leading rows are
    all-zero (ELL pads them entirely: stored value 0.0, column 0)."""
    rng = np.random.default_rng(seed)
    X = sp.random(n, g, density=density, format="csr",
                  random_state=int(rng.integers(1 << 31)),
                  data_rvs=lambda s: (rng.gamma(2.0, 1.0, s)
                                      + 0.1).astype(np.float32))
    if zero_rows:
        X = X.tolil()
        X[:zero_rows, :] = 0.0
        X = X.tocsr()
        X.eliminate_zeros()
    ell = ell_device_put(csr_to_ell(X))
    H = jnp.asarray(rng.random((n, k), np.float32) + 0.1)
    W = jnp.asarray(rng.random((k, g), np.float32) + 0.1)
    return X, ell, H, W


# shapes straddle the 128 block: ragged last row slab AND ragged last
# gene tile, plus an exact-multiple case
SHAPES = [(130, 100, 5), (256, 128, 4), (97, 61, 3)]


# ---------------------------------------------------------------------------
# kernel-vs-oracle parity (f32 tolerance: same math, different order)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,g,k", SHAPES)
def test_wh_at_nz_parity(n, g, k):
    _, ell, H, W = _fixture(n, g, k)
    got = pk.pallas_wh_at_nz(ell, H, W)
    want = ell_wh_at_nz(ell, H, W)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("n,g,k", SHAPES)
def test_kl_h_stats_parity(n, g, k):
    _, ell, H, W = _fixture(n, g, k)
    gn, gd = pk.pallas_kl_h_stats(ell, H, W)
    wn, wd = ell_kl_h_stats(ell, H, W)
    np.testing.assert_allclose(gn, wn, rtol=2e-5, atol=1e-6)
    # the data-independent denominator stays jnp: bitwise the oracle's
    np.testing.assert_array_equal(gd, wd)


@pytest.mark.parametrize("n,g,k", SHAPES)
def test_kl_h_newton_stats_parity(n, g, k):
    _, ell, H, W = _fixture(n, g, k)
    gn, gd, gh = pk.pallas_kl_h_newton_stats(ell, H, W)
    wn, wd, wh = ell_kl_h_newton_stats(ell, H, W)
    np.testing.assert_allclose(gn, wn, rtol=2e-5, atol=1e-6)
    np.testing.assert_array_equal(gd, wd)
    np.testing.assert_allclose(gh, wh, rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("n,g,k", SHAPES)
def test_kl_w_numer_parity(n, g, k):
    _, ell, H, W = _fixture(n, g, k)
    got = pk.pallas_kl_w_numer(ell, H, W)
    want = ell_kl_w_numer(ell, H, W)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("n,g,k", SHAPES)
def test_kl_w_stats_parity(n, g, k):
    _, ell, H, W = _fixture(n, g, k)
    gn, gd = pk.pallas_kl_w_stats(ell, H, W)
    wn, wd = ell_kl_w_stats(ell, H, W)
    np.testing.assert_allclose(gn, wn, rtol=2e-5, atol=1e-6)
    np.testing.assert_array_equal(gd, wd)


@pytest.mark.parametrize("n,g,k", SHAPES)
def test_kl_beta_err_parity(n, g, k):
    X, ell, H, W = _fixture(n, g, k)
    got = float(pk.pallas_kl_beta_err(ell, H, W))
    want = float(ell_beta_err(ell, H, W, 1.0))
    assert got == pytest.approx(want, rel=2e-5)


def test_all_zero_rows_and_exact_zero_absorption():
    """Fully padded rows (and the padded slots of every ragged row) must
    contribute exact +0.0 to every statistic — no NaN from 0*log(0)."""
    _, ell, H, W = _fixture(96, 64, 4, zero_rows=11, seed=2)
    numer, _ = pk.pallas_kl_h_stats(ell, H, W)
    wn = pk.pallas_kl_w_numer(ell, H, W)
    obj = float(pk.pallas_kl_beta_err(ell, H, W))
    assert np.isfinite(numer).all() and np.isfinite(wn).all()
    assert np.isfinite(obj)
    # a zero row has no nonzero support: its MU numerator is exactly 0
    np.testing.assert_array_equal(np.asarray(numer)[:11], 0.0)
    np.testing.assert_allclose(numer, ell_kl_h_stats(ell, H, W)[0],
                               rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(wn, ell_kl_w_numer(ell, H, W),
                               rtol=2e-5, atol=1e-6)


def test_bf16_ratio_band():
    """bf16 ratio variants: match the bf16 oracle within the bf16 band
    and the f32 oracle within the documented few-percent envelope."""
    _, ell, H, W = _fixture(130, 100, 5, seed=4)
    gn, _ = pk.pallas_kl_h_stats(ell, H, W, bf16_ratio=True)
    wn_bf16, _ = ell_kl_h_stats(ell, H, W, bf16_ratio=True)
    wn_f32, _ = ell_kl_h_stats(ell, H, W)
    np.testing.assert_allclose(gn, wn_bf16, rtol=2e-2)
    np.testing.assert_allclose(gn, wn_f32, rtol=5e-2)
    gw = pk.pallas_kl_w_numer(ell, H, W, bf16_ratio=True)
    ww = ell_kl_w_numer(ell, H, W, bf16_ratio=True)
    np.testing.assert_allclose(gw, ww, rtol=2e-2)


def test_update_steps_parity():
    """One full MU H/W step through ops.nmf dispatch: use_pallas=True
    matches the jnp ELL path at f32 tolerance."""
    _, ell, H, W = _fixture(130, 100, 5, seed=6)
    h_j = _update_H(ell, H, W, 1.0, 0.0, 0.0)
    h_p = _update_H(ell, H, W, 1.0, 0.0, 0.0, use_pallas=True)
    np.testing.assert_allclose(h_p, h_j, rtol=2e-5, atol=1e-6)
    w_j = _update_W(ell, H, W, 1.0, 0.0, 0.0)
    w_p = _update_W(ell, H, W, 1.0, 0.0, 0.0, use_pallas=True)
    np.testing.assert_allclose(w_p, w_j, rtol=2e-5, atol=1e-6)


def test_fit_batch_objective_parity():
    _, ell, H, W = _fixture(130, 100, 4, seed=8)
    _, _, err_j = nmf_fit_batch(ell, H, W, beta=1.0, max_iter=25)
    _, _, err_p = nmf_fit_batch(ell, H, W, beta=1.0, max_iter=25,
                                use_pallas=True)
    assert float(err_p) == pytest.approx(float(err_j), rel=1e-4)


def test_fit_online_objective_parity():
    X, _, H, W = _fixture(128, 64, 4, seed=9)
    chunked, pad = ell_chunk_rows(X, 64)
    Hc = H.reshape(2, 64, 4)
    _, _, err_j = nmf_fit_online(chunked, Hc, W, beta=1.0, n_passes=3)
    _, _, err_p = nmf_fit_online(chunked, Hc, W, beta=1.0, n_passes=3,
                                 use_pallas=True)
    assert float(err_p) == pytest.approx(float(err_j), rel=1e-4)


# ---------------------------------------------------------------------------
# knob resolution
# ---------------------------------------------------------------------------

class TestKnob:
    def test_words(self, monkeypatch):
        for w in ("", "0", "off", "false", "no"):
            monkeypatch.setenv(PALLAS_ENV, w)
            assert resolve_pallas() is False
        for w in ("1", "on", "true", "yes", "force"):
            monkeypatch.setenv(PALLAS_ENV, w)
            assert resolve_pallas() is True
        monkeypatch.delenv(PALLAS_ENV, raising=False)
        assert resolve_pallas() is False  # default off

    def test_auto_is_off_off_tpu(self, monkeypatch):
        monkeypatch.setenv(PALLAS_ENV, "auto")
        assert pallas_interpret()  # the suite runs on CPU
        assert resolve_pallas() is False

    def test_bad_word_names_the_knob(self, monkeypatch):
        monkeypatch.setenv(PALLAS_ENV, "bogus")
        with pytest.raises(ValueError, match=PALLAS_ENV):
            resolve_pallas()

    def test_override_wins(self, monkeypatch):
        monkeypatch.setenv(PALLAS_ENV, "0")
        assert resolve_pallas(override=True) is True
        monkeypatch.delenv(PALLAS_ENV, raising=False)
        assert resolve_pallas(override=False) is False
        assert pallas_available()

    def test_kernel_label_spelling(self):
        assert kernel_label(True, True) == "ell-pallas"
        assert kernel_label(True, False) == "ell-jnp"
        assert kernel_label(False, False, True) == "vmapped-bf16"
        assert kernel_label(False) == "vmapped"


# ---------------------------------------------------------------------------
# default-off byte identity
# ---------------------------------------------------------------------------

def test_default_off_lowering_identity():
    """knob=0 IS the pre-Pallas build: the default lowering equals an
    explicit use_pallas=False, and forced-on differs (engagement stays
    detectable in interpret mode, where no 'pallas' string survives in
    the lowered text)."""
    _, ell, H, W = _fixture(96, 64, 3, seed=1)
    default = nmf_fit_batch.lower(ell, H, W, beta=1.0,
                                  max_iter=8).as_text()
    off = nmf_fit_batch.lower(ell, H, W, beta=1.0, max_iter=8,
                              use_pallas=False).as_text()
    on = nmf_fit_batch.lower(ell, H, W, beta=1.0, max_iter=8,
                             use_pallas=True).as_text()
    assert default == off
    assert default != on


# ---------------------------------------------------------------------------
# dispatch through the sharded solvers
# ---------------------------------------------------------------------------

@pytest.fixture
def mesh():
    return Mesh(np.asarray(jax.devices()[:2]), ("cells",))


def _lowrank_csr(n, g, k, seed):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k) * 0.3, size=n)
    spectra = rng.gamma(0.3, 1.0, size=(k, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * 0.25).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    return sp.csr_matrix(X)


def test_rowshard_dispatch_parity(mesh, monkeypatch):
    """knob 0 vs 1 through the row-sharded solver: matched objectives
    and the engaged kernel label in the telemetry payload."""
    from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded

    monkeypatch.setenv("CNMF_TPU_SPARSE_BETA", "1")
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    X = _lowrank_csr(96, 48, 3, seed=5)
    runs = {}
    for knob in ("0", "1"):
        monkeypatch.setenv(PALLAS_ENV, knob)
        sink = []
        _, W, err = nmf_fit_rowsharded(
            X, 3, mesh, beta_loss="kullback-leibler", seed=11,
            n_passes=6, telemetry_sink=sink.append)
        (pay,) = sink
        runs[knob] = (W, float(err), pay["kernel"])
    assert runs["0"][2] == "ell-jnp"
    assert runs["1"][2] == "ell-pallas"
    assert runs["1"][1] == pytest.approx(runs["0"][1], rel=1e-4)
    np.testing.assert_allclose(runs["1"][0], runs["0"][0],
                               rtol=1e-4, atol=1e-5)


def test_grid2d_dense_lane_label(monkeypatch):
    """The 2-D grid runs dense pass programs regardless of the knob —
    its telemetry carries the literal dense-jnp label, and the knob is
    still consulted (validated) uniformly."""
    from cnmf_torch_tpu.parallel.grid2d import nmf_fit_grid2d

    grid = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                ("cells", "genes"))
    X = _lowrank_csr(64, 32, 3, seed=7).toarray()
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    monkeypatch.setenv(PALLAS_ENV, "1")
    sink = []
    _, _, err = nmf_fit_grid2d(X, 3, grid,
                               beta_loss="kullback-leibler", seed=3,
                               n_passes=4, telemetry_sink=sink.append)
    assert np.isfinite(err)
    (pay,) = sink
    assert pay["kernel"] == "dense-jnp"
    monkeypatch.setenv(PALLAS_ENV, "bogus")
    with pytest.raises(ValueError, match=PALLAS_ENV):
        nmf_fit_grid2d(X, 3, grid, beta_loss="kullback-leibler",
                       seed=3, n_passes=2)


# ---------------------------------------------------------------------------
# checkpoint identity across a kernel flip
# ---------------------------------------------------------------------------

def test_signature_kernel_flip_changes_identity():
    """A CNMF_TPU_PALLAS flip (either direction) must restart, not
    splice two accumulation orders' trajectories — the kernel label
    joins the signature ONLY when the kernels engage, so default-path
    checkpoints keep their pre-Pallas identity."""
    base = SolverRecipe().signature()
    assert "kernel=" not in base  # pre-Pallas identity preserved
    engaged = SolverRecipe().signature(kernel="ell-pallas")
    assert engaged != base and engaged.endswith(",kernel=ell-pallas")
    # the flip is visible in BOTH directions and per-label
    assert SolverRecipe().signature(kernel="ell-jnp") != engaged
    # sketch fields and kernel compose
    sk = SolverRecipe("sketch", 1, False, "env", sketch_dim=64)
    assert sk.signature(kernel="ell-pallas").count("kernel=") == 1
