"""`cnmf-tpu lint` engine tests (ISSUE 7): paired positive/negative
fixtures per rule family, suppression + baseline semantics, JSON output
shape, knob-registry round-trips, and the package-wide clean gate."""

import json
import os

import pytest

from cnmf_torch_tpu.analysis.engine import (DEFAULT_BASELINE, format_json,
                                            lint_paths, main as lint_main,
                                            write_baseline)
from cnmf_torch_tpu.utils import envknobs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_src(tmp_path, src, name="fixture.py", baseline=None):
    p = tmp_path / name
    p.write_text(src)
    return lint_paths([str(p)], baseline_path=baseline, doc_check=False)


def _rules(result):
    return sorted(f.rule for f in result.findings)


# ---------------------------------------------------------------------------
# trace-safety family
# ---------------------------------------------------------------------------

def test_host_sync_in_jitted_body_detected(tmp_path):
    res = _lint_src(tmp_path, """
import jax

@jax.jit
def f(x):
    return x.item()
""")
    assert _rules(res) == ["trace-host-sync"]
    assert res.findings[0].line == 6


def test_host_sync_outside_traced_scope_clean(tmp_path):
    res = _lint_src(tmp_path, """
import numpy as np

def fetch(x):
    return float(np.asarray(x).item())
""")
    assert res.findings == []


def test_host_sync_in_while_loop_body_and_partial_jit(tmp_path):
    res = _lint_src(tmp_path, """
import functools
import jax
import numpy as np
from jax import lax

def body(carry):
    return np.asarray(carry) + 1

out = lax.while_loop(lambda c: c < 3, body, 0)

@functools.partial(jax.jit, static_argnames=("mode",))
def g(x, mode):
    return x.block_until_ready()
""")
    assert _rules(res) == ["trace-host-sync", "trace-host-sync"]


def test_nested_traced_scope_gets_its_own_params(tmp_path):
    """A while_loop body nested inside a jitted function is analyzed with
    its OWN params traced plus the enclosing scope's by closure (review
    finding, this PR)."""
    res = _lint_src(tmp_path, """
import functools
import jax
from jax import lax

@functools.partial(jax.jit, static_argnames=("mode",))
def f(x, mode):
    def body(carry):
        if carry > 0:      # inner param: traced
            carry = carry - x
        if x > 0:          # closure over outer traced param
            carry = carry + 1
        if mode:           # closure over outer STATIC: exempt
            carry = carry * 2
        return carry
    return lax.while_loop(lambda c: c < 3, body, x)
""")
    assert _rules(res) == ["trace-branch", "trace-branch"]
    assert [f.line for f in res.findings] == [9, 11]


def test_tracer_function_passed_by_keyword_detected(tmp_path):
    res = _lint_src(tmp_path, """
from jax import lax

def body(c):
    return c.item() + 1

out = lax.while_loop(lambda c: c < 3, body_fun=body, init_val=0)
""")
    assert _rules(res) == ["trace-host-sync"]


def test_shape_probes_and_static_casts_clean(tmp_path):
    res = _lint_src(tmp_path, """
import jax

@jax.jit
def f(x):
    n = int(x.shape[0])
    m = float(len(x.shape))
    if x.ndim > 1:
        x = x.sum(axis=0)
    return x * n * m
""")
    assert res.findings == []


def test_nondeterminism_in_traced_scope(tmp_path):
    res = _lint_src(tmp_path, """
import random
import time
import jax

@jax.jit
def f(x):
    return x + time.time() + random.random()

def host_side():
    return time.time()
""")
    assert _rules(res) == ["trace-nondet", "trace-nondet"]


def test_branch_on_traced_param_detected_static_exempt(tmp_path):
    res = _lint_src(tmp_path, """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("telemetry",))
def f(x, telemetry):
    if telemetry:          # static: supported pattern
        x = x + 0
    if x > 0:              # traced: concretization error
        x = x - 1
    return x
""")
    assert _rules(res) == ["trace-branch"]
    assert "x" in res.findings[0].message


def test_branch_on_isinstance_and_shape_clean(tmp_path):
    res = _lint_src(tmp_path, """
import jax

@jax.jit
def f(X):
    if isinstance(X, tuple):
        X = X[0]
    if X.shape[0] > 4:
        X = X[:4]
    return X
""")
    assert res.findings == []


# ---------------------------------------------------------------------------
# knob hygiene family
# ---------------------------------------------------------------------------

def test_raw_env_read_detected(tmp_path):
    res = _lint_src(tmp_path, """
import os

depth = os.environ.get("CNMF_TPU_STREAM_DEPTH", "3")
spec = os.environ["JAX_COMPILATION_CACHE_DIR"]
present = "CNMF_TPU_TELEMETRY" in os.environ
via_getenv = os.getenv("CNMF_TPU_MAX_RETRIES")
other = os.environ.get("HOME")
""")
    assert _rules(res) == ["knob-raw-env"] * 4


def test_accessor_usage_clean_and_unregistered_detected(tmp_path):
    res = _lint_src(tmp_path, """
from cnmf_torch_tpu.utils.envknobs import env_flag, env_int

ok = env_int("CNMF_TPU_MAX_RETRIES", 2, lo=0)
bad = env_flag("CNMF_TPU_NOT_A_KNOB", True)
""")
    assert _rules(res) == ["knob-unregistered"]


def test_plan_bypass_detected_outside_resolvers(tmp_path):
    # a dispatch-class knob read through the accessors, outside the
    # planner-owned files and outside a registered resolver: flagged —
    # both the literal and the module-level *_ENV-constant spellings
    res = _lint_src(tmp_path, """
from cnmf_torch_tpu.utils.envknobs import env_int, env_str

PALLAS_ENV = "CNMF_TPU_PALLAS"

depth = env_int("CNMF_TPU_STREAM_DEPTH", 3, lo=1)
word = env_str(PALLAS_ENV, "auto")
""")
    assert _rules(res) == ["knob-plan-bypass"] * 2
    assert "CNMF_TPU_STREAM_DEPTH" in res.findings[0].message


def test_plan_bypass_exempts_registered_resolvers(tmp_path):
    # the SAME reads inside a PLAN_ACCESSORS-registered resolver
    # function are the sanctioned resolution sites
    res = _lint_src(tmp_path, """
from cnmf_torch_tpu.utils.envknobs import env_int, env_str

def stream_depth():
    return env_int("CNMF_TPU_STREAM_DEPTH", 3, lo=1)

def resolve_pallas():
    def inner():
        return env_str("CNMF_TPU_PALLAS", "auto")
    return inner()

# non-dispatch knobs never trip the rule anywhere
retries = env_int("CNMF_TPU_MAX_RETRIES", 2, lo=0)
""")
    assert res.findings == []


def test_envknobs_module_itself_exempt(tmp_path):
    utils = tmp_path / "utils"
    utils.mkdir()
    p = utils / "envknobs.py"
    p.write_text('import os\nv = os.environ.get("CNMF_TPU_TELEMETRY")\n')
    res = lint_paths([str(p)], doc_check=False)
    assert res.findings == []


def test_accessors_reject_unregistered_at_runtime():
    with pytest.raises(ValueError, match="not registered"):
        envknobs.env_int("CNMF_TPU_NOT_A_KNOB", 1)
    with pytest.raises(ValueError, match="not registered"):
        envknobs.env_is_set("CNMF_TPU_NOT_A_KNOB")


# ---------------------------------------------------------------------------
# artifact atomicity family
# ---------------------------------------------------------------------------

def test_nonatomic_writes_detected(tmp_path):
    res = _lint_src(tmp_path, """
import numpy as np

def save(df, path, arr):
    with open(path, "w") as f:
        f.write("x")
    np.savez(path + ".npz", arr=arr)
    df.to_csv(path + ".tsv", sep="\\t")
""")
    assert _rules(res) == ["artifact-nonatomic"] * 3


def test_atomic_artifact_block_clean(tmp_path):
    res = _lint_src(tmp_path, """
import numpy as np
from cnmf_torch_tpu.utils.anndata_lite import atomic_artifact

def save(df, path, arr, fig):
    with atomic_artifact(path) as tmp:
        with open(tmp, "w") as f:
            f.write("x")
    with atomic_artifact(path + ".npz") as tmp:
        np.savez(tmp, arr=arr)
    with atomic_artifact(path + ".png") as tmp:
        fig.savefig(tmp, format="png")

def read(path):
    with open(path) as f:          # read mode: never flagged
        return f.read()
""")
    assert res.findings == []


# ---------------------------------------------------------------------------
# telemetry schema family
# ---------------------------------------------------------------------------

def test_unknown_event_type_and_missing_field_detected(tmp_path):
    res = _lint_src(tmp_path, """
def report(events, wall):
    events.emit("frobnicate", foo=1)
    events.emit("stage", stage="combine")
    events.emit("stage", stage="combine", wall_s=wall)
""")
    assert _rules(res) == ["telemetry-schema", "telemetry-schema"]
    msgs = " ".join(f.message for f in res.findings)
    assert "frobnicate" in msgs and "wall_s" in msgs


def test_emit_splat_and_dynamic_type_skipped(tmp_path):
    res = _lint_src(tmp_path, """
def forward(events, etype, fields):
    events.emit(etype, **fields)      # dynamic: runtime smoke covers it
    events.emit("fault", **fields)    # splat: field set unknowable
""")
    assert res.findings == []


# ---------------------------------------------------------------------------
# concurrency family
# ---------------------------------------------------------------------------

def test_unlocked_module_state_mutation_detected(tmp_path):
    res = _lint_src(tmp_path, """
import threading

_cache = {}
_flag = False
_lock = threading.Lock()

def poke(k, v):
    _cache[k] = v

def latch():
    global _flag
    _flag = True
""")
    assert _rules(res) == ["lock-discipline", "lock-discipline"]


def test_nested_scope_binding_does_not_shadow_outer(tmp_path):
    """A nested function binding the same name must not mask the outer
    function's unlocked mutation (review finding, this PR)."""
    res = _lint_src(tmp_path, """
_state = {}

def outer(v):
    _state["k"] = v          # unlocked mutation: must fire
    def inner():
        _state = {}          # nested local: shadows only inner
        _state["k"] = 0      # clean (local)
        return _state
    return inner
""")
    assert _rules(res) == ["lock-discipline"]
    assert res.findings[0].line == 5


def test_locked_mutation_and_local_shadow_clean(tmp_path):
    res = _lint_src(tmp_path, """
import threading

_cache = {}
_other = {}
_lock = threading.Lock()

def poke(k, v):
    with _lock:
        _cache[k] = v

def shadowed(k, v):
    _cache = {}        # local: shadows the module binding
    _cache[k] = v
    return _cache

def annotated(k, v):
    _cache: dict = {}  # annotated local: still a shadow
    _cache[k] = v
    if (_other := dict()):   # walrus local: still a shadow
        _other[k] = v
    return _cache, _other
""")
    assert res.findings == []


# ---------------------------------------------------------------------------
# suppressions, baseline, output
# ---------------------------------------------------------------------------

def test_inline_suppression_same_line_and_line_above(tmp_path):
    res = _lint_src(tmp_path, """
import os

a = os.environ.get("CNMF_TPU_TELEMETRY")  # cnmf-lint: disable=knob-raw-env
# cnmf-lint: disable=knob-raw-env
b = os.environ.get("CNMF_TPU_PROFILE_DIR")
c = os.environ.get("CNMF_TPU_MAX_RETRIES")  # cnmf-lint: disable=lock-discipline
""")
    assert _rules(res) == ["knob-raw-env"]  # wrong rule id doesn't suppress
    assert res.suppressed == 2
    assert res.findings[0].line == 7


def test_baseline_absorbs_then_new_finding_fails(tmp_path):
    src = 'import os\nv = os.environ.get("CNMF_TPU_TELEMETRY")\n'
    p = tmp_path / "mod.py"
    p.write_text(src)
    pre = lint_paths([str(p)], doc_check=False)
    assert len(pre.findings) == 1

    baseline = tmp_path / "baseline.json"
    write_baseline(str(baseline), pre.findings)
    clean = lint_paths([str(p)], baseline_path=str(baseline),
                       doc_check=False)
    assert clean.findings == [] and len(clean.baselined) == 1

    # a NEW violation is not hidden by the old baseline (and line drift
    # of the baselined one stays absorbed: fingerprint is rule+text)
    p.write_text("# moved down a line\n" + src
                 + 'w = os.environ.get("CNMF_TPU_PROFILE_DIR")\n')
    res = lint_paths([str(p)], baseline_path=str(baseline),
                     doc_check=False)
    assert len(res.findings) == 1 and len(res.baselined) == 1
    assert "CNMF_TPU_PROFILE_DIR" in res.findings[0].message


def test_json_output_shape(tmp_path):
    res = _lint_src(tmp_path, """
import os
v = os.environ.get("CNMF_TPU_TELEMETRY")
""")
    data = json.loads(format_json(res))
    assert data["version"] == 1 and data["files"] == 1
    (f,) = data["findings"]
    assert set(f) == {"path", "line", "rule", "message", "hint", "text"}
    assert f["rule"] == "knob-raw-env"
    assert data["counts"] == {"knob-raw-env": 1}
    assert data["families"]["knobs"] == 1


def test_parse_error_is_a_finding(tmp_path):
    res = _lint_src(tmp_path, "def broken(:\n")
    assert _rules(res) == ["lint-parse-error"]


# ---------------------------------------------------------------------------
# knob registry round-trip + doc drift
# ---------------------------------------------------------------------------

def test_knob_table_round_trip():
    table = envknobs.knob_table()
    parsed = envknobs.parse_knob_table(table)
    documented = {n: k for n, k in envknobs.REGISTRY.items()
                  if k.documented}
    assert set(parsed) == set(documented)
    for name, (default, doc) in parsed.items():
        assert default == documented[name].default
        assert doc == documented[name].doc


def test_readme_drift_detected(tmp_path):
    from cnmf_torch_tpu.analysis.rules_knobs import check_knob_docs

    readme = tmp_path / "README.md"
    table = envknobs.knob_table().splitlines()
    # drop one knob row, corrupt another default, rewrite a third's doc
    table.pop(2)
    name3, default3, _ = (c.strip() for c in
                          table[3].strip("|").split(" | ", 2))
    table[3] = f"| {name3} | STALE_DEFAULT | doesn't matter |"
    name4, _, doc4 = (c.strip() for c in
                      table[4].strip("|").split(" | ", 2))
    table[4] = table[4].replace(doc4, "hand-edited description")
    readme.write_text("## Environment knobs\n\n" + "\n".join(table)
                      + "\n| `CNMF_TPU_BOGUS_KNOB` | `1` | nothing |\n")
    findings = check_knob_docs(str(readme))
    kinds = sorted(f.text.split(":")[0] for f in findings)
    assert kinds == ["missing row", "stale default", "stale doc",
                     "unregistered row"]


def test_parse_knob_table_tolerates_pipe_in_doc():
    row = ("| `CNMF_TPU_TELEMETRY` | `0` | choose `a` | `b` | `c` here |")
    parsed = envknobs.parse_knob_table(row)
    assert parsed == {"CNMF_TPU_TELEMETRY":
                      ("`0`", "choose `a` | `b` | `c` here")}


def test_real_readme_matches_registry():
    from cnmf_torch_tpu.analysis.rules_knobs import check_knob_docs

    assert check_knob_docs(os.path.join(REPO, "README.md")) == []


# ---------------------------------------------------------------------------
# the gate: the shipped package lints clean against an EMPTY baseline
# ---------------------------------------------------------------------------

def test_package_lints_clean_with_empty_baseline():
    with open(DEFAULT_BASELINE) as f:
        assert json.load(f)["findings"] == []
    res = lint_paths([os.path.join(REPO, "cnmf_torch_tpu")],
                     baseline_path=DEFAULT_BASELINE)
    assert res.findings == []


def test_cli_exit_codes_and_knob_table(tmp_path, capsys):
    assert lint_main(["--knob-table"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("| knob | default | what it does |")
    assert "CNMF_TPU_TELEMETRY" in out

    bad = tmp_path / "bad.py"
    bad.write_text('import os\nv = os.environ.get("CNMF_TPU_TELEMETRY")\n')
    assert lint_main([str(bad), "--baseline", "", "--no-doc-check"]) == 1
    assert "knob-raw-env" in capsys.readouterr().out
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    assert lint_main([str(ok), "--baseline", "", "--no-doc-check"]) == 0
    capsys.readouterr()

    # --write-baseline grandfathers, then the same paths gate clean
    bl = tmp_path / "bl.json"
    assert lint_main([str(bad), "--baseline", str(bl), "--write-baseline",
                      "--no-doc-check"]) == 0
    assert lint_main([str(bad), "--baseline", str(bl),
                      "--no-doc-check"]) == 0
    capsys.readouterr()

    # "--baseline ''" means no baseline; combining it with
    # --write-baseline must NOT fall back to the checked-in default
    with pytest.raises(SystemExit) as exc:
        lint_main([str(bad), "--baseline", "", "--write-baseline",
                   "--no-doc-check"])
    assert exc.value.code == 2
