"""Per-stage tracing: wall-clock ledger + optional XLA profiler traces.

The reference has no tracing at all — only stdout progress prints
(``/root/reference/src/cnmf/cnmf.py:884, 793, 897``; SURVEY.md §5.1 calls
this out as a gap to fill). This module provides:

  * :class:`StageTimer` — context manager recording per-stage wall-clock
    (and optional metadata) to ``<run_dir>/cnmf_tmp/<name>.timings.tsv``,
    appended across pipeline invocations so a resumed run accumulates a
    complete timeline;
  * :func:`trace` — wraps a stage in a ``jax.profiler`` trace when
    ``CNMF_TPU_PROFILE_DIR`` is set, producing TensorBoard-loadable XLA
    traces of the device work with zero overhead when unset.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

__all__ = ["StageTimer", "trace", "PROFILE_ENV", "percentile",
           "latency_summary", "HIST_EDGES"]

PROFILE_ENV = "CNMF_TPU_PROFILE_DIR"

# log-ish histogram bucket edges for latency summaries, in the caller's
# unit (serving uses milliseconds): fine buckets where SLOs live, coarse
# tails, one overflow bucket. Shared with the live metrics registry
# (obs/metrics.py) so a scraped /metrics histogram and the post-hoc
# report's latency_summary bucket the same way.
_HIST_EDGES = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
               1000.0, 2000.0, 5000.0)
HIST_EDGES = _HIST_EDGES


def percentile(values, q: float) -> float:
    """Linear-interpolated percentile (numpy's default method) over an
    unsorted sequence — the ONE percentile implementation shared by the
    serving tier's latency accounting (``bench.py --tier serve``) and the
    telemetry report's serving section, instead of a third hand-rolled
    variant next to the report's nearest-rank medians."""
    vals = sorted(float(v) for v in values)
    if not vals:
        raise ValueError("percentile of an empty sequence")
    if len(vals) == 1:
        return vals[0]
    pos = (len(vals) - 1) * (float(q) / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    frac = pos - lo
    return vals[lo] * (1.0 - frac) + vals[hi] * frac


def latency_summary(values, percentiles=(50.0, 95.0, 99.0)) -> dict:
    """Latency distribution summary: count/mean/max, the requested
    percentiles (``p50``/``p95``/``p99`` keys), and a fixed-edge histogram
    (``{"<=1", ..., ">5000": count}`` in the caller's unit — serving
    passes milliseconds). Empty input yields ``{"count": 0}`` so callers
    can always embed the result."""
    vals = [float(v) for v in values]
    if not vals:
        return {"count": 0}
    out = {"count": len(vals),
           "mean": sum(vals) / len(vals),
           "max": max(vals)}
    for q in percentiles:
        label = ("p%g" % q).replace(".", "_")
        out[label] = percentile(vals, q)
    hist: dict = {}
    edges = _HIST_EDGES
    for v in vals:
        for edge in edges:
            if v <= edge:
                label = "<=%g" % edge
                break
        else:
            label = ">%g" % edges[-1]
        hist[label] = hist.get(label, 0) + 1
    # stable bucket order (dicts preserve insertion): edges first, overflow
    ordered = {}
    for edge in edges:
        label = "<=%g" % edge
        if label in hist:
            ordered[label] = hist[label]
    overflow = ">%g" % edges[-1]
    if overflow in hist:
        ordered[overflow] = hist[overflow]
    out["histogram"] = ordered
    return out


def _sanitize_field(v) -> str:
    """TSV fields are single-line, tab-free by contract: meta values with
    tabs/newlines used to shift every later column and corrupt positional
    parsers (``bench.iter_stage_rows``)."""
    s = str(v)
    for ch in ("\t", "\n", "\r"):
        if ch in s:
            s = s.replace(ch, " ")
    return s


class StageTimer:
    """Append-only wall-clock ledger for pipeline stages.

    Thread-safe: ``k_selection_plot`` runs up to 4 consensus stats passes
    concurrently, all recording into one TSV — records serialize under a
    lock (ADVICE r5 #4) so the header is written exactly once and rows
    never interleave mid-line (``bench.py:iter_stage_rows`` parses the
    file positionally).

    ``events``: optional :class:`~cnmf_torch_tpu.utils.telemetry.EventLog`
    — every recorded row is mirrored as a ``stage`` event, so the
    structured stream carries the same walls/bytes as the TSV without a
    second measurement site."""

    # one warning per PROCESS when the ledger is unwritable: per-instance
    # state would re-warn for every stats pass of a K-selection sweep
    _oserror_warned = False
    _oserror_lock = threading.Lock()

    def __init__(self, timings_path: str | None, events=None):
        self.timings_path = timings_path
        self.events = events
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str, nbytes: int | None = None, **meta):
        """Time a stage. ``nbytes`` (bytes the stage moved/produced) fills
        the throughput columns — staging stages record it so host_prep vs
        H2D vs device walls carry GB/s, not just seconds."""
        t0 = time.perf_counter()
        err = ""
        try:
            yield
        except BaseException as exc:
            err = type(exc).__name__
            raise
        finally:
            elapsed = time.perf_counter() - t0
            self._record(name, elapsed, err, meta, nbytes)

    def record(self, name: str, seconds: float, nbytes: int | None = None,
               **meta):
        """Append a pre-measured row (the streaming engine measures its
        host_prep/h2d/device phases across threads itself — a context
        manager around any one of them would measure the wrong wall)."""
        self._record(name, float(seconds), "", meta, nbytes)

    def _record(self, name: str, elapsed: float, err: str, meta: dict,
                nbytes: int | None = None):
        if self.events is not None:
            self.events.emit("stage", stage=str(name),
                             wall_s=round(float(elapsed), 6),
                             nbytes=int(nbytes) if nbytes else None,
                             error=err or None,
                             meta={str(k): meta[k] for k in sorted(meta)}
                             if meta else None)
        if self.timings_path is None:
            return
        meta_str = ";".join(f"{k}={_sanitize_field(v)}"
                            for k, v in sorted(meta.items()))
        gbps = ("" if not nbytes or elapsed <= 0
                else f"{nbytes / elapsed / 1e9:.3f}")
        try:
            with self._lock:
                header_needed = not os.path.exists(self.timings_path)
                # append-only ledger, not a probed artifact: an atomic
                # rewrite would drop rows raced in by sibling processes,
                # and a torn tail row is tolerated by every reader
                with open(self.timings_path, "a") as f:  # cnmf-lint: disable=artifact-nonatomic
                    if header_needed:
                        # bytes/gb_per_s sit AFTER wall_seconds: the one
                        # external parser (bench.iter_stage_rows) reads
                        # columns [:2] positionally
                        f.write("stage\twall_seconds\tbytes\tgb_per_s\t"
                                "timestamp\terror\tmeta\n")
                    f.write(f"{_sanitize_field(name)}\t{elapsed:.4f}\t"
                            f"{nbytes if nbytes else ''}\t{gbps}\t"
                            f"{time.time():.1f}\t{_sanitize_field(err)}\t"
                            f"{meta_str}\n")
        except OSError as exc:
            # tracing must never take the pipeline down — but a silently
            # missing ledger cost a round of debugging; warn once/process
            with StageTimer._oserror_lock:
                if not StageTimer._oserror_warned:
                    StageTimer._oserror_warned = True
                    import warnings

                    warnings.warn(
                        "StageTimer: cannot append to %r (%s); timing rows "
                        "from this process will be dropped silently from "
                        "here on" % (self.timings_path, exc),
                        RuntimeWarning, stacklevel=3)


# One profiler session at a time is a JAX-level constraint; stages both
# NEST in one thread (k_selection_plot -> consensus) and run CONCURRENTLY
# across threads (up to 4 stats passes). A non-blocking lock serves both:
# the first stage to acquire owns the session, every nested or concurrent
# stage inside it is a no-op (nested device work is already captured by
# the outer session; concurrent stages simply go untraced rather than
# racing two `jax.profiler.trace` sessions open, which raises).
_trace_lock = threading.Lock()


@contextlib.contextmanager
def trace(stage_name: str):
    """XLA profiler trace of a stage when CNMF_TPU_PROFILE_DIR is set.

    Reentrant- and thread-safe: only one profiler session can exist, so
    whichever stage acquires the (non-blocking) session lock first traces;
    stages nested inside it or racing it from sibling threads no-op.
    """
    from .envknobs import env_str

    profile_dir = env_str(PROFILE_ENV, "")
    if not profile_dir or not _trace_lock.acquire(blocking=False):
        yield
        return
    import jax

    try:
        with jax.profiler.trace(os.path.join(profile_dir, stage_name)):
            yield
    finally:
        _trace_lock.release()
