"""Measured solver-cost ratios: a startup microbench cached per device
fingerprint (ISSUE 11 satellite, feeding ROADMAP item 5's autotuner).

The accelerated-MU schedule (``ops/recipe.py:auto_inner_repeats``) derives
ρ — H sub-iterations per W update — from STATIC flop-count ratios whose
clamp was measured once on CPU. Real kernels diverge from flop counts
(gather-bound ELL passes, fusion, memory formats differ per backend), so
this module times one H-repeat against one W-update per lane on the LIVE
device at a probe shape, stores ``measured_ratio / static_ratio`` per
lane, and ``auto_inner_repeats`` multiplies its static ratio by that
scale (falling back to the static schedule whenever no cache exists).

The cache is one JSON per device fingerprint under the system temp dir
(atomic replace; survives processes, not reboots on tmpfs — the bench is
~1 s, so a cold cache is cheap). ``models/cnmf.py:factorize`` calls
:func:`maybe_autotune_rho` once up front when the accel knobs could
engage an amu recipe; everything here is best-effort — any failure
resolves to the static schedule, never an error.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

__all__ = ["device_fingerprint", "cache_path", "measure_rho_scales",
           "maybe_autotune_rho", "cached_rho_scale"]

_PROBE_N, _PROBE_G, _PROBE_K = 2048, 512, 10
_PROBE_DENSITY = 0.05

_memo: dict = {}
_memo_lock = threading.Lock()


def device_fingerprint() -> str:
    """Backend + device kind + count — the identity a measured ratio is
    valid for (a resumed run on different hardware re-measures)."""
    import jax

    d = jax.devices()[0]
    kind = str(getattr(d, "device_kind", "unknown")).replace(" ", "_")
    return f"{jax.default_backend()}-{kind}-x{len(jax.devices())}"


def cache_path(cache_dir: str | None = None) -> str:
    base = cache_dir or os.path.join(tempfile.gettempdir(),
                                     "cnmf_tpu_autotune")
    return os.path.join(base, f"rho_{device_fingerprint()}.json")


def _time_call(fn, *args, repeats: int = 5) -> float:
    """Median wall of ``fn(*args)`` with block_until_ready, after one
    warm-up dispatch (compile + upload excluded from the measurement)."""
    import jax

    jax.block_until_ready(fn(*args))
    walls = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


def measure_rho_scales() -> dict:
    """Run the microbench: per lane, the measured W-update/H-repeat wall
    ratio divided by the static flop ratio ``auto_inner_repeats`` would
    use at the probe shape. Returns the cache payload."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import scipy.sparse as sp

    from ..ops.nmf import _apply_rate, _update_H, _update_W
    from ..ops.sparse import csr_to_ell, ell_device_put, ell_w_table

    n, g, k = _PROBE_N, _PROBE_G, _PROBE_K
    rng = np.random.default_rng(0)
    H = jnp.asarray(rng.uniform(0.1, 1.0, (n, k)).astype(np.float32))
    W = jnp.asarray(rng.uniform(0.1, 1.0, (k, g)).astype(np.float32))
    Xd = jnp.asarray(rng.gamma(1.0, 1.0, (n, g)).astype(np.float32))

    scales: dict = {}

    # beta=2: H repeat = rate against hoisted XW^T/WW^T (k-sized);
    # W update = the full statistics step
    numer0 = Xd @ W.T
    WWT = W @ W.T
    h_rep_b2 = jax.jit(lambda h: _apply_rate(h, numer0, h @ WWT, 0.0, 0.0))
    w_upd_b2 = jax.jit(lambda h, w: _update_W(Xd, h, w, 2.0, 0.0, 0.0))
    static_b2 = (2.0 * n * g * k) / max(n * k * k, 1)
    meas_b2 = (_time_call(w_upd_b2, H, W)
               / max(_time_call(h_rep_b2, H), 1e-9))
    scales["b2"] = meas_b2 / static_b2

    # dense beta=1: repeat and W update are the same full-pass class
    h_rep_kl = jax.jit(lambda h: _update_H(Xd, h, W, 1.0, 0.0, 0.0))
    w_upd_kl = jax.jit(lambda h, w: _update_W(Xd, h, w, 1.0, 0.0, 0.0))
    scales["dense"] = (_time_call(w_upd_kl, H, W)
                       / max(_time_call(h_rep_kl, H), 1e-9)) / 1.0

    # ELL beta=1: repeat reads the pre-gathered slab table; the W update
    # rebuilds tables and walks the transpose index set
    mask = rng.uniform(size=(n, g)) < _PROBE_DENSITY
    Xs = sp.csr_matrix(np.where(mask, np.asarray(Xd), 0.0))
    E = ell_device_put(csr_to_ell(Xs))
    w_ell = E.width
    table = ell_w_table(W, E.cols)
    h_rep_ell = jax.jit(
        lambda h: _update_H(E, h, W, 1.0, 0.0, 0.0, w_table=table))
    w_upd_ell = jax.jit(lambda h, w: _update_W(E, h, w, 1.0, 0.0, 0.0))
    static_ell = (n * w_ell * (4 * k + 2)) / max(n * w_ell * (2 * k + 2), 1)
    scales["ell"] = (_time_call(w_upd_ell, H, W)
                     / max(_time_call(h_rep_ell, H), 1e-9)) / static_ell

    return {"fingerprint": device_fingerprint(),
            "probe": {"n": n, "g": g, "k": k,
                      "density": _PROBE_DENSITY, "ell_width": int(w_ell)},
            "scales": {lane: round(float(v), 4)
                       for lane, v in scales.items()},
            "measured_at": time.time()}


def _load(path: str) -> dict | None:
    try:
        with open(path) as f:
            payload = json.load(f)
        if payload.get("fingerprint") != device_fingerprint():
            return None
        return payload
    except Exception:
        return None


def maybe_autotune_rho(cache_dir: str | None = None,
                       force: bool = False,
                       beta: float | None = None) -> dict | None:
    """Ensure the measured-ρ cache for this device exists and is loaded
    into the in-process memo. Measures (and atomically writes the JSON)
    only when no valid cache is present, and only when the accel knobs
    could actually engage an amu schedule — ``CNMF_TPU_ACCEL`` off or an
    explicit ``CNMF_TPU_INNER_REPEATS`` pin means the measurement would
    never be read, so the bench is skipped. Best-effort: returns the
    payload or ``None``; never raises.

    Determinism: the measured ρ is a jit static and part of the
    checkpoint identity signature, so it must agree wherever programs
    must agree. On MULTI-HOST pods the lane is disabled outright
    (``jax.process_count() > 1`` → static schedule): per-host timing
    jitter could resolve different ρ on different hosts and compile
    mismatched SPMD programs. Single-host, a lost cache re-measures and
    may land a different ρ — the checkpoint identity then RESTARTS the
    replicate (the documented recipe-change contract, never a splice);
    pin ``CNMF_TPU_INNER_REPEATS`` for resume-stable long runs."""
    try:
        from .envknobs import env_str

        if not force:
            accel = env_str("CNMF_TPU_ACCEL", "0").strip().lower()
            rho_pin = env_str("CNMF_TPU_INNER_REPEATS", "").strip().lower()
            if accel in ("", "0", "off", "false", "no") or \
                    rho_pin not in ("", "auto"):
                return None
            # amu-reachability (``beta`` known): a run whose engaged
            # recipe can only be sketch (CNMF_TPU_SKETCH forces the
            # solver lane for beta=1) or dna (KL_NEWTON on steers an
            # engaged beta=1 acceleration to Newton) never consults
            # auto_inner_repeats — skip the bench instead of paying a
            # ~1 s startup it cannot read
            if beta is not None and float(beta) == 1.0:
                from .envknobs import env_flag

                sk = env_str("CNMF_TPU_SKETCH", "0").strip().lower()
                if sk in ("1", "on", "true", "yes", "force") or \
                        env_flag("CNMF_TPU_KL_NEWTON", True):
                    return None
            import jax

            if jax.process_count() > 1:
                return None
        path = cache_path(cache_dir)
        payload = None if force else _load(path)
        if payload is None:
            payload = measure_rho_scales()
            os.makedirs(os.path.dirname(path), exist_ok=True)
            from .anndata_lite import atomic_artifact

            with atomic_artifact(path) as tmp:
                with open(tmp, "w") as f:
                    json.dump(payload, f)
        with _memo_lock:
            _memo[path] = payload
        return payload
    except Exception:
        return None


def cached_rho_scale(beta: float, ell: bool = False,
                     cache_dir: str | None = None) -> float | None:
    """Read-only lane lookup for ``auto_inner_repeats``: the measured
    scale for this (β, encoding) lane, or ``None`` (static fallback)
    when no cache has been written for this device. Never measures.
    Multi-host pods always get ``None`` — a cache written by an earlier
    single-host run on one machine must not steer ρ differently across
    hosts compiling one SPMD program (see :func:`maybe_autotune_rho`)."""
    try:
        import jax

        if jax.process_count() > 1:
            return None
        path = cache_path(cache_dir)
        with _memo_lock:
            payload = _memo.get(path)
        if payload is None:
            payload = _load(path)
            if payload is None:
                return None
            with _memo_lock:
                _memo[path] = payload
        lane = "b2" if float(beta) == 2.0 else ("ell" if ell else "dense")
        val = payload.get("scales", {}).get(lane)
        return float(val) if val is not None else None
    except Exception:
        return None
