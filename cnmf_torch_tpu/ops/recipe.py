"""Solver recipes — the ONE resolution of *which convergence math runs*.

Every previous perf PR changed the memory system (ELL encoding, bf16
chains, bundled contractions); COMPLETENESS closes that line with
"further gains need different math, not a better kernel". This module is
the different math's dispatch layer: a :class:`SolverRecipe` names the
iteration scheme a β-divergence solve runs —

  * ``mu``   — plain alternating multiplicative updates (the seed
    behavior; the only recipe whose trajectories are pinned element-wise
    against the sklearn/nmf-torch oracles);
  * ``amu``  — accelerated MU (Gillis & Glineur, arXiv:1107.5194):
    ``inner_repeats`` cheap H sub-iterations per expensive W update,
    with a stagnation early-exit per lane. The repeats re-use the
    loop-invariant W products (β=2: the hoisted ``XWᵀ``/``WWᵀ``
    statistics; ELL β∈{1,0}: the pre-gathered W slab table), which is
    where the per-repeat cost collapses;
  * ``dna``  — Diagonalized Newton for KL (Van hamme, arXiv:1301.3389):
    per-element diagonal-Hessian steps clipped to the nonnegativity
    boundary, with a per-row/per-column monotone MU fallback lane
    selected by comparing the two candidates' exact objective
    contributions (rows of D_KL(X‖HW) decouple for fixed W, columns for
    fixed H, so the selection preserves MU's monotonicity guarantee
    outright). Measured on the bench fixtures: 4–6× fewer outer
    iterations to a fixed KL objective tolerance than plain MU
    (``bench.py --tier accel``);
  * ``hals`` — the β=2 hierarchical-ALS family (``algo='halsvar'``),
    previously reachable only through ``run_nmf`` — the recipe selector
    is now its dispatch site for replicate sweeps too;
  * ``sketch`` — randomized sketched KL (ISSUE 11, following arXiv
    1604.04026's randomized-subsampling treatment of large sparse
    KL-NMF): the H updates stay exact, while each W update runs against
    a ``sketch_dim``-row random subsample of X (the MU ratio is
    invariant to the n/m scaling, so the subsampled statistics feed the
    unchanged update rate), with an EXACT full-data W update interleaved
    every ``sketch_exact_every`` iterations (and at iteration 0) to
    control bias. Sublinear W-update work in n; the stopping rule keeps
    evaluating the exact objective.

Resolution order: explicit caller arguments > env knobs > the auto
heuristic. Knobs (registered in ``utils/envknobs.py``):

  * ``CNMF_TPU_ACCEL``: ``auto`` (default since the execution planner,
    ISSUE 17) engages acceleration for batch β∈{1,0} MU solves (the
    lane whose trajectories are NOT pinned bit-exact by the parity
    suite) and resolves ``amu``/``dna`` from β; ``0`` pins plain MU —
    the compiled programs are byte-identical to a build without this
    module (same guarantee style as the telemetry flag; the parity
    escape hatch); ``1`` forces acceleration wherever the recipe is
    defined.
  * ``CNMF_TPU_INNER_REPEATS``: pins ρ; unset derives it from the
    1107.5194 cost ratio (H-repeat flops vs W-update flops — static in
    n/g/k and the ELL width, :func:`auto_inner_repeats`).
  * ``CNMF_TPU_KL_NEWTON``: ``1`` (default) lets an *engaged*
    acceleration pick DNA for β=1; ``0`` restricts it to the MU repeat
    schedule.
  * ``CNMF_TPU_SKETCH``: ``0`` (default) pins exact updates — programs
    byte-identical to a build without the sketch layer; ``1`` forces
    the ``sketch`` recipe for β=1 MU solves (and the sketched consensus
    stage, ``ops/sketch.py``); ``auto`` engages the consensus-side
    sketch only (tolerance-bounded distances) and leaves the solver
    lane off.
  * ``CNMF_TPU_SKETCH_DIM``: sampled rows per sketched W update (unset
    derives :func:`auto_sketch_rows` from n) — shared with the
    consensus projection dimension (``ops/sketch.py``).
  * ``CNMF_TPU_SKETCH_EXACT_EVERY``: exact-pass cadence E (default 4).

The resolved recipe is recorded whole: in the factorize provenance and
telemetry ``dispatch`` events (``models/cnmf.py``), in every sweep's
``replicates`` telemetry payload, and in the mid-run checkpoint identity
``params`` signature (``runtime/checkpoint.py``) — a resumed run must
never splice an MU trajectory onto a DNA one.

Stdlib-only (no jax import): the light runtime modules share it.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["SolverRecipe", "resolve_recipe", "auto_inner_repeats",
           "auto_sketch_rows", "ACCEL_ENV", "INNER_REPEATS_ENV",
           "KL_NEWTON_ENV", "SKETCH_ENV", "SKETCH_DIM_ENV",
           "SKETCH_EXACT_EVERY_ENV", "DEFAULT_SKETCH_EXACT_EVERY"]

ACCEL_ENV = "CNMF_TPU_ACCEL"
INNER_REPEATS_ENV = "CNMF_TPU_INNER_REPEATS"
KL_NEWTON_ENV = "CNMF_TPU_KL_NEWTON"
SKETCH_ENV = "CNMF_TPU_SKETCH"
SKETCH_DIM_ENV = "CNMF_TPU_SKETCH_DIM"
SKETCH_EXACT_EVERY_ENV = "CNMF_TPU_SKETCH_EXACT_EVERY"

DEFAULT_SKETCH_EXACT_EVERY = 4

_OFF_WORDS = ("", "0", "off", "false", "no")
_ON_WORDS = ("1", "on", "true", "yes", "force")


@dataclass(frozen=True)
class SolverRecipe:
    """One resolved iteration scheme for a β-divergence solve.

    ``algo``: ``mu`` | ``amu`` | ``dna`` | ``hals``. ``inner_repeats``:
    H sub-iterations per W update (``amu`` only; 1 otherwise).
    ``kl_newton``: the β=1 updates run diagonal-Newton steps with the
    MU fallback lane (``dna`` only). ``source`` records who decided
    (``default`` / ``env`` / ``auto`` / ``caller``) for provenance.
    """

    algo: str = "mu"
    inner_repeats: int = 1
    kl_newton: bool = False
    source: str = "default"
    sketch_dim: int = 0
    sketch_exact_every: int = 1

    def __post_init__(self):
        if self.algo not in ("mu", "amu", "dna", "hals", "sketch"):
            raise ValueError(f"unknown recipe algo {self.algo!r}")
        if self.inner_repeats < 1:
            raise ValueError(
                f"inner_repeats={self.inner_repeats}: must be >= 1")
        if self.kl_newton and self.algo != "dna":
            raise ValueError("kl_newton is the dna recipe's flag")
        if self.algo == "sketch":
            if self.sketch_dim < 1:
                raise ValueError(
                    "the sketch recipe needs sketch_dim >= 1 sampled rows")
            if self.sketch_exact_every < 1:
                raise ValueError(
                    f"sketch_exact_every={self.sketch_exact_every}: "
                    "must be >= 1")
            if self.inner_repeats != 1 or self.kl_newton:
                raise ValueError(
                    "the sketch recipe is exclusive with amu/dna fields")
        elif self.sketch_dim:
            raise ValueError("sketch_dim is the sketch recipe's field")

    @property
    def label(self) -> str:
        """Short human/telemetry label: ``mu``, ``amu(rho=3)``, ``dna``,
        ``hals``, ``sketch(m=512,E=4)``."""
        if self.algo == "amu":
            return f"amu(rho={self.inner_repeats})"
        if self.algo == "sketch":
            return (f"sketch(m={self.sketch_dim},"
                    f"E={self.sketch_exact_every})")
        return self.algo

    @property
    def is_identity(self) -> bool:
        """True when the recipe compiles the exact seed (plain-MU/HALS)
        programs — no inner repeats, no Newton lane, no sketched
        updates."""
        return (self.inner_repeats == 1 and not self.kl_newton
                and self.algo != "sketch")

    def signature(self, kernel: str | None = None) -> str:
        """Stable string for the checkpoint identity ``params`` field —
        two runs whose signatures differ must not splice trajectories.
        Sketch fields append only when the sketch lane is engaged, so
        pre-sketch checkpoints keep their identity.

        ``kernel`` (ISSUE 16): the engaged inner-loop kernel label
        (``ops/pallas/__init__.py:kernel_label``) — passed by callers
        ONLY when the fused Pallas kernels engage, so default-path
        checkpoints keep their pre-Pallas identity while a resume
        across a ``CNMF_TPU_PALLAS`` flip (either direction) restarts
        instead of splicing two accumulation orders' trajectories."""
        sig = (f"algo={self.algo},rho={int(self.inner_repeats)},"
               f"newton={int(self.kl_newton)}")
        if self.algo == "sketch":
            sig += (f",skdim={int(self.sketch_dim)},"
                    f"skE={int(self.sketch_exact_every)}")
        if kernel is not None:
            sig += f",kernel={kernel}"
        return sig

    def as_context(self) -> dict:
        """The telemetry ``dispatch`` event context."""
        return {"recipe": self.label, "algo": self.algo,
                "inner_repeats": int(self.inner_repeats),
                "kl_newton": bool(self.kl_newton), "source": self.source,
                "sketch_dim": int(self.sketch_dim),
                "sketch_exact_every": int(self.sketch_exact_every)}


def auto_sketch_rows(n: int | None) -> int:
    """Default sampled-row count for the sketched W update: n/8 clamped
    to [256, n] — small enough that the subsampled statistics pass is
    sublinear, large enough that the MU ratio's sampled numerator/
    denominator stay low-variance at single-cell sparsity (the exact
    interleave controls the residual bias either way). ``n`` unknown at
    the resolution site -> 2048 (run_nmf resolves before staging)."""
    if not n:
        return 2048
    return int(max(min(256, n), min(n, n // 8)))


def _measured_rho_scale(beta: float, ell: bool):
    """Measured correction to the static amu cost ratio, cached per
    device fingerprint by ``utils/autotune.py`` (ISSUE 11 satellite:
    the [2, 8] clamp and the flop-count ratios were CPU-measured
    constants). Returns ``None`` — static fallback — whenever no cache
    exists or the jax-side reader is unavailable; this module stays
    stdlib-only at import time (the import below is lazy and only runs
    while a rho is actually being derived, i.e. with jax importable)."""
    try:
        from ..utils.autotune import cached_rho_scale

        return cached_rho_scale(beta, ell=ell)
    except Exception:
        return None


def auto_inner_repeats(beta: float, n: int | None = None,
                       g: int | None = None, k: int | None = None,
                       ell_width: int | None = None,
                       ell: bool = False) -> int:
    """ρ from the 1107.5194 cost ratio: 1 + (W-update flops) //
    (H-repeat flops), clamped to [2, 8]. All inputs are static shape
    facts, so ρ never changes a compiled program's cache key at run time.

    The H-*repeat* cost is what a second-and-later H update costs with
    the loop-invariant W products hoisted out of the repeat loop:

      * β=2: the repeat is ``H @ (WWᵀ)`` against the precomputed
        ``XWᵀ``/``WWᵀ`` — k-sized, so the ratio is ~2g/k and ρ caps at 8;
      * ELL β∈{1,0}: the repeat re-reads the pre-gathered W slab table
        (``~n·w·(2k+2)`` flops) while the W update additionally rebuilds
        tables and walks the transpose index set (``~n·w·(4k+2)``) — ρ=3;
      * dense β∈{1,0}: repeat and W update are the same full WH pass —
        ρ=2 (the mild schedule; the measured win here is wall-clock
        per objective, not per-iteration).
    """
    beta = float(beta)
    ell = bool(ell) or ell_width is not None
    if n and g and k:
        if beta == 2.0:
            h_rep = n * k * k
            w_upd = 2 * n * g * k
        elif ell_width:
            h_rep = n * ell_width * (2 * k + 2)
            w_upd = n * ell_width * (4 * k + 2)
        elif ell:
            # ELL-encoded but the width is not known at this resolution
            # site (run_nmf resolves before staging): the width cancels
            # in the ratio, (4k+2)/(2k+2) -> rho=3 for any width
            return 3
        else:
            h_rep = 2 * n * g * k
            w_upd = 2 * n * g * k
        ratio = w_upd / max(h_rep, 1)
        scale = _measured_rho_scale(beta, ell)
        if scale is not None:
            # measured lane: the cached per-device scale corrects the
            # static flop ratio for the real kernel walls (gathers,
            # fusion, memory format), and the clamp widens to [2, 12] —
            # a device whose W update is genuinely 10x its H repeat may
            # schedule more repeats than the CPU-measured cap allowed
            return int(max(2, min(12, 1 + round(ratio * scale))))
        return int(max(2, min(8, 1 + round(ratio))))
    # shape-free fallbacks of the same ratios (the width cancels in the
    # ELL ratio, so flag-only resolution lands the same schedule)
    if beta == 2.0:
        return 8
    return 3 if ell else 2


def resolve_recipe(beta: float, mode: str, *, algo: str = "mu",
                   ell: bool = False, n: int | None = None,
                   g: int | None = None, k: int | None = None,
                   ell_width: int | None = None,
                   accel: str | None = None,
                   inner_repeats: int | None = None,
                   kl_newton: bool | None = None,
                   sketch: str | None = None,
                   sketch_dim: int | None = None,
                   sketch_exact_every: int | None = None) -> SolverRecipe:
    """Resolve the solver recipe for one (β, mode) solve.

    ``mode``: ``batch`` | ``online`` | ``rowshard``. ``algo`` is the
    ledger/caller algorithm choice (``mu`` or nmf-torch's ``halsvar``,
    which maps to the ``hals`` recipe outright). Explicit ``accel`` /
    ``inner_repeats`` / ``kl_newton`` / ``sketch*`` arguments win over
    the env knobs.

    Capability map (acceleration engages only where the scheme is
    defined; everything else resolves to plain ``mu``):

      * ``sketch`` — β=1 anywhere a W update runs (batch, online,
        rowshard: the scheme subsamples the W-update statistics, which
        every lane computes). Wins over the accel lanes when both are
        forced (the recipes are exclusive — one iteration scheme per
        solve); ``CNMF_TPU_SKETCH=auto`` leaves the solver lane off
        (the auto word engages the tolerance-bounded consensus sketch
        only, ``ops/sketch.py``);
      * ``dna`` — β=1 anywhere ``_chunk_h_solve``/``nmf_fit_batch``
        run (batch, online, rowshard);
      * ``amu`` — batch solves (the online/rowshard pass loops ALREADY
        repeat the cheap H solve per W update — their chunk inner loop
        is the 1107.5194 schedule natively, so there is nothing to add).
    """
    beta = float(beta)
    if algo in ("hals", "halsvar"):
        return SolverRecipe("hals", 1, False, "caller")
    if algo != "mu":
        raise ValueError(f"unknown solver algo {algo!r}")

    from ..utils.envknobs import env_flag, env_int, env_str

    # -- sketch lane (ISSUE 11) -------------------------------------------
    if sketch is None:
        sk_raw, sk_src = env_str(SKETCH_ENV, "0"), "env"
    else:
        sk_raw, sk_src = str(sketch), "caller"
    sk_raw = sk_raw.strip().lower()
    if sk_raw not in _OFF_WORDS + _ON_WORDS + ("auto",):
        raise ValueError(
            f"{SKETCH_ENV}={sk_raw!r}: expected 0, 1, or auto")
    # precedence: explicit caller arguments > env knobs (module
    # contract). An ENV-sourced sketch word must not override a caller
    # who explicitly pinned the accel family's fields; a CALLER-passed
    # ``sketch`` still wins outright.
    caller_pinned_accel = (accel is not None or inner_repeats is not None
                           or kl_newton is not None)
    if (sk_raw in _ON_WORDS and beta == 1.0
            and not (sketch is None and caller_pinned_accel)):
        m = sketch_dim
        if m is None:
            # the documented default is the string 'auto' (README knob
            # table): accept it (and '') as the unset sentinel, like
            # CNMF_TPU_INNER_REPEATS; anything else must parse as an int
            raw_dim = env_str(SKETCH_DIM_ENV, "auto").strip().lower()
            m = 0 if raw_dim in ("", "auto")                 else (env_int(SKETCH_DIM_ENV, 0, lo=0) or 0)
        if not m:
            # measured sketch-dim plan point (utils/autotune.py) wins
            # over the static n/8 heuristic; env/caller pins above win
            # outright (precedence pin > autotuned > heuristic)
            try:
                from ..utils.autotune import cached_plan_point

                m = cached_plan_point("sketch_dim")
            except Exception:
                m = None
            m = int(m) if m else auto_sketch_rows(n)
        if n:
            m = min(int(m), int(n))
        E = sketch_exact_every
        if E is None:
            E = env_int(SKETCH_EXACT_EVERY_ENV,
                        DEFAULT_SKETCH_EXACT_EVERY, lo=1)
        return SolverRecipe("sketch", 1, False, sk_src,
                            sketch_dim=int(m), sketch_exact_every=int(E))

    if accel is None:
        # default "auto" since the execution planner (ISSUE 17): batch
        # β∈{1,0} MU solves engage dna/amu out of the box, gated by the
        # accel parity suites; CNMF_TPU_ACCEL=0 remains the byte-identical
        # escape hatch (tests pin its lowering equality)
        accel_raw, source = env_str(ACCEL_ENV, "auto"), "env"
    else:
        accel_raw, source = str(accel), "caller"
    accel_raw = accel_raw.strip().lower()
    if accel_raw in _OFF_WORDS:
        return SolverRecipe("mu", 1, False,
                            "default" if accel is None else source)
    if accel_raw in _ON_WORDS:
        engaged = True
    elif accel_raw == "auto":
        # the auto lane: batch β∈{1,0} MU solves — where the iteration
        # count dominates and no parity suite pins the plain trajectory
        # bit-exact across encodings
        engaged = mode == "batch" and beta in (1.0, 0.0)
        source = source if accel is not None else "auto"
    else:
        raise ValueError(
            f"{ACCEL_ENV}={accel_raw!r}: expected 0, 1, or auto")
    if not engaged:
        return SolverRecipe("mu", 1, False, source)

    if kl_newton is None:
        kl_newton = env_flag(KL_NEWTON_ENV, True)
    if kl_newton and beta == 1.0:
        return SolverRecipe("dna", 1, True, source)
    if mode == "batch":
        rho = inner_repeats
        if rho is None:
            # the documented default is the string 'auto' (README knob
            # table): accept it (and '') as the unset sentinel, like
            # CNMF_TPU_SPARSE_BETA; anything else must parse as an int
            raw = env_str(INNER_REPEATS_ENV, "auto").strip().lower()
            rho = 0 if raw in ("", "auto") \
                else (env_int(INNER_REPEATS_ENV, 0, lo=0) or 0)
        if not rho:
            rho = auto_inner_repeats(beta, n, g, k,
                                     ell_width=ell_width if ell else None,
                                     ell=ell)
        if int(rho) > 1:
            return SolverRecipe("amu", int(rho), False, source)
    return SolverRecipe("mu", 1, False, source)
