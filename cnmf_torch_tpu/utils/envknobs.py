"""Strict env-knob parsing — the ONE definition (ISSUE 6 satellite).

Every numeric ``CNMF_TPU_*`` knob used to fall through to a confusing
downstream error on a typo; these helpers reject at parse time with a
one-line message naming the knob. Stdlib-only so the light runtime
modules (``runtime/checkpoint.py``) can share them with the jax-heavy
staging layer (``parallel/streaming.py``, ``parallel/multihost.py``)
without import-order consequences.
"""

from __future__ import annotations

import os

__all__ = ["env_int", "env_float"]


def env_int(name: str, default: int, lo: int | None = None) -> int:
    """Parse an integer knob: empty/unset -> ``default``; non-numeric or
    below the knob's floor raises ``ValueError`` naming the knob."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer")
    if lo is not None and val < lo:
        raise ValueError(f"{name}={raw!r}: must be >= {lo}")
    return val


def env_float(name: str, default: float, lo: float | None = None) -> float:
    """Parse a float knob with the same strictness as :func:`env_int`."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected a number")
    if lo is not None and val < lo:
        raise ValueError(f"{name}={raw!r}: must be >= {lo}")
    return val
