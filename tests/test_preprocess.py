"""Preprocess sidecar tests — coverage the reference never had (SURVEY.md
§4: "CITE-seq/Preprocess ... have no automated tests"): PCA vs sklearn,
seurat_v3 HVG recovery, Harmony batch-mixing improvement, MOE-ridge gene
correction, and the full preprocess -> prepare file handoff."""

import os

import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu.models.preprocess import Preprocess, stdscale_quantile_celing
from cnmf_torch_tpu.ops import moe_correct_ridge, pca, run_harmony, seurat_v3_hvg
from cnmf_torch_tpu.utils.anndata_lite import AnnDataLite


def test_pca_matches_sklearn(rng):
    from sklearn.decomposition import PCA as SkPCA

    X = rng.random((80, 30)).astype(np.float32)
    Xp, comps, ratio = pca(X, n_comps=5)
    sk = SkPCA(n_components=5, svd_solver="full").fit(X)
    np.testing.assert_allclose(np.abs(Xp), np.abs(sk.transform(X)),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(ratio, sk.explained_variance_ratio_,
                               rtol=1e-3)
    # svd_flip orientation should match sklearn exactly (same convention)
    np.testing.assert_allclose(comps, sk.components_, rtol=1e-3, atol=1e-3)


def test_seurat_v3_recovers_planted_hvgs(rng):
    n, g = 500, 300
    # Poisson genes; 30 "planted" genes are bimodal across two cell groups
    # with a multiplicative (mean-preserving-ish) rate split, so their
    # means stay inside the bulk regime and only their dispersion exceeds
    # the mean-variance trend (shifting means instead would let the local
    # trend fit *through* the planted genes — scanpy's loess included)
    lam = rng.uniform(0.5, 20.0, size=g)
    planted = rng.choice(g, size=30, replace=False)
    groups = rng.integers(0, 2, size=n).astype(bool)
    rate = np.tile(lam, (n, 1))
    rate[np.ix_(groups, planted)] *= 1.8
    rate[np.ix_(~groups, planted)] *= 0.2
    X = rng.poisson(rate).astype(np.float64)

    stats = seurat_v3_hvg(X, n_top_genes=30)
    assert stats.highly_variable.sum() == 30
    hits = np.isin(np.where(stats.highly_variable)[0], planted).sum()
    assert hits >= 25, f"only {hits}/30 planted HVGs recovered"

    # sparse path must agree with dense
    stats_sp = seurat_v3_hvg(sp.csr_matrix(X), n_top_genes=30)
    np.testing.assert_allclose(stats_sp.variances_norm.values,
                               stats.variances_norm.values, rtol=1e-4)
    assert (stats_sp.highly_variable.values
            == stats.highly_variable.values).all()


def test_seurat_v3_clipped_statistic_matches_scanpy_formula(rng):
    """Genes with clipped outlier cells: the statistic is the second moment
    of upper-clipped standardized values about the RAW mean (scanpy's
    formula), not re-centered on the clipped mean."""
    n, g = 100, 20
    X = rng.poisson(5.0, size=(n, g)).astype(np.float64)
    X[:3, 0] = 500.0  # extreme outliers in gene 0 -> clipping fires
    stats = seurat_v3_hvg(X, n_top_genes=5)
    from cnmf_torch_tpu.ops.seurat_v3 import _loess_trend

    mean = X.mean(axis=0)
    var = X.var(axis=0, ddof=1)
    fit = _loess_trend(np.log10(mean), np.log10(var))
    reg_std = np.sqrt(10.0 ** fit)
    Z = np.minimum((X - mean[None, :]) / reg_std[None, :], np.sqrt(n))
    expected = (Z ** 2).sum(axis=0) / (n - 1)
    np.testing.assert_allclose(stats.variances_norm.values, expected,
                               rtol=1e-4)
    # sparse path agrees on the clipped gene too
    stats_sp = seurat_v3_hvg(sp.csr_matrix(X), n_top_genes=5)
    np.testing.assert_allclose(stats_sp.variances_norm.values, expected,
                               rtol=1e-4)


def test_var_names_make_unique_avoids_new_collisions():
    adata = AnnDataLite(np.zeros((2, 3)),
                        var=pd.DataFrame(index=["GENE", "GENE-1", "GENE"]))
    adata.var_names_make_unique()
    assert list(adata.var.index) == ["GENE", "GENE-1", "GENE-2"]
    assert adata.var.index.is_unique


def test_pca_uncentered_ratio_bounded(rng):
    X = rng.random((50, 20)).astype(np.float32) + 100.0  # large mean offset
    _, _, ratio = pca(X, n_comps=5, zero_center=False)
    assert (ratio <= 1.0 + 1e-6).all()
    assert ratio.sum() <= 1.0 + 1e-6


def _two_batch_embedding(rng, n_per=150, d=10, shift=4.0):
    """Two biological groups x two batches; batch adds a constant offset."""
    bio = np.repeat([0, 1], n_per)
    batch = np.tile([0, 1], n_per)
    Z = rng.normal(size=(2 * n_per, d)).astype(np.float32)
    Z[bio == 1, 0] += 6.0                      # biological separation
    Z[batch == 1, 1] += shift                  # batch artifact
    obs = pd.DataFrame({"batch": [f"b{b}" for b in batch],
                        "bio": bio})
    return Z, obs, bio, batch


def test_run_harmony_reduces_batch_separation(rng):
    Z, obs, bio, batch = _two_batch_embedding(rng)
    res = run_harmony(Z, obs, "batch", theta=2.0, max_iter_harmony=10,
                      nclust=10, random_state=1)
    Zc = res.Z_corr.T
    assert Zc.shape == Z.shape

    def batch_gap(M):
        return np.linalg.norm(M[batch == 0].mean(0) - M[batch == 1].mean(0))

    def bio_gap(M):
        return np.linalg.norm(M[bio == 0].mean(0) - M[bio == 1].mean(0))

    assert batch_gap(Zc) < 0.35 * batch_gap(Z), (
        f"batch gap {batch_gap(Zc):.2f} vs original {batch_gap(Z):.2f}")
    assert bio_gap(Zc) > 0.7 * bio_gap(Z), "biological signal destroyed"
    assert res.R.shape[1] == Z.shape[0]
    assert res.Phi_moe.shape == (3, Z.shape[0])  # intercept + 2 batch levels


def test_run_harmony_multi_variable(rng):
    """Two batch variables at once: the diversity penalty sums over the
    variables (Harmony's dot-product projection), and both artifacts should
    shrink."""
    Z, obs, bio, batch = _two_batch_embedding(rng)
    # site must be orthogonal to biology (a confounded variable would make
    # removing it correctly destroy the signal)
    site = rng.integers(0, 2, size=len(batch))
    Z[site == 1, 2] += 3.0
    obs = obs.copy()
    obs["site"] = [f"s{s}" for s in site]
    res = run_harmony(Z, obs, ["batch", "site"], theta=2.0,
                      max_iter_harmony=10, nclust=10, random_state=1)
    Zc = res.Z_corr.T
    assert res.Phi_moe.shape == (5, Z.shape[0])  # intercept + 2 + 2 levels

    def gap(M, lab):
        return np.linalg.norm(M[lab == 0].mean(0) - M[lab == 1].mean(0))

    assert gap(Zc, batch) < 0.4 * gap(Z, batch)
    assert gap(Zc, site) < 0.4 * gap(Z, site)
    assert gap(Zc, bio) > 0.6 * gap(Z, bio)


def test_preprocess_plot_dir(tmp_path, rng):
    X = rng.poisson(10.0, size=(50, 30)).astype(float)
    adata = AnnDataLite(X)
    pp = Preprocess(random_seed=0, plot_dir=str(tmp_path / "plots"))
    pp.filter_adata(adata, min_cells_per_gene=1, min_counts_per_cell=1,
                    makeplots=True)
    pngs = list((tmp_path / "plots").glob("*.png"))
    assert pngs, "makeplots=True with plot_dir must save figures"


def test_moe_correct_ridge_removes_batch_offset(rng):
    # genes x cells matrix with a per-batch offset; a single-cluster R
    # reduces the MOE to one ridge expert that should strip the offset
    n, g = 200, 40
    batch = np.tile([0, 1], n // 2)
    X = rng.normal(5.0, 1.0, size=(g, n))
    X[:, batch == 1] += 3.0
    phi = np.stack([(batch == 0).astype(float),
                    (batch == 1).astype(float)])
    Phi_moe = np.vstack([np.ones((1, n)), phi])
    R = np.ones((1, n))
    lamb = np.array([0.0, 1.0, 1.0])
    Xc = moe_correct_ridge(X, R, Phi_moe, lamb)
    gap0 = np.abs(X[:, batch == 0].mean(1) - X[:, batch == 1].mean(1)).mean()
    gap1 = np.abs(Xc[:, batch == 0].mean(1) - Xc[:, batch == 1].mean(1)).mean()
    assert gap1 < 0.05 * gap0
    # intercept preserved: global mean barely moves
    assert abs(Xc.mean() - X.mean()) < 0.5


def test_moe_correct_ridge_matrix_lamb_matches_vector(rng):
    """harmonypy carries a full (B+1)x(B+1) lamb matrix; the vector form is
    a convenience — both must produce identical corrections."""
    n, g = 80, 12
    batch = np.tile([0, 1], n // 2)
    X = rng.normal(2.0, 1.0, size=(g, n))
    phi = np.stack([(batch == 0).astype(float), (batch == 1).astype(float)])
    Phi_moe = np.vstack([np.ones((1, n)), phi])
    R = rng.dirichlet(np.ones(3), size=n).T
    vec = np.array([0.0, 1.0, 1.0])
    np.testing.assert_allclose(
        moe_correct_ridge(X, R, Phi_moe, vec),
        moe_correct_ridge(X, R, Phi_moe, np.diag(vec)),
        rtol=0, atol=0)


def test_stdscale_quantile_ceiling_sparse_rejects_negatives(rng):
    """The sparse quantile path merges implicit zeros assuming nonnegative
    stored values; signed input must raise, not silently mis-threshold."""
    import pytest

    X = sp.csr_matrix(rng.normal(size=(30, 10)))
    with pytest.raises(ValueError, match="negative"):
        stdscale_quantile_celing(AnnDataLite(X), quantile_thresh=0.99)


def test_stdscale_quantile_ceiling_sparse_matches_dense(rng):
    X = rng.random((60, 25))
    X[X < 0.6] = 0.0
    a_dense = AnnDataLite(X.copy())
    a_sparse = AnnDataLite(sp.csr_matrix(X))
    stdscale_quantile_celing(a_dense, quantile_thresh=0.99)
    stdscale_quantile_celing(a_sparse, quantile_thresh=0.99)
    np.testing.assert_allclose(np.asarray(a_sparse.X.todense()), a_dense.X,
                               rtol=1e-5, atol=1e-7)


def test_filter_adata(rng):
    n, g = 100, 50
    X = rng.poisson(30.0, size=(n, g)).astype(float)
    X[:5, :] = 0.1          # low-count cells
    X[:, :3] = 0.0          # genes in no cells
    X[:, 3] = 0.0
    X[::2, 3] = 1.0          # gene in half the cells
    names = [f"G{i}" for i in range(g - 4)] + ["MT-ND1", "MT-CO1",
                                               "RP11.123", "DOT.GENE"]
    adata = AnnDataLite(X, var=pd.DataFrame(index=names))
    # make the MT genes carry most counts for the first 10 kept cells
    pp = Preprocess(random_seed=0)
    out = pp.filter_adata(adata, min_cells_per_gene=10,
                          min_counts_per_cell=50, filter_dot_genes=True,
                          filter_mito_genes=True, makeplots=False)
    assert "MT-ND1" not in out.var.index
    assert "RP11.123" not in out.var.index
    assert (out.obs["n_counts"] >= 50).all()
    assert out.n_obs == 95                     # the 5 low-count cells dropped
    # zero-cell genes dropped by the min_cells filter
    assert out.n_vars <= g - 4


def test_preprocess_for_cnmf_handoff_to_prepare(tmp_path, rng):
    """The three saved files must feed cNMF.prepare(counts_fn, tpm_fn,
    genes_file) — the documented integration contract (README.md:88-92)."""
    n, g = 120, 200
    usage = rng.dirichlet(np.ones(3) * 0.4, size=n)
    spectra = rng.gamma(0.4, 1.0, size=(3, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * 300.0).astype(float)
    X[X.sum(axis=1) == 0, 0] = 1
    adata = AnnDataLite(sp.csr_matrix(X),
                        obs=pd.DataFrame(index=[f"c{i}" for i in range(n)]),
                        var=pd.DataFrame(index=[f"g{j}" for j in range(g)]))

    pp = Preprocess(random_seed=0)
    base = str(tmp_path / "pp")
    adata_rna, tp10k, hvgs = pp.preprocess_for_cnmf(
        adata, n_top_rna_genes=100, save_output_base=base, makeplots=False)
    assert adata_rna.n_vars == 100
    assert len(hvgs) == 100
    assert tp10k.n_vars == g
    for suffix in (".Corrected.HVG.Varnorm.h5ad", ".TP10K.h5ad",
                   ".Corrected.HVGs.txt"):
        assert os.path.exists(base + suffix)

    from cnmf_torch_tpu import cNMF

    obj = cNMF(output_dir=str(tmp_path), name="pp_run")
    obj.prepare(base + ".Corrected.HVG.Varnorm.h5ad",
                tpm_fn=base + ".TP10K.h5ad",
                genes_file=base + ".Corrected.HVGs.txt",
                components=[3], n_iter=4, seed=4, batch_size=64,
                max_NMF_iter=50)
    obj.factorize()
    obj.combine()
    obj.consensus(3, density_threshold=2.0, show_clustering=False,
                  build_ref=False)
    assert os.path.exists(obj.paths["consensus_usages"] % (3, "2_0"))


def test_preprocess_citeseq_split(rng):
    n = 60
    X = rng.poisson(20.0, size=(n, 30)).astype(float)
    X[X.sum(axis=1) == 0, 0] = 1
    var = pd.DataFrame({
        "feature_types": ["Gene Expression"] * 25 + ["Antibody Capture"] * 5,
    }, index=[f"f{i}" for i in range(30)])
    adata = AnnDataLite(X, var=var)
    pp = Preprocess(random_seed=0)
    adata_rna, tp10k, hvgs = pp.preprocess_for_cnmf(
        adata, feature_type_col="feature_types", n_top_rna_genes=10,
        makeplots=False)
    assert adata_rna.n_vars == 10          # HVG-filtered RNA only
    assert tp10k.n_vars == 30              # RNA + ADT hstacked back
    # ADT rows renormalized separately: each cell's ADT block sums to 1e4
    adt = np.asarray(tp10k.X[:, 25:].todense() if sp.issparse(tp10k.X)
                     else tp10k.X[:, 25:])
    np.testing.assert_allclose(adt.sum(axis=1), 1e4, rtol=1e-3)


def test_harmony_corrected_genes_nonnegative(rng):
    n, g = 150, 60
    batch = np.tile([0, 1], n // 2)
    X = rng.poisson(8.0, size=(n, g)).astype(float)
    X[batch == 1, : g // 2] += rng.poisson(6.0, size=(n // 2, g // 2))
    X[X.sum(axis=1) == 0, 0] = 1
    obs = pd.DataFrame({"batch": [f"b{b}" for b in batch]},
                       index=[f"c{i}" for i in range(n)])
    adata = AnnDataLite(sp.csr_matrix(X), obs=obs,
                        var=pd.DataFrame(index=[f"g{j}" for j in range(g)]))
    pp = Preprocess(random_seed=0)
    adata_rna, _, hvgs = pp.preprocess_for_cnmf(
        adata, harmony_vars="batch", n_top_rna_genes=30, theta=2,
        makeplots=False, max_iter_harmony=5)
    Xc = np.asarray(adata_rna.X)
    assert (Xc >= 0).all(), "corrected expression must be clipped at zero"
    assert adata_rna.obsm["X_pca_harmony"].shape[0] == n
    assert len(hvgs) == 30


def test_select_features_mi(rng):
    n, g = 150, 40
    cluster = rng.integers(0, 3, size=n)
    X = rng.poisson(5.0, size=(n, g)).astype(float)
    # first 5 genes are strongly cluster-informative
    for c in range(3):
        X[np.ix_(cluster == c, range(5))] += c * 10
    adata = AnnDataLite(X, var=pd.DataFrame(index=[f"g{j}" for j in range(g)]))
    pp = Preprocess(random_seed=0)
    out = pp.select_features_MI(adata, cluster, n_top_features=5,
                                makeplots=False)
    top = set(out.var.index[out.var["highly_variable"]])
    assert len(top & {f"g{j}" for j in range(5)}) >= 4


def test_moe_ridge_matches_harmonypy_oracle(rng):
    """Numeric parity of the MOE ridge against a float64 re-derivation of
    the reference's moe_correct_ridge (preprocess.py:9-18 == harmonypy's):
    RMS agreement on a random fixture (VERDICT r2 weak #6 — behavioral
    tests alone would pass a wrong-but-plausible port)."""
    from cnmf_torch_tpu.ops.harmony import moe_correct_ridge
    from tests.reference_oracles import moe_correct_ridge_oracle

    d, n, K, B = 7, 90, 4, 3
    Z = rng.normal(size=(d, n)).astype(np.float32)
    R = rng.random(size=(K, n)).astype(np.float32)
    R /= R.sum(axis=0, keepdims=True)
    batches = rng.integers(0, B, size=n)
    phi = np.zeros((B, n), np.float32)
    phi[batches, np.arange(n)] = 1.0
    Phi_moe = np.concatenate([np.ones((1, n), np.float32), phi], axis=0)
    lamb = np.diag(np.concatenate([[0.0], np.full(B, 1.0)])).astype(
        np.float32)

    ours = moe_correct_ridge(Z, R, Phi_moe, lamb)
    want = moe_correct_ridge_oracle(Z, R, Phi_moe, lamb)
    rms = np.sqrt(np.mean((ours - want) ** 2))
    assert rms < 1e-4, rms


def test_harmony_cluster_round_matches_harmonypy_oracle(rng):
    """One full clustering round (centroid refresh + blockwise
    diversity-penalty R updates) agrees with the independent float64
    harmonypy-spec oracle when driven with the same block order — including
    the multi-variable case, where the penalty must SUM over batch
    variables (dot with phi), not multiply."""
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.harmony import (
        _block_R_update,
        _clustering_objective,
        _normalize_cols,
    )
    from tests.reference_oracles import harmony_cluster_round_oracle

    d, n, K = 6, 120, 5
    # two batch variables -> 2 + 3 = 5 one-hot rows
    b1 = rng.integers(0, 2, size=n)
    b2 = rng.integers(0, 3, size=n)
    phi = np.zeros((5, n), np.float32)
    phi[b1, np.arange(n)] = 1.0
    phi[2 + b2, np.arange(n)] = 1.0

    Z_cos = rng.normal(size=(d, n)).astype(np.float32)
    Z_cos /= np.linalg.norm(Z_cos, axis=0, keepdims=True)
    R0 = rng.random(size=(K, n)).astype(np.float32)
    R0 /= R0.sum(axis=0, keepdims=True)
    Pr_b = phi.sum(axis=1) / n
    sigma = np.full(K, 0.1, np.float32)
    theta = np.full(5, 2.0, np.float32)
    blocks = np.array_split(rng.permutation(n), 4)

    R_want, E_want, O_want, Y_want, obj_want = harmony_cluster_round_oracle(
        Z_cos, R0, phi, Pr_b, sigma, theta, blocks)

    # drive the jitted kernels through the identical sequence
    Rj = jnp.asarray(R0)
    Y = _normalize_cols(jnp.matmul(jnp.asarray(Z_cos), Rj.T))
    dist = 2.0 * (1.0 - jnp.matmul(Y.T, jnp.asarray(Z_cos)))
    E = jnp.outer(Rj.sum(axis=1), jnp.asarray(Pr_b))
    O = jnp.matmul(Rj, jnp.asarray(phi).T)
    for blk in blocks:
        blk = jnp.asarray(blk)
        R_blk, E, O = _block_R_update(
            dist[:, blk], jnp.asarray(phi)[:, blk], E, O, Rj[:, blk],
            jnp.asarray(Pr_b), jnp.asarray(sigma), jnp.asarray(theta))
        Rj = Rj.at[:, blk].set(R_blk)
    obj = float(_clustering_objective(Y, jnp.asarray(Z_cos), Rj, E, O,
                                      jnp.asarray(sigma),
                                      jnp.asarray(theta)))

    assert np.sqrt(np.mean((np.asarray(Rj) - R_want) ** 2)) < 1e-4
    assert np.sqrt(np.mean((np.asarray(Y) - Y_want) ** 2)) < 1e-5
    np.testing.assert_allclose(np.asarray(O), O_want, rtol=1e-3, atol=1e-4)
    assert abs(obj - obj_want) / abs(obj_want) < 1e-3


def test_fused_cluster_round_matches_blockwise_loop(rng):
    """The fused one-dispatch clustering round (_cluster_round: scan over
    padded equal-size blocks with sentinel masking) must reproduce the
    sequential per-block loop (_block_R_update) exactly, including when the
    cell count does not divide the block count."""
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.harmony import (
        _block_R_update,
        _cluster_round,
        _clustering_objective,
        _normalize_cols,
    )

    d, n, K, n_blocks = 5, 103, 4, 4          # 103 % 4 != 0 -> padding
    b = rng.integers(0, 3, size=n)
    phi = np.zeros((3, n), np.float32)
    phi[b, np.arange(n)] = 1.0
    Z_cos = rng.normal(size=(d, n)).astype(np.float32)
    Z_cos /= np.linalg.norm(Z_cos, axis=0, keepdims=True)
    R0 = rng.random(size=(K, n)).astype(np.float32)
    R0 /= R0.sum(axis=0, keepdims=True)
    Pr_b = jnp.asarray(phi.sum(axis=1) / n)
    sigma = jnp.full((K,), 0.1, jnp.float32)
    theta = jnp.full((3,), 2.0, jnp.float32)

    blk_len = int(np.ceil(n / n_blocks))
    perm = rng.permutation(n)
    perm_pad = np.full(n_blocks * blk_len, n, np.int32)
    perm_pad[:n] = perm
    valid = (perm_pad < n).astype(np.float32)

    E0 = jnp.outer(jnp.asarray(R0).sum(axis=1), Pr_b)
    O0 = jnp.matmul(jnp.asarray(R0), jnp.asarray(phi).T)

    R_f, E_f, O_f, obj_f = _cluster_round(
        jnp.asarray(Z_cos), jnp.asarray(R0), jnp.asarray(phi), E0, O0,
        jnp.asarray(perm_pad), jnp.asarray(valid), Pr_b, sigma, theta,
        n_blocks)

    # sequential reference: same blocks, one _block_R_update per block
    Rj = jnp.asarray(R0)
    Y = _normalize_cols(jnp.matmul(jnp.asarray(Z_cos), Rj.T))
    dist = 2.0 * (1.0 - jnp.matmul(Y.T, jnp.asarray(Z_cos)))
    E, O = E0, O0
    for blk in perm_pad.reshape(n_blocks, -1):
        blk = jnp.asarray(blk[blk < n])
        R_blk, E, O = _block_R_update(
            dist[:, blk], jnp.asarray(phi)[:, blk], E, O, Rj[:, blk],
            Pr_b, sigma, theta)
        Rj = Rj.at[:, blk].set(R_blk)
    obj_s = _clustering_objective(Y, jnp.asarray(Z_cos), Rj, E, O, sigma,
                                  theta)

    np.testing.assert_allclose(np.asarray(R_f), np.asarray(Rj),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(E_f), np.asarray(E),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(O_f), np.asarray(O),
                               rtol=1e-5, atol=1e-5)
    assert abs(float(obj_f) - float(obj_s)) / abs(float(obj_s)) < 1e-5


def test_cluster_phase_early_exit_and_exhaustion(rng):
    """_cluster_phase honors the host loop's stopping rule: at least two
    rounds before a convergence exit, exhaustion at max_iter otherwise, and
    the returned (obj_prev, obj) pair lets the caller reproduce the
    original objective bookkeeping."""
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.harmony import _cluster_phase, _normalize_cols

    d, n, K, n_blocks = 4, 64, 3, 4
    b = rng.integers(0, 2, size=n)
    phi = np.zeros((2, n), np.float32)
    phi[b, np.arange(n)] = 1.0
    Z = rng.normal(size=(d, n)).astype(np.float32)
    Z_cos = np.asarray(_normalize_cols(jnp.asarray(Z)))
    R0 = rng.random(size=(K, n)).astype(np.float32)
    R0 /= R0.sum(axis=0, keepdims=True)
    Pr_b = jnp.asarray(phi.sum(axis=1) / n)
    sigma = jnp.full((K,), 0.1, jnp.float32)
    theta = jnp.full((2,), 1.0, jnp.float32)
    E0 = jnp.outer(jnp.asarray(R0).sum(axis=1), Pr_b)
    O0 = jnp.matmul(jnp.asarray(R0), jnp.asarray(phi).T)

    blk = -(-n // n_blocks)
    perms = np.full((10, n_blocks * blk), n, np.int32)
    for i in range(10):
        perms[i, :n] = rng.permutation(n)

    # loose eps -> early exit after exactly 2 rounds
    *_, obj_prev, obj, rounds = _cluster_phase(
        jnp.asarray(Z_cos), jnp.asarray(R0), jnp.asarray(phi), E0, O0,
        jnp.asarray(perms), Pr_b, sigma, theta, jnp.float32(1e30),
        n_blocks, 10)
    assert int(rounds) == 2
    assert np.isfinite(float(obj_prev)) and np.isfinite(float(obj))

    # impossible eps -> runs all max_iter rounds
    *_, _, _, rounds = _cluster_phase(
        jnp.asarray(Z_cos), jnp.asarray(R0), jnp.asarray(phi), E0, O0,
        jnp.asarray(perms), Pr_b, sigma, theta, jnp.float32(0.0),
        n_blocks, 10)
    assert int(rounds) == 10
